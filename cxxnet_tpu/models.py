"""Model zoo: reference net architectures as netconfig strings.

These mirror the reference's example configs (the de-facto model zoo of
cxxnet): AlexNet (example/ImageNet/ImageNet.conf:26-130), the MNIST MLP/conv
recipes, and the kaggle_bowl plankton net. Input sizes are parameterizable so
tiny variants compile fast in tests and multi-chip dry runs.
"""

from __future__ import annotations

from typing import List, Tuple

from .nnet.trainer import Trainer
from .utils.config import parse_config_string


ALEXNET_NETCONFIG = """
netconfig=start
layer[0->1] = conv:conv1
  kernel_size = 11
  stride = 4
  nchannel = 96
layer[1->2] = relu
layer[2->3] = max_pooling
  kernel_size = 3
  stride = 2
layer[3->4] = lrn
  local_size = 5
  alpha = 0.001
  beta = 0.75
  knorm = 1
layer[4->5] = conv:conv2
  ngroup = 2
  nchannel = 256
  kernel_size = 5
  pad = 2
layer[5->6] = relu
layer[6->7] = max_pooling
  kernel_size = 3
  stride = 2
layer[7->8] = lrn
  local_size = 5
  alpha = 0.001
  beta = 0.75
  knorm = 1
layer[8->9] = conv:conv3
  nchannel = 384
  kernel_size = 3
  pad = 1
layer[9->10]= relu
layer[10->11] = conv:conv4
  nchannel = 384
  ngroup = 2
  kernel_size = 3
  pad = 1
layer[11->12] = relu
layer[12->13] = conv:conv5
  nchannel = 256
  ngroup = 2
  kernel_size = 3
  pad = 1
  init_bias = 1.0
layer[13->14] = relu
layer[14->15] = max_pooling
  kernel_size = 3
  stride = 2
layer[15->16] = flatten
layer[16->17] = fullc:fc6
  nhidden = 4096
  init_sigma = 0.005
  init_bias = 1.0
layer[17->18] = relu
layer[18->18] = dropout
  threshold = 0.5
layer[18->19] = fullc:fc7
  nhidden = 4096
  init_sigma = 0.005
  init_bias = 1.0
layer[19->20] = relu
layer[20->20] = dropout
  threshold = 0.5
layer[20->21] = fullc:fc8
  nhidden = 1000
layer[21->21] = softmax
netconfig=end
"""

ALEXNET_GLOBALS = """
momentum = 0.9
wmat:lr  = 0.01
wmat:wd  = 0.0005
bias:wd  = 0.000
bias:lr  = 0.02
lr:schedule = expdecay
lr:gamma = 0.1
lr:step = 100000
random_type = xavier
metric = error
"""


def alexnet_trainer(batch_size: int = 256, input_hw: int = 227,
                    dev: str = "tpu", extra_cfg: str = "") -> Trainer:
    """Build an AlexNet trainer with the reference recipe. input_hw can be
    shrunk (>= 67) for fast compile checks; 227 is the paper/reference size."""
    assert input_hw >= 67, "AlexNet needs input >= 67 with these strides"
    conf = (ALEXNET_NETCONFIG + ALEXNET_GLOBALS +
            "input_shape = 3,%d,%d\n" % (input_hw, input_hw) +
            "batch_size = %d\n" % batch_size +
            "dev = %s\n" % dev + extra_cfg)
    tr = Trainer()
    for k, v in parse_config_string(conf):
        tr.set_param(k, v)
    tr.init_model()
    return tr


def _inception_block(idx: int, node_in: str, nch: int) -> Tuple[str, str]:
    """One inception-style module: split 1->3, parallel 1x1/3x3/5x5 conv
    towers, ch_concat 3->1 (reference DAG features:
    src/layer/split_layer-inl.hpp, ch_concat at layer_impl-inl.hpp:61-62).
    Returns (netconfig text, output node name)."""
    p = "i%d" % idx
    txt = f"""
layer[{node_in}->{p}a,{p}b,{p}c] = split
layer[{p}a->{p}t1] = conv:{p}_1x1
  kernel_size = 1
  nchannel = {nch}
layer[{p}t1->{p}r1] = relu
layer[{p}b->{p}t3] = conv:{p}_3x3
  kernel_size = 3
  pad = 1
  nchannel = {nch}
layer[{p}t3->{p}r3] = relu
layer[{p}c->{p}t5] = conv:{p}_5x5
  kernel_size = 5
  pad = 2
  nchannel = {nch}
layer[{p}t5->{p}r5] = relu
layer[{p}r1,{p}r3,{p}r5->{p}out] = ch_concat
"""
    return txt, p + "out"


def inception_small_netconfig(n_blocks: int = 2, nch: int = 16,
                              n_class: int = 10) -> str:
    """A small GoogLeNet-flavored net: stem conv, n inception modules,
    global pooling head. Exercises split / parallel towers / ch_concat."""
    txt = """
netconfig=start
layer[0->stem] = conv:stem
  kernel_size = 3
  stride = 1
  pad = 1
  nchannel = %d
layer[stem->stemr] = relu
""" % nch
    node = "stemr"
    for i in range(n_blocks):
        blk, node = _inception_block(i, node, nch)
        txt += blk
    txt += """
layer[%s->gp] = avg_pooling
  kernel_size = 4
  stride = 4
layer[gp->fl] = flatten
layer[fl->out] = fullc:head
  nhidden = %d
layer[+0] = softmax
netconfig=end
random_type = xavier
metric = error
""" % (node, n_class)
    return txt


def inception_trainer(batch_size: int = 16, input_hw: int = 16,
                      dev: str = "cpu", n_blocks: int = 2,
                      extra_cfg: str = "") -> Trainer:
    conf = (inception_small_netconfig(n_blocks=n_blocks) +
            "input_shape = 3,%d,%d\n" % (input_hw, input_hw) +
            "batch_size = %d\n" % batch_size +
            "updater = adam\neta = 0.003\n" +
            "dev = %s\n" % dev + extra_cfg)
    tr = Trainer()
    for k, v in parse_config_string(conf):
        tr.set_param(k, v)
    tr.init_model()
    return tr


def _gnet_inception(name: str, node_in: str,
                    c1: int, c3r: int, c3: int, c5r: int, c5: int,
                    cp: int) -> Tuple[str, str]:
    """One GoogLeNet (Inception-v1) module: 1x1 / 1x1->3x3 / 1x1->5x5 /
    3x3-pool->1x1 towers, channel-concatenated (Szegedy et al. 2014).
    Expressed purely in the netconfig DSL (split + ch_concat)."""
    p = name
    txt = f"""
layer[{node_in}->{p}a,{p}b,{p}c,{p}d] = split
layer[{p}a->{p}t1] = conv:{p}_1x1
  kernel_size = 1
  nchannel = {c1}
layer[{p}t1->{p}o1] = relu
layer[{p}b->{p}t3r] = conv:{p}_3x3r
  kernel_size = 1
  nchannel = {c3r}
layer[{p}t3r->{p}r3r] = relu
layer[{p}r3r->{p}t3] = conv:{p}_3x3
  kernel_size = 3
  pad = 1
  nchannel = {c3}
layer[{p}t3->{p}o3] = relu
layer[{p}c->{p}t5r] = conv:{p}_5x5r
  kernel_size = 1
  nchannel = {c5r}
layer[{p}t5r->{p}r5r] = relu
layer[{p}r5r->{p}t5] = conv:{p}_5x5
  kernel_size = 5
  pad = 2
  nchannel = {c5}
layer[{p}t5->{p}o5] = relu
layer[{p}d->{p}pp] = max_pooling
  kernel_size = 3
  stride = 1
  pad = 1
layer[{p}pp->{p}tp] = conv:{p}_proj
  kernel_size = 1
  nchannel = {cp}
layer[{p}tp->{p}op] = relu
layer[{p}o1,{p}o3,{p}o5,{p}op->{p}out] = ch_concat
"""
    return txt, p + "out"


# (c1, c3r, c3, c5r, c5, pool_proj) per module — the paper's Table 1
GOOGLENET_MODULES = {
    "i3a": (64, 96, 128, 16, 32, 32),
    "i3b": (128, 128, 192, 32, 96, 64),
    "i4a": (192, 96, 208, 16, 48, 64),
    "i4b": (160, 112, 224, 24, 64, 64),
    "i4c": (128, 128, 256, 24, 64, 64),
    "i4d": (112, 144, 288, 32, 64, 64),
    "i4e": (256, 160, 320, 32, 128, 128),
    "i5a": (256, 160, 320, 32, 128, 128),
    "i5b": (384, 192, 384, 48, 128, 128),
}


def googlenet_netconfig(n_class: int = 1000, final_pool: int = 7) -> str:
    """GoogLeNet / Inception-v1 (the BASELINE.json 'ImageNet GoogLeNet'
    config): stem, 9 inception modules with maxpools between stages, global
    avg-pool head. LRN runs the Pallas kernel on TPU."""
    txt = """
netconfig=start
layer[0->n1] = conv:conv1
  kernel_size = 7
  stride = 2
  pad = 3
  nchannel = 64
layer[n1->n2] = relu
layer[n2->n3] = max_pooling
  kernel_size = 3
  stride = 2
layer[n3->n4] = lrn
  local_size = 5
  alpha = 0.0001
  beta = 0.75
  knorm = 1
layer[n4->n5] = conv:conv2r
  kernel_size = 1
  nchannel = 64
layer[n5->n6] = relu
layer[n6->n7] = conv:conv2
  kernel_size = 3
  pad = 1
  nchannel = 192
layer[n7->n8] = relu
layer[n8->n9] = lrn
  local_size = 5
  alpha = 0.0001
  beta = 0.75
  knorm = 1
layer[n9->n10] = max_pooling
  kernel_size = 3
  stride = 2
"""
    node = "n10"
    for mod in ("i3a", "i3b"):
        blk, node = _gnet_inception(mod, node, *GOOGLENET_MODULES[mod])
        txt += blk
    txt += """
layer[%s->p3] = max_pooling
  kernel_size = 3
  stride = 2
""" % node
    node = "p3"
    for mod in ("i4a", "i4b", "i4c", "i4d", "i4e"):
        blk, node = _gnet_inception(mod, node, *GOOGLENET_MODULES[mod])
        txt += blk
    txt += """
layer[%s->p4] = max_pooling
  kernel_size = 3
  stride = 2
""" % node
    node = "p4"
    for mod in ("i5a", "i5b"):
        blk, node = _gnet_inception(mod, node, *GOOGLENET_MODULES[mod])
        txt += blk
    txt += """
layer[%(node)s->gp] = avg_pooling
  kernel_size = %(fp)d
  stride = %(fp)d
layer[gp->fl] = flatten
layer[fl->fd] = dropout
  threshold = 0.4
layer[fd->out] = fullc:loss_fc
  nhidden = %(ncls)d
layer[+0] = softmax
netconfig=end
random_type = xavier
metric = error
""" % {"node": node, "fp": final_pool, "ncls": n_class}
    return txt


def googlenet_trainer(batch_size: int = 128, input_hw: int = 224,
                      dev: str = "tpu", n_class: int = 1000,
                      extra_cfg: str = "") -> Trainer:
    """GoogLeNet with the standard ImageNet recipe shape (224x224). For
    tests, input_hw can shrink (>= 32); the final avg-pool adapts."""
    assert input_hw >= 32
    final_pool = max(input_hw // 32, 1)
    conf = (googlenet_netconfig(n_class=n_class, final_pool=final_pool) +
            "input_shape = 3,%d,%d\n" % (input_hw, input_hw) +
            "batch_size = %d\n" % batch_size +
            "eta = 0.01\nmomentum = 0.9\nwd = 0.0002\n" +
            "dev = %s\n" % dev + extra_cfg)
    tr = Trainer()
    for k, v in parse_config_string(conf):
        tr.set_param(k, v)
    tr.init_model()
    return tr


def _transformer_block(p: str, node_in: str, dim: int, nhead: int,
                       ffn: int, attn_keys: str = "",
                       norm: bool = False) -> Tuple[str, str]:
    """One transformer block in the DSL, shared by the LM and ViT
    builders so the block shape lives in one place. Residuals connect the
    BLOCK INPUT (pre-norm form): out = x + att(norm(x)), then
    + ffn(norm(.)). norm=True inserts batch_norm (moving_average) before
    each sub-block; attn_keys are extra per-attention config lines
    (causal/rope/GQA/window)."""
    txt = ""
    att_in = node_in
    if norm:
        txt += ("layer[%(in)s->%(p)sn1] = batch_norm:%(p)s_bn1\n"
                "  moving_average = 1\n" % {"in": node_in, "p": p})
        att_in = p + "n1"
    txt += """layer[%(ai)s->%(p)satt] = attention:%(p)s_att
  nhead = %(nh)d
  init_sigma = 0.05
%(ak)slayer[%(in)s,%(p)satt->%(p)sres1] = add
""" % {"ai": att_in, "in": node_in, "p": p, "nh": nhead,
       "ak": "".join("  %s\n" % l.strip()
                     for l in attn_keys.splitlines() if l.strip())}
    ffn_in = p + "res1"
    if norm:
        txt += ("layer[%(p)sres1->%(p)sn2] = batch_norm:%(p)s_bn2\n"
                "  moving_average = 1\n" % {"p": p})
        ffn_in = p + "n2"
    txt += """layer[%(fi)s->%(p)sf1] = conv:%(p)s_ffn1
  kernel_size = 1
  nchannel = %(ffn)d
  init_sigma = 0.05
layer[%(p)sf1->%(p)sr] = relu
layer[%(p)sr->%(p)sf2] = conv:%(p)s_ffn2
  kernel_size = 1
  nchannel = %(dim)d
  init_sigma = 0.05
layer[%(p)sres1,%(p)sf2->%(p)sout] = add
""" % {"fi": ffn_in, "p": p, "ffn": ffn, "dim": dim}
    return txt, p + "out"


def transformer_lm_netconfig(vocab: int, dim: int = 64, nhead: int = 4,
                             nlayer: int = 2, ffn_mult: int = 2,
                             attn_extra: str = "") -> str:
    """Decoder-only transformer LM from the netconfig DSL (beyond the
    reference — the long-context model family): embed -> n x [causal
    attention + residual, 1x1-conv FFN + residual] -> vocab head ->
    per-position softmax (seq = 1). Residuals use the `add` layer.
    ``attn_extra``: extra per-attention-layer keys (e.g. "nkvhead = 2\\n
    attn_window = 1024\\nrope = 1\\n" for a GQA sliding-window recipe)."""
    txt = """
netconfig = start
layer[+1:emb] = embed:emb
  vocab_size = %d
  nhidden = %d
  pos_embed = 1
  init_sigma = 0.05
""" % (vocab, dim)
    node = "emb"
    for i in range(nlayer):
        blk, node = _transformer_block(
            "blk%d" % i, node, dim, nhead, ffn_mult * dim,
            attn_keys="causal = 1\n" + attn_extra)
        txt += "\n" + blk
    txt += """
layer[%s->logits] = conv:head
  kernel_size = 1
  nchannel = %d
  init_sigma = 0.05
layer[+0] = softmax
  seq = 1
netconfig = end
metric = seq
""" % (node, vocab)
    # `metric = seq` is not a metric — strip it; kept minimal
    txt = txt.replace("metric = seq\n", "")
    return txt


def transformer_lm_trainer(vocab: int = 50, seq: int = 16,
                           batch_size: int = 8, dim: int = 64,
                           nhead: int = 4, nlayer: int = 2,
                           dev: str = "cpu", extra_cfg: str = "",
                           attn_extra: str = "") -> Trainer:
    conf = (transformer_lm_netconfig(vocab, dim=dim, nhead=nhead,
                                     nlayer=nlayer,
                                     attn_extra=attn_extra) +
            "input_shape = 1,1,%d\n" % seq +
            "batch_size = %d\n" % batch_size +
            "label_vec[0,%d) = label\n" % seq +
            "updater = adam\neta = 0.003\n" +
            "dev = %s\n" % dev + extra_cfg)
    tr = Trainer()
    for k, v in parse_config_string(conf):
        tr.set_param(k, v)
    tr.init_model()
    return tr


def vit_netconfig(n_class: int, image_hw: int = 32, patch: int = 4,
                  dim: int = 64, nhead: int = 4, nlayer: int = 2,
                  ffn_mult: int = 2) -> str:
    """Vision transformer from the netconfig DSL (beyond the reference —
    composes existing pieces): patch-embedding conv (kernel = stride =
    patch) -> im2seq -> n x [batch_norm, RoPE attention + residual,
    1x1-conv FFN + residual] -> mean-pool over positions -> fullc head.
    RoPE supplies the position signal (row-major patch order, the im2seq
    flattening), so no learned position table is needed."""
    check_msg = "vit: patch must divide image_hw"
    assert image_hw % patch == 0, check_msg
    npos = (image_hw // patch) ** 2
    txt = """
netconfig = start
layer[0->pe] = conv:patch_embed
  kernel_size = %d
  stride = %d
  nchannel = %d
  random_type = xavier
layer[pe->sq] = im2seq
""" % (patch, patch, dim)
    node = "sq"
    for i in range(nlayer):
        blk, node = _transformer_block(
            "vb%d" % i, node, dim, nhead, ffn_mult * dim,
            attn_keys="rope = 1\n", norm=True)
        txt += "\n" + blk
    txt += """
layer[%s->gp] = avg_pooling
  kernel_height = 1
  kernel_width = %d
  stride = %d
layer[gp->fl] = flatten
layer[fl->out] = fullc:head
  nhidden = %d
  random_type = xavier
layer[+0] = softmax
netconfig = end
""" % (node, npos, npos, n_class)
    return txt


def vit_trainer(n_class: int = 10, image_hw: int = 32, patch: int = 4,
                batch_size: int = 16, dim: int = 64, nhead: int = 4,
                nlayer: int = 2, ffn_mult: int = 2, dev: str = "cpu",
                extra_cfg: str = "") -> Trainer:
    """Vision-transformer trainer (shrink image_hw/dim/nlayer for tests)."""
    conf = (vit_netconfig(n_class, image_hw=image_hw, patch=patch,
                          dim=dim, nhead=nhead, nlayer=nlayer,
                          ffn_mult=ffn_mult) +
            "input_shape = 3,%d,%d\n" % (image_hw, image_hw) +
            "batch_size = %d\n" % batch_size +
            "updater = adamw\neta = 0.003\nwd = 0.01\n" +
            "dev = %s\n" % dev + extra_cfg)
    tr = Trainer()
    for k, v in parse_config_string(conf):
        tr.set_param(k, v)
    tr.init_model()
    return tr


def _res_block(idx: int, node_in: str, nch: int, stride: int = 1,
               project: bool = False) -> Tuple[str, str]:
    """Basic residual block (two 3x3 convs + batch_norm, identity or
    1x1-projection shortcut, post-add relu), expressed in the layer DSL —
    beyond the reference's era (it ships concat but no residual nets); the
    `add` layer makes the family expressible."""
    p = "rb%d" % idx
    main_in = "%s_s0" % p
    short_in = "%s_s1" % p
    txt = "layer[%s->%s,%s] = split\n" % (node_in, main_in, short_in)
    txt += """layer[{mi}->{p}_c1] = conv:{p}_c1
  kernel_size = 3
  pad = 1
  stride = {stride}
  nchannel = {nch}
  random_type = kaiming
  no_bias = 1
layer[{p}_c1->{p}_b1] = batch_norm:{p}_b1
layer[{p}_b1->{p}_r1] = relu
layer[{p}_r1->{p}_c2] = conv:{p}_c2
  kernel_size = 3
  pad = 1
  nchannel = {nch}
  random_type = kaiming
  no_bias = 1
layer[{p}_c2->{p}_b2] = batch_norm:{p}_b2
""".format(p=p, mi=main_in, nch=nch, stride=stride)
    if project:
        txt += """layer[{si}->{p}_sc] = conv:{p}_sc
  kernel_size = 1
  stride = {stride}
  nchannel = {nch}
  random_type = kaiming
  no_bias = 1
layer[{p}_sc->{p}_sb] = batch_norm:{p}_sb
layer[{p}_b2,{p}_sb->{p}_add] = add
""".format(p=p, si=short_in, nch=nch, stride=stride)
    else:
        txt += "layer[%s_b2,%s->%s_add] = add\n" % (p, short_in, p)
    txt += "layer[%s_add->%s_out] = relu\n" % (p, p)
    return txt, "%s_out" % p


def resnet_netconfig(depths=(2, 2, 2, 2), base_ch: int = 64,
                     n_class: int = 1000, final_pool: int = 7) -> str:
    """ResNet-18-shaped netconfig (depths=(2,2,2,2)); shrink depths/base_ch
    for tests."""
    txt = "netconfig = start\n"
    txt += """layer[0->stem] = conv:stem
  kernel_size = 7
  pad = 3
  stride = 2
  nchannel = %d
  random_type = kaiming
  no_bias = 1
layer[stem->stem_b] = batch_norm:stem_b
layer[stem_b->stem_r] = relu
layer[stem_r->stem_p] = max_pooling
  kernel_size = 3
  stride = 2
""" % base_ch
    node = "stem_p"
    idx = 0
    for stage, n_blocks in enumerate(depths):
        nch = base_ch * (2 ** stage)
        for b in range(n_blocks):
            first = (b == 0 and stage > 0)
            blk, node = _res_block(idx, node, nch,
                                   stride=2 if first else 1,
                                   project=first)
            txt += blk
            idx += 1
    txt += """layer[%s->gap] = avg_pooling
  kernel_size = %d
  stride = %d
layer[gap->flat] = flatten
layer[flat->fc] = fullc:fc
  nhidden = %d
  random_type = kaiming
layer[fc->fc] = softmax
netconfig = end
""" % (node, final_pool, final_pool, n_class)
    return txt


def resnet_trainer(batch_size: int = 128, input_hw: int = 224,
                   dev: str = "tpu", n_class: int = 1000,
                   depths=(2, 2, 2, 2), base_ch: int = 64,
                   extra_cfg: str = "") -> Trainer:
    """ResNet-18-shaped trainer (shrink depths/base_ch/input_hw for
    tests)."""
    # stem(2) * pool(2) * one stride-2 per stage after the first
    downsample = 4 * (2 ** (len(depths) - 1))
    final_pool = max(input_hw // downsample, 1)
    conf = (resnet_netconfig(depths, base_ch, n_class,
                             final_pool=final_pool) +
            "input_shape = 3,%d,%d\n" % (input_hw, input_hw) +
            "batch_size = %d\n" % batch_size +
            "eta = 0.1\nmomentum = 0.9\nwd = 0.0001\n" +
            "dev = %s\n" % dev + extra_cfg)
    tr = Trainer()
    for k, v in parse_config_string(conf):
        tr.set_param(k, v)
    tr.init_model()
    return tr


# VGG (Simonyan & Zisserman 2014) — contemporary of the reference's era;
# deep uniform 3x3 stacks, the natural customer of `remat = 1` (13 conv
# activations at 224x224 otherwise dominate HBM)
VGG_STAGES = {
    "vgg11": ((64,), (128,), (256, 256), (512, 512), (512, 512)),
    "vgg16": ((64, 64), (128, 128), (256, 256, 256),
              (512, 512, 512), (512, 512, 512)),
}


def vgg_netconfig(arch: str = "vgg16", n_class: int = 1000,
                  fc_dim: int = 4096, remat: int = 0,
                  dropout: float = 0.5) -> str:
    """VGG in the layer DSL: 5 stages of 3x3/pad-1 conv+relu stacks, each
    followed by a 2x2/stride-2 max pool, then fc-relu-dropout x2 and the
    classifier head."""
    txt = "netconfig=start\n"
    if remat:
        txt += "remat = 1\n"
    node = "0"
    for s, widths in enumerate(VGG_STAGES[arch]):
        for c, width in enumerate(widths):
            name = "conv%d_%d" % (s + 1, c + 1)
            txt += """layer[%s->%s] = conv:%s
  kernel_size = 3
  pad = 1
  nchannel = %d
layer[%s->%sr] = relu
""" % (node, name, name, width, name, name)
            node = name + "r"
        txt += """layer[%s->pool%d] = max_pooling
  kernel_size = 2
  stride = 2
""" % (node, s + 1)
        node = "pool%d" % (s + 1)
    txt += "layer[%s->fl] = flatten\n" % node
    node = "fl"
    for i in (6, 7):
        txt += """layer[%s->fc%d] = fullc:fc%d
  nhidden = %d
layer[fc%d->fc%dr] = relu
layer[fc%dr->fc%dr] = dropout
  threshold = %g
""" % (node, i, i, fc_dim, i, i, i, i, dropout)
        node = "fc%dr" % i
    txt += """layer[%s->out] = fullc:head
  nhidden = %d
layer[+0] = softmax
netconfig=end
random_type = kaiming
metric = error
""" % (node, n_class)
    return txt


def vgg_trainer(batch_size: int = 64, input_hw: int = 224,
                dev: str = "tpu", n_class: int = 1000,
                arch: str = "vgg16", fc_dim: int = 4096,
                remat: int = 0, dropout: float = 0.5,
                extra_cfg: str = "") -> Trainer:
    """VGG trainer with the paper recipe; shrink input_hw/fc_dim for
    tests (input must be a multiple of 32 to survive the 5 pools)."""
    assert input_hw % 32 == 0, "VGG needs input divisible by 32"
    conf = (vgg_netconfig(arch, n_class, fc_dim=fc_dim,
                      remat=remat, dropout=dropout) +
            "input_shape = 3,%d,%d\n" % (input_hw, input_hw) +
            "batch_size = %d\n" % batch_size +
            "eta = 0.01\nmomentum = 0.9\nwd = 0.0005\n" +
            "dev = %s\n" % dev + extra_cfg)
    tr = Trainer()
    for k, v in parse_config_string(conf):
        tr.set_param(k, v)
    tr.init_model()
    return tr


# MobileNet-V1-style depthwise-separable stack — the grouped-conv
# extreme (ngroup = C: one input channel per group), exercising the
# reference's in-layer model-splitting mechanism
# (src/layer/convolution_layer-inl.hpp:92-96) at its limit while being
# the canonical bandwidth-lean conv recipe for edge/serving. Beyond the
# reference's zoo (its era predates depthwise separability going
# mainstream); built entirely from the stock `conv` layer.

MOBILENET_BLOCKS = ((64, 1), (128, 2), (128, 1), (256, 2),
                    (256, 1), (512, 2), (512, 1))


def _mobilenet_final_pool(blocks, input_hw: int) -> int:
    """GAP kernel for the final feature map: input / (stem 2x * block
    strides) — ONE definition so netconfig and trainer can't drift."""
    downsample = 2
    for _, s in blocks:
        downsample *= s
    return max(input_hw // downsample, 1)


def mobilenet_netconfig(n_class: int = 1000, base_ch: int = 32,
                        blocks=MOBILENET_BLOCKS,
                        final_pool: int = 0) -> str:
    """(out_channels, stride) per depthwise-separable block; shrink
    ``blocks``/``base_ch`` for tests. final_pool 0 = global average
    pool for a 224 input (derived from the block strides)."""
    if not final_pool:
        final_pool = _mobilenet_final_pool(blocks, 224)
    txt = """netconfig = start
layer[0->stem] = conv:stem
  kernel_size = 3
  pad = 1
  stride = 2
  nchannel = %d
  random_type = kaiming
  no_bias = 1
layer[stem->stem_b] = batch_norm:stem_b
layer[stem_b->stem_r] = relu
""" % base_ch
    node, c = "stem_r", base_ch
    for i, (ch, stride) in enumerate(blocks):
        txt += """layer[%s->dw%d] = conv:dw%d
  kernel_size = 3
  pad = 1
  stride = %d
  nchannel = %d
  ngroup = %d
  random_type = kaiming
  no_bias = 1
layer[dw%d->dwb%d] = batch_norm:dwb%d
layer[dwb%d->dwr%d] = relu
layer[dwr%d->pw%d] = conv:pw%d
  kernel_size = 1
  nchannel = %d
  random_type = kaiming
  no_bias = 1
layer[pw%d->pwb%d] = batch_norm:pwb%d
layer[pwb%d->pwr%d] = relu
""" % (node, i, i, stride, c, c, i, i, i, i, i, i, i, i, ch,
            i, i, i, i, i)
        node, c = "pwr%d" % i, ch
    txt += """layer[%s->gap] = avg_pooling
  kernel_size = %d
  stride = %d
layer[gap->flat] = flatten
layer[flat->fc] = fullc:fc
  nhidden = %d
  random_type = kaiming
layer[fc->fc] = softmax
netconfig = end
""" % (node, final_pool, final_pool, n_class)
    return txt


def mobilenet_trainer(batch_size: int = 256, input_hw: int = 224,
                      dev: str = "tpu", n_class: int = 1000,
                      base_ch: int = 32,
                      blocks=MOBILENET_BLOCKS,
                      extra_cfg: str = "") -> Trainer:
    """Depthwise-separable trainer (shrink blocks/base_ch/input_hw for
    tests)."""
    final_pool = _mobilenet_final_pool(blocks, input_hw)
    conf = (mobilenet_netconfig(n_class, base_ch, blocks,
                                final_pool=final_pool) +
            "input_shape = 3,%d,%d\n" % (input_hw, input_hw) +
            "batch_size = %d\n" % batch_size +
            "eta = 0.1\nmomentum = 0.9\nwd = 0.0001\n" +
            "dev = %s\n" % dev + extra_cfg)
    tr = Trainer()
    for k, v in parse_config_string(conf):
        tr.set_param(k, v)
    tr.init_model()
    return tr
