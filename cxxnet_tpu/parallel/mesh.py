"""Device mesh creation and ``dev=`` spec parsing.

Replaces the reference's device-thread spawning (CXXNetThreadTrainer dev
parsing, src/nnet/nnet_impl-inl.hpp:32-51): ``dev=gpu:0-3`` meant four GPU
worker threads; here it selects devices for a 1-D data mesh (higher-dim
meshes for tensor/pipeline parallelism are built by passing axis specs).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np
import jax
from jax.sharding import Mesh


def parse_device_spec(spec: str) -> Tuple[str, List[int]]:
    """Parse ``cpu`` / ``gpu`` / ``tpu`` / ``tpu:0-3`` / ``gpu:0,2`` into
    (kind, device_ids). Empty id list means "all available"."""
    if ":" not in spec:
        return spec, []
    kind, ids = spec.split(":", 1)
    if "-" in ids:
        a, b = ids.split("-")
        return kind, list(range(int(a), int(b) + 1))
    return kind, [int(x) for x in ids.split(",")]


def create_mesh(device_ids: Optional[Sequence[int]] = None,
                axes: Tuple[str, ...] = ("data",),
                shape: Optional[Tuple[int, ...]] = None) -> Mesh:
    """Create a mesh over the given devices (default: all).

    axes/shape allow multi-axis meshes, e.g. axes=("data", "model"),
    shape=(4, 2). A 1-D data mesh reproduces the reference's data-parallel
    topology with ICI all-reduce instead of the PS.
    """
    devs = jax.devices()
    if device_ids:
        id_map = {d.id: d for d in devs}
        picked = [id_map[i] for i in device_ids if i in id_map]
        # multi-process runs have non-contiguous global device ids (each
        # process numbers its own block), so `dev=tpu:0-7` style specs fall
        # back to positional selection when ids don't all resolve
        devs = picked if len(picked) == len(device_ids) \
            else jax.devices()[: len(device_ids)]
    if shape is None:
        shape = (len(devs),) + (1,) * (len(axes) - 1)
    arr = np.array(devs[: int(np.prod(shape))]).reshape(shape)
    return Mesh(arr, axes)
