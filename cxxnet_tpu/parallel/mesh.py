"""Device mesh creation and ``dev=`` spec parsing.

Replaces the reference's device-thread spawning (CXXNetThreadTrainer dev
parsing, src/nnet/nnet_impl-inl.hpp:32-51): ``dev=gpu:0-3`` meant four GPU
worker threads; here it selects devices for a 1-D data mesh (higher-dim
meshes for tensor/pipeline parallelism are built by passing axis specs).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np
import jax
from jax.sharding import Mesh


def parse_device_spec(spec: str) -> Tuple[str, List[int]]:
    """Parse ``cpu`` / ``gpu`` / ``tpu`` / ``tpu:0-3`` / ``gpu:0,2`` into
    (kind, device_ids). Empty id list means "all available"."""
    if ":" not in spec:
        return spec, []
    kind, ids = spec.split(":", 1)
    if "-" in ids:
        a, b = ids.split("-")
        return kind, list(range(int(a), int(b) + 1))
    return kind, [int(x) for x in ids.split(",")]


def backend_initialized() -> bool:
    """True when a jax backend is already live in this process. Peeks at
    jax's internal registry so the check itself never initializes (and
    thus never blocks on) a backend."""
    try:
        from jax._src import xla_bridge as xb
        return bool(getattr(xb, "_backends", None))
    except Exception:
        return False


_cpu_pinned = False


def ensure_platform(kind: str) -> None:
    """Make ``dev = cpu`` actually select the CPU backend even when the
    environment pins another jax platform (JAX_PLATFORMS is read before
    user code runs, so the env route cannot be overridden later). No-op
    unless kind is cpu and no backend has been initialized yet.

    The selection is process-wide (a jax constraint): once a dev=cpu
    trainer pinned the CPU backend, a later dev=tpu/gpu trainer in the
    same process would silently run on CPU — that case raises instead."""
    global _cpu_pinned
    if kind != "cpu":
        if _cpu_pinned:
            raise RuntimeError(
                "dev=%s requested, but this process already selected the "
                "CPU backend for an earlier dev=cpu trainer; jax supports "
                "one platform per process — use a separate process" % kind)
        return
    if backend_initialized():
        return  # backend already live; too late and unnecessary
    try:
        jax.config.update("jax_platforms", "cpu")
        _cpu_pinned = True
    except Exception:
        pass


def create_mesh(device_ids: Optional[Sequence[int]] = None,
                axes: Tuple[str, ...] = ("data",),
                shape: Optional[Tuple[int, ...]] = None) -> Mesh:
    """Create a mesh over the given devices (default: all).

    axes/shape allow multi-axis meshes, e.g. axes=("data", "model"),
    shape=(4, 2). A 1-D data mesh reproduces the reference's data-parallel
    topology with ICI all-reduce instead of the PS.
    """
    devs = jax.devices()
    if device_ids:
        id_map = {d.id: d for d in devs}
        picked = [id_map[i] for i in device_ids if i in id_map]
        # multi-process runs have non-contiguous global device ids (each
        # process numbers its own block), so `dev=tpu:0-7` style specs fall
        # back to positional selection when ids don't all resolve
        devs = picked if len(picked) == len(device_ids) \
            else jax.devices()[: len(device_ids)]
    if shape is None:
        shape = (len(devs),) + (1,) * (len(axes) - 1)
    arr = np.array(devs[: int(np.prod(shape))]).reshape(shape)
    return Mesh(arr, axes)
