"""Tensor parallelism: sharded dense layers over a mesh ``model`` axis.

The reference's closest trick is ``fullc_gather`` (SURVEY.md §2.9): for a
giant FC layer it allgathers (input, output-grad) activation pairs through
the parameter server and recomputes the weight gradient locally, instead of
syncing the huge weight gradient (src/updater/async_updater-inl.hpp:67-92).
The TPU-native generalization is to shard the FC weight itself across the
``model`` axis — Megatron-style column/row parallelism — so neither the
weight nor its gradient is ever materialized unsharded; XLA inserts the one
all-reduce (row-parallel) or none (column-parallel feeding row-parallel).

Two usage modes:
* GSPMD: just place the weight with `fullc_sharding()` and let XLA partition
  the matmul — this is what the Trainer does for `model_parallel > 1`.
* explicit shard_map: `column_parallel_dense` / `row_parallel_dense` below,
  for code that wants the collectives visible (tests, custom schedules).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ._compat import shard_map


def fullc_sharding(mesh: Mesh, axis: str = "model") -> NamedSharding:
    """Sharding for a fullc weight stored (num_hidden, num_input) — shard the
    output dim (column parallel in Megatron terms)."""
    return NamedSharding(mesh, P(axis, None))


def bias_sharding(mesh: Mesh, axis: str = "model") -> NamedSharding:
    return NamedSharding(mesh, P(axis))


def _colp(x, w, b, axis_name):
    # x replicated, w: (out/n, in) shard -> y: (batch, out/n) shard
    y = x @ w.T
    if b is not None:
        y = y + b
    return y


def _rowp(x, w, b, axis_name):
    # x: (batch, in/n) shard, w: (out, in/n) shard -> partial sums all-reduced
    y = lax.psum(x @ w.T, axis_name)
    if b is not None:
        y = y + b
    return y


def column_parallel_dense(x, w, b, mesh: Mesh, *, axis: str = "model"):
    """y = x @ w.T + b with w sharded on the output dim. x replicated in,
    y sharded (axis) out. No collective on the forward path."""
    fn = shard_map(functools.partial(_colp, axis_name=axis), mesh=mesh,
                   in_specs=(P(), P(axis, None),
                             P(axis) if b is not None else None),
                   out_specs=P(None, axis))
    return fn(x, w, b)


def row_parallel_dense(x, w, b, mesh: Mesh, *, axis: str = "model"):
    """y = x @ w.T + b with w sharded on the input dim and x sharded to
    match; one psum produces the replicated output — the canonical second
    half of a Megatron pair."""
    in_specs = (P(None, axis), P(None, axis), P() if b is not None else None)
    fn = shard_map(functools.partial(_rowp, axis_name=axis), mesh=mesh,
                   in_specs=in_specs, out_specs=P())
    return fn(x, w, b)


def _ep_local(x, w_exp, gates, *, axis_name):
    # x: (B, din) batch shard; w_exp: (E/n, din, dout) local experts;
    # gates: (B, E/n) local gate probabilities for this device's experts
    y = jnp.einsum("bi,eio->ebo", x, w_exp)          # every expert, dense
    y = jnp.maximum(y, 0.0)                          # expert FFN activation
    out = jnp.einsum("ebo,be->bo", y, gates)         # gate-weighted combine
    return lax.psum(out, axis_name)                  # sum over expert shards


def expert_parallel_ffn(x, w_experts, gate_probs, mesh: Mesh, *,
                        axis: str = "ep", batch_axis: Optional[str] = None):
    """Expert parallelism: experts sharded over the ``axis`` mesh dim, each
    device runs its local experts densely over all tokens and one psum
    combines the gate-weighted outputs.

    x: (batch, d_in); w_experts: (n_experts, d_in, d_out); gate_probs:
    (batch, n_experts). Dense dispatch (every expert sees every token,
    zeroed by the gate) is the XLA-friendly form — static shapes, MXU-sized
    matmuls — and is exact for soft gating; top-k gating just passes
    sparse gate_probs. ``batch_axis`` names a mesh axis the batch dim is
    sharded over (the trainer's "data" axis on a (data, ep) mesh) so EP
    composes with data parallelism without gathering activations.
    """
    n = mesh.shape[axis]
    if w_experts.shape[0] % n != 0:
        raise ValueError("expert_parallel_ffn: n_experts %d not divisible by "
                         "mesh axis %r size %d" % (w_experts.shape[0], axis, n))
    fn = shard_map(functools.partial(_ep_local, axis_name=axis), mesh=mesh,
                   in_specs=(P(batch_axis, None), P(axis, None, None),
                             P(batch_axis, axis)),
                   out_specs=P(batch_axis, None))
    return fn(x, w_experts, gate_probs)
