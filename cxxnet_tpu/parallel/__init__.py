"""Parallelism: device meshes, sharding rules, and collectives.

TPU-native replacement for the reference's entire distribution stack —
mshadow-ps push/pull parameter server + per-GPU worker threads
(SURVEY.md §2.9-§2.10). Strategy mapping:

* single-node multi-GPU data parallelism (dev=gpu:a-b, batch split across
  NeuralNetThreads, PS "local" sync)      -> batch sharded over the mesh
  'data' axis; XLA inserts the gradient all-reduce over ICI
* distributed PS (param_server=dist, update_on_server=1, server-side
  optimizer)                              -> ZeRO-style sharded optimizer
  state (weight-update sharding) over the data axis
* per-tensor async push/pull overlap      -> XLA latency-hiding scheduler
  within the single jitted train step
"""

from .mesh import create_mesh, parse_device_spec  # noqa: F401
from .sharding import (batch_sharding, replicated, shard_opt_state,  # noqa: F401
                       zero_sharding)
