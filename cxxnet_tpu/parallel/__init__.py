"""Parallelism: device meshes, sharding rules, and collectives.

TPU-native replacement for the reference's entire distribution stack —
mshadow-ps push/pull parameter server + per-GPU worker threads
(SURVEY.md §2.9-§2.10). Strategy mapping:

* single-node multi-GPU data parallelism (dev=gpu:a-b, batch split across
  NeuralNetThreads, PS "local" sync)      -> batch sharded over the mesh
  'data' axis; XLA inserts the gradient all-reduce over ICI
* distributed PS (param_server=dist, update_on_server=1, server-side
  optimizer)                              -> ZeRO-style sharded optimizer
  state (weight-update sharding) over the data axis
* per-tensor async push/pull overlap      -> XLA latency-hiding scheduler
  within the single jitted train step
"""

from .mesh import (backend_initialized, create_mesh,  # noqa: F401
                   ensure_platform, parse_device_spec)
from .sharding import (batch_sharding, replicated,  # noqa: F401
                       zero_sharding)
from . import collectives  # noqa: F401
from .ring import attention_reference, ring_attention, ulysses_attention  # noqa: F401
from .tensor import (column_parallel_dense, expert_parallel_ffn,  # noqa: F401
                     fullc_sharding, row_parallel_dense)
from .pipeline import (pipeline_apply, pipeline_apply_stages,  # noqa: F401
                       stage_sharding)
from .multihost import (create_hybrid_mesh, fetch_global,  # noqa: F401
                        init_distributed,
                        virtual_cpu_env, worker_shard_params)
