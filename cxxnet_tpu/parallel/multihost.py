"""Multi-host / multi-slice support: process init and hybrid DCN x ICI meshes.

Replaces the reference's distributed launch story — parameter-server
processes started through MPI (`example/MNIST/mpi.conf`, `bin/cxxnet.ps`,
SURVEY.md §2.9 row 2) — with the jax runtime's multi-controller model: every
host runs the same program, `jax.distributed.initialize` forms the cluster,
and a hybrid mesh lays data parallelism across DCN (slices) while
tensor/sequence axes stay inside a slice on ICI. Workers shard input data by
process index exactly like the reference's `dist_num_worker`/`PS_RANK`
scheme (src/io/iter_thread_imbin-inl.hpp:189-211) — see
`worker_shard_params`.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence, Tuple

import numpy as np
import jax
from jax.sharding import Mesh


def virtual_cpu_env(n_devices: int, base_env=None) -> dict:
    """Environment for a subprocess that should see an n-device virtual CPU
    backend (the sandbox stand-in for a real multi-chip slice; see
    tests/conftest.py). Starts from ``base_env`` (default: os.environ),
    forces JAX_PLATFORMS=cpu, and replaces any existing
    ``xla_force_host_platform_device_count`` flag while preserving other
    XLA_FLAGS. Must be applied before the child imports jax."""
    env = dict(os.environ if base_env is None else base_env)
    env["JAX_PLATFORMS"] = "cpu"
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if "xla_force_host_platform_device_count" not in f]
    flags.append("--xla_force_host_platform_device_count=%d" % n_devices)
    env["XLA_FLAGS"] = " ".join(flags)
    return env


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None) -> None:
    """Join the multi-host cluster. No-op for single-process runs; args
    default from the standard env (JAX_COORDINATOR_ADDRESS etc. or TPU
    metadata)."""
    if num_processes is None:
        num_processes = int(os.environ.get("CXXNET_NUM_WORKER", "0")) or None
    if process_id is None:
        pid = os.environ.get("CXXNET_WORKER_RANK", os.environ.get("PS_RANK"))
        process_id = int(pid) if pid is not None else None
    if num_processes in (None, 0, 1) and coordinator_address is None:
        return
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)


def create_hybrid_mesh(ici_shape: Sequence[int],
                       dcn_shape: Sequence[int],
                       axes: Tuple[str, ...]) -> Mesh:
    """Mesh whose leading factors split across DCN (slices) and trailing
    across ICI, so collectives on ICI axes never cross slice boundaries.

    Example: 2 slices x 8 chips, axes=("data","model"):
        create_hybrid_mesh(ici_shape=(1, 8), dcn_shape=(2, 1), axes)
    puts 'data' over DCN and 'model' over in-slice ICI.

    On platforms whose devices carry no ``slice_index`` (CPU multi-process
    runs — the sandbox's DCN stand-in) the process is the DCN granule.
    This is the mesh the Trainer builds automatically for multi-process
    jobs, so ``model_parallel``/``seq_parallel`` collectives stay inside a
    process while the data axis crosses hosts.
    """
    from jax.experimental import mesh_utils
    import numpy as np
    n_granules = int(np.prod(tuple(dcn_shape)))
    slices = {getattr(d, "slice_index", None) for d in jax.devices()}
    # TPU slices are the natural DCN granule; when the platform reports
    # no (or too few) slices — CPU multi-process runs report one slice —
    # the process is the granule
    kw = {} if None not in slices and len(slices) == n_granules \
        else {"process_is_granule": True}
    devices = mesh_utils.create_hybrid_device_mesh(
        mesh_shape=tuple(ici_shape), dcn_mesh_shape=tuple(dcn_shape), **kw)
    return Mesh(devices, axes)


def worker_shard_params() -> Tuple[int, int]:
    """(num_workers, rank) for input sharding — the reference's
    dist_num_worker / dist_worker_rank derived from the process topology."""
    return jax.process_count(), jax.process_index()


def fetch_global(x) -> "np.ndarray":
    """Host numpy value of a possibly process-spanning jax.Array.

    In multi-process training, arrays sharded over the global mesh (ZeRO
    optimizer shards, TP weights, eval outputs) span non-addressable
    devices; a plain device_get raises. Fully-replicated or local arrays
    fetch directly; anything else is allgathered to every host first.

    COLLECTIVE CONTRACT: the allgather path is a cross-process collective —
    in multi-process runs EVERY process must call fetch_global on the same
    array in the same order. Guarding a call site by rank (e.g.
    ``if process_index() == 0: save_model(...)``) deadlocks the cluster.
    The same contract therefore applies to every API that uses it:
    Trainer.save_model / evaluate / predict / extract_feature / get_weight
    and NeuralNet.save_model_blob."""
    import numpy as np
    if isinstance(x, jax.Array) and not x.is_fully_addressable \
            and not x.sharding.is_fully_replicated:
        from jax.experimental import multihost_utils
        return np.asarray(multihost_utils.process_allgather(x, tiled=True))
    return np.asarray(jax.device_get(x))
