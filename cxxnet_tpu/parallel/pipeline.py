"""Pipeline parallelism: GPipe-style microbatch pipelining over a ``pipe``
mesh axis.

Green-field for the TPU build (the reference has no model partitioning at
all, SURVEY.md §2.9 "Not present"). The design is the standard TPU
collective-permute pipeline: stage s lives on device s of the ``pipe`` axis;
activations hop one ICI neighbor per tick via ppermute; a scan over
n_micro + n_stages - 1 ticks drains the bubble. The whole schedule is one
jitted program, so XLA overlaps the hop with the next microbatch's compute.

Constraint (documented, checked): stage boundaries must share one activation
shape — stages are "equal-width", e.g. repeated blocks of a deep MLP/resnet
trunk. That is the shape-uniformity XLA needs to trace one stage body for
all devices.
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import collectives
from ._compat import shard_map


def stage_sharding(mesh: Mesh, axis: str = "pipe") -> NamedSharding:
    """Sharding for stacked per-stage params: leading dim = stage index."""
    return NamedSharding(mesh, P(axis))


def _pipeline_local(params, x, *, axis_name: str, n_micro: int,
                    stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray]):
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    # local slice of the stacked stage params: leading dim 1 -> this stage
    params = jax.tree.map(lambda p: p[0], params)
    outbuf = jnp.zeros_like(x)
    cur = jnp.zeros_like(x[0])
    # forward hop: stage s -> s+1 (no wraparound; device 0 ingests fresh
    # microbatches, so its incoming edge is unused)
    perm = [(i, i + 1) for i in range(n - 1)]

    def tick(carry, t):
        cur, outbuf = carry
        x_t = lax.dynamic_index_in_dim(x, jnp.clip(t, 0, n_micro - 1),
                                       axis=0, keepdims=False)
        inp = jnp.where(idx == 0, x_t, cur)
        y = stage_fn(params, inp)
        done_t = t - (n - 1)
        pos = jnp.clip(done_t, 0, n_micro - 1)
        valid = (done_t >= 0) & (idx == n - 1)
        slot = lax.dynamic_index_in_dim(outbuf, pos, axis=0, keepdims=False)
        outbuf = lax.dynamic_update_index_in_dim(
            outbuf, jnp.where(valid, y, slot), pos, axis=0)
        cur = collectives.ppermute(y, axis_name, perm)
        return (cur, outbuf), None

    (_, outbuf), _ = lax.scan(tick, (cur, outbuf),
                              jnp.arange(n_micro + n - 1))
    # only the last stage wrote real outputs; psum broadcasts them (the other
    # shards are zeros)
    return collectives.psum(outbuf, axis_name)


def pipeline_apply(stage_fn, stacked_params, x, mesh: Mesh, *,
                   axis: str = "pipe"):
    """Run microbatches through a pipeline of stages.

    stage_fn(params_s, act) -> act     one stage's forward
    stacked_params: pytree whose leaves have leading dim n_stages (sharded
                    or shardable on ``axis``)
    x: (n_micro, microbatch, ...) input microbatches

    Returns (n_micro, microbatch, ...) outputs, replicated. Differentiable —
    the backward pipeline runs as the transposed scan with reversed hops.
    """
    n_stages = mesh.shape[axis]
    for leaf in jax.tree.leaves(stacked_params):
        if leaf.shape[0] != n_stages:
            raise ValueError(
                "pipeline_apply: stacked params leading dim %d != %d stages "
                "on mesh axis %r" % (leaf.shape[0], n_stages, axis))
    n_micro = x.shape[0]
    fn = shard_map(
        functools.partial(_pipeline_local, axis_name=axis, n_micro=n_micro,
                          stage_fn=stage_fn),
        mesh=mesh,
        in_specs=(P(axis), P()),
        out_specs=P())
    return fn(stacked_params, x)
