"""Pipeline parallelism: GPipe-style microbatch pipelining over a ``pipe``
mesh axis.

Green-field for the TPU build (the reference has no model partitioning at
all, SURVEY.md §2.9 "Not present"). The design is the standard TPU
collective-permute pipeline: stage s lives on device s of the ``pipe`` axis;
activations hop one ICI neighbor per tick via ppermute; a scan over
n_micro + n_stages - 1 ticks drains the bubble. The whole schedule is one
jitted program, so XLA overlaps the hop with the next microbatch's compute.

Constraint (documented, checked): stage boundaries must share one activation
shape — stages are "equal-width", e.g. repeated blocks of a deep MLP/resnet
trunk. That is the shape-uniformity XLA needs to trace one stage body for
all devices.
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import collectives
from ._compat import axis_size, shard_map


def stage_sharding(mesh: Mesh, axis: str = "pipe") -> NamedSharding:
    """Sharding for stacked per-stage params: leading dim = stage index."""
    return NamedSharding(mesh, P(axis))


def _pipeline_local(params, x, *, axis_name: str, n_micro: int,
                    stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray]):
    n = axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    # local slice of the stacked stage params: leading dim 1 -> this stage
    params = jax.tree.map(lambda p: p[0], params)
    cur = jnp.zeros_like(x[0])
    # forward hop: stage s -> s+1 (no wraparound; device 0 ingests fresh
    # microbatches, so its incoming edge is unused)
    perm = [(i, i + 1) for i in range(n - 1)]

    # finished microbatches leave as scan OUTPUTS, not an in-carry buffer
    # (the carry is AD-stashed per tick — see _pipeline_local_switch)
    def tick(cur, t):
        x_t = lax.dynamic_index_in_dim(x, jnp.clip(t, 0, n_micro - 1),
                                       axis=0, keepdims=False)
        inp = jnp.where(idx == 0, x_t, cur)
        y = stage_fn(params, inp)
        done = (t - (n - 1) >= 0) & (idx == n - 1)
        y_out = jnp.where(done, y, 0.0)
        cur = collectives.ppermute(y, axis_name, perm)
        return cur, y_out

    _, ys = lax.scan(tick, cur, jnp.arange(n_micro + n - 1))
    # ticks n-1 .. n-1+n_micro hold microbatches 0..n_micro in order on
    # the last stage (zeros elsewhere); psum broadcasts them
    return collectives.psum(ys[n - 1: n - 1 + n_micro], axis_name)


def _pipeline_local_switch(params, x, state0=None, *, axis_name: str,
                           n_micro: int, stage_fns, state_masks=None,
                           data_axis=None):
    """Like _pipeline_local, but heterogeneous stages: every device traces
    all stage bodies once and lax.switch selects its own by pipeline rank.
    All bodies map a (micro_batch, F) padded boundary vector to another —
    F = widest stage boundary — so the ppermute hop and the scan carry stay
    shape-uniform even when the underlying activations are not.

    With ``state0`` (an (S,) vector of non-gradient layer state, e.g. BN
    running stats), stage bodies take and return the state vector too:
    each device chains its OWN stage's slots across its microbatches (EMA
    order matches single-device sequential batches) and the final vector
    combines the per-stage slots via ``state_masks`` (a (n_stages, S)
    ownership mask) with a psum over the pipe axis; ``data_axis`` names a
    composed data axis to pmean per-shard statistics over."""
    n = axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    cur = jnp.zeros_like(x[0])
    perm = [(i, i + 1) for i in range(n - 1)]
    with_state = state0 is not None

    # Finished microbatches leave the scan as per-tick OUTPUTS (ys), not
    # as an in-carry output buffer: anything riding the carry is stashed
    # by AD at EVERY tick (an O(n_micro^2 * F) activation bill measured
    # in TestPipelineMemoryProof); a scan output is written once.
    def tick(carry, t):
        cur, st = carry
        x_t = lax.dynamic_index_in_dim(x, jnp.clip(t, 0, n_micro - 1),
                                       axis=0, keepdims=False)
        inp = jnp.where(idx == 0, x_t, cur)
        # stage `idx` works on microbatch t - idx at tick t (clipped while
        # the bubble fills/drains; those results are masked out anyway)
        micro_id = jnp.clip(t - idx, 0, n_micro - 1)
        if with_state:
            y, st_new = lax.switch(idx, stage_fns, params, inp, micro_id,
                                   st)
            # only commit state from real microbatches: bubble ticks run on
            # zeros and drain ticks would re-run (and re-EMA) the last one
            real = (t - idx >= 0) & (t - idx < n_micro)
            st = jnp.where(real, st_new, st)
        else:
            y = lax.switch(idx, stage_fns, params, inp, micro_id)
        done = (t - (n - 1) >= 0) & (idx == n - 1)
        y_out = jnp.where(done, y, 0.0)
        cur = collectives.ppermute(y, axis_name, perm)
        return (cur, st), y_out

    st0 = state0 if with_state else jnp.zeros((0,), x.dtype)
    (_, st), ys = lax.scan(tick, (cur, st0),
                           jnp.arange(n_micro + n - 1))
    # ticks n-1 .. n-1+n_micro hold microbatches 0..n_micro in order on
    # the last stage (zeros elsewhere); psum broadcasts them
    out = collectives.psum(ys[n - 1: n - 1 + n_micro], axis_name)
    if not with_state:
        return out
    own = lax.dynamic_index_in_dim(state_masks, idx, axis=0,
                                   keepdims=False)
    st = collectives.psum(jnp.where(own, st, 0.0), axis_name)
    if data_axis is not None:
        st = collectives.pmean(st, data_axis)
    return out, st


def pipeline_apply_stages(stage_fns, params, x, mesh: Mesh, *,
                          axis: str = "pipe", batch_spec=None,
                          params_spec=None, state0=None, state_masks=None):
    """Heterogeneous-stage GPipe over the mesh's ``axis``.

    stage_fns: one callable per stage, each
               (params, padded, micro_id) -> padded where padded is
               (micro_batch, F) — the stage slices its real input out of
               the padded vector and re-pads its output. micro_id is the
               traced index of the microbatch being processed (for
               per-microbatch rng folds in stochastic layers)
    params:    pytree passed to every stage. By default replicated over
               ``axis`` (each body indexes only its own layers' entries);
               with ``params_spec`` (e.g. P(axis, None) for a stage-packed
               (n_stages, F_p) array) it is SHARDED over the pipe axis and
               each body receives only its own rank's shard — per-device
               parameter ownership with zero parameter comm
    x:         (n_micro, micro_batch, F) padded input microbatches
    batch_spec: optional mesh axis name to keep the micro_batch dim sharded
               on (data parallelism composed with the pipeline)

    Returns (n_micro, micro_batch, F), replicated over ``axis``.
    With ``state0`` + ``state_masks`` (non-gradient layer state, e.g. BN
    running stats — see _pipeline_local_switch) the stage bodies take and
    return the (S,) state vector as a fourth argument and the call
    returns ``(out, state)`` instead.
    Differentiable; the backward pipeline is the transposed scan with
    reversed hops. This is the config-DSL pipeline path (trainer key
    ``pipeline_parallel``); `pipeline_apply` remains the fast path for
    uniform repeated-block stacks.
    """
    n_stages = mesh.shape[axis]
    if len(stage_fns) != n_stages:
        raise ValueError(
            "pipeline_apply_stages: %d stage fns for %d-way mesh axis %r"
            % (len(stage_fns), n_stages, axis))
    n_micro = x.shape[0]
    bspec = P(None, batch_spec, None) if batch_spec else P()
    pspec = params_spec if params_spec is not None else P()
    # Every mesh axis is MANUAL here, including a composed ``model`` axis:
    # stage bodies do tensor parallelism with explicit group-local
    # collectives (fullc all-gathers its column-parallel outputs over model
    # pairs at its own pipe rank). Leaving model automatic instead is a
    # DEADLOCK: Shardy would insert 8-participant resharding collectives
    # inside the rank-divergent lax.switch branches, and devices at other
    # pipe ranks never arrive at them. Manual model collectives lower with
    # replica groups that never span pipe ranks, so divergence is safe.
    if state0 is None:
        fn = shard_map(
            functools.partial(_pipeline_local_switch, axis_name=axis,
                              n_micro=n_micro, stage_fns=tuple(stage_fns)),
            mesh=mesh, in_specs=(pspec, bspec), out_specs=bspec)
        return fn(params, x)
    fn = shard_map(
        functools.partial(_pipeline_local_switch, axis_name=axis,
                          n_micro=n_micro, stage_fns=tuple(stage_fns),
                          state_masks=state_masks, data_axis=batch_spec),
        mesh=mesh, in_specs=(pspec, bspec, P()),
        out_specs=(bspec, P()))
    return fn(params, x, state0)


def pipeline_apply(stage_fn, stacked_params, x, mesh: Mesh, *,
                   axis: str = "pipe"):
    """Run microbatches through a pipeline of stages.

    stage_fn(params_s, act) -> act     one stage's forward
    stacked_params: pytree whose leaves have leading dim n_stages (sharded
                    or shardable on ``axis``)
    x: (n_micro, microbatch, ...) input microbatches

    Returns (n_micro, microbatch, ...) outputs, replicated. Differentiable —
    the backward pipeline runs as the transposed scan with reversed hops.
    """
    n_stages = mesh.shape[axis]
    for leaf in jax.tree.leaves(stacked_params):
        if leaf.shape[0] != n_stages:
            raise ValueError(
                "pipeline_apply: stacked params leading dim %d != %d stages "
                "on mesh axis %r" % (leaf.shape[0], n_stages, axis))
    n_micro = x.shape[0]
    fn = shard_map(
        functools.partial(_pipeline_local, axis_name=axis, n_micro=n_micro,
                          stage_fn=stage_fn),
        mesh=mesh,
        in_specs=(P(axis), P()),
        out_specs=P())
    return fn(stacked_params, x)
