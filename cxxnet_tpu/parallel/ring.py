"""Sequence / context parallelism: ring attention and Ulysses all-to-all.

The reference framework is a 2014 CNN trainer with no sequence axis
(SURVEY.md §5 "Long-context: ABSENT"), so this module is green-field
TPU-first design: long sequences are sharded over a mesh ``sp`` axis and
attention runs either as

* **ring attention** — K/V blocks rotate around the ICI ring via ppermute
  while each device keeps its local Q block and accumulates the softmax
  online (numerically stable log-sum-exp carry). Comm per step is one
  neighbor hop, fully overlappable with the block matmul; memory is
  O(seq/n_devices) per device, enabling sequences that don't fit one chip.
* **Ulysses** — one all-to-all swaps sequence sharding for head sharding,
  attention runs dense locally, and a second all-to-all swaps back. Cheaper
  at moderate sequence lengths when heads >= devices.

Everything is expressed with shard_map + lax collectives so XLA schedules
the ICI transfers; the scan over ring steps is reverse-differentiable
(ppermute has a transpose rule), so the same code serves training.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import collectives
from ._compat import axis_size, shard_map


def attention_reference(q, k, v, *, causal: bool = False,
                        scale: Optional[float] = None, window: int = 0,
                        q_offset=0):
    """Plain single-device attention, the golden model for the parallel
    variants. q: (batch, heads, seq, head_dim); k/v may carry FEWER heads
    (grouped-query attention): nkv must divide nh and each group of
    nh/nkv query heads attends to one shared k/v head — no materialized
    broadcast. window > 0 (requires causal) keeps only the last ``window``
    keys per query — sliding-window attention (Mistral-style local
    attention). ``q_offset`` (static or traced) is the global position of
    q's first row when q is a chunk of a longer sequence (the in-pipeline
    sequence-parallel path computes each sp rank's query chunk against
    the full k/v)."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    assert window == 0 or causal, "window attention requires causal"
    b, nh, sq, d = q.shape
    nkv = k.shape[1]
    assert nh % nkv == 0, "query heads must be a multiple of kv heads"
    g = nh // nkv
    qg = q.reshape(b, nkv, g, sq, d)
    s = jnp.einsum("bngqd,bnkd->bngqk", qg, k) * scale
    if causal:
        skv = k.shape[2]
        qpos = q_offset + jnp.arange(sq)[:, None]
        kpos = jnp.arange(skv)[None, :]
        keep = qpos >= kpos
        if window > 0:
            keep = jnp.logical_and(keep, qpos - kpos < window)
        s = jnp.where(keep, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bngqk,bnkd->bngqd", p, v).reshape(b, nh, sq, d)


def decode_attention_chunked(q, k, v, *, pos, scale: Optional[float] = None,
                             window: int = 0, chunk: int = 256):
    """Single-position cache attention that reads only the LIVE prefix.

    Equals ``attention_reference(q, k, v, causal=True, q_offset=pos)``
    for a one-row query at global position ``pos`` (traced), but instead
    of scoring against the full static-length cache it runs an online-
    softmax ``lax.while_loop`` over ``chunk``-row cache blocks
    [c_lo, pos // chunk] — a flash-decode step in plain XLA. The dense
    path reads L_max rows per generated token regardless of position
    (static shapes), which the r5 decode trace showed is ~2x the useful
    traffic on average (doc/performance.md, decode roofline); here the
    loop bound is data-dependent, which XLA's while supports. With
    ``window > 0`` the loop also starts at the first chunk inside the
    window (the dense path merely masks those reads). Accumulation is
    float32 (better than the dense path's activation-dtype softmax).

    q: (b, nh, 1, d); k/v: (b, nkv, L_max, d) caches, GQA-sized.
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    b, nh, sq, d = q.shape
    assert sq == 1, "decode_attention_chunked is a single-position step"
    nkv, l_max = k.shape[1], k.shape[2]
    assert nh % nkv == 0, "query heads must be a multiple of kv heads"
    assert l_max % chunk == 0, \
        "cache length %d must be divisible by decode_chunk %d" \
        % (l_max, chunk)
    g = nh // nkv
    qg = q.reshape(b, nkv, g, d).astype(jnp.float32)
    pos = jnp.asarray(pos, jnp.int32)
    c_hi = pos // chunk                       # last live chunk, inclusive
    if window > 0:
        c_lo = jnp.maximum(0, (pos - (window - 1)) // chunk)
    else:
        c_lo = jnp.int32(0)

    def body(carry):
        c, m, l, acc = carry
        kc = lax.dynamic_slice(k, (0, 0, c * chunk, 0),
                               (b, nkv, chunk, d)).astype(jnp.float32)
        vc = lax.dynamic_slice(v, (0, 0, c * chunk, 0),
                               (b, nkv, chunk, d)).astype(jnp.float32)
        s = jnp.einsum("bngd,bnkd->bngk", qg, kc) * scale
        kpos = c * chunk + jnp.arange(chunk)[None, None, None, :]
        keep = kpos <= pos
        if window > 0:
            keep = jnp.logical_and(keep, pos - kpos < window)
        s = jnp.where(keep, s, -jnp.inf)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        # exp(-inf - -inf) would be nan on the first all-masked chunk;
        # m_new is finite whenever any key is live, and c_lo..c_hi always
        # contains live keys, so guard only the carry rescale
        alpha = jnp.where(jnp.isfinite(m), jnp.exp(m - m_new), 0.0)
        p = jnp.exp(s - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha[..., None] \
            + jnp.einsum("bngk,bnkd->bngd", p, vc)[:, :, :, None, :]
        return c + 1, m_new, l_new, acc_new

    m0 = jnp.full((b, nkv, g, 1), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, nkv, g, 1), jnp.float32)
    acc0 = jnp.zeros((b, nkv, g, 1, d), jnp.float32)
    _, _, l, acc = lax.while_loop(
        lambda carry: carry[0] <= c_hi, body, (c_lo, m0, l0, acc0))
    out = acc[:, :, :, 0, :] / l
    return out.reshape(b, nh, 1, d).astype(q.dtype)


# per-step score tiles are capped at (RING_Q_CHUNK, skv): the local block
# computation runs as a sequential lax.map over query chunks, so memory per
# device stays O(chunk * skv) instead of O((L/n)^2) — the single-chip flash
# kernel's tiling idea applied inside the ring step
RING_Q_CHUNK = 1024


def _ring_attention_local(q, k, v, *, axis_name: str, causal: bool,
                          scale: float, q_chunk: int = 0, window: int = 0):
    """Per-shard body: online-softmax over rotating K/V blocks.

    q: (b, h, sq, d) local query block; k, v: (b, nkv, skv, d) local
    key/value blocks — nkv may be smaller than h (grouped-query attention):
    the ring then rotates the nkv-sized blocks (GQA's bandwidth saving
    applies to the ICI hops) and each step broadcasts to the query heads
    only transiently for the tile compute. Runs axis_size steps; at step t
    the device holds the K/V block originally on device (idx - t) mod n.
    """
    n = axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    b, h, sq, d = q.shape
    skv = k.shape[2]
    kv_groups = h // k.shape[1]
    q_off = idx * sq
    q_chunk = min(sq, q_chunk if q_chunk > 0 else RING_Q_CHUNK)
    while sq % q_chunk != 0:     # largest divisor <= requested chunk
        q_chunk -= 1
    n_chunks = sq // q_chunk

    def chunked(arr):
        # (b, h, sq, ...) -> (n_chunks, b, h, q_chunk, ...): lax.map's
        # leading axis, so one (q_chunk, skv) score tile is live at a time
        return arr.reshape(arr.shape[:2] + (n_chunks, q_chunk) +
                           arr.shape[3:]).transpose(
                               (2, 0, 1, 3) + tuple(
                                   4 + i for i in range(arr.ndim - 3)))

    # q and the (m, l, acc) carry live in chunked layout for the whole
    # scan — the transposes happen once outside, not per ring step
    q_ch = chunked(q)                                    # (nc, b, h, qc, d)
    m0 = jnp.full((n_chunks, b, h, q_chunk), -jnp.inf, q.dtype)
    l0 = jnp.zeros((n_chunks, b, h, q_chunk), q.dtype)
    acc0 = jnp.zeros((n_chunks, b, h, q_chunk, d), q.dtype)

    def step(carry, t):
        k_blk, v_blk, m, l, acc = carry
        src = (idx - t) % n  # whose block we hold this step
        kpos = src * skv + jnp.arange(skv)[None, :]
        # GQA: expand kv heads to the query heads for this step's tiles
        # only — the scan carry (and the ring hop below) stay nkv-sized
        k_cmp = k_blk if kv_groups == 1 else \
            jnp.repeat(k_blk, kv_groups, axis=1)
        v_cmp = v_blk if kv_groups == 1 else \
            jnp.repeat(v_blk, kv_groups, axis=1)

        def one_chunk(args):
            ci, q_c, m_c, l_c, acc_c = args

            def compute(_):
                s = jnp.einsum("bhqd,bhkd->bhqk", q_c, k_cmp) * scale
                if causal:
                    qpos = (q_off + ci * q_chunk +
                            jnp.arange(q_chunk)[:, None])
                    keep = qpos >= kpos
                    if window > 0:
                        keep = jnp.logical_and(keep, qpos - kpos < window)
                    s_ = jnp.where(keep, s, -jnp.inf)
                else:
                    s_ = s
                m_new = jnp.maximum(m_c, jnp.max(s_, axis=-1))
                # guard fully-masked rows (all -inf): exp(-inf - -inf)
                alpha = jnp.where(jnp.isinf(m_c) & jnp.isinf(m_new),
                                  jnp.zeros_like(m_c),
                                  jnp.exp(m_c - m_new))
                p = jnp.exp(s_ - m_new[..., None])
                p = jnp.where(jnp.isinf(s_) & (s_ < 0),
                              jnp.zeros_like(p), p)
                l_new = l_c * alpha + jnp.sum(p, axis=-1)
                acc_new = acc_c * alpha[..., None] + \
                    jnp.einsum("bhqk,bhkd->bhqd", p, v_cmp)
                return m_new, l_new, acc_new

            if not causal:
                return compute(None)
            # skip the whole chunk x block tile when it is entirely above
            # the causal diagonal or entirely older than the window — the
            # chunk map is a sequential lax.map, so cond executes one
            # branch (roughly halving causal ring compute)
            q_start = q_off + ci * q_chunk
            k_start = src * skv
            need = k_start <= q_start + (q_chunk - 1)
            if window > 0:
                need = jnp.logical_and(
                    need, q_start - (k_start + skv - 1) < window)
            return lax.cond(need, compute,
                            lambda _: (m_c, l_c, acc_c), None)

        # remat: without it AD would save every chunk's (qc, skv) p tile,
        # re-materializing the O(sq*skv) residual the chunking removes —
        # the backward pass recomputes s/p per chunk instead
        m, l, acc = lax.map(jax.checkpoint(one_chunk),
                            (jnp.arange(n_chunks), q_ch, m, l, acc))
        # rotate K/V to the next device on the ring (skippable on the last
        # step, but keeping it unconditional keeps the scan body uniform)
        k_blk = collectives.ring_shift(k_blk, axis_name)
        v_blk = collectives.ring_shift(v_blk, axis_name)
        return (k_blk, v_blk, m, l, acc), None

    (_, _, _, l, acc), _ = lax.scan(step, (k, v, m0, l0, acc0),
                                    jnp.arange(n))
    # back to (b, h, sq, d), normalized
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return out.transpose(1, 2, 0, 3, 4).reshape(b, h, sq, d)


# ---------------------------------------------------------------------------
# flash-kernel ring step (opt-in: CXXNET_RING=flash) — ops/ring_flash.py
# runs each ring step's online-softmax update fully in VMEM; backward is a
# second ring pass (dq accumulates locally, dk/dv travel with their block)
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _ring_flash_local(q, k, v, axis_name, causal, scale, interpret,
                      window=0):
    out, _ = _ring_flash_fwd(q, k, v, axis_name, causal, scale, interpret,
                             window)
    return out


def _ring_flash_fwd(q, k, v, axis_name, causal, scale, interpret,
                    window=0):
    from ..ops import ring_flash as rf
    n = axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    b, h, sq, d = q.shape
    skv = k.shape[2]
    nkv = k.shape[1]
    g = h // nkv
    bh = b * h
    qf = q.reshape(bh, sq, d)
    kf, vf = (t.reshape(b * nkv, skv, d) for t in (k, v))

    def expand(blk):
        # GQA: broadcast the nkv kv heads to the query heads for the
        # kernel call only — the ring hop stays nkv-sized
        if g == 1:
            return blk
        return jnp.repeat(blk.reshape(b, nkv, skv, d), g,
                          axis=1).reshape(bh, skv, d)

    from ..ops.flash_attn import NEG_INF
    m0 = jnp.full((bh, sq, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((bh, sq, 1), jnp.float32)
    acc0 = jnp.zeros((bh, sq, d), jnp.float32)

    def step(carry, t):
        k_blk, v_blk, m, l, acc = carry
        src = (idx - t) % n
        offs = jnp.stack([idx * sq, src * skv]).astype(jnp.int32)
        m, l, acc = rf.fwd_step(qf, expand(k_blk), expand(v_blk), m, l,
                                acc, offs, causal=causal, scale=scale,
                                interpret=interpret, window=window)
        k_blk = collectives.ring_shift(k_blk, axis_name)
        v_blk = collectives.ring_shift(v_blk, axis_name)
        return (k_blk, v_blk, m, l, acc), None

    (_, _, m, l, acc), _ = lax.scan(step, (kf, vf, m0, l0, acc0),
                                    jnp.arange(n))
    l_safe = jnp.maximum(l, 1e-30)
    out = (acc / l_safe).astype(q.dtype).reshape(b, h, sq, d)
    lse = m + jnp.log(l_safe)                                # (bh, sq, 1)
    return out, (q, k, v, out, lse)


def _ring_flash_bwd(axis_name, causal, scale, interpret, window, res, g):
    from ..ops import ring_flash as rf
    q, k, v, out, lse = res
    n = axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    b, h, sq, d = q.shape
    skv = k.shape[2]
    nkv = k.shape[1]
    groups = h // nkv
    bh = b * h
    qf = q.reshape(bh, sq, d)
    kf, vf = (t.reshape(b * nkv, skv, d) for t in (k, v))

    def expand(blk):
        if groups == 1:
            return blk
        return jnp.repeat(blk.reshape(b, nkv, skv, d), groups,
                          axis=1).reshape(bh, skv, d)

    def group_sum(full):
        # (b*h, skv, d) query-head-resolution grads -> kv-head resolution
        return full.reshape(b, nkv, groups, skv, d).sum(axis=2).reshape(
            b * nkv, skv, d)

    dof = g.reshape(bh, sq, d)
    of = out.reshape(bh, sq, d)
    delta = jnp.sum(dof.astype(jnp.float32) * of.astype(jnp.float32),
                    axis=-1, keepdims=True)                  # (bh, sq, 1)
    dq0 = jnp.zeros((bh, sq, d), jnp.float32)
    dkv0 = jnp.zeros((b * nkv, skv, d), jnp.float32)

    def step(carry, t):
        k_blk, v_blk, dk_blk, dv_blk, dq = carry
        src = (idx - t) % n
        offs = jnp.stack([idx * sq, src * skv]).astype(jnp.int32)
        k_full, v_full = expand(k_blk), expand(v_blk)
        dq = rf.dq_step(qf, k_full, v_full, dof, lse, delta, dq, offs,
                        causal=causal, scale=scale, interpret=interpret,
                        window=window)
        if groups == 1:
            dk_blk, dv_blk = rf.dkv_step(
                qf, k_full, v_full, dof, lse, delta, dk_blk, dv_blk, offs,
                causal=causal, scale=scale, interpret=interpret,
                window=window)
        else:
            # GQA: the kernel produces query-head-resolution kv grads;
            # group-sum them into the nkv-sized accumulators that ride
            # the ring
            zero = jnp.zeros((bh, skv, d), jnp.float32)
            dkf, dvf = rf.dkv_step(
                qf, k_full, v_full, dof, lse, delta, zero, zero, offs,
                causal=causal, scale=scale, interpret=interpret,
                window=window)
            dk_blk = dk_blk + group_sum(dkf)
            dv_blk = dv_blk + group_sum(dvf)
        # rotate the K/V block together with its gradient accumulators:
        # after n shifts each block is home with every device's
        # contribution summed in
        k_blk = collectives.ring_shift(k_blk, axis_name)
        v_blk = collectives.ring_shift(v_blk, axis_name)
        dk_blk = collectives.ring_shift(dk_blk, axis_name)
        dv_blk = collectives.ring_shift(dv_blk, axis_name)
        return (k_blk, v_blk, dk_blk, dv_blk, dq), None

    (_, _, dk, dv, dq), _ = lax.scan(
        step, (kf, vf, dkv0, dkv0, dq0), jnp.arange(n))
    shape_q = (b, h, sq, d)
    shape_kv = (b, nkv, skv, d)
    return (dq.astype(q.dtype).reshape(shape_q),
            dk.astype(k.dtype).reshape(shape_kv),
            dv.astype(v.dtype).reshape(shape_kv))


_ring_flash_local.defvjp(_ring_flash_fwd, _ring_flash_bwd)


def _ring_flash_enabled(sq: int, skv: int, d: int) -> bool:
    """Default ON wherever the kernels run (validated on-chip by
    tools/check_tpu_kernels.py); CXXNET_RING=dense is the opt-out.
    CXXNET_RING=flash still forces the kernel path off-TPU (Pallas
    interpreter — how the CPU tests execute the exact kernel code)."""
    import os
    mode = os.environ.get("CXXNET_RING", "")
    if mode in ("dense", "off", "0", "xla"):
        return False
    from .. import ops as _ops
    if getattr(_ops, "_use_pallas", None) is False:
        return False   # explicit global kill-switch always wins
    if not _ops.use_pallas() and mode != "flash":
        # auto mode follows the global Pallas dispatch (TPU backend, or
        # tests forcing set_use_pallas(True))
        return False
    from ..ops import ring_flash as rf
    return rf.supports(sq, skv, d)


def ring_attention(q, k, v, mesh: Mesh, *, axis_name: str = "sp",
                   causal: bool = False, scale: Optional[float] = None,
                   batch_axis: Optional[str] = None, q_chunk: int = 0,
                   window: int = 0):
    """Ring attention over sequence-sharded q, k, v: (b, h, seq, d) with seq
    sharded on ``axis_name``. Returns output with the same sharding.
    ``batch_axis`` names a mesh axis to shard the batch dim over (pass the
    trainer's "data" axis on a (data, sp) mesh — a None batch spec would
    replicate the global batch on every chip). ``q_chunk`` caps the live
    score tile per ring step (default RING_Q_CHUNK)."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    spec = P(batch_axis, None, axis_name, None)
    n = mesh.shape[axis_name]
    sq = q.shape[2] // n
    if _ring_flash_enabled(sq, k.shape[2] // n, q.shape[-1]):
        interpret = jax.default_backend() != "tpu"
        fn = shard_map(
            lambda q_, k_, v_: _ring_flash_local(
                q_, k_, v_, axis_name, causal, scale, interpret, window),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
        return fn(q, k, v)
    fn = shard_map(
        functools.partial(_ring_attention_local, axis_name=axis_name,
                          causal=causal, scale=scale, q_chunk=q_chunk,
                          window=window),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return fn(q, k, v)


def _ulysses_local(q, k, v, *, axis_name: str, causal: bool, scale: float,
                   window: int = 0):
    n = axis_size(axis_name)

    def seq_to_heads(x):
        # (b, h, s/n, d) -> (b, h/n, s, d): split heads, gather sequence
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    def heads_to_seq(x):
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    # after the all-to-all each device holds h/n full-length heads — the
    # single-chip flash kernel applies as-is, keeping the local attention
    # O(L) in memory instead of materializing the (L, L) score matrix.
    # GQA: the all-to-alls above moved nkv-sized k/v; both the flash
    # kernel (grouped BlockSpec row map) and the dense reference consume
    # grouped k/v natively.
    from .. import ops
    if ops.use_pallas() and ops.flash_supported(qh.shape[2], qh.shape[3]):
        out = ops.flash_attention(qh, kh, vh, causal=causal, scale=scale,
                                  window=window)
    else:
        out = attention_reference(qh, kh, vh, causal=causal, scale=scale,
                                  window=window)
    return heads_to_seq(out)


def ulysses_attention(q, k, v, mesh: Mesh, *, axis_name: str = "sp",
                      causal: bool = False, scale: Optional[float] = None,
                      batch_axis: Optional[str] = None, window: int = 0):
    """Ulysses sequence parallelism: all-to-all seq->heads, dense local
    attention, all-to-all back. Requires heads % axis_size == 0.
    ``batch_axis`` as in ring_attention."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    n = mesh.shape[axis_name]
    if q.shape[1] % n != 0:
        raise ValueError("ulysses needs heads (%d) divisible by sp axis (%d)"
                         % (q.shape[1], n))
    if k.shape[1] % n != 0:
        raise ValueError("ulysses needs kv heads (%d) divisible by sp axis "
                         "(%d); broadcast k/v to the query heads first"
                         % (k.shape[1], n))
    spec = P(batch_axis, None, axis_name, None)
    fn = shard_map(
        functools.partial(_ulysses_local, axis_name=axis_name,
                          causal=causal, scale=scale, window=window),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return fn(q, k, v)
