"""shard_map compatibility: jax >= 0.8 moved it to jax.shard_map and renamed
check_rep -> check_vma. Collective-heavy bodies (ring scans, pipelines) mix
axis-varying and invariant carries, so the replication/vma check is disabled
either way.

Also carries a narrow jax-0.9 workaround: differentiating lax.switch whose
branches sample PRNG noise asymmetrically (a dropout stage next to a
dropout-free stage in the GPipe pipeline) pads the missing typed-key
residual with ``zeros_like_aval``, which returns float0 for key avals and
trips the cond partial-eval typematch invariant
(jax/_src/lax/control_flow/conditionals.py:619). We teach zeros_like_aval
to produce a zero KEY instead — the padded residual is dead in the branches
that receive it, so any well-typed placeholder is correct. The patch is
applied lazily (first pipelined forward), not at import, so processes that
never differentiate a pipeline keep stock jax behavior."""

from __future__ import annotations

import inspect


def _patch_key_zeros() -> None:
    try:
        import jax
        import jax.numpy as jnp
        from jax._src import ad_util

        if getattr(ad_util, "_cxxnet_key_zeros_patch", False):
            return
        orig = ad_util.zeros_like_aval

        def zeros_like_aval(aval):
            dt = getattr(aval, "dtype", None)
            if dt is not None and jax.dtypes.issubdtype(
                    dt, jax.dtypes.prng_key):
                impl = dt._impl
                kd = jnp.zeros(tuple(aval.shape) + tuple(impl.key_shape),
                               jnp.uint32)
                return jax.random.wrap_key_data(kd, impl=impl.name)
            return orig(aval)

        ad_util.zeros_like_aval = zeros_like_aval
        ad_util._cxxnet_key_zeros_patch = True
    except Exception:   # pragma: no cover - future jax may not need/fit it
        pass

try:
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map as _shard_map

_params = inspect.signature(_shard_map).parameters
if "check_vma" in _params:
    _CHECK_KW = "check_vma"
elif "check_rep" in _params:
    _CHECK_KW = "check_rep"
else:  # pragma: no cover
    _CHECK_KW = None


def shard_map(f, mesh, in_specs, out_specs):
    kwargs = {_CHECK_KW: False} if _CHECK_KW else {}
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      **kwargs)


def axis_size(axis_name):
    """Named-axis size inside a shard_map/pmap body. ``lax.axis_size``
    only exists in newer jax; older versions use the psum-of-1 idiom,
    which the tracer statically evaluates to a concrete python int (so
    ring step counts / perm tables built from it stay static)."""
    from jax import lax
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    return lax.psum(1, axis_name)
