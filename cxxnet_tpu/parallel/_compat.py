"""shard_map compatibility: jax >= 0.8 moved it to jax.shard_map and renamed
check_rep -> check_vma. Collective-heavy bodies (ring scans, pipelines) mix
axis-varying and invariant carries, so the replication/vma check is disabled
either way."""

from __future__ import annotations

import inspect

try:
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map as _shard_map

_params = inspect.signature(_shard_map).parameters
if "check_vma" in _params:
    _CHECK_KW = "check_vma"
elif "check_rep" in _params:
    _CHECK_KW = "check_rep"
else:  # pragma: no cover
    _CHECK_KW = None


def shard_map(f, mesh, in_specs, out_specs):
    kwargs = {_CHECK_KW: False} if _CHECK_KW else {}
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      **kwargs)
