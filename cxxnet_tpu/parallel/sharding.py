"""Sharding rules: batch sharding, replication, and ZeRO-style optimizer
state sharding (the reference's ``update_on_server`` equivalent).

The reference runs the optimizer on parameter-server processes with the
weights partitioned by key (src/nnet/nnet_ps_server.cpp); the TPU-native
analogue is weight-update sharding: optimizer state (and the update compute)
is sharded across the data axis, with XLA emitting reduce-scatter +
all-gather instead of all-reduce (see PAPERS.md "Automatic Cross-Replica
Sharding of Weight Update in Data-Parallel Training").
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def batch_sharding(mesh: Mesh, axis: str = "data") -> NamedSharding:
    """Shard the leading (batch) dim across the data axis."""
    return NamedSharding(mesh, P(axis))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def _extend_base_split(mesh: Mesh, shape, base_spec: P, axis: str):
    """Compose a data-axis split with an existing tensor/expert-parallel
    placement: extend the split ON THE SAME dim, tp-axis major, so each
    device's shard nests inside its own TP slice (no cross-shard reshard
    per step). Works for dim-0 TP (fullc wmat) and later-dim TP (conv
    output channels). The pipeline's P("pipe", None) packed base keeps its
    base_spec: dim 0 equals the pipe-axis size, so the joint split never
    divides. Shared by zero_sharding (opt state) and fsdp_shardings
    (params) — ONE composition rule, so the two can never drift apart."""
    n = mesh.shape[axis]
    d = next(i for i, a in enumerate(base_spec) if a is not None)
    tp_axis = base_spec[d]
    if shape[d] % (n * mesh.shape[tp_axis]) == 0:
        spec = list(base_spec)
        spec[d] = (tp_axis, axis)
        return NamedSharding(mesh, P(*spec))
    return NamedSharding(mesh, base_spec)


def zero_sharding(mesh: Mesh, x: Any, axis: str = "data",
                  base_spec: P = None) -> NamedSharding:
    """Sharding for one optimizer-state tensor: split the first dim across
    the data axis when divisible, else replicate.

    base_spec carries an existing tensor-parallel placement (e.g.
    P('model', None) for a TP fullc weight): the ZeRO split composes with it
    — dim 0 sharded over ('data', 'model') jointly when divisible — instead
    of overriding it, which would force an all-to-all reshard every step."""
    n = mesh.shape[axis]
    shape = getattr(x, "shape", ())
    if (base_spec and any(a is not None for a in base_spec)
            and len(shape) == len(base_spec)):
        return _extend_base_split(mesh, shape, base_spec, axis)
    if len(shape) > 0:
        # no TP placement: the tensor is replicated over EVERY mesh axis,
        # so its optimizer state may shard over all of them jointly (each
        # device owns 1/total of the update) — greedily extend the data
        # axis with every other axis that keeps dim 0 divisible
        joint, prod = [], 1
        for a in (axis,) + tuple(x for x in mesh.axis_names if x != axis):
            sz = mesh.shape[a]
            if sz > 1 and shape[0] % (prod * sz) == 0:
                joint.append(a)
                prod *= sz
        if prod > 1 and shape[0] >= prod:
            # canonical spec form: a single axis is the plain string —
            # P(("data",)) and P("data") mean the same placement but
            # stopped comparing equal in newer jax PartitionSpec
            return NamedSharding(
                mesh, P(joint[0] if len(joint) == 1 else tuple(joint)))
    return NamedSharding(mesh, P())


def shard_opt_state_with_specs(mesh: Mesh, opt_state, base_shardings,
                               axis: str = "data"):
    """ZeRO constraint for the trainer's per-layer opt-state structure
    (list of {weight key: state pytree}), composing with the TP placements
    in base_shardings (same structure as params, or None)."""
    out = []
    for i, layer_state in enumerate(opt_state):
        d = {}
        for key, st in layer_state.items():
            base = None
            if base_shardings is not None:
                nsh = base_shardings[i].get(key)
                base = nsh.spec if nsh is not None else None

            def constrain(x, base=base):
                return jax.lax.with_sharding_constraint(
                    x, zero_sharding(mesh, x, axis, base_spec=base))

            d[key] = jax.tree.map(constrain, st)
        out.append(d)
    return out


def fsdp_shardings(mesh: Mesh, layers, params, base_shardings=None,
                   axis: str = "data"):
    """Fully-sharded data parallelism (trainer key ``fsdp``): the params
    THEMSELVES are sharded over the data axis — GSPMD all-gathers each
    weight just-in-time for its op and reduce-scatters its gradient, so
    per-device param+grad+opt memory scales 1/dp (ZeRO-3; the logical
    end point of the reference's bigarray handling,
    src/updater/async_updater-inl.hpp:165-174, which kept big tensors
    server-side and pulled them on demand).

    Per tensor: split the first dim divisible by the data-axis size,
    composing with an existing tensor/expert-parallel placement on the
    same dim (tp-major, like zero_sharding). Skipped: 1-D tensors
    (biases/norm scales — sharding saves nothing and complicates their
    broadcasts) and non-trainable state (BN running stats; direct
    assignment in the step stays trivially replicated)."""
    n = mesh.shape[axis]
    out = []
    for i, (lay, p) in enumerate(zip(layers, params)):
        shard = {}
        state = set(lay.state_keys()) if hasattr(lay, "state_keys") else ()
        for key, val in p.items():
            base = None
            if base_shardings is not None and key in base_shardings[i]:
                base = base_shardings[i][key].spec
            shape = getattr(val, "shape", ())
            if key in state or len(shape) < 2 or n <= 1:
                shard[key] = NamedSharding(mesh, base or P())
                continue
            if base is not None and any(a is not None for a in base):
                shard[key] = _extend_base_split(mesh, shape, base, axis)
                continue
            for d in range(len(shape)):
                if shape[d] % n == 0 and shape[d] >= n:
                    spec = [None] * len(shape)
                    spec[d] = axis
                    shard[key] = NamedSharding(mesh, P(*spec))
                    break
            else:
                shard[key] = NamedSharding(mesh, P())
        out.append(shard)
    return out


def param_shardings(mesh: Mesh, layers, params):
    """Per-layer weight shardings for tensor/expert parallelism, driven by
    which axes the mesh carries (so the strategies compose on one mesh):

    * ``model`` axis (``model_parallel`` config key) — Megatron-style
      splits, generalizing the reference's in-layer model sharding
      (``ngroup`` grouped conv, src/layer/convolution_layer-inl.hpp:92-96;
      ``fullc_gather``, src/updater/async_updater-inl.hpp:67-92):
        - fullc wmat (out, in): split the output dim (column parallel)
        - conv wmat (g, co/g, ci_khkw): split the output-channel dim —
          output-feature-sharded convolution
      Attention projections stay replicated: the fused [q|k|v] column
      layout cannot align a contiguous model-axis split with the q/k/v
      block boundaries (GSPMD would re-shard the activation every step);
      head-level attention parallelism is the sp axis's job (Ulysses
      all-to-all shards heads exactly).
      XLA/GSPMD propagates activation shardings and inserts collectives.
    * ``ep`` axis (``expert_parallel``): the moe layer's expert stack is
      split on the expert dim, matching expert_parallel_ffn's shard_map
      specs.

    Everything else (biases, norms, embeddings) is replicated."""
    out = []
    for lay, p in zip(layers, params):
        shard = {}
        for key, val in p.items():
            shape = getattr(val, "shape", ())
            shard[key] = NamedSharding(mesh, tp_spec(lay, key, shape, mesh))
        out.append(shard)
    return out


def tp_spec(lay, key, shape, mesh: Mesh) -> P:
    """The tensor/expert-parallel PartitionSpec for one weight tensor.
    Drives the GSPMD placements of the NON-pipelined path; pipelined stage
    bodies instead do manual TP (layers read ctx.manual_tp and slice +
    all-gather themselves — see parallel/pipeline.py on why GSPMD
    placements cannot reach inside the stage shard_map)."""
    tname = getattr(lay, "type_name", "")
    if "model" in mesh.axis_names:
        n_model = mesh.shape["model"]
        if (tname == "fullc" and key == "wmat"
                and len(shape) == 2 and shape[0] % n_model == 0):
            return P("model", None)
        if (tname == "conv" and key == "wmat"
                and len(shape) == 3 and shape[1] % n_model == 0):
            return P(None, "model", None)
    if (tname == "moe" and key == "experts" and "ep" in mesh.axis_names
            and len(shape) >= 1 and shape[0] % mesh.shape["ep"] == 0):
        return P("ep", None, None)
    return P()


