"""Sharding rules: batch sharding, replication, and ZeRO-style optimizer
state sharding (the reference's ``update_on_server`` equivalent).

The reference runs the optimizer on parameter-server processes with the
weights partitioned by key (src/nnet/nnet_ps_server.cpp); the TPU-native
analogue is weight-update sharding: optimizer state (and the update compute)
is sharded across the data axis, with XLA emitting reduce-scatter +
all-gather instead of all-reduce (see PAPERS.md "Automatic Cross-Replica
Sharding of Weight Update in Data-Parallel Training").
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def batch_sharding(mesh: Mesh, axis: str = "data") -> NamedSharding:
    """Shard the leading (batch) dim across the data axis."""
    return NamedSharding(mesh, P(axis))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def zero_sharding(mesh: Mesh, x: Any, axis: str = "data",
                  base_spec: P = None) -> NamedSharding:
    """Sharding for one optimizer-state tensor: split the first dim across
    the data axis when divisible, else replicate.

    base_spec carries an existing tensor-parallel placement (e.g.
    P('model', None) for a TP fullc weight): the ZeRO split composes with it
    — dim 0 sharded over ('data', 'model') jointly when divisible — instead
    of overriding it, which would force an all-to-all reshard every step."""
    n = mesh.shape[axis]
    shape = getattr(x, "shape", ())
    if (base_spec and len(base_spec) > 0 and base_spec[0] is not None
            and len(shape) == len(base_spec)
            and shape[0] % mesh.shape[base_spec[0]] == 0):
        tp_axis = base_spec[0]
        joint = n * mesh.shape[tp_axis]
        if shape[0] % joint == 0:
            # tp axis major: each device's opt-state shard nests inside its
            # own param shard, so no cross-model-shard reshard per step
            return NamedSharding(mesh, P((tp_axis, axis), *base_spec[1:]))
        return NamedSharding(mesh, base_spec)
    if len(shape) > 0 and shape[0] % n == 0 and shape[0] >= n:
        return NamedSharding(mesh, P(axis))
    return NamedSharding(mesh, P())


def shard_opt_state_with_specs(mesh: Mesh, opt_state, base_shardings,
                               axis: str = "data"):
    """ZeRO constraint for the trainer's per-layer opt-state structure
    (list of {weight key: state pytree}), composing with the TP placements
    in base_shardings (same structure as params, or None)."""
    out = []
    for i, layer_state in enumerate(opt_state):
        d = {}
        for key, st in layer_state.items():
            base = None
            if base_shardings is not None:
                nsh = base_shardings[i].get(key)
                base = nsh.spec if nsh is not None else None

            def constrain(x, base=base):
                return jax.lax.with_sharding_constraint(
                    x, zero_sharding(mesh, x, axis, base_spec=base))

            d[key] = jax.tree.map(constrain, st)
        out.append(d)
    return out


def param_shardings(mesh: Mesh, layers, params):
    """Per-layer weight shardings for tensor/expert parallelism, driven by
    which axes the mesh carries (so the strategies compose on one mesh):

    * ``model`` axis (``model_parallel`` config key): fullc weights split on
      the output dim — the TP generalization of the reference's
      ``fullc_gather`` giant-FC trick
      (src/updater/async_updater-inl.hpp:67-92); XLA/GSPMD propagates
      activation shardings and inserts the collectives.
    * ``ep`` axis (``expert_parallel``): the moe layer's expert stack is
      split on the expert dim, matching expert_parallel_ffn's shard_map
      specs.

    Everything else is replicated."""
    has_model = "model" in mesh.axis_names
    has_ep = "ep" in mesh.axis_names
    out = []
    for lay, p in zip(layers, params):
        shard = {}
        for key, val in p.items():
            shape = getattr(val, "shape", ())
            tname = getattr(lay, "type_name", "")
            if (has_model and tname == "fullc" and len(shape) >= 1
                    and shape[0] % mesh.shape["model"] == 0):
                spec = P("model", *([None] * (len(shape) - 1)))
            elif (has_ep and tname == "moe" and key == "experts"
                    and shape[0] % mesh.shape["ep"] == 0):
                spec = P("ep", None, None)
            else:
                spec = P()
            shard[key] = NamedSharding(mesh, spec)
        out.append(shard)
    return out


def shard_opt_state(mesh: Mesh, opt_state: Any, axis: str = "data") -> Any:
    """Apply ZeRO-style sharding constraints to an optimizer-state pytree
    inside jit (weight-update sharding)."""
    def constrain(x):
        return jax.lax.with_sharding_constraint(x, zero_sharding(mesh, x, axis))
    return jax.tree.map(constrain, opt_state)
