"""Sharding rules: batch sharding, replication, and ZeRO-style optimizer
state sharding (the reference's ``update_on_server`` equivalent).

The reference runs the optimizer on parameter-server processes with the
weights partitioned by key (src/nnet/nnet_ps_server.cpp); the TPU-native
analogue is weight-update sharding: optimizer state (and the update compute)
is sharded across the data axis, with XLA emitting reduce-scatter +
all-gather instead of all-reduce (see PAPERS.md "Automatic Cross-Replica
Sharding of Weight Update in Data-Parallel Training").
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def batch_sharding(mesh: Mesh, axis: str = "data") -> NamedSharding:
    """Shard the leading (batch) dim across the data axis."""
    return NamedSharding(mesh, P(axis))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def zero_sharding(mesh: Mesh, x: Any, axis: str = "data") -> NamedSharding:
    """Sharding for one optimizer-state tensor: split the first dim across
    the data axis when divisible, else replicate."""
    n = mesh.shape[axis]
    shape = getattr(x, "shape", ())
    if len(shape) > 0 and shape[0] % n == 0 and shape[0] >= n:
        return NamedSharding(mesh, P(axis))
    return NamedSharding(mesh, P())


def shard_opt_state(mesh: Mesh, opt_state: Any, axis: str = "data") -> Any:
    """Apply ZeRO-style sharding constraints to an optimizer-state pytree
    inside jit (weight-update sharding)."""
    def constrain(x):
        return jax.lax.with_sharding_constraint(x, zero_sharding(mesh, x, axis))
    return jax.tree.map(constrain, opt_state)
