"""Named-axis collectives: the TPU-native communication backend.

This is the replacement surface for mshadow-ps `ISharedModel` (SURVEY.md
§2.10): where the reference pushes/pulls per-tensor gradients through a
parameter server (src/updater/async_updater-inl.hpp:94-143), the TPU design
expresses the same dataflow as XLA collectives over mesh axes — all-reduce
over ICI inside a slice, DCN across slices — and lets the latency-hiding
scheduler overlap them with compute (the reference's per-tensor priority
scheme, src/updater/updater_impl-inl.hpp:84, done by the compiler instead).

These wrappers exist so higher layers (trainer, ring attention, pipeline)
speak one vocabulary; each is a direct jax.lax collective.

Telemetry: each wrapper bumps a ``collective.<op>`` counter when its
Python body runs — under jit that is TRACE time, so the counters report
how many collective ops each compiled program CONTAINS (per compile, not
per executed step). Runtime cost of the collectives lives in the XLA
profile (profile_dir); these counters are the cheap structural view that
says which programs carry ring traffic at all.
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

import jax
from jax import lax

from . import _compat
from ..utils import telemetry

AxisName = Union[str, Sequence[str]]


def psum(x, axis_name: AxisName):
    """All-reduce sum over a mesh axis (gradient sync; replaces PS Push+Pull
    of summed gradients, src/updater/async_updater-inl.hpp:101-131)."""
    telemetry.count("collective.psum")
    return lax.psum(x, axis_name)


def pmean(x, axis_name: AxisName):
    """All-reduce mean (metric aggregation across data shards)."""
    telemetry.count("collective.pmean")
    return lax.pmean(x, axis_name)


def all_gather(x, axis_name: AxisName, *, axis: int = 0, tiled: bool = True):
    """Gather shards along ``axis`` from every device on the mesh axis
    (replaces the `fullc_gather` activation allgather,
    src/updater/async_updater-inl.hpp:67-92)."""
    telemetry.count("collective.all_gather")
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name: AxisName, *, axis: int = 0):
    """Reduce-scatter: sum across the axis, each device keeps one shard
    (the ZeRO / update_on_server gradient path)."""
    telemetry.count("collective.reduce_scatter")
    return lax.psum_scatter(x, axis_name, scatter_dimension=axis, tiled=True)


def ppermute(x, axis_name: AxisName, perm):
    """Point-to-point permutation over ICI neighbors (ring steps)."""
    telemetry.count("collective.ppermute")
    return lax.ppermute(x, axis_name, perm)


def ring_shift(x, axis_name: str, shift: int = 1):
    """Rotate shards around the ring: device i's value goes to i+shift."""
    telemetry.count("collective.ring_shift")
    n = _compat.axis_size(axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm)


def all_to_all(x, axis_name: AxisName, *, split_axis: int, concat_axis: int):
    """All-to-all redistribution (Ulysses-style sequence<->head reshard)."""
    telemetry.count("collective.all_to_all")
    return lax.all_to_all(x, axis_name, split_axis=split_axis,
                          concat_axis=concat_axis, tiled=True)


def axis_index(axis_name: str):
    return lax.axis_index(axis_name)


def axis_size(axis_name: str):
    return _compat.axis_size(axis_name)
