"""cxxnet_tpu — a TPU-native neural-net training framework.

A ground-up reimplementation of the capabilities of cxxnet (the 2014 DMLC
C++/CUDA convnet trainer built on mshadow/mshadow-ps), redesigned for TPU:

* compute path: jax / XLA / Pallas — layers are pure functions assembled into
  one jitted train step (replaces mshadow expression templates + CUDA kernels,
  reference: /root/reference/src/layer, src/nnet/neural_net-inl.hpp)
* parallelism: jax.sharding.Mesh + sharding annotations; gradient sync is an
  XLA all-reduce over ICI (replaces mshadow-ps push/pull parameter server,
  reference: src/nnet/nnet_impl-inl.hpp, src/updater/async_updater-inl.hpp)
* user surface: config-file DSL, iterator chains, trainer tasks
  (train/finetune/pred/extract), checkpoint/finetune semantics and the
  Python `DataIter`/`Net`/`train` API are kept compatible with the reference
  (reference: src/cxxnet_main.cpp, wrapper/cxxnet.py).
"""

__version__ = "0.1.0"

from . import utils  # noqa: F401


def __getattr__(name):
    # lazy: api pulls in jax/io; keep bare `import cxxnet_tpu` light
    if name == "api":
        import importlib
        return importlib.import_module(".api", __name__)
    raise AttributeError(name)
