"""Optimizers (updaters) with reference-compatible semantics and schedules.

TPU-native counterpart of src/updater/: one Updater per weight tensor
(created per layer via the visitor walk, src/updater/updater_impl-inl.hpp:49),
but expressed as pure functions folded into the jitted train step — the
reference's per-tensor AsyncUpdater push/pull overlap
(src/updater/async_updater-inl.hpp) is subsumed by XLA's latency-hiding
scheduler once gradients+updates live in one compiled program.
"""

from .param import UpdaterParam  # noqa: F401
from .updaters import Updater, create_updater, encode_data_key, decode_tag  # noqa: F401
