"""Updater hyper-parameters and learning-rate / momentum schedules.

Mirrors src/updater/param.h:13-136, including:
* tag-scoped overrides — ``wmat:lr = 0.01`` applies only to updaters whose
  tag is ``wmat`` (param.h:100-104)
* schedules (param.h:76-95): constant / expdecay / polydecay / factor
* clamp of momentum to final_momentum and of lr to lr_minimum (reference
  behavior — with one deliberate fix: the floor never RAISES lr above the
  requested eta, so fine-tuning LRs below the 1e-5 default minimum are
  honored instead of silently clamped up)

schedule_epoch() is jit-safe: ``epoch`` may be a traced jnp scalar, so one
compiled train step serves every epoch without recompilation.
"""

from __future__ import annotations

import jax.numpy as jnp


class UpdaterParam:
    def __init__(self, tag: str = ""):
        self.tag = tag
        self.silent = 0
        self.base_lr = 0.01
        self.wd = 0.0
        self.momentum = 0.9
        self.lr_schedule = 0
        self.momentum_schedule = 0
        self.lr_step = 1
        self.lr_gamma = 0.5
        self.lr_alpha = 0.5
        self.lr_factor = 0.1
        self.lr_minimum = 0.00001
        self.start_epoch = 0
        self.base_momentum = 0.5
        self.final_momentum = 0.90
        self.saturation_epoch = 0
        self.clip_gradient = 0.0
        self.lr_warmup = 0      # linear warmup steps (0 -> none)
        self.lr_total = 0       # cosine horizon in updates (required)

    def set_param(self, name: str, val: str) -> None:
        # tag-scoped override: "wmat:lr" applies when tag == "wmat"
        if self.tag and name.startswith(self.tag):
            if len(name) > len(self.tag) and name[len(self.tag)] == ":":
                name = name[len(self.tag) + 1:]
        if name in ("lr", "eta"):
            self.base_lr = float(val)
        if name == "wd":
            self.wd = float(val)
        if name == "momentum":
            self.momentum = float(val)
        if name == "silent":
            self.silent = int(val)
        if name == "momentum_schedule":
            self.momentum_schedule = int(val)
        if name == "clip_gradient":
            self.clip_gradient = float(val)
        if name == "final_momentum":
            self.final_momentum = float(val)
        if name == "base_momentum":
            self.base_momentum = float(val)
        if name == "saturation_epoch":
            self.saturation_epoch = int(val)
        if name.startswith("lr:") or name.startswith("eta:"):
            sub = name.split(":", 1)[1]
            if sub == "schedule":
                self.lr_schedule = {"constant": 0, "expdecay": 1,
                                    "polydecay": 2, "factor": 3,
                                    "cosine": 4}.get(val, self.lr_schedule)
            if sub == "gamma":
                self.lr_gamma = float(val)
            if sub == "alpha":
                self.lr_alpha = float(val)
            if sub == "step":
                self.lr_step = int(val)
            if sub == "factor":
                self.lr_factor = float(val)
            if sub == "minimum_lr":
                self.lr_minimum = float(val)
            if sub == "start_epoch":
                self.start_epoch = int(val)
            if sub == "warmup":
                self.lr_warmup = int(val)
            if sub == "total":
                self.lr_total = int(val)

    def schedule_epoch(self, epoch):
        """Return (learning_rate, momentum) at `epoch` updates
        (param.h ScheduleEpoch; epoch counts optimizer updates, not rounds).
        jit-safe in `epoch`."""
        e = jnp.asarray(epoch, jnp.float32)
        if self.lr_schedule == 0:
            lr = jnp.asarray(self.base_lr, jnp.float32)
        elif self.lr_schedule == 1:
            lr = self.base_lr * jnp.power(self.lr_gamma, e / self.lr_step)
        elif self.lr_schedule == 2:
            lr = self.base_lr * jnp.power(
                1.0 + jnp.floor(e / self.lr_step) * self.lr_gamma, -self.lr_alpha)
        elif self.lr_schedule == 3:
            lr = self.base_lr * jnp.power(self.lr_factor, jnp.floor(e / self.lr_step))
        elif self.lr_schedule == 4:
            # cosine decay to lr_minimum over lr:total updates (beyond the
            # reference's schedule set; the transformer-era default)
            if self.lr_total <= 0:
                raise ValueError(
                    "lr_schedule = 4 (cosine) requires lr:total > 0 — "
                    "without it the schedule would collapse to "
                    "minimum_lr after the first update")
            frac = jnp.clip(e / self.lr_total, 0.0, 1.0)
            lr = self.lr_minimum + 0.5 * (self.base_lr - self.lr_minimum) \
                * (1.0 + jnp.cos(jnp.pi * frac))
        else:
            raise ValueError("unknown schedule type")
        momentum = jnp.asarray(self.momentum, jnp.float32)
        if self.momentum_schedule and self.saturation_epoch:
            # intended linear warmup toward final_momentum (the reference's
            # stateful accumulation saturates to the same fixed point)
            momentum = self.base_momentum + \
                (self.final_momentum - self.base_momentum) / self.saturation_epoch * e
        momentum = jnp.minimum(momentum, self.final_momentum)
        # floor at lr_minimum, but never above the requested base lr (a
        # base_lr below the 1e-5 default minimum must be honored exactly —
        # fine-tuning at eta = 3e-6 would otherwise silently run 1e-5)
        lr = jnp.maximum(lr, min(self.lr_minimum, self.base_lr))
        lr = jnp.where(e < self.start_epoch, self.base_lr, lr)
        if self.lr_warmup > 0:
            # linear ramp 0 -> scheduled lr over the first lr:warmup updates
            lr = lr * jnp.clip((e + 1.0) / self.lr_warmup, 0.0, 1.0)
        return lr, momentum
