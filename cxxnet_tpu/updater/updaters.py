"""SGD / NAG / Adam updaters as pure state-transition functions.

Numerics match the reference exactly:
* sgd  — momentum SGD with weight decay and clip-with-NaN-zeroing
         (src/updater/sgd_updater-inl.hpp:73-88, clip struct :15-22)
* nag  — Nesterov momentum (src/updater/nag_updater-inl.hpp:66-74)
* adam — the reference's formulation with bias correction folded into lr_t
         and wd *subtracted* from the gradient (src/updater/adam_updater-inl.hpp:77-87
         — note the reference's sign on wd; reproduced as-is)

Each Updater owns one weight tensor's hyper-params (tag-scoped schedules) and
exposes init_state / apply, both jit-safe. The optimizer state pytree can be
sharded across the data mesh axis for a ZeRO-style ``update_on_server``
equivalent (see cxxnet_tpu.parallel).
"""

from __future__ import annotations

from typing import Dict

import numpy as np
import jax.numpy as jnp

from .param import UpdaterParam

kDataKeyStep = 4


def encode_data_key(layer_index: int, tag: str) -> int:
    """PS key scheme: key = layer_index*4 + {0: wmat, 1: bias}
    (src/updater/updater.h:150-163)."""
    if tag == "bias":
        return layer_index * kDataKeyStep + 1
    if tag == "wmat":
        return layer_index * kDataKeyStep + 0
    raise ValueError("EncodeDataKey: only support weight tag: wmat or bias")


def decode_tag(key: int) -> str:
    r = key % kDataKeyStep
    if r == 0:
        return "wmat"
    if r == 1:
        return "bias"
    raise ValueError("invalid key")


def _clip_nan(g, bound):
    """Gradient clip that also zeroes NaNs (reference struct clip).

    The zeroing cannot count host-side from in here (it runs inside the
    jitted step), so visibility comes from the trainer's health scalars:
    with ``health_monitor=1`` the step counts NaN gradient elements on
    device (nnet/trainer.py ``_make_train_step``) and the host monitor
    accumulates them into the ``health/nan_grads_zeroed`` telemetry
    counter (utils/health.py) — the corruption this clip used to mask
    silently now shows up in the run log."""
    g = jnp.where(jnp.isnan(g), 0.0, g)
    return jnp.clip(g, -bound, bound)


class Updater:
    kind = "none"
    # The packed pipeline update (trainer._pp_pack) applies one group
    # member's apply() to the whole (k, F_p) stage array and selects per
    # element by group id. That is only correct when apply() is purely
    # elementwise (no per-tensor reductions). sgd/nag/adam/adamw are; an
    # updater with a norm-based trust ratio or global clip must set this
    # False, which makes _pp_pack refuse the pipeline_parallel config
    # (a per-tensor fallback is not implemented).
    elementwise = True

    def __init__(self, tag: str):
        self.param = UpdaterParam(tag)

    def set_param(self, name: str, val: str) -> None:
        self.param.set_param(name, val)
        # tag-scoped override for subclass keys too: "wmat:beta1" reaches
        # AdamUpdater/AdamWUpdater as "beta1" (UpdaterParam strips the
        # prefix only for its own fields)
        tag = self.param.tag
        if tag and name.startswith(tag + ":"):
            name = name[len(tag) + 1:]
        self._set_extra(name, val)

    def _set_extra(self, name: str, val: str) -> None:
        """Subclass hook for optimizer-specific keys (tag prefix already
        stripped)."""

    def init_state(self, w: np.ndarray) -> Dict[str, np.ndarray]:
        return {}

    def apply(self, w, g, state, epoch):
        """Return (new_w, new_state). All jnp, jit-safe; `epoch` counts
        optimizer updates (the reference's epoch_counter)."""
        raise NotImplementedError


class SGDUpdater(Updater):
    kind = "sgd"

    def init_state(self, w):
        return {"m": np.zeros_like(w, dtype=np.float32)}

    def apply(self, w, g, state, epoch):
        p = self.param
        lr, momentum = p.schedule_epoch(epoch)
        if p.clip_gradient != 0.0:
            g = _clip_nan(g, p.clip_gradient)
        m = state["m"] * momentum + (-lr) * (g + p.wd * w)
        return w + m, {"m": m}


class NAGUpdater(Updater):
    kind = "nag"

    def init_state(self, w):
        return {"m": np.zeros_like(w, dtype=np.float32)}

    def apply(self, w, g, state, epoch):
        p = self.param
        lr, momentum = p.schedule_epoch(epoch)
        old_m = state["m"]
        m = old_m * momentum + (-lr) * (g + p.wd * w)
        w = w + (1 + momentum) * m - momentum * old_m
        return w, {"m": m}


class AdamUpdater(Updater):
    kind = "adam"

    def __init__(self, tag: str):
        super().__init__(tag)
        self.decay1 = 0.1
        self.decay2 = 0.001

    def _set_extra(self, name, val):
        if name == "beta1":
            self.decay1 = float(val)
        if name == "beta2":
            self.decay2 = float(val)

    def init_state(self, w):
        return {"m1": np.zeros_like(w, dtype=np.float32),
                "m2": np.zeros_like(w, dtype=np.float32)}

    def apply(self, w, g, state, epoch):
        p = self.param
        if p.wd > 0.0:
            g = g - p.wd * w  # reference sign, adam_updater-inl.hpp:79
        e = jnp.asarray(epoch, jnp.float32)
        fix1 = 1.0 - jnp.power(1.0 - self.decay1, e + 1)
        fix2 = 1.0 - jnp.power(1.0 - self.decay2, e + 1)
        lr_t = p.base_lr * jnp.sqrt(fix2) / fix1
        m1 = state["m1"] + self.decay1 * (g - state["m1"])
        m2 = state["m2"] + self.decay2 * (jnp.square(g) - state["m2"])
        w = w - lr_t * (m1 / (jnp.sqrt(m2) + 1e-8))
        return w, {"m1": m1, "m2": m2}


class AdamWUpdater(Updater):
    """AdamW (beyond the reference): decoupled weight decay — wd scales the
    weight directly instead of entering the moment estimates (Loshchilov &
    Hutter 2019) — with the standard beta convention (beta1/beta2 are the
    RETENTION rates, defaults 0.9/0.999) and the scheduled lr, so cosine /
    warmup / tag-scoped overrides compose. The transformer-LM recipe's
    optimizer; ``updater = adam`` stays the reference formulation."""

    kind = "adamw"

    def __init__(self, tag: str):
        super().__init__(tag)
        self.beta1 = 0.9
        self.beta2 = 0.999
        self.eps = 1e-8

    def _set_extra(self, name, val):
        if name == "beta1":
            self.beta1 = float(val)
        if name == "beta2":
            self.beta2 = float(val)
        if name == "adam_eps":
            self.eps = float(val)

    def init_state(self, w):
        return {"m1": np.zeros_like(w, dtype=np.float32),
                "m2": np.zeros_like(w, dtype=np.float32)}

    def apply(self, w, g, state, epoch):
        p = self.param
        lr, _ = p.schedule_epoch(epoch)
        if p.clip_gradient != 0.0:
            g = _clip_nan(g, p.clip_gradient)
        e = jnp.asarray(epoch, jnp.float32)
        m1 = self.beta1 * state["m1"] + (1.0 - self.beta1) * g
        m2 = self.beta2 * state["m2"] + (1.0 - self.beta2) * jnp.square(g)
        mhat = m1 / (1.0 - jnp.power(self.beta1, e + 1))
        vhat = m2 / (1.0 - jnp.power(self.beta2, e + 1))
        w = w - lr * (mhat / (jnp.sqrt(vhat) + self.eps) + p.wd * w)
        return w, {"m1": m1, "m2": m2}


_KINDS = {"sgd": SGDUpdater, "nag": NAGUpdater, "adam": AdamUpdater,
          "adamw": AdamWUpdater}


def create_updater(kind: str, tag: str) -> Updater:
    """Factory (reference CreateUpdater_, src/updater/updater_impl-inl.hpp:18-30)."""
    if kind not in _KINDS:
        raise ValueError("unknown updater type %s" % kind)
    return _KINDS[kind](tag)
