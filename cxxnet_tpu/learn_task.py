"""The learn task driver: train / finetune / pred / extract from a config file.

Reimplements CXXNetLearnTask (src/cxxnet_main.cpp:16-478) — same config keys,
task loop, checkpoint naming (models/%04d.model with a leading net_type int),
``continue=1`` auto-resume scan, pred/extract output formats — driving the
TPU trainer instead of GPU worker threads.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import List, Optional, Tuple

import numpy as np

from .io import create_iterator
from .nnet.trainer import Trainer, create_net
from .utils import checkpoint as ckpt
from .utils import health
from .utils import perf
from .utils import serializer
from .utils import statusd
from .utils import telemetry
from .utils.config import ConfigIterator


class _SeededSession:
    """Maps the dispatcher's per-request dispatch ordinal onto the conf
    sampling seed — ``seed = gen_seed + seq``, exactly what the solo
    backend passes to ``generate``; the per-slot RNG therefore keys on
    the request's dispatch ordinal, never on batch composition, and
    batched streams are token-exact vs solo dispatch."""

    def __init__(self, inner, seed0: int):
        self._inner = inner
        self._seed0 = int(seed0)
        self.nslots = inner.nslots

    @property
    def closed(self):
        return self._inner.closed

    def prefill(self, slot, toks, seq):
        return self._inner.prefill(slot, toks, self._seed0 + int(seq))

    def step(self):
        return self._inner.step()

    def retire(self, slot):
        self._inner.retire(slot)

    def free_slots(self):
        return self._inner.free_slots()

    def kv_account(self):
        # the live KV/HBM occupancy account rides through untouched —
        # servd's per-bucket account and /batchz read the REAL session
        # geometry (cache nbytes, live token extents)
        return self._inner.kv_account()

    def close(self):
        self._inner.close()


class _SlotBackendAdapter:
    """Continuous-batching slot backend over ``Trainer.decode_session``
    — what servd's batching dispatcher drives when ``serve_buckets`` is
    set (doc/serving.md "Continuous batching"). Reads the trainer
    THROUGH the task so a hot reload's swapped-in trainer serves the
    next session (the dispatcher closes every session before a
    reload — slot caches hold the old model's K/V)."""

    def __init__(self, task, buckets, kv_block: int = 0,
                 kv_pool_frac: float = 0.5, prefix_reuse: bool = True,
                 retained_frac: float = 1.0):
        self.task = task
        self.buckets = list(buckets)
        # serve_kv_block > 0 arms the PAGED decode KV cache
        # (doc/performance.md "Decode KV cache"): every session this
        # adapter opens shares one trainer-wide block pool, sized at
        # dense-equivalent capacity (largest bucket x l_max rows) and
        # clamped under serve_kv_pool_frac of the perf ledger's live
        # HBM headroom when the ledger is on
        self.kv_block = int(kv_block)
        self.kv_pool_frac = float(kv_pool_frac)
        self.prefix_reuse = bool(prefix_reuse)
        # serve_retained_frac: retired conversations stay trie-resident
        # (evictable, refcount 0) up to this fraction of the pool — the
        # multi-turn warm-cache (doc/robustness.md "Memory governance")
        self.retained_frac = float(retained_frac)

    def admits(self, toks):
        t = self.task
        l_max = t.net_trainer.net_cfg.param.input_shape[2]
        if len(toks) + t.gen_new > l_max:
            return ("prompt len %d + gen_new %d exceeds the net's "
                    "sequence length %d" % (len(toks), t.gen_new, l_max))
        return None

    def _pool(self):
        """The shared paged pool (created on first use, re-created
        across a hot reload by ``decode_kv_pool``'s params-generation
        key). None in dense mode."""
        if self.kv_block <= 0:
            return None
        t = self.task
        l_max = t.net_trainer.net_cfg.param.input_shape[2]
        cap = perf.ledger().decode_pool_cap_bytes(self.kv_pool_frac) \
            if perf.enabled() else None
        return t.net_trainer.decode_kv_pool(
            self.kv_block,
            pool_tokens=max(self.buckets) * l_max,
            prefix_reuse=self.prefix_reuse, bytes_cap=cap,
            retained_frac=self.retained_frac)

    def _live_pool(self):
        """The pool if it EXISTS and is open — the account/gate hooks
        must never create one (they run per publish, even idle)."""
        if self.kv_block <= 0:
            return None
        p = getattr(self.task.net_trainer, "_kv_pool", None)
        return None if p is None or p.closed else p

    def kv_pool_account(self):
        """servd's block-exact pool account hook (None in dense mode
        or before the first paged session)."""
        p = self._live_pool()
        return p.account() if p is not None else None

    def kv_free_blocks(self):
        """Admissible headroom for servd's gather budget (None
        disarms). Free PLUS evictable-retained blocks — reporting the
        bare free list under retention would defer requests forever
        while reclaimable memory sits parked (the evict-before-defer
        livelock)."""
        p = self._live_pool()
        return p.alloc.available_blocks if p is not None else None

    def kv_shed_retained(self, target_free):
        """servd's pressure-latch shed hook: evict retained (LRU,
        deepest-suffix-first) until the free list reaches
        ``target_free``. Returns blocks recycled (0 in dense mode)."""
        p = self._live_pool()
        if p is None:
            return 0
        return p.alloc.evict_retained(target_free=target_free)

    def kv_fresh_blocks(self, toks):
        """Blocks an admission would pull off the free list right now
        (prefix-credited) — servd pops a queued request only when this
        fits the budget, so pool exhaustion is a deterministic FIFO
        queue-wait, never a device OOM."""
        p = self._live_pool()
        if p is None:
            return None
        return p.alloc.fresh_need(len(toks), self.task.gen_new, toks)

    def session(self, bucket):
        t = self.task
        return _SeededSession(
            t.net_trainer.decode_session(
                bucket, t.gen_new, temperature=t.gen_temperature,
                top_k=t.gen_topk, kv_pool=self._pool()),
            t.gen_seed)


class LearnTask:
    def __init__(self):
        self.task = "train"
        self.net_type = 0
        self.reset_net_type = -1
        self.net_trainer: Optional[Trainer] = None
        self.itr_train = None
        self.itr_pred = None
        self.itr_evals = []
        self.eval_names: List[str] = []
        self.name_model_dir = "models"
        self.num_round = 10
        self.test_io = 0
        # profile_dir=<dir>: capture a jax profiler (xprof) trace of the
        # second training round into <dir> (the first round compiles).
        # Replaces the reference's wall-clock-only observability
        # (SURVEY.md §5 tracing/profiling).
        self.profile_dir = ""
        # telemetry_log=<path>: structured JSONL run log (spans, counters,
        # compile events; utils/telemetry.py). A Chrome-trace export is
        # written next to it (<path>.trace.json) at end of run, and the
        # end-of-run summary table prints unless silent. Multihost runs
        # put a %d rank placeholder in the path (one shard per process;
        # merge with tools/telemetry_report.py --merge).
        self.telemetry_log = ""
        # status_port=<p>: live introspection HTTP service
        # (utils/statusd.py, doc/observability.md): /metrics (Prometheus),
        # /healthz (200/503 off the watchdog + recovery state), /statusz
        # (human page), /trace (Chrome-trace ring snapshot). Port 0 binds
        # an ephemeral port (printed); -1 (default) = off. Binds loopback
        # unless status_host widens it (0.0.0.0 lets a Prometheus server
        # on another host scrape — the endpoints are unauthenticated).
        self.status_port = -1
        self.status_host = ""
        self._status_telemetry = False
        # perf_ledger=1 (default): the live program performance ledger
        # (utils/perf.py) — every compiled program gets a cost/memory
        # card (XLA cost_analysis FLOPs, memory_analysis bytes, a
        # roofline-predicted time vs the measured latency histogram),
        # rendered at /programz, as cxxnet_program_*//cxxnet_hbm_*
        # metrics, and as program_card JSONL events. Armed only when
        # telemetry is on (telemetry_log or status_port); the memory
        # tier pays one background re-compile per new program — set
        # perf_ledger=0 to card nothing.
        self.perf_ledger = 1
        # profilez_dir=<dir>: where /profilez?secs=N on-demand profiler
        # captures land (one numbered subdir per capture). Default:
        # "profilez" next to the telemetry log (or ./profilez).
        self.profilez_dir = ""
        self._perf_enabled = False
        self.silent = 0
        self.start_counter = 0
        self.max_round = 1 << 31
        self.continue_training = 0
        self.save_period = 1
        # checkpoint robustness knobs (doc/robustness.md): retention
        # (ckpt_keep_last=N keeps the newest N numbered checkpoints,
        # ckpt_keep_every=K additionally keeps every K-th as a long-horizon
        # anchor; 0 = keep all, the reference behavior), IO retries with
        # exponential backoff for flaky NFS/GCS-fuse mounts, durable
        # fsync (ckpt_fsync=0 trades durability for test speed), and the
        # SIGTERM/SIGINT emergency-checkpoint handler (preempt_save=0
        # restores the default die-on-signal behavior)
        self.ckpt_keep_last = 0
        self.ckpt_keep_every = 0
        self.ckpt_retries = 2
        self.ckpt_fsync = 1
        self.preempt_save = 1
        # training-health watchdog + automatic recovery (utils/health.py,
        # doc/robustness.md): health_monitor=1 turns on per-step
        # non-finite/loss-spike detection; on anomaly the policy rolls
        # back to the newest valid checkpoint, replays with the offending
        # batch window quarantined (nonfinite_action=rollback), suppresses
        # the bad update on device (skip), or dies with a diagnostic dump
        # (abort / retries exhausted). watchdog_timeout>0 starts a thread
        # that dumps all-thread stacks when the step loop or the prefetch
        # pipeline goes silent.
        self.health_monitor = 0
        self.nonfinite_action = "rollback"
        self.loss_spike_factor = 0.0     # 0 = spike detection off
        self.loss_spike_warmup = 20
        self.rollback_backoff = 1.0      # LR scale per rollback (1 = off)
        self.rollback_max_retries = 2
        self.watchdog_timeout = 0.0      # seconds; 0 = watchdog off
        self.watchdog_action = "warn"
        self._health: Optional[health.HealthMonitor] = None
        self._recovery: Optional[health.RecoveryPolicy] = None
        self._start_counter_conf = False
        # resume cursor recovered from a checkpoint's training-state
        # section: applied right before the train loop (after the
        # continue-path eval, which must not consume the restored rng)
        self._resume_state = None
        self._resume_batches = 0
        self._preempt: Optional[ckpt.PreemptionGuard] = None
        self._preempt_noted = False
        self._stop_training = False
        self.name_model_in = "NULL"
        self.name_pred = "pred.txt"
        self.print_step = 100
        self.extract_node_name = ""
        self.name_export = "model.stablehlo"
        self.export_batch = 0
        self.name_prompt_in = "prompts.txt"
        self.name_gen_out = "gen.txt"
        # serving frontend (utils/servd.py, doc/serving.md): task = serve
        # always runs through it (bounded admission queue + shedding,
        # deadlines, backend supervision + circuit breaker, graceful
        # drain, ADMIN reload / SIGHUP hot model reload); serve_port >= 0
        # ADDITIONALLY serves the TCP line protocol (0 = ephemeral,
        # printed; loopback unless serve_host widens it)
        self.serve_port = -1
        self.serve_host = ""
        self.serve_queue = 64
        self.serve_deadline_ms = 0.0     # 0 = no default deadline
        self.serve_drain_ms = 5000.0
        self.serve_breaker_fails = 5
        self.serve_breaker_cooldown_ms = 1000.0
        self.serve_stall_s = 120.0       # wedged-backend probe bound
        # continuous batching (doc/serving.md "Continuous batching"):
        # serve_buckets = "1,2,4,8" arms the iteration-granularity
        # batching dispatcher over Trainer.decode_session — queued
        # compatible requests coalesce (up to serve_batch_max within a
        # serve_batch_window_ms gather window) into the smallest bucket
        # that fits, and a finished sequence frees its slot to the next
        # queued request MID-DECODE. Empty = one request per decode
        # pass (the pre-batching solo dispatch).
        self.serve_buckets = ""
        self.serve_batch_max = 8
        self.serve_batch_window_ms = 2.0
        # serve_kv_block > 0 arms the PAGED decode KV cache
        # (doc/performance.md "Decode KV cache"): the batched sessions'
        # dense slot-major caches become fixed-size KV blocks of this
        # many tokens on a shared free-list pool — per-slot block
        # tables, shared-prefix prefill-once reuse (serve_prefix_reuse),
        # mid-decode block reclaim at retirement, block-budgeted
        # admission (exhaustion = deterministic queue-wait). Must
        # divide the net's sequence length. 0 (default) = dense.
        self.serve_kv_block = 0
        # fraction of the perf ledger's live HBM headroom the pool may
        # claim (bytes_cap on Trainer.decode_kv_pool; ledger off = no
        # cap, the pool sizes at dense-equivalent capacity)
        self.serve_kv_pool_frac = 0.5
        self.serve_prefix_reuse = 1
        # retained conversation cache (doc/robustness.md "Memory
        # governance"): a retired sequence's registered blocks stay
        # trie-resident at refcount 0 — evictable headroom, not a
        # commitment — so the next turn of a multi-turn conversation
        # revives its prefix instead of re-prefilling it. Cap as a
        # fraction of the usable pool; 0 restores free-instantly.
        self.serve_retained_frac = 1.0
        # KV pressure latch: free-list percentage below which servd
        # sheds retained mass proactively (cxxnet_decode_kv_pressure),
        # and the hysteresis clear threshold it sheds back up to
        self.serve_kv_pressure_pct = 10.0
        self.serve_kv_pressure_clear_pct = 25.0
        # decode-datapath observability (doc/observability.md "Decode
        # datapath"): the iteration-level scheduler flight ring behind
        # statusd /batchz (one record per decode iteration: slots,
        # admissions/retirements, queue pressure, KV utilization), and
        # the convoy threshold — a sequence aboard >=
        # serve_convoy_iters step iterations while queued work waits
        # at zero free slots latches cxxnet_decode_convoy and emits
        # ONE decode_convoy transition event per episode
        self.serve_batch_flight_cap = 256
        self.serve_convoy_iters = 64
        # compile-cliff observability (doc/observability.md "Compile
        # flight recorder"): serve_plen_buckets declares the prompt
        # lengths clients are padded/bucketed to — with serve_buckets
        # it spans the EXPECTED program grid
        # (Trainer.expected_decode_grid), arming the warm-grid
        # readiness account: cxxnet_ready_programs_pct, /compilez,
        # per-replica warm fraction on /fleetz. Empty = no declared
        # grid (readiness reads "-" everywhere; compiles still ring).
        self.serve_plen_buckets = ""
        # serve_warm_ready_pct > 0 gates readiness on the warm grid:
        # /healthz answers 503 "warming: ..." (router state WARMING —
        # probed, never routed) until that percentage of the expected
        # programs has compiled. 0 (default) keeps a cold replica
        # routable — it serves, it just pays compile cliffs in-band.
        self.serve_warm_ready_pct = 0.0
        # serving SLOs + request tracing (doc/observability.md "Request
        # tracing & SLOs"): every request gets a phase-attributed trace
        # in a bounded flight recorder (statusd /trace?request=<id>,
        # /requestz) and feeds a rolling error-budget account — a
        # request that errors, or blows slo_ttft_ms / slo_p99_ms, burns
        # budget; the cxxnet_slo_burn gauge flips at >= 1x burn rate.
        # Latency objectives default 0 = availability-only SLO.
        self.slo_ttft_ms = 0.0
        self.slo_p99_ms = 0.0
        self.slo_availability = 0.999
        self.slo_window_s = 300.0
        self.serve_flight_cap = 256
        # fleet router (utils/routerd.py, doc/serving.md "Replicated
        # serving fleet"): task = route spreads client connections over
        # the servd replicas listed in route_replicas (health-aware
        # least-loaded dispatch, retry-on-shed, rolling ADMIN reload,
        # SIGTERM fleet drain). No model is loaded — the router is a
        # pure fleet-layer process.
        self.route_port = 0              # 0 = ephemeral, printed
        self.route_host = ""
        self.route_replicas = ""         # host:port:status_port, comma-sep
        self.route_probe_ms = 200.0
        self.route_retries = 2
        self.route_stall_s = 30.0        # per-attempt response bound
        # fleet observability plane (doc/observability.md "Fleet
        # observability"): the router's per-request flight ring (every
        # routed request's candidates/attempts/retries — /requestz,
        # stitched /trace?request=<id>), the federation cadence (pull +
        # exactly merge every replica's serve histograms/SLO window
        # into cxxnet_fleet_* series; 0 = off), and the per-replica
        # outlier detector thresholds (p99 vs fleet median).
        self.route_flight_cap = 256
        self.fleet_federate_ms = 1000.0
        self.fleet_outlier_ratio = 3.0
        self.fleet_outlier_min_n = 20
        # closed-loop fleet autoscaler (doc/robustness.md "Fleet
        # autoscaling"): route_standby_replicas lists pre-provisioned
        # host:port:status_port replicas held OUT of dispatch until the
        # policy loop — fleet SLO burn >= route_scale_up_burn, or
        # queued work with zero free decode slots — admits one; an
        # admitted standby idle for route_scale_down_idle_s retires
        # back to standby. Bounds default to [primary count, total];
        # at most one action per route_scale_cooldown_s (hysteresis).
        self.route_standby_replicas = ""
        self.route_scale_min = 0         # 0 = the primary count
        self.route_scale_max = 0         # 0 = primaries + standbys
        self.route_scale_up_burn = 1.0
        self.route_scale_down_idle_s = 30.0
        self.route_scale_cooldown_s = 10.0
        # multi-tenant weighted-fair QoS (doc/serving.md "Multi-tenant
        # QoS"): route_tenants = "free:1,paid:4" arms per-tenant
        # weighted-fair admission on BOTH the router and the servd
        # replicas (share the same value fleet-wide), per-tenant
        # counters/SLO floors, and fair-share shed charging; clients
        # name their tenant with the TENANT <id> wire prefix, and
        # prefix-less clients are the serve_tenant_default tenant.
        self.route_tenants = ""
        self.serve_tenant_default = "default"
        # zero-loss failover (doc/robustness.md "Failover & hedging"):
        # route_replay re-executes a lost-contact generation attempt on
        # a surviving replica (deterministic stack: token-identical;
        # guarded by the replica reload count so a replay never splices
        # model generations); route_hedge_ms launches one duplicate of
        # a still-unanswered request after that many ms (-1 = track the
        # federated serve p99, 0 = off), first answer wins, capped at
        # route_hedge_max_pct of in-flight and denied to tenants over
        # fair share.
        self.route_replay = 1
        self.route_hedge_ms = 0.0
        self.route_hedge_max_pct = 10.0
        self.gen_new = 16
        self.gen_temperature = 0.0
        self.gen_topk = 0
        self.gen_seed = 0
        self.output_format = 1
        self.device = "tpu"
        # multi-host launch (replaces the reference's PS/MPI launcher,
        # bin/cxxnet.ps + mpi.conf): coordinator/num_worker/worker_rank
        # bring up the jax distributed runtime before device init; the
        # values also default from env (CXXNET_NUM_WORKER,
        # CXXNET_WORKER_RANK / PS_RANK)
        self.coordinator = ""
        self.num_worker = 0
        self.worker_rank = -1
        self.cfg: List[Tuple[str, str]] = [("dev", "tpu")]

    # ------------------------------------------------------------------
    def run(self, argv: List[str]) -> int:
        if len(argv) < 1:
            print("Usage: <config>")
            return 0
        for name, val in ConfigIterator(argv[0], argv[1:]):
            self.set_param(name, val)
        pidx = None
        if self.coordinator or self.num_worker > 1:
            from .parallel import init_distributed
            init_distributed(
                coordinator_address=self.coordinator or None,
                num_processes=self.num_worker or None,
                process_id=self.worker_rank if self.worker_rank >= 0
                else None)
            # distributed runtime is up: tag this process's telemetry
            # shard / metric series with its rank
            import jax
            pidx = jax.process_index()
        if self.telemetry_log:
            telemetry.enable(self.telemetry_log, process_index=pidx)
            telemetry.event({"ev": "run_meta", "task": self.task,
                             "dev": self.device})
        if self.status_port >= 0:
            if not telemetry.enabled():
                # /metrics and /statusz read the telemetry registry: run
                # it in-memory (no JSONL sink) when no log was configured
                telemetry.enable(process_index=pidx)
                self._status_telemetry = True
            try:
                srv = statusd.start(self.status_port,
                                    host=self.status_host)
            except (OSError, OverflowError) as e:
                # a taken/privileged port — or an out-of-range one, which
                # socket.bind raises as OverflowError — must not kill a
                # training run over an observability feature: warn, run
                # blind
                sys.stderr.write(
                    "WARNING: statusd: cannot bind port %d (%s); live "
                    "introspection disabled for this run\n"
                    % (self.status_port, e))
                if self._status_telemetry:
                    telemetry.disable()
                    self._status_telemetry = False
            else:
                statusd.set_run_info(task=self.task, dev=self.device,
                                     config=list(self.cfg))
                if not self.silent:
                    # stderr: operational chatter — task = serve's stdout
                    # is a response stream (one line per request)
                    print("statusd: live introspection on port %d "
                          "(/metrics /healthz /livez /statusz /trace)"
                          % srv.port, file=sys.stderr, flush=True)
        if statusd.active() is not None:
            # /profilez rides statusd alone — on-demand profiling has
            # no dependency on (and must survive disabling) the ledger
            pdir = self.profilez_dir or os.path.join(
                os.path.dirname(self.telemetry_log) or ".", "profilez")
            statusd.set_profiler(perf.ProfilerCapture(pdir))
        if self.perf_ledger and telemetry.enabled():
            # the program performance ledger rides the recompile
            # detector: every program this run compiles gets a
            # cost/memory card (/programz, cxxnet_program_* series,
            # program_card JSONL events)
            perf.enable()
            self._perf_enabled = True
            statusd.set_perf(perf.ledger())
        try:
            with telemetry.span("init"):
                # the router is a pure fleet-layer process: no net, no
                # iterators, no jax use — replicas own the models
                if self.task != "route":
                    self.init()
            if not self.silent:
                # serve's stdout carries exactly one response line per
                # request — startup chatter goes to stderr there
                print("initializing end, start working",
                      file=sys.stderr if self.task == "serve"
                      else sys.stdout)
            if self.task in ("train", "finetune"):
                self.task_train()
            elif self.task == "pred":
                self.task_predict()
            elif self.task == "pred_raw":
                self.task_predict_raw()
            elif self.task == "extract":
                self.task_extract_feature()
            elif self.task == "export":
                self.task_export()
            elif self.task == "generate":
                self.task_generate()
            elif self.task == "serve":
                self.task_serve()
            elif self.task == "route":
                self.task_route()
        finally:
            if self._perf_enabled:
                # let queued card analyses land in the JSONL before the
                # summary event seals the log
                perf.drain(10.0)
                perf.disable()
                self._perf_enabled = False
            srv = statusd.active()
            if srv is not None and srv.profiler is not None:
                # an in-flight /profilez capture must be stopped and
                # JOINED before teardown — a daemon thread inside
                # native profiler code at interpreter exit segfaults,
                # turning a clean drain into rc -11
                srv.profiler.shutdown()
            if self.status_port >= 0:
                statusd.stop()
            if self.telemetry_log:
                summary = telemetry.finish(close=True)
                if summary and not self.silent:
                    self._print_telemetry_summary(summary)
            elif self._status_telemetry:
                telemetry.disable()
                self._status_telemetry = False
        return 0

    def set_param(self, name: str, val: str) -> None:
        if val == "default":
            return
        if name == "net_type":
            self.net_type = int(val)
        if name == "reset_net_type":
            self.reset_net_type = int(val)
        if name == "print_step":
            self.print_step = int(val)
        if name == "continue":
            self.continue_training = int(val)
        if name == "save_model":
            self.save_period = int(val)
        if name == "start_counter":
            self.start_counter = int(val)
            self._start_counter_conf = True
        if name == "model_in":
            self.name_model_in = val
        if name == "model_dir":
            self.name_model_dir = val
        if name == "num_round":
            self.num_round = int(val)
        if name == "max_round":
            self.max_round = int(val)
        if name == "silent":
            self.silent = int(val)
        if name == "task":
            self.task = val
        if name == "dev":
            self.device = val
        if name == "test_io":
            self.test_io = int(val)
        if name == "profile_dir":
            self.profile_dir = val
        if name == "telemetry_log":
            self.telemetry_log = val
        if name == "status_port":
            self.status_port = int(val)
        if name == "perf_ledger":
            self.perf_ledger = int(val)
        if name == "profilez_dir":
            self.profilez_dir = val
        if name == "status_host":
            self.status_host = val
        if name == "ckpt_keep_last":
            self.ckpt_keep_last = int(val)
        if name == "ckpt_keep_every":
            self.ckpt_keep_every = int(val)
        if name == "ckpt_retries":
            self.ckpt_retries = int(val)
        if name == "ckpt_fsync":
            self.ckpt_fsync = int(val)
        if name == "preempt_save":
            self.preempt_save = int(val)
        if name == "health_monitor":
            self.health_monitor = int(val)
        if name == "nonfinite_action":
            self.nonfinite_action = val
        if name == "loss_spike_factor":
            self.loss_spike_factor = float(val)
        if name == "loss_spike_warmup":
            self.loss_spike_warmup = int(val)
        if name == "rollback_backoff":
            self.rollback_backoff = float(val)
        if name == "rollback_max_retries":
            self.rollback_max_retries = int(val)
        if name == "watchdog_timeout":
            self.watchdog_timeout = float(val)
        if name == "watchdog_action":
            self.watchdog_action = val
        if name == "coordinator":
            self.coordinator = val
        if name == "num_worker":
            self.num_worker = int(val)
        if name == "worker_rank":
            self.worker_rank = int(val)
        if name == "serve_port":
            self.serve_port = int(val)
        if name == "serve_host":
            self.serve_host = val
        if name == "serve_queue":
            self.serve_queue = int(val)
        if name == "serve_deadline_ms":
            self.serve_deadline_ms = float(val)
        if name == "serve_drain_ms":
            self.serve_drain_ms = float(val)
        if name == "serve_breaker_fails":
            self.serve_breaker_fails = int(val)
        if name == "serve_breaker_cooldown_ms":
            self.serve_breaker_cooldown_ms = float(val)
        if name == "serve_stall_s":
            self.serve_stall_s = float(val)
        if name == "serve_buckets":
            self.serve_buckets = val
        if name == "serve_batch_max":
            self.serve_batch_max = int(val)
        if name == "serve_batch_window_ms":
            self.serve_batch_window_ms = float(val)
        if name == "serve_kv_block":
            self.serve_kv_block = int(val)
        if name == "serve_kv_pool_frac":
            self.serve_kv_pool_frac = float(val)
        if name == "serve_prefix_reuse":
            self.serve_prefix_reuse = int(val)
        if name == "serve_retained_frac":
            self.serve_retained_frac = float(val)
        if name == "serve_kv_pressure_pct":
            self.serve_kv_pressure_pct = float(val)
        if name == "serve_kv_pressure_clear_pct":
            self.serve_kv_pressure_clear_pct = float(val)
        if name == "serve_batch_flight_cap":
            self.serve_batch_flight_cap = int(val)
        if name == "serve_convoy_iters":
            self.serve_convoy_iters = int(val)
        if name == "serve_plen_buckets":
            self.serve_plen_buckets = val
        if name == "serve_warm_ready_pct":
            self.serve_warm_ready_pct = float(val)
        if name == "slo_ttft_ms":
            self.slo_ttft_ms = float(val)
        if name == "slo_p99_ms":
            self.slo_p99_ms = float(val)
        if name == "slo_availability":
            self.slo_availability = float(val)
        if name == "slo_window_s":
            self.slo_window_s = float(val)
        if name == "serve_flight_cap":
            self.serve_flight_cap = int(val)
        if name == "route_port":
            self.route_port = int(val)
        if name == "route_host":
            self.route_host = val
        if name == "route_replicas":
            self.route_replicas = val
        if name == "route_probe_ms":
            self.route_probe_ms = float(val)
        if name == "route_retries":
            self.route_retries = int(val)
        if name == "route_stall_s":
            self.route_stall_s = float(val)
        if name == "route_flight_cap":
            self.route_flight_cap = int(val)
        if name == "route_standby_replicas":
            self.route_standby_replicas = val
        if name == "route_scale_min":
            self.route_scale_min = int(val)
        if name == "route_scale_max":
            self.route_scale_max = int(val)
        if name == "route_scale_up_burn":
            self.route_scale_up_burn = float(val)
        if name == "route_scale_down_idle_s":
            self.route_scale_down_idle_s = float(val)
        if name == "route_scale_cooldown_s":
            self.route_scale_cooldown_s = float(val)
        if name == "route_tenants":
            self.route_tenants = val
        if name == "serve_tenant_default":
            self.serve_tenant_default = val
        if name == "route_replay":
            self.route_replay = int(val)
        if name == "route_hedge_ms":
            self.route_hedge_ms = float(val)
        if name == "route_hedge_max_pct":
            self.route_hedge_max_pct = float(val)
        if name == "fleet_federate_ms":
            self.fleet_federate_ms = float(val)
        if name == "fleet_outlier_ratio":
            self.fleet_outlier_ratio = float(val)
        if name == "fleet_outlier_min_n":
            self.fleet_outlier_min_n = int(val)
        if name == "extract_node_name":
            self.extract_node_name = val
        if name == "export_out":
            self.name_export = val
        if name == "export_batch":
            self.export_batch = int(val)
        if name == "prompt_in":
            self.name_prompt_in = val
        if name == "gen_out":
            self.name_gen_out = val
        if name == "gen_new":
            self.gen_new = int(val)
        if name == "gen_temperature":
            self.gen_temperature = float(val)
        if name == "gen_topk":
            self.gen_topk = int(val)
        if name == "gen_seed":
            self.gen_seed = int(val)
        if name == "output_format":
            self.output_format = 1 if val == "txt" else 0
        self.cfg.append((name, val))

    # ------------------------------------------------------------------
    def init(self) -> None:
        if self.task == "train" and self.continue_training:
            if self._sync_latest_model() == 0:
                raise RuntimeError(
                    "Init: Cannot find models for continue training. "
                    "Please specify it by model_in instead.")
            print("Init: Continue training from round %d" % self.start_counter)
            self._create_iterators()
            return
        self.continue_training = 0
        if self.name_model_in == "NULL":
            assert self.task == "train", "must specify model_in if not training"
            self.net_trainer = self._create_net()
            self.net_trainer.init_model()
        elif self.task == "finetune":
            self._copy_model()
        else:
            self._load_model()
        self._create_iterators()

    def _model_path(self, counter: int) -> str:
        return os.path.join(self.name_model_dir, "%04d.model" % counter)

    def _sync_latest_model(self) -> int:
        """Find and load the newest VALID checkpoint in model_dir.

        Replaces the reference's stop-at-first-hole scan (:135-157), which
        silently restarted from scratch whenever save_period > 1 left gaps
        in the numbering. This scan lists every <counter>.model (gaps
        fine), ranks an emergency (mid-round preemption) checkpoint by the
        progress recorded in its training-state section, verifies CRC
        framing and a full parse newest-first, quarantines anything
        corrupt to <name>.corrupt, and falls back to the next-newest valid
        file — a torn or bit-flipped checkpoint costs at most one save
        interval, never the run."""
        d = self.name_model_dir
        # candidates: (progress = (resume_counter, batches_done), path,
        # prefetched payload or None). A numbered checkpoint c resumes at
        # (c + 1, 0); the emergency file carries its cursor inside.
        cands = [((c + 1, 0), p, None, None)
                 for c, p in ckpt.scan_checkpoints(d)
                 if c >= self.start_counter]
        epath = os.path.join(d, ckpt.EMERGENCY_NAME)
        if os.path.exists(epath):
            try:
                payload, fmt = ckpt.read_verified(
                    epath, retries=self.ckpt_retries)
                st = ckpt.peek_state(payload) or {}
                prog = (int(st.get("start_counter", 0)),
                        int(st.get("batches_done", 0)))
                if prog[0] > self.start_counter:
                    cands.append((prog, epath, payload, fmt))
            except ckpt.CheckpointCorruptError as e:
                ckpt.quarantine(epath, reason=str(e))
            except OSError as e:     # unreadable even after retries:
                sys.stderr.write(    # skip, but never quarantine
                    "WARNING: cannot read %s (%s); skipping\n" % (epath, e))
        cands.sort(key=lambda t: t[0], reverse=True)
        for prog, path, payload, fmt in cands:
            try:
                if payload is None:
                    payload, fmt = ckpt.read_verified(
                        path, retries=self.ckpt_retries)
            except ckpt.CheckpointCorruptError as e:
                ckpt.quarantine(path, reason=str(e))
                continue
            except OSError as e:
                sys.stderr.write(
                    "WARNING: cannot read %s (%s); skipping\n" % (path, e))
                continue
            try:
                r = serializer.Reader(payload)
                self.net_type = r.read_int32()
                net = self._create_net()
                net.load_model(r)
                state = net.load_training_state(r)
            except Exception as e:
                if fmt == "v1":
                    # the CRC verified, so the bytes are exactly what the
                    # writer saved: this is a net/config mismatch, NOT
                    # file corruption. Abort loudly instead of
                    # destructively quarantining healthy checkpoints.
                    raise RuntimeError(
                        "checkpoint %s is intact (CRC verified) but "
                        "failed to load: %s — likely a net/updater config "
                        "mismatch with the current run; fix the config "
                        "(or remove the file) and retry" % (path, e)) \
                        from e
                # legacy file without integrity framing: a parse failure
                # here IS the corruption signal — quarantine and fall back
                ckpt.quarantine(path, reason=str(e))
                continue
            self.net_trainer = net
            self.start_counter = prog[0]
            self._resume_state = state
            self._resume_batches = prog[1] if state is not None else 0
            telemetry.event({"ev": "ckpt_restore", "path": path,
                             "counter": prog[0] - 1,
                             "batches_done": self._resume_batches})
            if not self.silent and self._resume_batches:
                # stderr: this scan also runs on a serve hot reload,
                # where stdout is the response stream
                print("Init: resuming mid-round from %s (%d batches into "
                      "round %d)" % (path, self._resume_batches,
                                     prog[0] - 1), file=sys.stderr)
            return 1
        return 0

    def _read_model_file(self, path: str) -> serializer.Reader:
        """Open a model file with integrity verification: framed files
        (this writer) are CRC-checked, footer-less seed/legacy files pass
        through untouched; a torn or bit-flipped file raises
        CheckpointCorruptError instead of deserializing garbage."""
        payload, _ = ckpt.read_verified(path, retries=self.ckpt_retries)
        return serializer.Reader(payload)

    def _load_model(self) -> None:
        base = os.path.basename(self.name_model_in)
        try:
            self.start_counter = int(base.split(".")[0])
        except ValueError:
            # proceeding with a guessed counter silently mis-numbers every
            # subsequent checkpoint (and the continue=1 scan keyed on it),
            # so for TRAINING an un-inferable name is an error unless the
            # config pins the counter explicitly. Inference tasks (pred /
            # extract / export / generate / serve) never use the counter —
            # arbitrary model names stay fine there.
            if not self._start_counter_conf and self.task == "train":
                raise ValueError(
                    "Cannot infer start_counter from model name %r: "
                    "expected '<counter>.model' (the save_model naming, "
                    "e.g. 0042.model). Rename the file or set "
                    "start_counter=<n> in the config." % self.name_model_in
                ) from None
        r = self._read_model_file(self.name_model_in)
        self.net_type = r.read_int32()
        self.net_trainer = self._create_net()
        self.net_trainer.load_model(r)
        self.start_counter += 1

    def _copy_model(self) -> None:
        r = self._read_model_file(self.name_model_in)
        self.net_type = r.read_int32()
        self.net_trainer = self._create_net()
        self.net_trainer.copy_model_from(r)

    def _is_writer(self) -> bool:
        """Multi-process: every rank serializes (fetch_global is
        collective) but exactly one touches the filesystem."""
        import jax
        return jax.process_count() <= 1 or jax.process_index() == 0

    def _write_checkpoint(self, name: str, resume_counter: int,
                          batches_done: int) -> None:
        """Serialize net_type + model + optimizer + training state and
        atomically write it with integrity framing (tmp + fsync + rename,
        CRC32 footer) and retry-with-backoff on transient IO errors."""
        t0 = time.perf_counter()
        w = serializer.Writer()
        w.write_int32(self.net_type)
        self.net_trainer.save_model(w)
        self.net_trainer.save_training_state(
            w, extra={"start_counter": int(resume_counter),
                      "batches_done": int(batches_done)})
        if not self._is_writer():
            return
        payload = w.f.getbuffer()   # zero-copy view of the BytesIO buffer
        os.makedirs(self.name_model_dir, exist_ok=True)
        ckpt.write_checkpoint(name, payload, fsync=bool(self.ckpt_fsync),
                              retries=self.ckpt_retries)
        telemetry.event({"ev": "ckpt_save", "path": name,
                         "bytes": len(payload),
                         "counter": int(resume_counter) - 1,
                         "batches_done": int(batches_done),
                         "seconds": round(time.perf_counter() - t0, 6)})

    def _save_model(self, force: bool = False) -> bool:
        """Round-boundary checkpoint; returns whether a file was written.

        The counter is checked BEFORE the increment (the reference
        incremented first, so save_period=k saved rounds k-1, 2k-1, ...
        and never round 0); the session's final round — num_round reached
        OR the max_round per-invocation cap exhausted — saves regardless
        of save_period (``force``), so a clean exit never loses work."""
        counter = self.start_counter
        self.start_counter += 1
        if self.save_period == 0:
            return False
        if counter % self.save_period != 0 and not force:
            return False
        self._write_checkpoint(self._model_path(counter),
                               self.start_counter, 0)
        if self._is_writer():
            # a numbered checkpoint strictly supersedes any emergency
            # file (its progress tuple is newer by construction)
            epath = os.path.join(self.name_model_dir, ckpt.EMERGENCY_NAME)
            try:
                if os.path.exists(epath):
                    os.remove(epath)
            except OSError:
                pass
            ckpt.gc_stale_tmp(self.name_model_dir)
            if self.ckpt_keep_last > 0:
                ckpt.apply_retention(self.name_model_dir,
                                     keep_last=self.ckpt_keep_last,
                                     keep_every=self.ckpt_keep_every)
        return True

    def _save_emergency(self, batches_done: int) -> None:
        """One mid-round emergency checkpoint at a step boundary (the
        preemption path): full state including the iterator cursor, so
        resume re-enters the SAME round and fast-forwards past the
        already-trained batches."""
        name = os.path.join(self.name_model_dir, ckpt.EMERGENCY_NAME)
        with telemetry.span("checkpoint", kind="emergency"):
            self._write_checkpoint(name, self.start_counter, batches_done)
        if not self.silent:
            print("preemption: emergency checkpoint -> %s (round %d, "
                  "batch %d)" % (name, self.start_counter - 1,
                                 batches_done))

    def _preempt_requested(self) -> bool:
        if self._preempt is None or not self._preempt.requested:
            return False
        if not self._preempt_noted:
            # the signal handler only sets flags (async-signal safety:
            # telemetry's lock may be held by this very thread when the
            # signal lands) — the loop emits the event on first notice
            self._preempt_noted = True
            telemetry.event({"ev": "preempt_signal",
                             "signum": self._preempt.signum})
        return True

    @staticmethod
    def _iter_chain_stable(it) -> bool:
        """Whether every iterator in the chain replays an identical epoch
        order after restart (exact mid-round resume; see IIterator)."""
        while it is not None:
            if not getattr(it, "stable_epoch_order", True):
                return False
            it = getattr(it, "base", None)
        return True

    def _create_net(self) -> Trainer:
        if self.reset_net_type != -1:
            self.net_type = self.reset_net_type
        net = create_net(self.net_type)
        for k, v in self.cfg:
            net.set_param(k, v)
        return net

    def _create_iterators(self) -> None:
        """Sectioned iterator parsing (reference :214-264): data=/eval=/pred=
        blocks terminated by iter=end; keys outside blocks are defaults
        applied to every iterator."""
        flag = 0
        evname = ""
        itcfg: List[Tuple[str, str]] = []
        defcfg: List[Tuple[str, str]] = []
        for name, val in self.cfg:
            if name == "data":
                flag = 1
                continue
            if name == "eval":
                evname = val
                flag = 2
                continue
            if name == "pred":
                flag = 3
                self.name_pred = val
                continue
            if name == "iter" and val == "end":
                assert flag != 0, "wrong configuration file"
                if flag == 1 and self.task not in ("pred", "export", "generate"):
                    assert self.itr_train is None, "can only have one data"
                    self.itr_train = create_iterator(itcfg)
                if flag == 2 and self.task not in ("pred", "export", "generate"):
                    self.itr_evals.append(create_iterator(itcfg))
                    self.eval_names.append(evname)
                if flag == 3 and self.task in ("pred", "pred_raw", "extract"):
                    assert self.itr_pred is None, "can only have one data:test"
                    self.itr_pred = create_iterator(itcfg)
                flag = 0
                itcfg = []
                continue
            if flag == 0:
                defcfg.append((name, val))
            else:
                itcfg.append((name, val))
        for itr in ([self.itr_train] if self.itr_train else []) + \
                ([self.itr_pred] if self.itr_pred else []) + self.itr_evals:
            for k, v in defcfg:
                itr.set_param(k, v)
            itr.init()

    # ------------------------------------------------------------------
    def task_train(self) -> None:
        start = time.monotonic()   # elapsed-time origin: never wall clock
        self._stop_training = False
        self._preempt_noted = False
        # cooperative preemption is single-process only: the stop flag is
        # per-rank, so in a multi-process run ranks would observe the
        # signal at different step boundaries and issue MISMATCHED
        # collectives (one rank in the emergency save's fetch_global,
        # another in the next train step) — a distributed hang. Multi-host
        # fleets rely on the round-boundary checkpoints instead.
        import jax
        enabled = bool(self.preempt_save) and jax.process_count() <= 1
        if self.preempt_save and not enabled and not self.silent:
            print("preempt_save: disabled (multi-process run — emergency "
                  "checkpoints require single-process training)")
        if self.health_monitor:
            self._health = health.HealthMonitor(
                spike_factor=self.loss_spike_factor,
                spike_warmup=self.loss_spike_warmup)
            self._recovery = health.RecoveryPolicy(
                action=self.nonfinite_action,
                backoff=self.rollback_backoff,
                max_retries=self.rollback_max_retries)
            # /healthz serves 503 while an anomaly is unresolved (the
            # watchdog heartbeat channels are consulted unconditionally)
            statusd.wire_health(self._recovery)
        wd = None
        if self.watchdog_timeout > 0:
            # the step channel arms itself at the FIRST completed batch
            # (pre-arming would false-alarm on a first-compile longer
            # than the timeout) and is paused across eval/checkpoint
            wd = health.Watchdog(self.watchdog_timeout,
                                 action=self.watchdog_action).start()
        with ckpt.PreemptionGuard(enabled=enabled) as guard:
            self._preempt = guard
            try:
                self._task_train_loop(start)
            finally:
                self._preempt = None
                if wd is not None:
                    wd.stop()

    def _task_train_loop(self, start: float) -> None:
        if self.continue_training == 0 and self.name_model_in == "NULL":
            self._save_model()
        else:
            if not self.silent:
                print("continuing from round %d" % (self.start_counter - 1))
            for itr, nm in zip(self.itr_evals, self.eval_names):
                sys.stderr.write(self.net_trainer.evaluate(itr, nm))
            sys.stderr.write("\n")
            sys.stderr.flush()
        # apply the checkpoint's training-state cursor HERE — after the
        # continue-path eval above (which draws from the rng stream and
        # would absorb a restored metric accumulator), right before the
        # first update, so a preempted run resumes bit-for-bit
        if self._resume_state is not None:
            self.net_trainer.restore_training_state(self._resume_state)
            self._resume_state = None
        if self.itr_train is None:
            return
        if self.test_io != 0:
            print("start I/O test")
        cc = self.max_round
        rounds_done = 0
        profiling = False
        while self.start_counter <= self.num_round and cc > 0:
            cc -= 1
            rnd = self.start_counter - 1
            if self.profile_dir and rounds_done == 1:
                import jax
                jax.profiler.start_trace(self.profile_dir)
                profiling = True
            statusd.update_progress(round=rnd, num_round=self.num_round)
            if not self.silent:
                print("update round %d" % rnd)
            # the session's last round — by the schedule (num_round) OR by
            # the per-invocation cap (max_round) — always checkpoints, so
            # a clean exit never loses finished rounds to save_period gaps
            last_round = (cc == 0 or self.start_counter == self.num_round)
            try:
                with telemetry.span("round", round=rnd):
                    stats = self._train_one_round(
                        start, skip_batches=self._resume_batches,
                        final_round=last_round)
            except health.TrainingAnomalyError as e:
                # rollback: restore the newest valid checkpoint and
                # re-enter the loop; the offending batch window is
                # quarantined so the replay excludes it. (A rollback
                # attempt consumes one unit of the max_round budget —
                # irrelevant at the default cap, and it bounds a
                # pathological rollback storm under a tight one.)
                self._recover_from_anomaly(e.anomaly)
                continue
            if self._recovery is not None:
                self._recovery.on_round_complete()
            self._resume_batches = 0
            t_input, t_step, t_eval, t_ckpt, n_img = stats
            wall = t_input + t_step
            if self.test_io != 0:
                print("round %d: io-only %.1f images/sec" %
                      (rnd, n_img / t_input if t_input > 0 else 0.0))
            elif not self.silent and wall > 0:
                print("round %d: input-wait %.1f%% (io %.1f img/s when "
                      "blocked, step %.1f img/s)" %
                      (rnd, 100.0 * t_input / wall,
                       n_img / t_input if t_input > 0 else float("inf"),
                       n_img / t_step if t_step > 0 else float("inf")))
            if telemetry.enabled():
                # the per-round breakdown as ONE structured event (the
                # telemetry-backed form of the prints above; per-batch
                # io.wait / train.step spans carry the fine grain)
                telemetry.event({
                    "ev": "round", "round": rnd, "images": n_img,
                    "input_wait_s": round(t_input, 6),
                    "step_s": round(t_step, 6),
                    "eval_s": round(t_eval, 6),
                    "checkpoint_s": round(t_ckpt, 6)})
                telemetry.sample_device_memory()
                telemetry.flush()
            rounds_done += 1
            if profiling:
                import jax
                jax.profiler.stop_trace()
                profiling = False
                if not self.silent:
                    print("profiler trace written to %s" % self.profile_dir)
            if self._stop_training:
                telemetry.event({"ev": "preempt_exit", "round": rnd})
                if not self.silent:
                    print("preemption: checkpointed, exiting cleanly "
                          "(resume with continue=1)")
                return
        if not self.silent:
            print("updating end, %.0f sec in all"
                  % (time.monotonic() - start))

    def _train_one_round(self, start: float, skip_batches: int = 0,
                         final_round: bool = False):
        """One pass over itr_train + eval + checkpoint. Returns the round
        breakdown (input-wait, step, eval, checkpoint seconds, images) —
        the input-starvation probe the reference treats as a design axis
        (thread_buffer.h:22): time blocked on the input pipeline
        (next+value) vs in the device step is the number that says
        whether the loader keeps up."""
        sample_counter = 0
        hm = self._health
        rnd = self.start_counter - 1
        self.net_trainer.start_round(self.start_counter)
        self.itr_train.before_first()
        t_input = t_step = t_eval = t_ckpt = 0.0
        n_img = 0
        batches_done = 0
        if skip_batches:
            # mid-round resume: replay the round's prefix without compute
            # (base iterators seek O(1); buffered chains drain batches)
            if not self._iter_chain_stable(self.itr_train):
                print("WARNING: the training iterator's epoch order is "
                      "not replay-stable (windowed shuffle); mid-round "
                      "resume is approximate — some prefix batches may "
                      "repeat or be skipped this round")
            with telemetry.span("resume.skip", batches=skip_batches):
                batches_done = self.itr_train.skip(skip_batches)
            sample_counter = batches_done
            if not self.silent:
                print("resume: fast-forwarded %d batches into round %d"
                      % (batches_done, self.start_counter - 1))
        while True:
            t0 = time.perf_counter()
            if self._recovery is not None \
                    and self._recovery.should_skip(rnd, batches_done):
                # quarantined batch window (a prior anomaly): fast-forward
                # the data cursor past it without training — the rollback
                # replay's exclusion of the offending batch
                if self.itr_train.skip(1) == 0:
                    break
                telemetry.event({"ev": "health_skip_batch", "round": rnd,
                                 "batch": batches_done})
                telemetry.count("health/batches_skipped")
                sample_counter += 1
                batches_done += 1
                continue
            if not self.itr_train.next():
                break
            batch = self.itr_train.value()
            t1 = time.perf_counter()
            t_input += t1 - t0
            # span recorded post hoc so the terminal (exhausted) next()
            # never shows up as an io.wait — the span totals match the
            # round event's input_wait_s exactly
            telemetry.span_event("io.wait", t0, t1 - t0)
            if self.test_io == 0:
                self.net_trainer.update(batch)
                t_step += time.perf_counter() - t1
                if hm is not None:
                    # check the PREVIOUS step's health vector (pipelined:
                    # its compute is done, the fetch cannot stall us)
                    anomaly = hm.observe(rnd, batches_done,
                                         self.net_trainer.last_health)
                    if anomaly is not None:
                        self._on_anomaly(anomaly)
            health.beat("train.step")
            n_img += batch.batch_size - batch.num_batch_padd
            sample_counter += 1
            batches_done += 1
            statusd.update_progress(batch=batches_done)
            if sample_counter % self.print_step == 0 and not self.silent:
                print("round %8d:[%8d] %.0f sec elapsed" %
                      (self.start_counter - 1, sample_counter,
                       time.monotonic() - start))
            if self.test_io == 0 and self._preempt_requested():
                # preemption at a step boundary: one emergency checkpoint
                # with the iterator cursor, then a clean exit — the
                # user-level checkpoint/restore recovery contract
                t0 = time.perf_counter()
                bad = hm.drain() if hm is not None else None
                if bad is not None:
                    # never persist post-anomaly state as a checkpoint:
                    # resume restarts from the last numbered one instead
                    telemetry.event({"ev": "health_anomaly_at_preempt",
                                     "anomaly": bad.id})
                else:
                    self._save_emergency(batches_done)
                t_ckpt = time.perf_counter() - t0
                self._stop_training = True
                return t_input, t_step, t_eval, t_ckpt, n_img
        # eval + checkpoint are legitimately step-silent: disarm the step
        # channel so the watchdog doesn't false-alarm (re-armed by the
        # next round's first batch)
        health.pause("train.step")
        if hm is not None:
            # settle the round's health BEFORE eval/checkpoint: a bad
            # final step must roll back, never be saved as "good"
            anomaly = hm.drain()
            if anomaly is not None:
                self._on_anomaly(anomaly)
        if self.test_io == 0:
            t0 = time.perf_counter()
            sys.stderr.write("[%d]" % self.start_counter)
            if not self.itr_evals:
                with telemetry.span("eval", dataset="train"):
                    sys.stderr.write(self.net_trainer.evaluate(None, "train"))
            for itr, nm in zip(self.itr_evals, self.eval_names):
                with telemetry.span("eval", dataset=nm):
                    sys.stderr.write(self.net_trainer.evaluate(itr, nm))
            sys.stderr.write("\n")
            sys.stderr.flush()
            t_eval = time.perf_counter() - t0
        t0 = time.perf_counter()
        with telemetry.span("checkpoint"):
            saved = self._save_model(force=final_round)
        t_ckpt = time.perf_counter() - t0
        if self._preempt_requested():
            # signal arrived during eval/checkpoint: the round is complete;
            # if save_period skipped the round checkpoint, write an
            # emergency one so no finished work is lost
            if not saved:
                self._save_emergency(0)
            self._stop_training = True
        return t_input, t_step, t_eval, t_ckpt, n_img

    # ------------------------------------------------------------------
    # training-health recovery (utils/health.py, doc/robustness.md)
    def _on_anomaly(self, anomaly) -> None:
        """Route a detected anomaly through the recovery policy: 'skip'
        logs and continues (the device guard already suppressed the bad
        update), 'rollback' unwinds the round via TrainingAnomalyError,
        'abort' dumps diagnostics and dies."""
        decision = self._recovery.decide(anomaly)
        if decision == "skip":
            # the on-device guard only suppresses NON-FINITE steps; a
            # finite loss spike in skip mode was APPLIED to the weights
            # and is logged, not suppressed — event + counter say which
            suppressed = anomaly.kind == "nonfinite"
            if not self.silent:
                print("health: %s -> %s" % (
                    anomaly.describe(),
                    "skip (update suppressed on device)" if suppressed
                    else "logged (skip mode does not suppress finite "
                         "spikes)"))
            telemetry.event({"ev": "health_skip", "anomaly": anomaly.id,
                            "kind": anomaly.kind, "round": anomaly.round,
                             "batch": anomaly.batch,
                             "suppressed": suppressed})
            telemetry.count("health/updates_suppressed" if suppressed
                            else "health/spikes_logged")
            return
        if not self.silent:
            print("health: %s -> %s" % (anomaly.describe(), decision))
        if decision == "abort":
            reason = ("nonfinite_action=abort" if self.nonfinite_action ==
                      "abort" else "%d consecutive rollbacks exhausted "
                      "rollback_max_retries=%d" % (self._recovery.retries,
                                                   self.rollback_max_retries))
            telemetry.event({"ev": "health_abort", "anomaly": anomaly.id,
                             "reason": reason})
            health.dump_diagnostics(reason, anomaly)
            raise RuntimeError(
                "health: training anomaly (%s); aborting: %s"
                % (anomaly.describe(), reason))
        raise health.TrainingAnomalyError(anomaly)

    def _recover_from_anomaly(self, anomaly) -> None:
        """Roll back to the newest valid checkpoint and let the train
        loop re-enter the restored round; the offending batch window is
        excluded on replay (RecoveryPolicy.should_skip) and the
        accumulated LR backoff is re-applied to the fresh trainer."""
        pol = self._recovery
        telemetry.event({"ev": "health_rollback", "anomaly": anomaly.id,
                         "retry": pol.retries, "round": anomaly.round,
                         "batch": anomaly.batch, "lr_scale": pol.lr_scale,
                         "skip": pol.skipped()})
        telemetry.count("health/rollbacks")
        health.pause("train.step")   # checkpoint reload is step-silent
        self._health.reset_pending()
        self._resume_state = None
        self._resume_batches = 0
        # any valid checkpoint qualifies: drop the scan floor before the
        # rescan (it normally encodes "don't resume older than the run's
        # own progress", which is exactly what a rollback must undo)
        self.start_counter = 0
        if self._sync_latest_model() == 0:
            raise RuntimeError(
                "health: anomaly at round %d batch %d requires a rollback "
                "but no valid checkpoint exists in %s (save_model=0?); "
                "cannot recover" % (anomaly.round, anomaly.batch,
                                    self.name_model_dir))
        if not self.silent:
            print("health: rolled back to round %d (retry %d/%d, lr x%g)"
                  % (self.start_counter - 1, pol.retries,
                     self.rollback_max_retries, pol.lr_scale))
        if self._resume_state is not None:
            self.net_trainer.restore_training_state(self._resume_state)
            self._resume_state = None
        if not self._iter_chain_stable(self.itr_train):
            print("WARNING: the training iterator's epoch order is not "
                  "replay-stable (windowed shuffle); the rollback replay "
                  "sees a different batch order and the quarantined "
                  "window is positional — recovery is approximate")
        self.net_trainer.scale_lr(pol.lr_scale)
        # recovery complete (checkpoint restored, replay armed): flip
        # /healthz back to 200
        pol.resolve()

    @staticmethod
    def _print_telemetry_summary(summary: dict) -> None:
        """End-of-run telemetry table: top spans by total time, compile
        cost, counters — the at-a-glance per-phase breakdown."""
        spans = summary.get("spans", {})
        print("---- telemetry summary ----")
        if spans:
            print("%-18s %7s %10s %9s %9s %9s" %
                  ("span", "count", "total_s", "p50_ms", "p99_ms",
                   "max_ms"))
            for name, a in sorted(spans.items(),
                                  key=lambda kv: -kv[1]["total_s"])[:12]:
                print("%-18s %7d %10.3f %9.2f %9.2f %9.2f" %
                      (name, a["count"], a["total_s"], a["p50_ms"],
                       a["p99_ms"], a["max_ms"]))
        comp = summary.get("compiles", {})
        if comp.get("count"):
            print("compiles: %d (%.2fs) %s" %
                  (comp["count"], comp["total_s"],
                   " ".join("%s=%d" % kv
                            for kv in sorted(comp["by_cause"].items()))))
        for name, v in sorted(summary.get("counters", {}).items()):
            print("counter %-24s %s" % (name, v))
        for name, v in sorted(summary.get("gauges", {}).items()):
            print("gauge   %-24s %s" % (name, v))

    def task_predict(self) -> None:
        assert self.itr_pred is not None, \
            "must specify a predict iterator to generate predictions"
        print("start predicting...")
        with open(self.name_pred, "w") as fo:
            self.itr_pred.before_first()
            while self.itr_pred.next():
                batch = self.itr_pred.value()
                pred = self.net_trainer.predict(batch)
                assert batch.num_batch_padd < batch.batch_size, \
                    "num batch pad must be smaller"
                for v in pred[: len(pred) - batch.num_batch_padd]:
                    fo.write("%g\n" % v)
        print("finished prediction, write into %s" % self.name_pred)

    def task_predict_raw(self) -> None:
        """task = pred_raw: one space-separated row of raw output-node
        values (class probabilities after softmax) per input row. The
        reference ACCEPTS this task string in its iterator wiring
        (src/cxxnet_main.cpp:242) and its kaggle_bowl example depends on
        it (example/kaggle_bowl/pred.conf + make_submission.py), but its
        task dispatch never implements it — implemented here the way the
        submission maker expects."""
        assert self.itr_pred is not None, \
            "must specify a predict iterator to generate predictions"
        print("start predicting (raw)...")
        with open(self.name_pred, "w") as fo:
            self.itr_pred.before_first()
            while self.itr_pred.next():
                batch = self.itr_pred.value()
                out = self.net_trainer.extract_feature(batch, "top[-1]")
                out = np.asarray(out).reshape(out.shape[0], -1)
                assert batch.num_batch_padd < batch.batch_size, \
                    "num batch pad must be smaller"
                for row in out[: len(out) - batch.num_batch_padd]:
                    fo.write(" ".join("%g" % v for v in row) + "\n")
        print("finished prediction, write into %s" % self.name_pred)

    def task_extract_feature(self) -> None:
        assert self.itr_pred is not None, \
            "must specify a predict iterator to generate predictions"
        assert self.extract_node_name != "", \
            "extract node name must be specified in task extract_feature."
        print("start predicting...")
        name_meta = self.name_pred + ".meta"
        nrow = 0
        dshape = (0, 0, 0)
        mode = "w" if self.output_format else "wb"
        with open(self.name_pred, mode) as fo:
            self.itr_pred.before_first()
            while self.itr_pred.next():
                batch = self.itr_pred.value()
                pred = self.net_trainer.extract_feature(
                    batch, self.extract_node_name)
                sz = pred.shape[0] - batch.num_batch_padd
                nrow += sz
                for j in range(sz):
                    row = pred[j].reshape(-1)
                    if self.output_format:
                        fo.write(" ".join("%g" % x for x in row) + " \n")
                    else:
                        fo.write(row.astype("<f4").tobytes())
                if sz:
                    dshape = pred.shape[1:]
        with open(name_meta, "w") as fm:
            fm.write("%d,%d,%d,%d\n" % (nrow, dshape[0], dshape[1], dshape[2]))
        print("finished prediction, write into %s" % self.name_pred)

    def task_generate(self) -> None:
        """task = generate: KV-cached continuation of token-id prompts
        (sequence nets; model_in required). ``prompt_in`` is a text file
        of space-separated integer token ids, one prompt per line —
        lines may have DIFFERENT lengths (ragged batch; per-row prompt
        lengths feed Trainer.generate's prompt_lens). ``gen_new`` tokens
        are appended per prompt with greedy decoding by default
        (gen_temperature / gen_topk / gen_seed for sampling) and written
        to ``gen_out``, one space-separated id line per prompt."""
        rows = []
        with open(self.name_prompt_in) as f:
            for line in f:
                line = line.split()
                if line:
                    rows.append([int(t) for t in line])
        assert rows, "prompt_in %s has no prompts" % self.name_prompt_in
        from .utils.servd import embed_vocab
        vocab = embed_vocab(self.net_trainer.net)
        if vocab:
            bad = [t for r in rows for t in r if not 0 <= t < vocab]
            assert not bad, (
                "prompt_in contains token ids outside the net's "
                "vocab_size %d (e.g. %d) — wrong tokenizer? (jit would "
                "silently clamp them)" % (vocab, bad[0]))
        lens = [len(r) for r in rows]
        max_p = max(lens)
        prompts = [r + [0] * (max_p - len(r)) for r in rows]
        out = self.net_trainer.generate(
            prompts, self.gen_new, temperature=self.gen_temperature,
            top_k=self.gen_topk, seed=self.gen_seed, prompt_lens=lens)
        with open(self.name_gen_out, "w") as fo:
            for row in out:
                fo.write(" ".join(str(int(t)) for t in row) + "\n")
        print("generated %d x %d tokens into %s"
              % (out.shape[0], out.shape[1], self.name_gen_out))

    def task_serve(self) -> None:
        """task = serve: online serving through the production frontend
        (utils/servd.py, doc/serving.md). The stdin/stdout line loop of
        the reference-era task is still the default surface — each input
        line is one prompt of space-separated token ids, answered with
        one line (the gen_new-token continuation, or ``ERR <class>``) —
        but every request now runs through the frontend engine: backend
        supervision (an exception answers ``ERR backend`` and feeds the
        circuit breaker instead of killing the loop), per-request
        deadlines (``DEADLINE <ms>`` prefix / serve_deadline_ms),
        admission control, hot model reload (``ADMIN reload`` / SIGHUP
        picks up the newest valid checkpoint in model_dir between
        requests), and graceful drain on SIGTERM/SIGINT (finish accepted
        requests within serve_drain_ms, flush telemetry, exit 0).
        serve_port >= 0 additionally serves concurrent TCP clients with
        the same line protocol; after stdin EOF the process then keeps
        serving until a drain signal. The KV-cached decode program is
        compiled per prompt-length signature and reused across requests
        (bucket client-side prompt lengths to keep compilations few);
        batch is 1 per request by design — the latency-bound serving
        case; use task = generate for offline batch throughput."""
        import signal

        from .utils import servd

        vocab = servd.embed_vocab(self.net_trainer.net)
        statusd.update_progress(served=0, errors=0)

        def backend(toks, seq):
            # reads net_trainer THROUGH self so a hot reload's swapped-in
            # trainer serves the very next request
            return self.net_trainer.generate(
                [toks], self.gen_new, temperature=self.gen_temperature,
                top_k=self.gen_topk, seed=self.gen_seed + seq)[0]

        def newest_ckpt_sig():
            # identity of the newest checkpoint candidates (newest
            # numbered + emergency file): any new or rewritten file
            # changes the signature, so a matching one means a reload
            # would re-load the very model being served
            paths = []
            cands = ckpt.scan_checkpoints(self.name_model_dir)
            if cands:
                paths.append(cands[-1][1])
            epath = os.path.join(self.name_model_dir,
                                 ckpt.EMERGENCY_NAME)
            if os.path.exists(epath):
                paths.append(epath)
            sig = []
            for p in paths:
                try:
                    fst = os.stat(p)
                except OSError:
                    continue
                sig.append((os.path.realpath(p), fst.st_mtime_ns,
                            fst.st_size))
            return tuple(sig)

        # seed the signature when the model being served IS the newest
        # candidate, so an operator's blind SIGHUP loop starts out free
        served_sig = [newest_ckpt_sig()]
        if served_sig[0] and not (
                len(served_sig[0]) == 1 and served_sig[0][0][0]
                == os.path.realpath(self.name_model_in)):
            served_sig[0] = None

        def reload_fn():
            # a reload that would re-load the checkpoint already being
            # served must be FREE: rebuilding the trainer discards every
            # compiled decode program — the recompile latency cliff —
            # for a bit-identical model
            sig = newest_ckpt_sig()
            if sig and sig == served_sig[0]:
                if not self.silent:
                    print("serve: reload skipped — already serving the "
                          "newest checkpoint", file=sys.stderr,
                          flush=True)
                return False
            # newest valid checkpoint in model_dir (the continue=1 scan:
            # CRC-verified newest-first, corrupt files quarantined);
            # nothing valid = keep the current model and say so
            prev_counter = self.start_counter
            self.start_counter = 0
            if self._sync_latest_model() == 0:
                self.start_counter = prev_counter
                sys.stderr.write(
                    "WARNING: serve reload: no valid checkpoint in %s; "
                    "keeping the current model\n" % self.name_model_dir)
                return False
            served_sig[0] = sig
            # the old model's paged KV pool holds old-weight K/V and
            # the reload path has already closed every session on it:
            # release NOW so the HBM account reads 0 until the first
            # post-reload admission rebuilds the pool (the account must
            # never report freed memory as allocated)
            try:
                self.net_trainer.release_kv_pool()
            except Exception:
                pass
            if not self.silent:
                # stderr: stdout is the response stream (one line per
                # request — a banner there desyncs positional clients)
                print("serve: reloaded model (round %d checkpoint)"
                      % (self.start_counter - 1), file=sys.stderr,
                      flush=True)
            return True

        # SLO error-budget account: every completed request feeds it;
        # the burn-rate gauges ride /metrics and the transition events
        # ride the telemetry log (report exit-2 gate)
        slo = statusd.SLOTracker(
            ttft_ms=self.slo_ttft_ms, p99_ms=self.slo_p99_ms,
            availability=self.slo_availability,
            window_s=self.slo_window_s)
        # multi-tenant QoS: the SAME route_tenants value the fleet
        # router enforces (the fairness verdict must agree fleet-wide),
        # with one SLOTracker per tenant — same objectives, separate
        # error budgets, so a noisy tenant's sheds cannot burn the
        # victim's window
        tenants = servd.parse_tenants(self.route_tenants)
        slo_tenants = {}
        if tenants:
            if self.serve_tenant_default not in tenants:
                tenants[self.serve_tenant_default] = 1.0
            slo_tenants = {
                t: statusd.SLOTracker(
                    ttft_ms=self.slo_ttft_ms, p99_ms=self.slo_p99_ms,
                    availability=self.slo_availability,
                    window_s=self.slo_window_s)
                for t in tenants}
            if not self.silent:
                print("serve: multi-tenant QoS on (%s; default %r)"
                      % (",".join("%s:%g" % kv
                                  for kv in sorted(tenants.items())),
                         self.serve_tenant_default),
                      file=sys.stderr, flush=True)
        # continuous batching: serve_buckets = "1,2,4,8" swaps the
        # one-request-per-pass worker for the iteration-granularity
        # batching dispatcher over Trainer.decode_session (the slot
        # counts are the compile-once bucket grid — keep it short, each
        # bucket is one decode-step program)
        slot_backend = None
        bucket_list = [int(x) for x in
                       str(self.serve_buckets).replace(",", " ").split()]
        if bucket_list:
            slot_backend = _SlotBackendAdapter(
                self, bucket_list, kv_block=self.serve_kv_block,
                kv_pool_frac=self.serve_kv_pool_frac,
                prefix_reuse=bool(self.serve_prefix_reuse),
                retained_frac=self.serve_retained_frac)
            if not self.silent:
                print("serve: continuous batching on (buckets %s, "
                      "batch_max %d, window %.1fms%s)"
                      % (sorted(set(bucket_list)), self.serve_batch_max,
                         self.serve_batch_window_ms,
                         ", paged kv block %d" % self.serve_kv_block
                         if self.serve_kv_block > 0 else ""),
                      file=sys.stderr, flush=True)
        fe = servd.ServeFrontend(
            backend, queue_size=self.serve_queue,
            deadline_ms=self.serve_deadline_ms,
            drain_ms=self.serve_drain_ms,
            breaker_fails=self.serve_breaker_fails,
            breaker_cooldown_ms=self.serve_breaker_cooldown_ms,
            stall_after_s=self.serve_stall_s,
            vocab=vocab, reload_fn=reload_fn,
            slo=slo, flight_cap=self.serve_flight_cap,
            slot_backend=slot_backend,
            batch_max=self.serve_batch_max,
            batch_window_ms=self.serve_batch_window_ms,
            batch_flight_cap=self.serve_batch_flight_cap,
            convoy_iters=self.serve_convoy_iters,
            kv_pressure_pct=self.serve_kv_pressure_pct,
            kv_pressure_clear_pct=self.serve_kv_pressure_clear_pct,
            tenants=tenants, tenant_default=self.serve_tenant_default,
            slo_tenants=slo_tenants)
        fe.start()
        # request introspection: /trace?request=<id> + /requestz serve
        # the flight ring, /metrics + /statusz the SLO account (no-ops
        # without status_port)
        statusd.set_flight_recorder(fe.flight)
        statusd.set_slo(slo)
        statusd.set_slo_tenants(slo_tenants)
        if slot_backend is not None:
            # decode-datapath observability (doc/observability.md
            # "Decode datapath"): /batchz + the cxxnet_decode_* series
            # + the /trace slot-Gantt lanes serve from the frontend's
            # iteration ring, and the perf ledger charges the live
            # decode KV cache against HBM headroom
            statusd.set_batch(fe)
            perf.set_decode_kv(fe.decode_kv_bytes)
            plen_list = [int(x) for x in
                         str(self.serve_plen_buckets)
                         .replace(",", " ").split()]
            if plen_list and getattr(self, "_perf_enabled", False):
                # warm-grid readiness (doc/observability.md "Compile
                # flight recorder"): declare the expected program grid
                # on the ledger (serve_buckets x serve_plen_buckets x
                # admit/step variants), wire the frontend's warm
                # account to it — cxxnet_ready_programs_pct, the ADMIN
                # warm_programs/expected_programs ints the router
                # federates, and (serve_warm_ready_pct > 0) the
                # "warming" health gate
                perf.ledger().set_expected_grid(
                    self.net_trainer.expected_decode_grid(
                        bucket_list, plen_list,
                        temperature=self.gen_temperature,
                        top_k=self.gen_topk,
                        kv_block=self.serve_kv_block))
                fe.set_warm_account(
                    perf.ledger().readiness,
                    ready_pct=self.serve_warm_ready_pct)
        if self.serve_port >= 0:
            try:
                port = fe.listen(self.serve_port, host=self.serve_host)
            except (OSError, OverflowError) as e:
                # like the statusd bind guard: a taken port must not kill
                # serving — warn, fall back to the stdin surface
                sys.stderr.write(
                    "WARNING: servd: cannot bind port %d (%s); TCP "
                    "serving disabled, stdin loop only\n"
                    % (self.serve_port, e))
            else:
                if not self.silent:
                    # stderr, not stdout: stdout carries exactly one
                    # response line per stdin request
                    print("servd: serving on port %d (line protocol; "
                          "DEADLINE/ADMIN prefixes, ERR classes — "
                          "doc/serving.md)" % port, file=sys.stderr,
                          flush=True)
        # /healthz flips 503 while draining or breaker-open (readiness);
        # /livez only dies with the worker thread (liveness)
        statusd.register_probe("serving", fe.health_probe)
        statusd.register_probe("serving.worker", fe.liveness_probe,
                               liveness=True)
        wd = None
        if self.watchdog_timeout > 0:
            # the serve.accept / serve.worker channels beat from the
            # frontend's threads (paused across idle periods)
            wd = health.Watchdog(self.watchdog_timeout,
                                 action=self.watchdog_action).start()
        old_hup = None
        try:
            # SIGHUP = hot reload; the handler only sets a flag
            # (async-signal safety, like PreemptionGuard)
            old_hup = signal.signal(
                signal.SIGHUP, lambda s, f: fe.request_reload())
        except (AttributeError, ValueError, OSError):
            pass                 # no SIGHUP (platform) / not main thread
        stdin_done = threading.Event()

        def pump():
            reply = lambda text: print(text, flush=True)  # noqa: E731
            for line in sys.stdin:
                # wait=True keeps responses in request order — the stdin
                # contract — while still running the full engine path
                fe.submit(line.rstrip("\n"), reply, wait=True)
            stdin_done.set()

        threading.Thread(target=pump, name="cxn-serve-stdin",
                         daemon=True).start()
        try:
            with ckpt.PreemptionGuard() as guard:
                # serve until drain is requested; a stdin EOF ends a
                # pipe-driven run unless TCP clients are being served
                # (then only the signal does — sleep, don't spin)
                while not guard.requested:
                    if stdin_done.is_set() and not fe.listening:
                        break
                    time.sleep(0.1)
                if guard.requested:
                    telemetry.event({"ev": "preempt_signal",
                                     "signum": guard.signum})
                    if not self.silent:
                        print("serve: drain requested (signal %s); "
                              "finishing accepted requests"
                              % guard.signum, file=sys.stderr, flush=True)
        finally:
            stats = fe.drain()
            if wd is not None:
                wd.stop()
            if old_hup is not None:
                try:
                    signal.signal(signal.SIGHUP, old_hup)
                except (ValueError, OSError):
                    pass
        telemetry.event(dict({"ev": "serve_done"}, **stats))
        print("served %d prompts (%d request errors)"
              % (stats["served"], stats["errors"]),
              file=sys.stderr, flush=True)
        if stats["shed"] or stats["deadline"]:
            print("  shed %d, deadline-expired %d (of %d accepted)"
                  % (stats["shed"], stats["deadline"], stats["accepted"]),
                  file=sys.stderr, flush=True)

    def task_route(self) -> None:
        """task = route: the replicated-fleet router (utils/routerd.py,
        doc/serving.md "Replicated serving fleet"). Speaks the exact
        servd line protocol on ``route_port`` and spreads client
        connections over the ``task = serve`` replicas listed in
        ``route_replicas`` (``host:serve_port:status_port``, comma
        separated): health-aware dispatch fed by each replica's statusd
        ``/healthz`` + load gauges, least-loaded power-of-two-choices,
        transparent retry of never-dispatched sheds on another replica
        within the client's remaining DEADLINE budget, dead-replica
        ejection with exponential-backoff re-probe, and fleet-level
        ``ADMIN reload`` (or SIGHUP) rolled across replicas one drain
        window at a time — capacity never drops below N-1. SIGTERM/
        SIGINT drains the router (in-flight routed requests finish,
        counters reconcile, exit 0); replicas are their own processes
        and drain on their own signals."""
        import signal

        from .utils import routerd, servd

        replicas = routerd.parse_replicas(self.route_replicas)
        assert replicas, \
            "task = route needs route_replicas = host:port:status_port[,...]"
        route_tenants = servd.parse_tenants(self.route_tenants)
        if route_tenants and self.serve_tenant_default \
                not in route_tenants:
            route_tenants[self.serve_tenant_default] = 1.0
        router = routerd.Router(
            replicas, probe_ms=self.route_probe_ms,
            retries=self.route_retries, stall_s=self.route_stall_s,
            drain_ms=self.serve_drain_ms,
            flight_cap=self.route_flight_cap,
            federate_ms=self.fleet_federate_ms,
            outlier_ratio=self.fleet_outlier_ratio,
            outlier_min_n=self.fleet_outlier_min_n,
            standby_replicas=self.route_standby_replicas,
            scale_min=self.route_scale_min,
            scale_max=self.route_scale_max,
            scale_up_burn=self.route_scale_up_burn,
            scale_down_idle_s=self.route_scale_down_idle_s,
            scale_cooldown_s=self.route_scale_cooldown_s,
            tenants=self.route_tenants,
            tenant_default=self.serve_tenant_default,
            replay=bool(self.route_replay),
            hedge_ms=self.route_hedge_ms,
            hedge_max_pct=self.route_hedge_max_pct,
            # the router's own per-tenant windows (door sheds): same
            # objectives as the replicas', merged into the federated
            # per-tenant burn account
            slo_tenants={
                t: statusd.SLOTracker(
                    ttft_ms=self.slo_ttft_ms, p99_ms=self.slo_p99_ms,
                    availability=self.slo_availability,
                    window_s=self.slo_window_s)
                for t in route_tenants})
        router.start()
        port = router.listen(self.route_port, host=self.route_host)
        # one synchronous sweep so /fleetz and the first dispatches see
        # probed state, not optimism (a dead replica listed in the conf
        # is ejected before traffic arrives)
        router.probe_now()
        statusd.set_fleet(router)
        statusd.set_slo_tenants(router.slo_tenants)
        # the routing flight ring: /requestz lists every routed
        # request's attempts, /trace?request=<id> stitches the
        # cross-process trace (set_fleet makes /trace prefer the
        # stitched view on this process)
        statusd.set_flight_recorder(router.flight)
        statusd.register_probe("routing", router.health_probe)
        statusd.register_probe("routing.prober", router.liveness_probe,
                               liveness=True)
        if not self.silent:
            up = sum(1 for r in router._replicas
                     if r.state == routerd.UP)
            print("routerd: routing on port %d over %d replicas "
                  "(%d up; servd line protocol — doc/serving.md)"
                  % (port, len(replicas), up), file=sys.stderr,
                  flush=True)
        wd = None
        if self.watchdog_timeout > 0:
            wd = health.Watchdog(self.watchdog_timeout,
                                 action=self.watchdog_action).start()
        # SIGHUP = rolling fleet reload. The handler only sets a flag
        # (request_rolling_reload takes locks — not async-signal-safe);
        # the main loop converts it.
        hup_flag = {"on": False}
        old_hup = None
        try:
            old_hup = signal.signal(
                signal.SIGHUP,
                lambda s, f: hup_flag.update(on=True))
        except (AttributeError, ValueError, OSError):
            pass                 # no SIGHUP (platform) / not main thread
        try:
            with ckpt.PreemptionGuard() as guard:
                while not guard.requested:
                    if hup_flag["on"]:
                        hup_flag["on"] = False
                        if router.request_rolling_reload() \
                                and not self.silent:
                            print("route: rolling fleet reload "
                                  "started (SIGHUP)", file=sys.stderr,
                                  flush=True)
                    time.sleep(0.1)
                telemetry.event({"ev": "preempt_signal",
                                 "signum": guard.signum})
                if not self.silent:
                    print("route: fleet drain requested (signal %s)"
                          % guard.signum, file=sys.stderr, flush=True)
        finally:
            stats = router.drain()
            if wd is not None:
                wd.stop()
            if old_hup is not None:
                try:
                    signal.signal(signal.SIGHUP, old_hup)
                except (ValueError, OSError):
                    pass
        telemetry.event(dict({"ev": "route_done"}, **stats))
        print("routed %d requests (%d served, %d errors, %d shed, "
              "%d deadline, %d retries)"
              % (stats["accepted"], stats["served"], stats["errors"],
                 stats["shed"], stats["deadline"], stats["retries"]),
              file=sys.stderr, flush=True)

    def task_export(self) -> None:
        """task = export: AOT-compile the inference forward (params baked
        in) into a self-contained StableHLO artifact at export_out.
        extract_node_name selects a named node / top[-k] (default: the
        last node, the pred surface); export_batch overrides the batch
        dimension (default batch_size; -1 = symbolic batch, one artifact
        serves any n >= 1). Reload anywhere with
        cxxnet_tpu.api.load_exported — serving needs jax only."""
        blob = self.net_trainer.export_forward(
            node_name=self.extract_node_name,
            batch_size=self.export_batch)
        with open(self.name_export, "wb") as fo:
            fo.write(blob)
        print("exported forward (%d bytes) into %s"
              % (len(blob), self.name_export))


def main(argv: List[str]) -> int:
    return LearnTask().run(argv)
