"""Binary stream serialization, wire-compatible with the reference model files.

Reproduces the reference's utils::IStream helper encodings
(src/utils/io.h:36-103):

* std::string  -> uint64-LE length + raw bytes
* std::vector<T> -> uint64-LE element count + packed elements
* POD structs  -> raw little-endian bytes (we pack with struct)

Tensors: the reference serializes weights with mshadow's
``TensorContainer::SaveBinary`` (e.g. src/layer/fullc_layer-inl.hpp:47-49).
mshadow is an external dependency not vendored in the reference tree, so
bit-compatibility cannot be verified; we use the documented mshadow-1.0 layout:
``int32 ndim`` followed by ``ndim × uint32`` shape dims, then raw float32 data
in row-major order.
"""

from __future__ import annotations

import io
import struct
from typing import BinaryIO, List, Sequence

import numpy as np


class Writer:
    def __init__(self, stream: BinaryIO = None):
        self.f = stream if stream is not None else io.BytesIO()

    def write_raw(self, data: bytes) -> None:
        self.f.write(data)

    def write_int32(self, v: int) -> None:
        self.f.write(struct.pack("<i", v))

    def write_uint32(self, v: int) -> None:
        self.f.write(struct.pack("<I", v))

    def write_uint64(self, v: int) -> None:
        self.f.write(struct.pack("<Q", v))

    def write_float(self, v: float) -> None:
        self.f.write(struct.pack("<f", v))

    def write_string(self, s: str) -> None:
        b = s.encode("utf-8")
        self.write_uint64(len(b))
        self.f.write(b)

    def write_int_vector(self, vec: Sequence[int]) -> None:
        self.write_uint64(len(vec))
        if vec:
            self.f.write(struct.pack("<%di" % len(vec), *vec))

    def write_tensor(self, arr: np.ndarray) -> None:
        arr = np.ascontiguousarray(arr, dtype=np.float32)
        self.write_int32(arr.ndim)
        for d in arr.shape:
            self.write_uint32(d)
        self.f.write(arr.tobytes())

    def getvalue(self) -> bytes:
        return self.f.getvalue()


# sanity bounds for length fields: a corrupt (truncated / bit-flipped)
# header must fail loudly here, not turn into a multi-GB allocation or a
# silently garbage-shaped tensor downstream
_MAX_BLOB = 1 << 40       # 1 TiB: no single field is ever this large
_MAX_TENSOR_NDIM = 32


class Reader:
    def __init__(self, data):
        if isinstance(data, (bytes, bytearray)):
            self.f: BinaryIO = io.BytesIO(data)
        else:
            self.f = data

    def read_raw(self, size: int) -> bytes:
        if size < 0:
            raise ValueError("corrupt model file: negative field size %d"
                             % size)
        b = self.f.read(size)
        if len(b) != size:
            raise EOFError("unexpected end of model file: wanted %d bytes, "
                           "got %d (truncated checkpoint?)" % (size, len(b)))
        return b

    def read_int32(self) -> int:
        return struct.unpack("<i", self.read_raw(4))[0]

    def read_uint32(self) -> int:
        return struct.unpack("<I", self.read_raw(4))[0]

    def read_uint64(self) -> int:
        return struct.unpack("<Q", self.read_raw(8))[0]

    def read_float(self) -> float:
        return struct.unpack("<f", self.read_raw(4))[0]

    def read_string(self) -> str:
        n = self.read_uint64()
        if n > _MAX_BLOB:
            raise ValueError("corrupt model file: string length %d" % n)
        return self.read_raw(n).decode("utf-8")

    def read_int_vector(self) -> List[int]:
        n = self.read_uint64()
        if n == 0:
            return []
        if 4 * n > _MAX_BLOB:
            raise ValueError("corrupt model file: vector length %d" % n)
        return list(struct.unpack("<%di" % n, self.read_raw(4 * n)))

    def read_tensor(self) -> np.ndarray:
        ndim = self.read_int32()
        if not 0 <= ndim <= _MAX_TENSOR_NDIM:
            raise ValueError("corrupt model file: tensor ndim %d" % ndim)
        shape = tuple(self.read_uint32() for _ in range(ndim))
        count = int(np.prod(shape, dtype=np.int64)) if shape else 1
        if 4 * count > _MAX_BLOB:
            raise ValueError("corrupt model file: tensor shape %s" % (shape,))
        data = np.frombuffer(self.read_raw(4 * count), dtype="<f4").copy()
        return data.reshape(shape)

    def at_eof(self) -> bool:
        pos = self.f.tell()
        b = self.f.read(1)
        if b:
            self.f.seek(pos)
            return False
        return True
