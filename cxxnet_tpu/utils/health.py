"""Training-health subsystem: anomaly detection, recovery, watchdog.

A long training run must survive the events that kill or silently corrupt
it in the reference stack: a non-finite loss or gradient (one bad batch,
an overflowing LR), a diverging run (loss spike), a wedged input pipeline
(hung NFS read, dead decode worker), and corrupt data records. Production
frameworks treat all of these as *recoverable* and drive recovery off the
checkpoint machinery (TensorFlow makes user-level checkpoint/restore the
sole fault-tolerance primitive, arxiv 1605.08695 §4.2); PR 2 built the
durable checkpoints, this module makes the stack use them automatically:

* **HealthMonitor** — consumes the per-step health scalars the trainer
  computes INSIDE the jitted step (loss, global grad-norm², non-finite
  gradient element count; nnet/trainer.py ``_make_train_step``). Vectors
  are checked one step LATE: the fetch of step N-1's scalars happens
  after step N was dispatched, so by then the value is resident and the
  host never stalls the device pipeline to look at it. An EMA detector
  additionally flags loss SPIKES (finite divergence). Detected anomalies
  emit ``health_anomaly`` telemetry events.
* **RecoveryPolicy** — the pure-host detect→rollback→skip state machine
  (no jax; ``selftest()`` below simulates it and ``make check`` gates on
  it): on anomaly, roll back to the newest valid checkpoint, quarantine
  the offending (round, batch) window so the replay excludes it
  (``IIterator.skip`` fast-forwards past it), optionally back the LR off
  by ``rollback_backoff`` per retry, and abort with a diagnostic dump
  after ``rollback_max_retries`` consecutive rollbacks.
* **Watchdog** — a daemon thread watching heartbeat channels
  (``beat("train.step")`` from the train loop, ``beat("io.prefetch")``
  from the batch prefetcher): a channel silent past the timeout gets
  all-thread stacks dumped to stderr and a ``watchdog_stall`` telemetry
  event + flush BEFORE any action (``warn``, or ``abort`` = exit code
  70), so a hung run always leaves a diagnosis behind.

learn_task.py wires these behind the conf keys ``health_monitor=1``,
``nonfinite_action=rollback|skip|abort``, ``loss_spike_factor``,
``loss_spike_warmup``, ``rollback_backoff``, ``rollback_max_retries``,
``watchdog_timeout``, ``watchdog_action`` (doc/robustness.md documents
the full recovery state machine and the telemetry events).

This module deliberately imports no jax: the policy logic must be
testable (and ``python -m cxxnet_tpu.utils.health --selftest`` runnable)
on a box with no accelerator stack at all.
"""

from __future__ import annotations

import os
import sys
import threading
import time
import traceback
from collections import deque
from typing import Dict, Optional

import numpy as np

from . import lockrank
from . import telemetry

__all__ = [
    "Anomaly", "TrainingAnomalyError", "HealthMonitor", "RecoveryPolicy",
    "Watchdog", "beat", "pause", "channel_status", "dump_all_stacks",
    "dump_diagnostics", "note_nonfinite", "selftest",
]

# health-vector slot layout, shared with nnet/trainer.py _make_train_step
H_LOSS, H_GNORM_SQ, H_NAN_GRADS, H_OK = 0, 1, 2, 3

# ranked (utils/lockrank.py): anomaly ids are allocated from
# telemetry/watchdog callbacks, so the ordering discipline covers it
_id_lock = lockrank.lock("health.ids")
_next_anomaly_id = [0]


def _new_id() -> int:
    with _id_lock:
        _next_anomaly_id[0] += 1
        return _next_anomaly_id[0]


class Anomaly:
    """One detected training anomaly (which step, what went wrong)."""

    __slots__ = ("id", "kind", "round", "batch", "loss", "grad_norm_sq",
                 "nan_grads")

    def __init__(self, kind: str, round_: int, batch: int, loss: float,
                 grad_norm_sq: float, nan_grads: int):
        self.id = _new_id()
        self.kind = kind
        self.round = int(round_)
        self.batch = int(batch)
        self.loss = float(loss)
        self.grad_norm_sq = float(grad_norm_sq)
        self.nan_grads = int(nan_grads)

    def describe(self) -> str:
        return ("%s at round %d batch %d (loss=%g, grad_norm_sq=%g, "
                "nan_grads=%d)" % (self.kind, self.round, self.batch,
                                   self.loss, self.grad_norm_sq,
                                   self.nan_grads))


class TrainingAnomalyError(RuntimeError):
    """Raised by the train loop when the recovery policy wants a rollback;
    the driver catches it, restores the newest valid checkpoint, and
    re-enters the loop with the offending batch window quarantined."""

    def __init__(self, anomaly: Anomaly):
        super().__init__(anomaly.describe())
        self.anomaly = anomaly


class HealthMonitor:
    """Host-side detector over the per-step health vectors.

    ``observe(round, batch, vec)`` queues the CURRENT step's device vector
    and checks the PREVIOUS one (whose compute has certainly finished by
    the time the next step was dispatched — the ``np.asarray`` fetch never
    introduces a pipeline bubble); ``drain()`` checks whatever is still
    queued (call it before eval/checkpoint so a bad step can never be
    persisted as "good"). Both return the detected :class:`Anomaly` or
    None. Detection identifies the EXACT offending step even though the
    check runs late — the vector is queued with its (round, batch) key.
    """

    def __init__(self, spike_factor: float = 0.0, spike_warmup: int = 20,
                 spike_decay: float = 0.98):
        self.spike_factor = float(spike_factor)
        self.spike_warmup = int(spike_warmup)
        self.spike_decay = float(spike_decay)
        self._pending = deque()
        self._ema = 0.0
        self._nseen = 0
        self.anomaly_count = 0

    def observe(self, round_: int, batch: int, health) -> Optional[Anomaly]:
        if health is None:
            return None
        self._pending.append((round_, batch, health))
        if len(self._pending) > 1:
            return self._check(*self._pending.popleft())
        return None

    def drain(self) -> Optional[Anomaly]:
        while self._pending:
            a = self._check(*self._pending.popleft())
            if a is not None:
                return a
        return None

    def reset_pending(self) -> None:
        """Drop queued vectors (they reference a trainer that a rollback
        is about to discard)."""
        self._pending.clear()

    # ------------------------------------------------------------------
    def _check(self, round_: int, batch: int, health) -> Optional[Anomaly]:
        h = np.asarray(health, np.float32)
        loss = float(h[H_LOSS])
        gn_sq = float(h[H_GNORM_SQ])
        nan_grads = int(h[H_NAN_GRADS])
        if nan_grads > 0:
            # the elements updater _clip_nan silently zeroes (with
            # clip_gradient set) — or that reach the optimizer raw —
            # made visible as a counter instead of vanishing
            telemetry.count("health/nan_grads_zeroed", nan_grads)
        if not (np.isfinite(loss) and np.isfinite(gn_sq)):
            return self._anomaly("nonfinite", round_, batch, loss, gn_sq,
                                 nan_grads)
        if self.spike_factor > 0.0:
            if self._nseen >= self.spike_warmup \
                    and loss > self.spike_factor * max(self._ema, 1e-12):
                return self._anomaly("loss_spike", round_, batch, loss,
                                     gn_sq, nan_grads)
            self._nseen += 1
            self._ema = loss if self._nseen == 1 else (
                self.spike_decay * self._ema
                + (1.0 - self.spike_decay) * loss)
        return None

    def _anomaly(self, kind, round_, batch, loss, gn_sq, nan_grads):
        a = Anomaly(kind, round_, batch, loss, gn_sq, nan_grads)
        self.anomaly_count += 1
        telemetry.count("health/anomalies")
        telemetry.event({"ev": "health_anomaly", "id": a.id, "kind": kind,
                         "round": a.round, "batch": a.batch,
                         "loss": _json_num(loss),
                         "grad_norm_sq": _json_num(gn_sq),
                         "nan_grads": a.nan_grads})
        return a


def _json_num(x: float):
    """NaN/Inf as strings so the JSONL log stays strict-JSON parseable."""
    return float(x) if np.isfinite(x) else repr(float(x))


class RecoveryPolicy:
    """Pure-host state machine mapping anomalies to recovery decisions.

    States: HEALTHY → (anomaly) → one of

    * ``rollback`` — quarantine the offending (round, batch), fold the LR
      backoff into ``lr_scale``, count a retry; the driver restores the
      newest valid checkpoint and replays, skipping quarantined batches.
    * ``skip`` — the trainer's on-device guard already suppressed the
      non-finite update (``nonfinite_action=skip``); nothing to restore.
      Loss spikes are logged only in this mode.
    * ``abort`` — ``nonfinite_action=abort``, or retries exhausted
      (``retries > max_retries``); the driver dumps diagnostics and dies.

    A completed round resets the consecutive-retry counter
    (``on_round_complete``); the quarantine set and ``lr_scale`` persist
    for the rest of the run.
    """

    ACTIONS = ("rollback", "skip", "abort")

    def __init__(self, action: str = "rollback", backoff: float = 1.0,
                 max_retries: int = 2):
        if action not in self.ACTIONS:
            raise ValueError("nonfinite_action must be one of %s, got %r"
                             % ("|".join(self.ACTIONS), action))
        self.action = action
        self.backoff = float(backoff)
        self.max_retries = int(max_retries)
        self.retries = 0          # consecutive rollbacks without a
        #                           completed round
        self.total_rollbacks = 0
        self.lr_scale = 1.0
        self._skip: Dict[int, set] = {}
        # the anomaly currently being recovered from: set by a
        # rollback/abort decision, cleared by resolve() once the driver's
        # restore completes. statusd's /healthz serves 503 while set —
        # the "don't route traffic / don't trust this run" window.
        self.pending: Optional[Anomaly] = None

    def decide(self, anomaly: Anomaly) -> str:
        """'skip' | 'rollback' | 'abort'. A 'rollback' decision has
        already quarantined the offending batch and folded the backoff
        into ``lr_scale`` (apply via Trainer.scale_lr after restoring)."""
        if self.action == "abort":
            self.pending = anomaly
            return "abort"
        if self.action == "skip":
            return "skip"          # suppressed on device: nothing pending
        self.pending = anomaly
        self.retries += 1
        if self.retries > self.max_retries:
            return "abort"
        self.total_rollbacks += 1
        self._skip.setdefault(anomaly.round, set()).add(anomaly.batch)
        if self.backoff != 1.0:
            self.lr_scale *= self.backoff
        return "rollback"

    def resolve(self) -> None:
        """The driver finished recovering (checkpoint restored, replay
        armed): clear the unresolved-anomaly state so /healthz returns to
        200. Aborts never resolve — the endpoint stays 503 for whatever
        scrape catches the dying process."""
        self.pending = None

    def should_skip(self, round_: int, batch: int) -> bool:
        s = self._skip.get(int(round_))
        return s is not None and int(batch) in s

    def skipped(self):
        """The quarantined windows as a JSON-friendly sorted list."""
        return [[r, b] for r in sorted(self._skip)
                for b in sorted(self._skip[r])]

    def on_round_complete(self) -> None:
        self.retries = 0


# ----------------------------------------------------------------------
# watchdog: heartbeat channels + stalled-run stack dumps
_beats: Dict[str, float] = {}
_active_watchdog: Optional["Watchdog"] = None


def beat(channel: str = "train.step") -> None:
    """Heartbeat a liveness channel. No-op unless a Watchdog is running;
    one dict store under the GIL, safe from any thread (the train loop,
    the prefetcher, decode workers)."""
    if _active_watchdog is not None:
        _beats[channel] = time.monotonic()


def pause(channel: str = "train.step") -> None:
    """Disarm a liveness channel for a legitimately-silent phase — the
    round-end eval/checkpoint, the gap between prefetch passes, a long
    first-compile — so the watchdog doesn't false-alarm (or, with
    watchdog_action=abort, kill a healthy run). The next beat() on the
    channel re-arms it. Cheap and safe from any thread."""
    _beats.pop(channel, None)
    wd = _active_watchdog
    if wd is not None:
        wd._fired.pop(channel, None)


def channel_status():
    """Live heartbeat view for statusd: ``[(channel, age_s, timeout_s,
    overdue), ...]`` over every ARMED channel (paused channels are
    legitimately silent and excluded, same as the watchdog's own scan).
    Empty when no watchdog is running — /healthz then has no heartbeat
    opinion at all rather than a stale one."""
    wd = _active_watchdog
    if wd is None:
        return []
    now = time.monotonic()
    return [(ch, now - t, wd.timeout, (now - t) > wd.timeout)
            for ch, t in list(_beats.items())]


def dump_all_stacks(out=None, header: str = "") -> str:
    """Write every thread's current stack to ``out`` (default stderr) —
    the post-mortem a wedged run otherwise never leaves behind."""
    names = {t.ident: t.name + (" [daemon]" if t.daemon else "")
             for t in threading.enumerate()}
    lines = [header] if header else []
    for tid, frame in sorted(sys._current_frames().items()):
        lines.append("--- thread %s (%d) ---" % (names.get(tid, "?"), tid))
        for entry in traceback.format_stack(frame):
            lines.extend(entry.rstrip("\n").splitlines())
    text = "\n".join(lines) + "\n"
    f = out or sys.stderr
    f.write(text)
    try:
        f.flush()
    except Exception:
        pass
    return text


def dump_diagnostics(reason: str, anomaly: Optional[Anomaly] = None,
                     out=None) -> None:
    """The abort path's post-mortem: reason + anomaly + all-thread stacks
    to stderr, telemetry flushed — everything a dying run can still say."""
    f = out or sys.stderr
    f.write("HEALTH ABORT: %s\n" % reason)
    if anomaly is not None:
        f.write("  anomaly: %s\n" % anomaly.describe())
    dump_all_stacks(out=f, header="-- diagnostic all-thread stack dump --")
    try:
        telemetry.flush()
    except Exception:
        pass


class Watchdog:
    """Daemon thread that fires when a heartbeat channel goes silent for
    longer than ``timeout`` seconds.

    Firing means: all-thread stack dump to stderr, ``watchdog_stall``
    telemetry event, telemetry flush — all BEFORE the action. Action
    ``warn`` leaves the process alone (it may recover: a slow NFS read, a
    long GC); ``abort`` exits with code 70 after the dump, the
    hang-converted-to-restartable-death used under a supervisor that
    resumes with ``continue=1``. Each stall fires once; a fresh beat on
    the channel re-arms it. Only channels that have beaten since their
    last ``pause()`` are monitored: call sites disarm across
    legitimately-silent phases (round-end eval/checkpoint, between
    prefetch passes) so those never false-alarm. Size ``timeout`` above
    the worst single-step cost INCLUDING a jit recompile — a mid-round
    recompile is silent time on the step channel like any other.
    """

    def __init__(self, timeout: float, action: str = "warn",
                 poll: Optional[float] = None, on_stall=None):
        if action not in ("warn", "abort"):
            raise ValueError("watchdog_action must be warn|abort, got %r"
                             % action)
        self.timeout = float(timeout)
        self.action = action
        self.poll = poll if poll is not None else \
            max(0.05, min(self.timeout / 4.0, 1.0))
        self.on_stall = on_stall
        self.stalls = 0
        self._fired: Dict[str, float] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "Watchdog":
        global _active_watchdog
        _beats.clear()
        self._stop.clear()
        _active_watchdog = self
        self._thread = threading.Thread(target=self._run,
                                        name="cxn-watchdog", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        global _active_watchdog
        if _active_watchdog is self:
            _active_watchdog = None
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def __enter__(self) -> "Watchdog":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.stop()
        return False

    # ------------------------------------------------------------------
    def _run(self) -> None:
        while not self._stop.wait(self.poll):
            now = time.monotonic()
            for ch, t in list(_beats.items()):
                # fire once per stall: remember the beat timestamp we
                # fired for; any newer beat re-arms the channel
                if now - t > self.timeout and self._fired.get(ch) != t:
                    self._fired[ch] = t
                    self._fire(ch, now - t)

    def _fire(self, channel: str, age: float) -> None:
        self.stalls += 1
        dump_all_stacks(header=(
            "WATCHDOG: channel %r silent for %.2fs (timeout %.2fs) — "
            "all-thread stack dump follows" % (channel, age, self.timeout)))
        telemetry.event({"ev": "watchdog_stall", "channel": channel,
                         "stalled_s": round(age, 3),
                         "timeout_s": self.timeout, "action": self.action})
        telemetry.count("health/watchdog_stalls")
        try:
            telemetry.flush()
        except Exception:
            pass
        if self.on_stall is not None:
            try:
                self.on_stall(channel, age)
            except Exception:
                pass
        if self.action == "abort":
            sys.stderr.write(
                "WATCHDOG: aborting the wedged process (exit code 70); "
                "resume with continue=1\n")
            sys.stderr.flush()
            os._exit(70)


# ----------------------------------------------------------------------
_warned_sites = set()


def note_nonfinite(where: str, count: int = 1) -> None:
    """Route a host-observed non-finite metric/eval value through a
    health event (warn once per site + counter) instead of a hard crash.
    The jit metric path cannot raise on NaN, so the reference's host-only
    ``FloatingPointError`` was an inconsistent contract — both paths now
    surface the same way (utils/metric.py). The emitted anomaly carries
    ``resolution: "warned"`` so tools/telemetry_report.py does not count
    it as an unrecovered training anomaly."""
    telemetry.count("health/nonfinite_metric", count)
    telemetry.event({"ev": "health_anomaly", "id": _new_id(),
                     "kind": "metric_nonfinite", "where": where,
                     "count": int(count), "resolution": "warned"})
    if where not in _warned_sites:
        _warned_sites.add(where)
        sys.stderr.write(
            "WARNING: non-finite value(s) in %s; excluded and counted "
            "(health/nonfinite_metric)\n" % where)


# ----------------------------------------------------------------------
def _sim_vec(loss: float, nan_grads: int = 0):
    gn = float("nan") if not np.isfinite(loss) else 1.0
    ok = 1.0 if np.isfinite(loss) else 0.0
    return np.asarray([loss, gn, float(nan_grads), ok], np.float32)


def selftest(verbose: bool = False) -> int:
    """Pure-host simulation of the detect→rollback→skip state machine —
    no jax, no net; ``make check`` gates on it.

    The simulated "trainer" state is the list of (round, batch) updates
    applied; a checkpoint is a copy of that list at each round boundary,
    exactly like learn_task's save schedule. Bad batches yield non-finite
    (or spiking) health vectors through the real HealthMonitor and
    RecoveryPolicy, and the assertions pin the recovery contract: the
    final state equals a clean run with the bad batches excluded, the LR
    backoff compounds per rollback, and retries exhaust into abort.
    """

    class _Roll(Exception):
        pass

    class _Abort(Exception):
        pass

    def run(bad, action="rollback", backoff=1.0, max_retries=2,
            spike=0.0, rounds=3, batches=4):
        mon = HealthMonitor(spike_factor=spike, spike_warmup=1)
        pol = RecoveryPolicy(action=action, backoff=backoff,
                             max_retries=max_retries)
        state = []
        ckpts = {0: []}              # learn_task saves round 0's start too

        def decide(a):
            d = pol.decide(a)
            if d == "abort":
                raise _Abort(a.describe())
            if d == "rollback":
                raise _Roll()
            # 'skip': on-device guard already suppressed it — undo the
            # simulated application the way jnp.where(ok, new, old) does
            state.remove((a.round, a.batch))

        r = 0
        try:
            while r < rounds:
                try:
                    b = 0
                    while b < batches:
                        if pol.should_skip(r, b):
                            b += 1
                            continue
                        is_bad = (r, b) in bad
                        state.append((r, b))
                        loss = (100.0 if spike else float("nan")) \
                            if is_bad else 1.0
                        a = mon.observe(r, b, _sim_vec(loss,
                                                       3 if is_bad else 0))
                        if a is not None:
                            decide(a)
                        b += 1
                    a = mon.drain()
                    if a is not None:
                        decide(a)
                except _Roll:
                    mon.reset_pending()
                    r = max(ckpts)
                    state = list(ckpts[r])
                    continue
                pol.on_round_complete()
                r += 1
                ckpts[r] = list(state)
        except _Abort:
            return state, pol, True
        return state, pol, False

    clean = [(r, b) for r in range(3) for b in range(4)]

    # 1. no anomalies: nothing skipped, nothing rolled back
    state, pol, aborted = run(bad=set())
    assert state == clean and not aborted and pol.total_rollbacks == 0

    # 2. one non-finite batch: rollback + replay excludes exactly it
    state, pol, aborted = run(bad={(1, 2)}, backoff=0.5)
    assert state == [x for x in clean if x != (1, 2)], state
    assert not aborted and pol.total_rollbacks == 1
    assert abs(pol.lr_scale - 0.5) < 1e-12

    # 3. two bad batches in one round: two rollbacks, both excluded,
    #    backoff compounds
    state, pol, aborted = run(bad={(1, 1), (1, 3)}, backoff=0.5)
    assert state == [x for x in clean if x not in ((1, 1), (1, 3))]
    assert pol.total_rollbacks == 2 and abs(pol.lr_scale - 0.25) < 1e-12

    # 4. loss spike drives the same machinery
    state, pol, aborted = run(bad={(2, 0)}, spike=5.0, backoff=0.5)
    assert state == [x for x in clean if x != (2, 0)] and not aborted
    assert pol.total_rollbacks == 1

    # 5. every batch bad: retries exhaust into abort
    state, pol, aborted = run(bad={(0, b) for b in range(4)},
                              max_retries=2)
    assert aborted and pol.retries == 3

    # 6. skip mode: no rollbacks, bad updates suppressed in place
    state, pol, aborted = run(bad={(0, 1), (2, 2)}, action="skip")
    assert state == [x for x in clean if x not in ((0, 1), (2, 2))]
    assert not aborted and pol.total_rollbacks == 0

    # 7. abort mode dies on first anomaly
    state, pol, aborted = run(bad={(0, 0)}, action="abort")
    assert aborted

    if verbose:
        print("health selftest: detect/rollback/skip state machine ok "
              "(7 scenarios)")
    return 0


if __name__ == "__main__":
    if "--selftest" in sys.argv[1:]:
        sys.exit(selftest(verbose=True))
    print(__doc__)
    sys.exit(1)
