"""Live introspection service: /metrics, /healthz, /statusz, /trace.

Everything telemetry (PR 1) and the health watchdog (PR 3) record was
post-mortem — JSONL logs and end-of-run summaries nobody can see while a
multi-hour training job or a ``task = serve`` loop is actually running.
Production systems treat pull-based live monitoring as first-class runtime
instrumentation (TF's system paper, arxiv 1605.08695); this module is that
surface: a stdlib-only ``http.server`` on a daemon thread, enabled by the
conf key ``status_port=<p>`` (port 0 = ephemeral, printed at startup; the
learn-task driver starts it for every task including serve).

Endpoints:

* ``/metrics`` — Prometheus text format (scrapable): every telemetry
  counter as a ``_total`` series, gauges, and the fixed-bucket latency
  histograms (``telemetry.HIST_BUCKETS``) as ``_seconds_bucket{le=...}``
  series — step time, io wait, h2d, per-request serve latency. All series
  carry a ``process`` label so a multihost scrape attributes shards.
* ``/healthz`` — READINESS: 200 while the process should receive traffic
  / be trusted, 503 while a heartbeat channel is overdue
  (``health.channel_status``) or ANY registered probe fails — the learn
  task wires the RecoveryPolicy's unresolved-anomaly state here (a
  rollback in flight flips it until recovery completes), and the serving
  frontend (utils/servd.py) wires its draining / circuit-breaker-open
  state. The k8s readiness-probe contract.
* ``/livez`` — LIVENESS: 503 only when the process itself is broken — an
  overdue heartbeat (hang) or a probe registered with ``liveness=True``
  (e.g. a dead serve worker thread). A draining or breaker-open server
  is NOT ready but IS alive: /healthz 503, /livez 200 — so a supervisor
  stops routing without restarting a process that is shutting down
  cleanly. The k8s liveness-probe contract.
* ``/statusz`` — the human page: run config, round/batch progress,
  step-time p50/p90/p99, recompile count and causes, checkpoint age,
  device-memory gauges, counters, health detail.
* ``/trace`` — a Chrome-trace JSON snapshot of the recent-event ring
  buffer (load in chrome://tracing or ui.perfetto.dev) — the last ~4096
  events of a LIVE run, no log file needed.

The server binds in ``start()`` (so ``status_port=0`` resolves to a real
port before the run begins), serves each request on its own thread
(ThreadingHTTPServer), and reads only snapshot copies of telemetry state
(``metrics_snapshot`` takes the registry lock once per scrape) — a scrape
never blocks the train loop beyond one lock acquisition. Binds loopback
by default (the endpoints expose run config and event detail,
unauthenticated); set ``status_host=0.0.0.0`` to let a Prometheus server
on another machine scrape.

Deliberately jax-free (like health.py): ``python -m
cxxnet_tpu.utils.statusd --selftest`` serves, scrapes, and validates on a
box with no accelerator stack; ``make check`` gates on it.
"""

from __future__ import annotations

import html
import json
import re
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, List, Optional, Tuple

from . import health as health_mod
from . import telemetry

__all__ = [
    "StatusServer", "start", "stop", "active", "set_run_info",
    "update_progress", "register_probe", "wire_health",
    "prometheus_metrics", "PROM_LINE_RE", "selftest",
]

_NAME_SAN = re.compile(r"[^a-zA-Z0-9_]")

# one exposition line: metric name, optional {label="value",...}, value.
# Shared with tests — the validity contract /metrics promises scrapers.
PROM_LINE_RE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*'
    r'(\{[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\\n]|\\.)*"'
    r'(,[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\\n]|\\.)*")*\})?'
    r' (?:[-+]?[0-9]*\.?[0-9]+(?:[eE][-+]?[0-9]+)?|\+Inf|-Inf|NaN)$')


def _mname(name: str) -> str:
    """Telemetry name -> Prometheus metric name (``train.step`` ->
    ``cxxnet_train_step``)."""
    n = _NAME_SAN.sub("_", str(name))
    if n and n[0].isdigit():
        n = "_" + n
    return "cxxnet_" + n


def _lesc(value: str) -> str:
    """Prometheus label-value escaping (backslash, quote, newline)."""
    return str(value).replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


def _num(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def prometheus_metrics(snapshot: dict, progress: Optional[dict] = None,
                       health_failures: Optional[list] = None,
                       channels: Optional[list] = None,
                       live_failures: Optional[list] = None) -> str:
    """Render a ``telemetry.metrics_snapshot()`` as Prometheus text
    exposition format 0.0.4. Pure function of its inputs — the selftest
    and tests validate its output without a socket. ``channels`` is the
    heartbeat snapshot the caller derived ``health_failures`` from, so
    one scrape can never contradict itself (healthy gauge vs overdue
    heartbeat ages from two different instants)."""
    p = str(snapshot.get("process", 0))
    base = '{process="%s"}' % _lesc(p)
    out: List[str] = []

    def emit(name, mtype, value, labels=base, help_=None):
        if help_:
            out.append("# HELP %s %s" % (name, help_))
        out.append("# TYPE %s %s" % (name, mtype))
        out.append("%s%s %s" % (name, labels, _fmt(value)))

    def _fmt(v):
        if isinstance(v, float):
            if v != v:
                return "NaN"
            if v == float("inf"):
                return "+Inf"
            if v == float("-inf"):
                return "-Inf"
            return repr(v)
        return str(v)

    emit("cxxnet_up", "gauge", 1,
         help_="1 while the introspection service is serving")
    emit("cxxnet_uptime_seconds", "gauge",
         round(float(snapshot.get("uptime_s", 0.0)), 3))
    emit("cxxnet_compiles_total", "counter", int(snapshot.get("compiles", 0)),
         help_="jit recompiles detected since run start")
    emit("cxxnet_compile_seconds_total", "counter",
         float(snapshot.get("compile_s", 0.0)))
    if health_failures is not None:
        emit("cxxnet_healthy", "gauge", 0 if health_failures else 1,
             help_="1 when /healthz (readiness) returns 200")
    if live_failures is not None:
        emit("cxxnet_live", "gauge", 0 if live_failures else 1,
             help_="1 when /livez (liveness) returns 200")
    if channels is None:
        channels = health_mod.channel_status()
    if channels:
        # ONE TYPE line for the whole family (the exposition spec allows
        # one per metric name; the channels are label values)
        out.append("# TYPE cxxnet_heartbeat_age_seconds gauge")
        for ch, age, timeout, overdue in channels:
            out.append(
                'cxxnet_heartbeat_age_seconds{process="%s",channel="%s"}'
                ' %s' % (_lesc(p), _lesc(ch), _fmt(round(age, 3))))
    for key in ("round", "num_round", "batch", "served", "errors",
                "shed", "deadline"):
        v = (progress or {}).get(key)
        if _num(v):
            emit("cxxnet_progress_" + key, "gauge", v)
    for name, v in sorted(snapshot.get("counters", {}).items()):
        if _num(v):
            emit(_mname(name) + "_total", "counter", v)
    for name, v in sorted(snapshot.get("gauges", {}).items()):
        if _num(v):
            emit(_mname(name), "gauge", v)
    for name, h in sorted(snapshot.get("hists", {}).items()):
        mname = _mname(name) + "_seconds"
        out.append("# TYPE %s histogram" % mname)
        counts = {int(i): int(c) for i, c in
                  (h.get("buckets") or {}).items()}
        cum = 0
        for i, le in enumerate(telemetry.HIST_BUCKETS):
            cum += counts.get(i, 0)
            out.append('%s_bucket{process="%s",le="%g"} %d'
                       % (mname, _lesc(p), le, cum))
        total = int(h.get("count", 0))
        out.append('%s_bucket{process="%s",le="+Inf"} %d'
                   % (mname, _lesc(p), total))
        out.append('%s_sum%s %s' % (mname, base,
                                    _fmt(float(h.get("sum", 0.0)))))
        out.append('%s_count%s %d' % (mname, base, total))
    return "\n".join(out) + "\n"


class _HTTPServer(ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True
    statusd: "StatusServer"


class _Endpoint(BaseHTTPRequestHandler):
    server_version = "cxxnet-statusd/1"
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):   # quiet: no per-scrape stderr spam
        pass

    def _reply(self, code: int, ctype: str, body: bytes) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):   # noqa: N802 (BaseHTTPRequestHandler contract)
        srv = self.server.statusd
        path = self.path.split("?", 1)[0]
        try:
            if path == "/metrics":
                self._reply(200, "text/plain; version=0.0.4; charset=utf-8",
                            srv.metrics_text().encode("utf-8"))
            elif path == "/healthz":
                fails = srv.health_failures()
                if fails:
                    body = "unhealthy\n" + "".join(
                        "%s: %s\n" % (n, d) for n, d in fails)
                    self._reply(503, "text/plain; charset=utf-8",
                                body.encode("utf-8"))
                else:
                    self._reply(200, "text/plain; charset=utf-8", b"ok\n")
            elif path == "/livez":
                fails = srv.health_failures(liveness_only=True)
                if fails:
                    body = "dead\n" + "".join(
                        "%s: %s\n" % (n, d) for n, d in fails)
                    self._reply(503, "text/plain; charset=utf-8",
                                body.encode("utf-8"))
                else:
                    self._reply(200, "text/plain; charset=utf-8",
                                b"alive\n")
            elif path in ("/", "/statusz"):
                self._reply(200, "text/html; charset=utf-8",
                            srv.statusz_html().encode("utf-8"))
            elif path == "/trace":
                trace = telemetry.events_to_chrome(
                    srv.registry.recent_events())
                self._reply(200, "application/json",
                            json.dumps(trace).encode("utf-8"))
            else:
                self._reply(404, "text/plain; charset=utf-8",
                            b"not found; endpoints: /metrics /healthz "
                            b"/livez /statusz /trace\n")
        except Exception as e:    # a broken probe must not kill the server
            try:
                self._reply(500, "text/plain; charset=utf-8",
                            ("internal error: %r\n" % e).encode("utf-8"))
            except Exception:
                pass


class StatusServer:
    """The live-introspection HTTP server. Construct + ``start()`` binds
    a daemon thread; ``stop()`` shuts it down. One per process (the
    module-level ``start``/``stop`` manage the singleton the learn task
    uses); tests build isolated instances against private registries."""

    def __init__(self, port: int = 0, host: str = "",
                 registry=None):
        self.registry = registry if registry is not None else telemetry._REG
        self.run_info: Dict[str, object] = {}
        self.progress: Dict[str, object] = {}
        # (name, probe_fn, liveness): see register_probe
        self.probes: List[Tuple[str, Callable[[], Tuple[bool, str]],
                                bool]] = []
        # loopback by default: /statusz exposes the full run config (data
        # and model paths included), so wide exposure is OPT-IN —
        # status_host=0.0.0.0 for a cross-host Prometheus scrape
        self._httpd = _HTTPServer((host or "127.0.0.1", int(port)),
                                  _Endpoint)
        self._httpd.statusd = self
        self.host = self._httpd.server_address[0]
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None
        self.t0_wall = time.time()

    # -- lifecycle -----------------------------------------------------
    def start(self) -> "StatusServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="cxn-statusd",
            daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def __enter__(self) -> "StatusServer":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.stop()
        return False

    # -- wiring --------------------------------------------------------
    def register_probe(self, name: str,
                       fn: Callable[[], Tuple[bool, str]],
                       liveness: bool = False) -> None:
        """``fn() -> (ok, detail)``; a False (or raising) probe flips
        /healthz (readiness) to 503 with the detail in the body.
        ``liveness=True`` probes additionally flip /livez — reserve those
        for "restart me" conditions (dead thread), not "don't route to
        me" ones (draining, breaker open, rollback in flight)."""
        self.probes.append((name, fn, bool(liveness)))

    def wire_health(self, recovery=None) -> None:
        """Wire the standard health sources: the watchdog heartbeat
        channels are always consulted (health.channel_status); a
        RecoveryPolicy adds the unresolved-anomaly probe — 503 from the
        moment an anomaly decides rollback/abort until the driver calls
        ``recovery.resolve()`` after the restore."""
        if recovery is not None:
            def _probe():
                a = recovery.pending
                if a is None:
                    return True, "no unresolved anomaly"
                return False, "unresolved anomaly: " + a.describe()
            self.register_probe("anomaly", _probe)

    def all_failures(self, channels: Optional[list] = None) \
            -> Tuple[List[Tuple[str, str]], List[Tuple[str, str]]]:
        """ONE evaluation of every heartbeat channel and probe ->
        ``(readiness_failures, liveness_failures)`` — so a scrape that
        needs both views (the cxxnet_healthy and cxxnet_live gauges)
        runs each probe once and the two lists can never disagree about
        a single evaluation. An overdue heartbeat fails BOTH: a hung
        process is neither routable nor worth keeping; probe failures
        are readiness-only unless registered with ``liveness=True``."""
        if channels is None:
            channels = health_mod.channel_status()
        ready: List[Tuple[str, str]] = []
        live: List[Tuple[str, str]] = []
        for ch, age, timeout, overdue in channels:
            if overdue:
                f = ("watchdog:" + ch,
                     "heartbeat silent %.2fs (timeout %.2fs)"
                     % (age, timeout))
                ready.append(f)
                live.append(f)
        for name, fn, liveness in list(self.probes):
            try:
                ok, detail = fn()
            except Exception as e:
                ok, detail = False, "probe raised: %r" % e
            if not ok:
                ready.append((name, detail))
                if liveness:
                    live.append((name, detail))
        return ready, live

    def health_failures(self, channels: Optional[list] = None,
                        liveness_only: bool = False) \
            -> List[Tuple[str, str]]:
        """Readiness failures by default; ``liveness_only=True`` gives
        the /livez view (overdue heartbeats + liveness probes)."""
        ready, live = self.all_failures(channels)
        return live if liveness_only else ready

    # -- renderers -----------------------------------------------------
    def metrics_text(self) -> str:
        # ONE heartbeat snapshot and ONE probe pass per scrape: the
        # healthy/live gauges and the per-channel age rows must agree
        # within a single response
        channels = health_mod.channel_status()
        ready, live = self.all_failures(channels)
        return prometheus_metrics(
            self.registry.metrics_snapshot(),
            progress=dict(self.progress),
            health_failures=ready,
            channels=channels,
            live_failures=live)

    def statusz_html(self) -> str:
        reg = self.registry
        snap = reg.metrics_snapshot()
        s = reg.summary()
        esc = html.escape
        parts = ["<html><head><title>cxxnet statusz</title></head>"
                 "<body><h1>cxxnet_tpu statusz</h1>"]

        def table(title, rows):
            if not rows:
                return
            parts.append("<h2>%s</h2><pre>" % esc(title))
            w = max(len(str(k)) for k, _ in rows)
            for k, v in rows:
                parts.append("%-*s  %s" % (w, esc(str(k)), esc(str(v))))
            parts.append("</pre>")

        info = [(k, v) for k, v in self.run_info.items() if k != "config"]
        info.append(("uptime", "%.1fs" % snap["uptime_s"]))
        info.append(("process", snap["process"]))
        info.append(("started", time.strftime(
            "%Y-%m-%d %H:%M:%S", time.localtime(self.t0_wall))))
        table("run", info)
        prog = sorted(self.progress.items())
        table("progress", prog)

        channels = health_mod.channel_status()
        fails, live_fails = self.all_failures(channels)
        rows = [("healthz (ready)", "503 UNHEALTHY" if fails
                 else "200 ok"),
                ("livez (alive)", "503 DEAD" if live_fails
                 else "200 alive")]
        rows += [("probe " + n, d) for n, d in fails]
        for ch, age, timeout, overdue in channels:
            rows.append(("heartbeat " + ch, "%.2fs ago (timeout %.1fs)%s"
                         % (age, timeout, " OVERDUE" if overdue else "")))
        table("health", rows)

        ck = reg.last_event("ckpt_save")
        if ck is not None and "ts" in ck:
            table("checkpoint", [
                ("last save", ck.get("path", "?")),
                ("age", "%.1fs" % (snap["uptime_s"] - ck["ts"])),
                ("bytes", ck.get("bytes", "?"))])

        hist_rows = []
        for name, a in sorted(s.get("hists", {}).items(),
                              key=lambda kv: -kv[1]["sum_s"]):
            hist_rows.append((name, "n=%d p50=%.2fms p90=%.2fms p99=%.2fms"
                              % (a["count"], a["p50_ms"], a["p90_ms"],
                                 a["p99_ms"])))
        table("latency histograms", hist_rows)

        comp = s.get("compiles", {})
        if comp.get("count"):
            table("recompiles", [("count", comp["count"]),
                                 ("total_s", comp["total_s"])] +
                  sorted(comp.get("by_cause", {}).items()))
        table("counters", sorted(snap["counters"].items()))
        table("gauges", sorted(snap["gauges"].items()))

        cfg = self.run_info.get("config")
        if cfg:
            parts.append("<details><summary>config (%d keys)</summary><pre>"
                         % len(cfg))
            for k, v in cfg:
                parts.append("%s = %s" % (esc(str(k)), esc(str(v))))
            parts.append("</pre></details>")
        parts.append("<p>endpoints: <a href='/metrics'>/metrics</a> "
                     "<a href='/healthz'>/healthz</a> "
                     "<a href='/trace'>/trace</a></p></body></html>")
        return "\n".join(parts)


# ----------------------------------------------------------------------
# module-level singleton surface (the learn-task wiring); every function
# is a cheap no-op while no server is running, so instrumented call
# sites (per-batch progress updates) cost one attribute test by default
_SERVER: Optional[StatusServer] = None


def start(port: int = 0, host: str = "", registry=None) -> StatusServer:
    global _SERVER
    stop()
    _SERVER = StatusServer(port, host=host, registry=registry).start()
    return _SERVER


def stop() -> None:
    global _SERVER
    if _SERVER is not None:
        s, _SERVER = _SERVER, None
        s.stop()


def active() -> Optional[StatusServer]:
    return _SERVER


def set_run_info(**kv) -> None:
    s = _SERVER
    if s is not None:
        s.run_info.update(kv)


def update_progress(**kv) -> None:
    s = _SERVER
    if s is not None:
        s.progress.update(kv)


def register_probe(name: str, fn, liveness: bool = False) -> None:
    s = _SERVER
    if s is not None:
        s.register_probe(name, fn, liveness=liveness)


def wire_health(recovery=None) -> None:
    s = _SERVER
    if s is not None:
        s.wire_health(recovery)


# ----------------------------------------------------------------------
def selftest(verbose: bool = False) -> int:
    """Serve on port 0, scrape every endpoint over a real socket,
    validate the Prometheus text format, flip /healthz with a failing
    probe, shut down. Jax-free; ``make check`` gates on it."""
    from urllib.request import urlopen
    from urllib.error import HTTPError

    reg = telemetry._Registry()
    reg.enable()                       # in-memory sink
    with reg.span("selftest.step"):
        time.sleep(0.001)
    reg.count("selftest.requests", 3)
    reg.gauge("selftest.level", 7)
    reg.hist("selftest.latency", 0.012)

    srv = StatusServer(0, host="127.0.0.1", registry=reg).start()
    try:
        base = "http://127.0.0.1:%d" % srv.port

        metrics = urlopen(base + "/metrics", timeout=5).read().decode()
        for line in metrics.splitlines():
            if not line or line.startswith("#"):
                continue
            assert PROM_LINE_RE.match(line), \
                "invalid Prometheus line: %r" % line
        assert "cxxnet_selftest_requests_total" in metrics
        assert 'cxxnet_selftest_step_seconds_bucket' in metrics
        assert 'le="+Inf"' in metrics

        assert urlopen(base + "/healthz", timeout=5).status == 200
        assert urlopen(base + "/livez", timeout=5).status == 200
        srv.register_probe("boom", lambda: (False, "injected failure"))
        try:
            urlopen(base + "/healthz", timeout=5)
            raise AssertionError("healthz should be 503")
        except HTTPError as e:
            assert e.code == 503
            assert "injected failure" in e.read().decode()
        # a readiness failure is NOT a liveness failure: /livez stays 200
        assert urlopen(base + "/livez", timeout=5).status == 200
        m = urlopen(base + "/metrics", timeout=5).read().decode()
        assert 'cxxnet_healthy{process="0"} 0' in m
        assert 'cxxnet_live{process="0"} 1' in m
        srv.register_probe("dead", lambda: (False, "worker died"),
                           liveness=True)
        try:
            urlopen(base + "/livez", timeout=5)
            raise AssertionError("livez should be 503")
        except HTTPError as e:
            assert e.code == 503
            assert "worker died" in e.read().decode()
        srv.probes.clear()

        page = urlopen(base + "/statusz", timeout=5).read().decode()
        assert "statusz" in page and "selftest.requests" in page
        trace = json.loads(urlopen(base + "/trace", timeout=5).read())
        assert any(t.get("ph") == "X" for t in trace["traceEvents"])

        try:
            urlopen(base + "/nope", timeout=5)
            raise AssertionError("unknown path should 404")
        except HTTPError as e:
            assert e.code == 404
    finally:
        srv.stop()
        reg.disable()
    if verbose:
        print("statusd selftest: /metrics /healthz /livez /statusz "
              "/trace ok (Prometheus format valid, readiness vs liveness "
              "flips, 404)")
    return 0


if __name__ == "__main__":
    if "--selftest" in sys.argv[1:]:
        sys.exit(selftest(verbose=True))
    print(__doc__)
    sys.exit(1)
