"""Live introspection service: /metrics, /healthz, /statusz, /trace.

Everything telemetry (PR 1) and the health watchdog (PR 3) record was
post-mortem — JSONL logs and end-of-run summaries nobody can see while a
multi-hour training job or a ``task = serve`` loop is actually running.
Production systems treat pull-based live monitoring as first-class runtime
instrumentation (TF's system paper, arxiv 1605.08695); this module is that
surface: a stdlib-only ``http.server`` on a daemon thread, enabled by the
conf key ``status_port=<p>`` (port 0 = ephemeral, printed at startup; the
learn-task driver starts it for every task including serve).

Endpoints:

* ``/metrics`` — Prometheus text format (scrapable): every telemetry
  counter as a ``_total`` series, gauges, and the fixed-bucket latency
  histograms (``telemetry.HIST_BUCKETS``) as ``_seconds_bucket{le=...}``
  series — step time, io wait, h2d, per-request serve latency. All series
  carry a ``process`` label so a multihost scrape attributes shards.
  ``?json=1`` returns the RAW registry snapshot plus the SLO window —
  exact bucket counts, the fleet router's federation feed
  (utils/routerd.py ``federate_now``: the merge stays bucket-count
  addition with no text-format round trip).
* ``/healthz`` — READINESS: 200 while the process should receive traffic
  / be trusted, 503 while a heartbeat channel is overdue
  (``health.channel_status``) or ANY registered probe fails — the learn
  task wires the RecoveryPolicy's unresolved-anomaly state here (a
  rollback in flight flips it until recovery completes), and the serving
  frontend (utils/servd.py) wires its draining / circuit-breaker-open
  state. The k8s readiness-probe contract.
* ``/livez`` — LIVENESS: 503 only when the process itself is broken — an
  overdue heartbeat (hang) or a probe registered with ``liveness=True``
  (e.g. a dead serve worker thread). A draining or breaker-open server
  is NOT ready but IS alive: /healthz 503, /livez 200 — so a supervisor
  stops routing without restarting a process that is shutting down
  cleanly. The k8s liveness-probe contract.
* ``/statusz`` — the human page: run config, round/batch progress,
  step-time p50/p90/p99, recompile count and causes, checkpoint age,
  device-memory gauges, counters, health detail.
* ``/trace`` — a Chrome-trace JSON snapshot of the recent-event ring
  buffer (load in chrome://tracing or ui.perfetto.dev) — the last ~4096
  events of a LIVE run, no log file needed. With a flight recorder
  registered (the serving frontend's per-request ring),
  ``/trace?request=<id>`` instead returns ONE request's phase-attributed
  Chrome trace (queue_wait / dispatch / prefill / decode + the
  recompiles it paid) — open a single slow request in Perfetto. On a
  ROUTER process (``set_fleet``) the same query returns the STITCHED
  cross-process trace: the router's attempt lane plus every touched
  replica's phase lanes, fetched live and aligned on the shared wall
  epoch (utils/routerd.py ``stitched_trace``).
* ``/requestz`` — the flight recorder's ring, newest first: request
  id, outcome, phase split (or a router's attempt list), TTFT, tokens
  — the index you grab a ``/trace?request=<id>`` id from. HTML by
  default with ``?json=1`` for the raw snapshot (the /fleetz and
  /programz contract), ``?n=<k>`` bounds the listing, and
  ``?request=<id>`` returns ONE raw record — the feed the fleet
  router's cross-process trace stitch reads from each replica.
* ``/programz`` — the program performance ledger (utils/perf.py): one
  row per compiled program — shapes signature, XLA FLOPs, per-device
  peak bytes, compile seconds, roofline-predicted vs measured p50/p99
  time, MFU% — plus the HBM peak/headroom account. ``?json=1`` returns
  the raw snapshot.
* ``/profilez?secs=N`` — start an on-demand ``jax.profiler`` trace
  capture of the next N seconds into the run-scoped ``profilez_dir``
  (one capture at a time — a concurrent request gets 409), so a live
  slow replica can be xprof'd without restarting it. Loopback-bound
  like every other endpoint unless ``status_host`` widens the bind.
* ``/fleetz`` — the serving fleet's routing table (utils/routerd.py,
  registered by ``task = route``): one row per replica — state machine
  (up / draining / breaker_open / dead), load gauges, ejection backoff
  — plus the router's counters and the rolling-reload drain windows.
  ``?json=1`` returns the raw snapshot; /metrics exports the same
  account as the ``cxxnet_fleet_*`` series.
* ``/why?request=<id>`` — one request's slowdown AUTOPSY
  (utils/autopsy.py): its wall time decomposed into named causes
  (queue_wait / compile_stall / convoy_victim / kv_defer /
  eviction_storm / hedge_replay / slow_replica / decode_baseline) with
  seconds attributed to each and exactly ONE primary verdict. On a
  router process the verdict is stitched CROSS-PROCESS: the winning
  replica's own books refine the attempt latency lane, ``slow_replica``
  absorbing what they cannot account for. ``?json=1`` for the raw
  payload.
* ``/eventz`` — the fleet incident timeline: every transition-only
  event stream (decode convoy, KV pressure, SLO burn, fleet outliers,
  breaker, scale/reload/drain, broken books) merged into ONE
  wall-clock-aligned list of begin/end/point rows, each begin row
  carrying the requests whose autopsies cite its episode. On a router
  the timeline federates every replica's own feed under one clock.
  ``?json=1`` raw rows, ``?n=<k>`` newest rows.

Serving SLOs: an ``SLOTracker`` (objectives ``slo_ttft_ms`` /
``slo_p99_ms`` / ``slo_availability`` over a rolling window) turns each
completed request into an error-budget account: a request that errored
or blew a latency objective burns budget, and the burn RATE —
bad_fraction / (1 - availability) — is exported as
``cxxnet_slo_burn_rate`` with the alert gauge ``cxxnet_slo_burn``
flipping to 1 while the budget burns faster than 1x sustainable
(rendered on ``/statusz``, transition events in the telemetry log for
tools/telemetry_report.py's exit-2 gate).

The server binds in ``start()`` (so ``status_port=0`` resolves to a real
port before the run begins), serves each request on its own thread
(ThreadingHTTPServer), and reads only snapshot copies of telemetry state
(``metrics_snapshot`` takes the registry lock once per scrape) — a scrape
never blocks the train loop beyond one lock acquisition. Binds loopback
by default (the endpoints expose run config and event detail,
unauthenticated); set ``status_host=0.0.0.0`` to let a Prometheus server
on another machine scrape.

Deliberately jax-free (like health.py): ``python -m
cxxnet_tpu.utils.statusd --selftest`` serves, scrapes, and validates on a
box with no accelerator stack; ``make check`` gates on it.
"""

from __future__ import annotations

import html
import json
import re
import sys
import threading
import time
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, List, Optional, Tuple
from urllib.parse import parse_qs

from . import autopsy
from . import health as health_mod
from . import lockrank
from . import telemetry

__all__ = [
    "StatusServer", "SLOTracker", "start", "stop", "active",
    "set_run_info", "update_progress", "register_probe", "wire_health",
    "set_flight_recorder", "set_slo", "set_slo_tenants", "set_perf",
    "set_profiler", "set_batch",
    "set_fleet", "set_auditor",
    "prometheus_metrics", "programz_html", "fleetz_html",
    "requestz_html", "batchz_html", "why_html", "eventz_html",
    "ENDPOINTS", "PROM_LINE_RE", "selftest",
]

# Every endpoint the handler dispatches, with its query contract:
# (path, takes ?json=1, takes ?n=<k>). The 404 page and the
# parametrized endpoint-contract test both derive from THIS table, so
# an endpoint cannot ship without declaring (and honoring) its flags.
ENDPOINTS: Tuple[Tuple[str, bool, bool], ...] = (
    ("/metrics", True, False),
    ("/healthz", False, False),
    ("/livez", False, False),
    ("/statusz", False, False),
    ("/trace", False, False),
    ("/requestz", True, True),
    ("/batchz", True, True),
    ("/programz", True, True),
    ("/compilez", True, True),
    ("/profilez", False, False),
    ("/fleetz", True, True),
    ("/why", True, False),
    ("/eventz", True, True),
)

_NAME_SAN = re.compile(r"[^a-zA-Z0-9_]")

# one exposition line: metric name, optional {label="value",...}, value.
# Shared with tests — the validity contract /metrics promises scrapers.
PROM_LINE_RE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*'
    r'(\{[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\\n]|\\.)*"'
    r'(,[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\\n]|\\.)*")*\})?'
    r' (?:[-+]?[0-9]*\.?[0-9]+(?:[eE][-+]?[0-9]+)?|\+Inf|-Inf|NaN)$')


def _mname(name: str) -> str:
    """Telemetry name -> Prometheus metric name (``train.step`` ->
    ``cxxnet_train_step``)."""
    n = _NAME_SAN.sub("_", str(name))
    if n and n[0].isdigit():
        n = "_" + n
    return "cxxnet_" + n


def _lesc(value: str) -> str:
    """Prometheus label-value escaping (backslash, quote, newline)."""
    return str(value).replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


def _num(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


# the shared empty-series-sentinel renderer (None -> "n/a")
_ms = telemetry.fmt_ms


class SLOTracker:
    """Rolling-window serving SLO / error-budget tracker.

    Objectives (0 disables a latency objective):

    * ``ttft_ms`` — a request whose time-to-first-token (accept ->
      first token) exceeds this is an SLO violation;
    * ``p99_ms`` — same for end-to-end latency;
    * ``availability`` — the SLO target fraction of GOOD requests
      (default 0.999). Its complement is the **error budget**: the
      fraction of requests allowed to be bad while still meeting SLO.

    Every completed request is ``observe()``d: a request that errored
    (``ok=False``) or blew any latency objective is *bad*. Over the
    rolling ``window_s`` the tracker computes ``bad_fraction`` and the
    **burn rate** = bad_fraction / (1 - availability) — the classic
    error-budget form: 1x means bad requests arrive exactly as fast as
    the budget allows; 10x means the month's budget is gone in 3 days.
    The ``alert`` flag (exported as the ``cxxnet_slo_burn`` gauge, and
    as ``slo_burn`` transition events in the telemetry stream) flips to
    1 while burn_rate >= 1 with at least ``min_requests`` in the window
    — the floor keeps one unlucky request over an empty window from
    paging.

    Thread-safe and jax-free; the serving frontend calls ``observe``
    from its worker thread, /metrics and /statusz read ``snapshot()``.
    """

    def __init__(self, ttft_ms: float = 0.0, p99_ms: float = 0.0,
                 availability: float = 0.999, window_s: float = 300.0,
                 min_requests: int = 10, min_bad: int = 3,
                 clock=time.monotonic):
        self.ttft_ms = float(ttft_ms)
        self.p99_ms = float(p99_ms)
        self.availability = float(availability)
        # availability=1 would make every bad request an instant page
        # AND divide by zero: floor the budget at one-in-a-million
        self.budget = max(1.0 - self.availability, 1e-6)
        self.window_s = float(window_s)
        self.min_requests = max(1, int(min_requests))
        # with a tight budget (0.999 -> 0.1%) ONE error among 10
        # requests already reads as 100x burn: require a minimum count
        # of bad requests before paging, so a single recovered hiccup
        # in a busy window can't flip the gauge (and fail the report's
        # exit-2 gate) — the breaker analog needs 5 consecutive fails
        self.min_bad = max(1, int(min_bad))
        self._clock = clock
        # ranked: _update emits telemetry under this lock (deliberate —
        # transition ordering), so statusd.slo < telemetry.registry
        self._lock = lockrank.lock("statusd.slo")
        self._win: deque = deque()     # (t, violation reason or None)
        # incremental violation counts — observe()/scrape run on the
        # serving accept/worker threads under the lock, so the window
        # (QPS x window_s entries under sustained load) must never be
        # rescanned per request: append/evict keep these current
        self._by_reason: Dict[str, int] = {}
        self.alert = 0
        self.flips = 0

    def observe(self, ok: bool = True, ttft_s: Optional[float] = None,
                latency_s: Optional[float] = None) -> dict:
        """Account one completed request; returns the fresh snapshot."""
        reason = None
        if not ok:
            reason = "error"
        elif (self.ttft_ms > 0 and ttft_s is not None
                and ttft_s * 1e3 > self.ttft_ms):
            reason = "ttft"
        elif (self.p99_ms > 0 and latency_s is not None
                and latency_s * 1e3 > self.p99_ms):
            reason = "latency"
        with self._lock:
            self._win.append((self._clock(), reason))
            if reason is not None:
                self._by_reason[reason] = \
                    self._by_reason.get(reason, 0) + 1
        return self._update()

    def snapshot(self) -> dict:
        """The current window's accounting (evicts aged-out requests
        first, so a scrape long after the last request reads the live
        truth, not a stale burn)."""
        return self._update()

    def _update(self) -> dict:
        now = self._clock()
        with self._lock:
            while self._win and self._win[0][0] < now - self.window_s:
                _, evicted = self._win.popleft()
                if evicted is not None:
                    left = self._by_reason[evicted] - 1
                    if left:
                        self._by_reason[evicted] = left
                    else:
                        del self._by_reason[evicted]
            n = len(self._win)
            by_reason = dict(self._by_reason)
            bad = sum(by_reason.values())
            bad_fraction = bad / float(n) if n else 0.0
            burn_rate = bad_fraction / self.budget
            if n >= self.min_requests:
                alert = 1 if (burn_rate >= 1.0
                              and bad >= self.min_bad) else 0
            else:
                # too few requests in the window to judge either way:
                # HOLD the previous state. Clearing here would let a
                # zero-traffic scrape age the flood out of the window
                # and log a state-0 transition with no recovery
                # evidence — the report's end-of-log exit-2 gate would
                # then depend on scrape timing (the breaker analog:
                # open until a successful probe, not until silence)
                alert = self.alert
            flipped = alert != self.alert
            self.alert = alert
            if flipped:
                self.flips += 1
                # transition events, not per-request spam: the telemetry
                # log's last slo_burn state is the report's exit-2 gate,
                # so emit under the lock — two racing flips must land in
                # the log in the order the state machine took them
                telemetry.count("slo.burn_flips")
                telemetry.event({"ev": "slo_burn", "state": alert,
                                 "burn_rate": round(burn_rate, 4),
                                 "bad": bad, "window": n})
        return {"objectives": {"ttft_ms": self.ttft_ms,
                               "p99_ms": self.p99_ms,
                               "availability": self.availability},
                "window_s": self.window_s, "requests": n, "bad": bad,
                "by_reason": by_reason,
                "bad_fraction": round(bad_fraction, 6),
                "budget": round(self.budget, 6),
                # the alert floors ride the snapshot so the fleet
                # federation (routerd) can apply them FLEET-wide to
                # the merged window — the each-replica-just-under
                # case is exactly what the fleet account exists for
                "min_requests": self.min_requests,
                "min_bad": self.min_bad,
                "burn_rate": round(burn_rate, 4), "alert": alert}


def prometheus_metrics(snapshot: dict, progress: Optional[dict] = None,
                       health_failures: Optional[list] = None,
                       channels: Optional[list] = None,
                       live_failures: Optional[list] = None,
                       slo: Optional[dict] = None,
                       slo_tenants: Optional[dict] = None,
                       perf: Optional[dict] = None,
                       batch: Optional[dict] = None,
                       fleet: Optional[dict] = None,
                       books: Optional[dict] = None) -> str:
    """Render a ``telemetry.metrics_snapshot()`` as Prometheus text
    exposition format 0.0.4. Pure function of its inputs — the selftest
    and tests validate its output without a socket. ``channels`` is the
    heartbeat snapshot the caller derived ``health_failures`` from, so
    one scrape can never contradict itself (healthy gauge vs overdue
    heartbeat ages from two different instants)."""
    p = str(snapshot.get("process", 0))
    base = '{process="%s"}' % _lesc(p)
    out: List[str] = []

    def emit(name, mtype, value, labels=base, help_=None):
        if help_:
            out.append("# HELP %s %s" % (name, help_))
        out.append("# TYPE %s %s" % (name, mtype))
        out.append("%s%s %s" % (name, labels, _fmt(value)))

    def _fmt(v):
        if isinstance(v, float):
            if v != v:
                return "NaN"
            if v == float("inf"):
                return "+Inf"
            if v == float("-inf"):
                return "-Inf"
            return repr(v)
        return str(v)

    def emit_hist(mname, h):
        """One fixed-bucket histogram family (cumulative ``le`` rows)
        from a sparse ``Histogram.to_dict`` snapshot — shared by the
        registry's own series and the fleet-federated ones."""
        out.append("# TYPE %s histogram" % mname)
        counts = {int(i): int(c) for i, c in
                  (h.get("buckets") or {}).items()}
        cum = 0
        for i, le in enumerate(telemetry.HIST_BUCKETS):
            cum += counts.get(i, 0)
            out.append('%s_bucket{process="%s",le="%g"} %d'
                       % (mname, _lesc(p), le, cum))
        total = int(h.get("count", 0))
        out.append('%s_bucket{process="%s",le="+Inf"} %d'
                   % (mname, _lesc(p), total))
        out.append('%s_sum%s %s' % (mname, base,
                                    _fmt(float(h.get("sum", 0.0)))))
        out.append('%s_count%s %d' % (mname, base, total))

    emit("cxxnet_up", "gauge", 1,
         help_="1 while the introspection service is serving")
    emit("cxxnet_uptime_seconds", "gauge",
         round(float(snapshot.get("uptime_s", 0.0)), 3))
    emit("cxxnet_compiles_total", "counter", int(snapshot.get("compiles", 0)),
         help_="jit recompiles detected since run start")
    emit("cxxnet_compile_seconds_total", "counter",
         float(snapshot.get("compile_s", 0.0)))
    if health_failures is not None:
        emit("cxxnet_healthy", "gauge", 0 if health_failures else 1,
             help_="1 when /healthz (readiness) returns 200")
    if live_failures is not None:
        emit("cxxnet_live", "gauge", 0 if live_failures else 1,
             help_="1 when /livez (liveness) returns 200")
    if slo is not None:
        # the serving SLO account (SLOTracker.snapshot()): the alert
        # gauge first — cxxnet_slo_burn is the series alert rules watch
        emit("cxxnet_slo_burn", "gauge", int(slo.get("alert", 0)),
             help_="1 while the rolling-window error-budget burn rate "
                   "is >= 1x (SLO burning)")
        emit("cxxnet_slo_burn_rate", "gauge",
             float(slo.get("burn_rate", 0.0)),
             help_="bad_fraction / (1 - slo_availability) over the "
                   "rolling window")
        emit("cxxnet_slo_bad_fraction", "gauge",
             float(slo.get("bad_fraction", 0.0)))
        emit("cxxnet_slo_window_requests", "gauge",
             int(slo.get("requests", 0)))
    if slo_tenants:
        # per-tenant SLO floors (one SLOTracker per configured tenant):
        # labeled rows, so a noisy tenant's burn is visible NEXT TO the
        # victim's holding at 0 — the multi-tenant QoS acceptance
        fams = (("cxxnet_slo_tenant_burn",
                 lambda s: int(s.get("alert", 0)),
                 "1 while this tenant's own error budget burns >= 1x"),
                ("cxxnet_slo_tenant_burn_rate",
                 lambda s: float(s.get("burn_rate", 0.0)), None),
                ("cxxnet_slo_tenant_window_requests",
                 lambda s: int(s.get("requests", 0)), None))
        for mname, get, help_ in fams:
            if help_:
                out.append("# HELP %s %s" % (mname, help_))
            out.append("# TYPE %s gauge" % mname)
            for t in sorted(slo_tenants):
                out.append('%s{process="%s",tenant="%s"} %s'
                           % (mname, _lesc(p), _lesc(t),
                              _fmt(get(slo_tenants[t]))))
    if perf is not None:
        # the program performance ledger (perf.Ledger.snapshot()):
        # aggregates as plain gauges, per-program figures as labeled
        # families (one TYPE line per family, one row per card — the
        # heartbeat-channel pattern)
        hbm = perf.get("hbm") or {}
        if hbm.get("peak_bytes") is not None:
            emit("cxxnet_hbm_peak_bytes", "gauge", int(hbm["peak_bytes"]),
                 help_="largest per-device program footprint "
                       "(arguments+temp+output) the ledger has carded")
        if hbm.get("headroom_bytes") is not None:
            emit("cxxnet_hbm_headroom_bytes", "gauge",
                 int(hbm["headroom_bytes"]),
                 help_="device HBM capacity minus the peak program "
                       "footprint minus the live decode KV cache")
        if hbm.get("decode_kv_bytes") is not None:
            emit("cxxnet_hbm_decode_kv_bytes", "gauge",
                 int(hbm["decode_kv_bytes"]),
                 help_="live decode KV-cache bytes charged against "
                       "HBM headroom (persistent between programs)")
        if hbm.get("capacity_bytes") is not None:
            emit("cxxnet_hbm_capacity_bytes", "gauge",
                 int(hbm["capacity_bytes"]))
        cards = perf.get("cards") or []
        emit("cxxnet_program_cards", "gauge", len(cards),
             help_="compiled programs the performance ledger has carded")
        fams = (("cxxnet_program_flops", "flops",
                 "XLA cost_analysis FLOPs per execution"),
                ("cxxnet_program_bytes_accessed", "bytes_accessed", None),
                ("cxxnet_program_peak_bytes", "peak_bytes",
                 "per-device argument+temp+output bytes"),
                ("cxxnet_program_predicted_seconds", "predicted_s",
                 "roofline-predicted execution time"),
                ("cxxnet_program_compile_seconds", "compile_s", None),
                ("cxxnet_program_mfu_pct", "mfu_pct",
                 "achieved FLOPs vs chip peak at the measured p50"),
                ("cxxnet_program_roofline_eff_pct", "roofline_eff_pct",
                 "predicted/measured p50 — low means slower than the "
                 "hardware allows"))
        for mname, field, help_ in fams:
            rows = [c for c in cards if _num(c.get(field))]
            if not rows:
                continue
            if help_:
                out.append("# HELP %s %s" % (mname, help_))
            out.append("# TYPE %s gauge" % mname)
            for c in rows:
                out.append(
                    '%s{process="%s",program="%s",shapes="%s"} %s'
                    % (mname, _lesc(p), _lesc(c.get("name", "?")),
                       _lesc(c.get("sig", "?")), _fmt(c[field])))
        rd = perf.get("readiness") or {}
        if rd.get("ready_pct") is not None:
            # warm-grid readiness: absent entirely when no expected
            # program grid was registered (serve-only wiring) — the
            # absence-is-capability-signal convention
            emit("cxxnet_ready_programs_pct", "gauge", rd["ready_pct"],
                 help_="compiled fraction of the expected serving "
                       "program grid; below 100 the replica is still "
                       "paying compile cliffs on first hits")
            emit("cxxnet_expected_programs", "gauge",
                 int(rd.get("expected", 0)))
            emit("cxxnet_warm_programs", "gauge", int(rd.get("warm", 0)))
            bks = rd.get("buckets") or {}
            if bks:
                out.append("# TYPE cxxnet_ready_programs_bucket_pct "
                           "gauge")
                for b in sorted(bks):
                    out.append(
                        'cxxnet_ready_programs_bucket_pct{process="%s"'
                        ',bucket="%s"} %s'
                        % (_lesc(p), _lesc(str(b)),
                           _fmt(bks[b].get("ready_pct", 0.0))))
    if batch is not None:
        # the decode-datapath observability account
        # (servd.ServeFrontend.batch_snapshot()): the live KV/HBM
        # occupancy series paged KV (ROADMAP item 2) will be judged
        # against, per-bucket as labeled rows, plus the convoy latch
        out.append("# HELP cxxnet_decode_kv_bytes allocated decode "
                   "KV-cache bytes per warm session bucket")
        out.append("# TYPE cxxnet_decode_kv_bytes gauge")
        for b, bs in sorted((batch.get("buckets") or {}).items(),
                            key=lambda kv: int(kv[0])):
            out.append('cxxnet_decode_kv_bytes{process="%s",'
                       'bucket="%s"} %d'
                       % (_lesc(p), _lesc(str(b)),
                          int(bs.get("kv_bytes", 0))))
        out.append("# TYPE cxxnet_decode_kv_live_bytes gauge")
        for b, bs in sorted((batch.get("buckets") or {}).items(),
                            key=lambda kv: int(kv[0])):
            out.append('cxxnet_decode_kv_live_bytes{process="%s",'
                       'bucket="%s"} %d'
                       % (_lesc(p), _lesc(str(b)),
                          int(bs.get("kv_live_bytes", 0))))
        if _num(batch.get("kv_live_pct")):
            emit("cxxnet_decode_kv_live_pct", "gauge",
                 batch["kv_live_pct"],
                 help_="live-vs-allocated decode cache utilization — "
                       "the padding+dead-slot waste paged KV reclaims")
        if _num(batch.get("slot_waste_pct")):
            emit("cxxnet_decode_slot_waste_pct", "gauge",
                 batch["slot_waste_pct"],
                 help_="warm decode slots not decoding (bucket-"
                       "rounding waste)")
        emit("cxxnet_decode_convoy", "gauge",
             int(batch.get("convoy", 0)),
             help_="1 while a long sequence pins a full bucket with "
                   "queued work waiting (decode_convoy events mark "
                   "the transitions)")
        emit("cxxnet_decode_convoys_total", "counter",
             int(batch.get("convoys", 0)))
        pool = batch.get("pool")
        if pool is not None:
            # the paged-KV block pool account (doc/performance.md
            # "Decode KV cache"): free-list level, block-exact pool
            # bytes, and the prefix-reuse / copy-on-write lifetime
            # tallies — absent entirely (not zero) on dense backends,
            # the absence-is-the-capability-signal discipline
            emit("cxxnet_decode_kv_block_total", "gauge",
                 int(pool.get("blocks_total", 0)),
                 help_="allocatable KV blocks in the paged decode "
                       "pool (scratch block excluded)")
            emit("cxxnet_decode_kv_block_free", "gauge",
                 int(pool.get("blocks_free", 0)))
            emit("cxxnet_decode_kv_block_used", "gauge",
                 int(pool.get("blocks_used", 0)))
            emit("cxxnet_decode_kv_block_tokens", "gauge",
                 int(pool.get("block_tokens", 0)),
                 help_="cache rows per KV block (serve_kv_block)")
            emit("cxxnet_decode_kv_pool_bytes", "gauge",
                 int(pool.get("pool_bytes", 0)),
                 help_="the paged pool's real device array nbytes "
                       "(block-exact: equals cxxnet_decode_kv_bytes "
                       "under paging)")
            emit("cxxnet_decode_prefix_queries_total", "counter",
                 int(pool.get("prefix_queries", 0)),
                 help_="paged admissions completed (a deferred ask "
                       "retries and counts once, at success — "
                       "cxxnet_decode_kv_defers_total counts the "
                       "defers)")
            emit("cxxnet_decode_prefix_hits_total", "counter",
                 int(pool.get("prefix_hits", 0)),
                 help_="admissions that reused >= 1 resident shared-"
                       "prefix token (prefilled once, fleet-of-"
                       "buckets-wide)")
            emit("cxxnet_decode_prefix_hit_tokens_total", "counter",
                 int(pool.get("prefix_hit_tokens", 0)))
            emit("cxxnet_decode_prefix_cow_total", "counter",
                 int(pool.get("cow_copies", 0)),
                 help_="copy-on-write block demotions (whole-prompt "
                       "matches recomputing their last position)")
            emit("cxxnet_decode_kv_defers_total", "counter",
                 int(pool.get("alloc_failures", 0)),
                 help_="admissions deferred on block-pool exhaustion "
                       "(deterministic queue-wait, never a device "
                       "OOM)")
            if _num(pool.get("prefix_hit_rate")):
                emit("cxxnet_decode_prefix_hit_rate", "gauge",
                     pool["prefix_hit_rate"],
                     help_="share of admitted prompt tokens served "
                           "from resident shared blocks (token-"
                           "weighted, %)")
            # retained conversation cache (doc/robustness.md "Memory
            # governance"): parked refcount-0 blocks, the revival
            # tallies, eviction churn, and the pressure latch
            emit("cxxnet_decode_kv_block_retained", "gauge",
                 int(pool.get("blocks_retained", 0)),
                 help_="refcount-0 blocks parked in the retained "
                       "conversation cache (evictable headroom)")
            emit("cxxnet_decode_retained_hits_total", "counter",
                 int(pool.get("retained_hits", 0)),
                 help_="admissions that REVIVED a retired "
                       "conversation's blocks (the retained sub-"
                       "source of the prefix hit rate)")
            emit("cxxnet_decode_retained_hit_tokens_total", "counter",
                 int(pool.get("retained_hit_tokens", 0)))
            emit("cxxnet_decode_retained_evictions_total", "counter",
                 int(pool.get("retained_evictions", 0)),
                 help_="retained blocks recycled onto the free list "
                       "(LRU, deepest-suffix-first)")
            if _num(pool.get("retained_hit_rate")):
                emit("cxxnet_decode_retained_hit_rate", "gauge",
                     pool["retained_hit_rate"],
                     help_="share of admitted prompt tokens served "
                           "from RETAINED (refcount-0) blocks")
            if "pressure" in pool:
                emit("cxxnet_decode_kv_pressure", "gauge",
                     1 if pool.get("pressure") else 0,
                     help_="1 while the low-headroom latch sheds "
                           "retained mass (kv_pressure events mark "
                           "the transitions)")
    if fleet is not None:
        # the routing fleet (routerd.Router.fleet_snapshot()): per-state
        # counts as one labeled family, per-replica load/liveness rows
        # keyed by replica address (the heartbeat-channel pattern)
        reps = fleet.get("replicas") or []
        emit("cxxnet_fleet_replicas", "gauge", len(reps),
             help_="replicas configured behind the router")
        emit("cxxnet_fleet_replicas_eligible", "gauge",
             int(fleet.get("eligible", 0)),
             help_="replicas up and in rotation (not held by a "
                   "rolling reload)")
        by_state: Dict[str, int] = {}
        for r in reps:
            # a standby is NOT routable whatever its probe state says:
            # it gets its own state row, and replica_up 0 below — a
            # dashboard counting "up" must count replicas that accept
            # traffic, not held-out spares
            st = "standby" if r.get("standby") \
                else r.get("state", "?")
            by_state[st] = by_state.get(st, 0) + 1
        if by_state:
            out.append("# TYPE cxxnet_fleet_state gauge")
            for st in sorted(by_state):
                out.append('cxxnet_fleet_state{process="%s",state="%s"}'
                           ' %d' % (_lesc(p), _lesc(st), by_state[st]))
        fams = (("cxxnet_fleet_replica_up",
                 lambda r: 1 if (r.get("state") == "up"
                                 and not r.get("standby")) else 0,
                 "1 while the replica is routable"),
                ("cxxnet_fleet_replica_queue_depth",
                 lambda r: r.get("queue_depth", 0), None),
                ("cxxnet_fleet_replica_in_flight",
                 lambda r: r.get("in_flight", 0), None),
                ("cxxnet_fleet_replica_outstanding",
                 lambda r: r.get("outstanding", 0),
                 "requests this router currently has on the replica"),
                ("cxxnet_fleet_replica_lost_contact",
                 lambda r: r.get("lost", 0),
                 "lost-contact attempts charged to this replica "
                 "(each one fed the replay failover)"))
        for mname, get, help_ in fams:
            if not reps:
                continue
            if help_:
                out.append("# HELP %s %s" % (mname, help_))
            out.append("# TYPE %s gauge" % mname)
            for r in reps:
                out.append('%s{process="%s",replica="%s"} %s'
                           % (mname, _lesc(p),
                              _lesc(r.get("name", "?")),
                              _fmt(get(r))))
        # the router-local failover account (doc/observability.md
        # "Fleet observability"): route.* counters are router-owned,
        # not federated from replicas — emitted here so the headline
        # chaos acceptance can scrape replays/hedges off the router
        rstats = fleet.get("stats") or {}
        ffams = (("lost_contact", "attempts that went silent after "
                  "dispatch (EOF/timeout) — replay failover feed"),
                 ("replays", "lost attempts re-executed on a "
                  "different replica (deterministic replay)"),
                 ("replay_denied", "replays refused (generation "
                  "moved, or tenant over fair share)"),
                 ("hedges", "duplicate tail-hedge attempts launched"),
                 ("hedge_wins", "requests whose hedge answered first"),
                 ("discarded_late", "duplicate answers reaped and "
                  "discarded (exactly-once to the client)"))
        for k, help_ in ffams:
            if k in rstats:
                emit("cxxnet_fleet_failover_%s_total" % k, "counter",
                     int(rstats.get(k) or 0), help_=help_)
        # warm-grid readiness per replica: only rows for replicas
        # that declare a grid (absence is the capability signal —
        # a missing row, never a lying 0)
        wreps = [r for r in reps if r.get("warm_pct") is not None]
        if wreps:
            out.append("# HELP cxxnet_fleet_replica_warm_pct compiled "
                       "fraction of the replica's expected serving "
                       "program grid (ADMIN warm_programs/"
                       "expected_programs)")
            out.append("# TYPE cxxnet_fleet_replica_warm_pct gauge")
            for r in wreps:
                out.append(
                    'cxxnet_fleet_replica_warm_pct{process="%s"'
                    ',replica="%s"} %s'
                    % (_lesc(p), _lesc(r.get("name", "?")),
                       _fmt(r["warm_pct"])))
        fed = fleet.get("federation")
        if fed:
            # the federated fleet account (routerd.federation_snapshot)
            # — per-replica serve histograms merged EXACTLY (shared
            # fixed buckets: bucket-count addition) into fleet series,
            # counters summed, SLO over the merged windows, and the
            # per-replica outlier verdicts
            emit("cxxnet_fleet_federated_replicas", "gauge",
                 int(fed.get("replicas", 0)),
                 help_="replicas whose metrics the last federation "
                       "sweep reached")
            emit("cxxnet_fleet_federation_age_seconds", "gauge",
                 round(float(fed.get("age_s", 0.0)), 3))
            for name, h in sorted((fed.get("series") or {}).items()):
                emit_hist("cxxnet_fleet_"
                          + _NAME_SAN.sub("_", str(name)) + "_seconds",
                          {"buckets": h.get("buckets"),
                           "count": h.get("count", 0),
                           "sum": h.get("sum_s", 0.0)})
            for cname, v in sorted((fed.get("counters") or {}).items()):
                if _num(v):
                    emit("cxxnet_fleet_"
                         + _NAME_SAN.sub("_", str(cname)) + "_total",
                         "counter", v)
            fslo = fed.get("slo")
            if fslo is not None:
                emit("cxxnet_fleet_slo_burn", "gauge",
                     int(fslo.get("alert", 0)),
                     help_="1 while the FLEET-wide merged-window error "
                           "budget burns >= 1x — fires even when no "
                           "single replica's own alert floor trips")
                emit("cxxnet_fleet_slo_burn_rate", "gauge",
                     float(fslo.get("burn_rate", 0.0)))
                emit("cxxnet_fleet_slo_bad_fraction", "gauge",
                     float(fslo.get("bad_fraction", 0.0)))
                emit("cxxnet_fleet_slo_window_requests", "gauge",
                     int(fslo.get("requests", 0)))
            verdicts = fed.get("outliers") or {}
            if verdicts:
                out.append("# HELP cxxnet_fleet_outlier 1 while the "
                           "replica's serve p99 diverges from the "
                           "fleet median past fleet_outlier_ratio")
                out.append("# TYPE cxxnet_fleet_outlier gauge")
                for name in sorted(verdicts):
                    out.append(
                        'cxxnet_fleet_outlier{process="%s",'
                        'replica="%s"} %d'
                        % (_lesc(p), _lesc(name),
                           1 if verdicts[name].get("outlier") else 0))
                out.append("# TYPE cxxnet_fleet_replica_p99_seconds "
                           "gauge")
                for name in sorted(verdicts):
                    p99 = verdicts[name].get("p99_ms")
                    if p99 is None:
                        continue
                    out.append(
                        'cxxnet_fleet_replica_p99_seconds'
                        '{process="%s",replica="%s"} %s'
                        % (_lesc(p), _lesc(name),
                           _fmt(round(p99 / 1e3, 6))))
            dec = fed.get("decode")
            if dec:
                # the fleet-wide decode KV/HBM account (exact: byte
                # sums over the replicas' own accounts, live pct
                # recomputed from the sums — never a mean of means)
                emit("cxxnet_fleet_decode_kv_bytes", "gauge",
                     int(dec.get("kv_bytes", 0)),
                     help_="allocated decode KV-cache bytes summed "
                           "over the federated replicas")
                emit("cxxnet_fleet_decode_kv_live_bytes", "gauge",
                     int(dec.get("kv_live_bytes", 0)))
                if _num(dec.get("kv_live_pct")):
                    emit("cxxnet_fleet_decode_kv_live_pct", "gauge",
                         dec["kv_live_pct"])
                emit("cxxnet_fleet_decode_convoy_replicas", "gauge",
                     int(dec.get("convoy_replicas", 0)),
                     help_="replicas currently latched in a decode "
                           "convoy (a straggler pinning a full bucket "
                           "while work queues)")
                pl = dec.get("pool")
                if pl:
                    # paged-KV pool federation: block counts summed
                    # exactly over the paged replicas, fleet prefix
                    # hit rate recomputed from the token sums
                    emit("cxxnet_fleet_decode_kv_block_total", "gauge",
                         int(pl.get("blocks_total", 0)),
                         help_="paged decode KV blocks summed over "
                               "the federated replicas")
                    emit("cxxnet_fleet_decode_kv_block_free", "gauge",
                         int(pl.get("blocks_free", 0)))
                    if _num(pl.get("prefix_hit_rate")):
                        emit("cxxnet_fleet_decode_prefix_hit_rate",
                             "gauge", pl["prefix_hit_rate"],
                             help_="fleet share of admitted prompt "
                                   "tokens served from resident "
                                   "shared blocks (token-weighted, "
                                   "%)")
                    emit("cxxnet_fleet_decode_kv_defers_total",
                         "counter", int(pl.get("kv_defers", 0)))
                    emit("cxxnet_fleet_decode_kv_block_retained",
                         "gauge", int(pl.get("blocks_retained", 0)),
                         help_="retained conversation-cache blocks "
                               "summed over the federated replicas")
                    emit("cxxnet_fleet_decode_retained_hits_total",
                         "counter", int(pl.get("retained_hits", 0)))
                    if _num(pl.get("retained_hit_rate")):
                        emit("cxxnet_fleet_decode_retained_hit_rate",
                             "gauge", pl["retained_hit_rate"])
                    emit("cxxnet_fleet_decode_kv_pressure_replicas",
                         "gauge", int(pl.get("pressure_replicas", 0)),
                         help_="replicas currently latched in KV "
                               "memory pressure (shedding retained "
                               "mass)")
        scale = fleet.get("scale")
        if scale:
            # the closed-loop autoscaler's account (routerd
            # scale_snapshot): target = active replicas the policy
            # currently holds in rotation, plus the cumulative
            # transition count the fleet_scale JSONL events mirror
            emit("cxxnet_fleet_target_replicas", "gauge",
                 int(scale.get("target_replicas", 0)),
                 help_="replicas the autoscaler holds in rotation "
                       "(standbys excluded until a scale-up admits "
                       "them)")
            emit("cxxnet_fleet_scale_events_total", "counter",
                 int(scale.get("events", 0)),
                 help_="autoscaler scale-up/scale-down transitions")
            emit("cxxnet_fleet_standby_replicas", "gauge",
                 int(scale.get("standby", 0)))
        tenants = fleet.get("tenants")
        if tenants:
            # per-tenant fleet books: the router's own outcome counts
            # (labels bound by the conf tenant table), each tenant's
            # federated fleet p99, and its fleet-wide merged-window SLO
            # burn — the "noisy tenant sheds, victim holds" series
            tfams = (("cxxnet_fleet_tenant_accepted_total", "counter",
                      lambda d: (d.get("router") or {}).get("accepted")),
                     ("cxxnet_fleet_tenant_served_total", "counter",
                      lambda d: (d.get("router") or {}).get("served")),
                     ("cxxnet_fleet_tenant_shed_total", "counter",
                      lambda d: (d.get("router") or {}).get("shed")),
                     ("cxxnet_fleet_tenant_errors_total", "counter",
                      lambda d: (d.get("router") or {}).get("errors")),
                     ("cxxnet_fleet_tenant_weight", "gauge",
                      lambda d: d.get("weight")),
                     ("cxxnet_fleet_tenant_p99_seconds", "gauge",
                      lambda d: None if d.get("p99_ms") is None
                      else round(d["p99_ms"] / 1e3, 6)),
                     ("cxxnet_fleet_tenant_slo_burn", "gauge",
                      lambda d: None if d.get("slo") is None
                      else int(d["slo"].get("alert", 0))),
                     ("cxxnet_fleet_tenant_slo_burn_rate", "gauge",
                      lambda d: None if d.get("slo") is None
                      else float(d["slo"].get("burn_rate", 0.0))))
            for mname, mtype, get in tfams:
                rows = [(t, get(d)) for t, d in sorted(tenants.items())]
                rows = [(t, v) for t, v in rows if _num(v)]
                if not rows:
                    continue
                out.append("# TYPE %s %s" % (mname, mtype))
                for t, v in rows:
                    out.append('%s{process="%s",tenant="%s"} %s'
                               % (mname, _lesc(p), _lesc(t), _fmt(v)))
    if channels is None:
        channels = health_mod.channel_status()
    if channels:
        # ONE TYPE line for the whole family (the exposition spec allows
        # one per metric name; the channels are label values)
        out.append("# TYPE cxxnet_heartbeat_age_seconds gauge")
        for ch, age, timeout, overdue in channels:
            out.append(
                'cxxnet_heartbeat_age_seconds{process="%s",channel="%s"}'
                ' %s' % (_lesc(p), _lesc(ch), _fmt(round(age, 3))))
    for key in ("round", "num_round", "batch", "served", "errors",
                "shed", "deadline"):
        v = (progress or {}).get(key)
        if _num(v):
            emit("cxxnet_progress_" + key, "gauge", v)
    if books is not None:
        # the conservation-law auditor's account (telemetry.BooksAuditor
        # snapshot): one latched gauge row per law — a 1 is sticky until
        # an operator resets the auditor, so a scrape-miss between sweep
        # and page can never hide a violation. Broken laws that were
        # since unregistered (a drained router) still render their latch.
        laws = sorted(set(books.get("laws") or ())
                      | set(books.get("broken") or ()))
        if laws:
            out.append("# HELP cxxnet_books_broken 1 latched once the "
                       "named conservation law was ever violated")
            out.append("# TYPE cxxnet_books_broken gauge")
            broken = set(books.get("broken") or ())
            for law in laws:
                out.append('cxxnet_books_broken{process="%s",law="%s"} %d'
                           % (_lesc(p), _lesc(law),
                              1 if law in broken else 0))
        emit("cxxnet_books_laws", "gauge",
             len(books.get("laws") or ()),
             help_="conservation laws currently registered for sweeping")
        emit("cxxnet_books_sweeps_total", "counter",
             int(books.get("sweeps", 0)))
    for name, v in sorted(snapshot.get("counters", {}).items()):
        if _num(v):
            emit(_mname(name) + "_total", "counter", v)
    for name, v in sorted(snapshot.get("gauges", {}).items()):
        if _num(v):
            emit(_mname(name), "gauge", v)
    for name, h in sorted(snapshot.get("hists", {}).items()):
        emit_hist(_mname(name) + "_seconds", h)
    return "\n".join(out) + "\n"


def _mib(v) -> str:
    return "n/a" if v is None else "%.1f" % (v / float(1 << 20))


def programz_html(snap: dict) -> str:
    """Render a ``perf.Ledger.snapshot()`` as the /programz page: the
    HBM account, then one row per carded program. Pure function of the
    snapshot — the perf selftest and tests validate it socket-free."""
    esc = html.escape
    spec = snap.get("spec") or {}
    hbm = snap.get("hbm") or {}
    parts = ["<html><head><title>cxxnet programz</title></head>"
             "<body><h1>program performance ledger</h1><pre>"]
    parts.append("device spec: %s  peak %.1f TFLOP/s  HBM %.0f GB/s  "
                 "capacity %.1f GiB"
                 % (esc(str(spec.get("name", "?"))),
                    (spec.get("peak_flops") or 0.0) / 1e12,
                    (spec.get("hbm_bw") or 0.0) / 1e9,
                    (spec.get("hbm_capacity") or 0.0) / 2.0**30))
    peak = hbm.get("peak_bytes")
    head = hbm.get("headroom_bytes")
    dkv = hbm.get("decode_kv_bytes")
    parts.append("hbm: peak program footprint %s MiB   headroom %s MiB"
                 % (_mib(peak), _mib(head))
                 + ("   decode kv cache %s MiB (see /batchz)"
                    % _mib(dkv) if dkv is not None else ""))
    parts.append("</pre><h2>programs</h2><pre>")
    cols = ("program", "shapes", "cause", "n", "compile_s", "GFLOPs",
            "peak MiB", "pred ms", "p50 ms", "p99 ms", "MFU%", "eff%")
    fmt = "%-18s %-28s %-18s %3s %9s %9s %9s %8s %8s %8s %6s %6s"
    parts.append(fmt % cols)

    def num(v, scale=1.0, form="%.2f"):
        return "n/a" if v is None else form % (v * scale)

    for c in snap.get("cards") or []:
        if c.get("status") == "error":
            parts.append(fmt % (
                esc(c.get("name", "?")), esc(str(c.get("shapes", "?"))),
                esc(str(c.get("cause", "?"))), c.get("compiles", 0),
                num(c.get("compile_s")), "ERR", "ERR", "-", "-", "-",
                "-", "-"))
            parts.append("    analysis error: %s"
                         % esc(str(c.get("error"))))
            continue
        shared = c.get("series_shared_by", 1) > 1
        parts.append(fmt % (
            esc(c.get("name", "?")), esc(str(c.get("shapes", "?"))),
            esc(str(c.get("cause", "?"))), c.get("compiles", 0),
            num(c.get("compile_s")), num(c.get("flops"), 1e-9),
            _mib(c.get("peak_bytes")), num(c.get("predicted_s"), 1e3),
            num(c.get("measured_p50_ms")) + ("*" if shared else ""),
            num(c.get("measured_p99_ms")),
            num(c.get("mfu_pct"), form="%.1f"),
            num(c.get("roofline_eff_pct"), form="%.1f")))
    if not snap.get("cards"):
        parts.append("(no programs carded yet — nothing compiled since "
                     "the ledger was enabled)")
    parts.append("</pre><p>pred = max(flops/peak, bytes/bw) roofline; "
                 "MFU% and eff% join the measured latency histogram "
                 "(doc/performance.md \"Live program ledger\"); "
                 "* = several signatures of this program share one "
                 "measured series, so p50/MFU/eff aggregate them; "
                 "<a href='/programz?json=1'>json</a> "
                 "<a href='/statusz'>statusz</a></p></body></html>")
    return "\n".join(parts)


def compilez_html(body: dict) -> str:
    """Render the compile flight recorder as the /compilez page: the
    warm-grid readiness account, then one row per recorded compile
    (newest first) with its trigger attribution — which request /
    dispatcher window paid the cliff. Pure function of the
    ``{"compiles", "total", "shown", "readiness"}`` body the handler
    builds — the perf selftest and tests validate it socket-free."""
    esc = html.escape
    rd = body.get("readiness") or {}
    parts = ["<html><head><title>cxxnet compilez</title></head>"
             "<body><h1>compile flight recorder</h1><pre>"]
    pct = rd.get("ready_pct")
    if pct is None:
        parts.append("warm grid: no expected program grid registered "
                     "(serve-only; learn_task wires it from "
                     "serve_buckets/serve_plen_buckets)")
    else:
        parts.append("warm grid: %d/%d programs compiled (%.1f%% ready)"
                     % (rd.get("warm", 0), rd.get("expected", 0), pct))
        for b, st in sorted((rd.get("buckets") or {}).items()):
            parts.append("  bucket %-10s %d/%d (%.1f%%)"
                         % (esc(str(b)), st.get("warm", 0),
                            st.get("expected", 0),
                            st.get("ready_pct", 0.0)))
        cold = rd.get("cold_keys") or []
        if cold:
            parts.append("  cold: " + " ".join(esc(k) for k in cold))
    parts.append("</pre><h2>compiles (%d shown of %d recorded)</h2><pre>"
                 % (body.get("shown", 0), body.get("total", 0)))
    cols = ("seq", "ts", "program", "cause", "seconds", "trigger",
            "key")
    fmt = "%5s %9s %-18s %-19s %8s %-24s %s"
    parts.append(fmt % cols)
    for r in body.get("compiles") or []:
        trig = r.get("trigger_request") or r.get("trigger_context") \
            or "-"
        parts.append(fmt % (
            r.get("seq", "?"),
            "%.2f" % r["ts"] if r.get("ts") is not None else "n/a",
            esc(str(r.get("name", "?"))), esc(str(r.get("cause", "?"))),
            "%.3f" % r.get("seconds", 0.0), esc(str(trig)),
            esc(str(r.get("key") or r.get("shapes") or "?"))))
    if not body.get("compiles"):
        parts.append("(no compiles recorded since the ledger was "
                     "enabled)")
    parts.append("</pre><p>trigger = the request id (prefill paid the "
                 "cliff inside that request) or the dispatcher window "
                 "(session:/step: — every request aboard the batch "
                 "stalled; their flight records carry it as "
                 "compile_stall_s); "
                 "<a href='/compilez?json=1'>json</a> "
                 "<a href='/programz'>programz</a> "
                 "<a href='/statusz'>statusz</a></p></body></html>")
    return "\n".join(parts)


def fleetz_html(snap: dict) -> str:
    """Render a ``routerd.Router.fleet_snapshot()`` as the /fleetz
    page: one row per replica (state machine + load + ejection
    backoff), the router's counters, and the recent rolling-reload
    drain windows. Pure function of the snapshot — the routerd
    selftest and tests validate it socket-free."""
    esc = html.escape
    parts = ["<html><head><title>cxxnet fleetz</title></head>"
             "<body><h1>serving fleet</h1><pre>"]
    reps = snap.get("replicas") or []
    parts.append("replicas: %d configured, %d eligible%s%s"
                 % (len(reps), snap.get("eligible", 0),
                    "  DRAINING" if snap.get("draining") else "",
                    "  ROLLING-RELOAD" if snap.get("reloading")
                    else ""))
    parts.append("</pre><h2>replicas</h2><pre>")
    cols = ("replica", "state", "hold", "queue", "in_flight",
            "outstanding", "lost", "buckets", "blocks", "retained",
            "warm", "ejections", "probed", "detail")
    fmt = ("%-21s %-12s %-4s %5s %9s %11s %5s %-12s %-9s %-9s %-9s "
           "%9s %8s  %s")
    parts.append(fmt % cols)
    for r in reps:
        age = r.get("last_probe_age_s")
        # the per-bucket load signal (ADMIN stats bucket.<b>.*): each
        # warm bucket as <size>:<active>/<size> — the column
        # disaggregated scheduling will route on; "-" pre-batching
        bks = " ".join(
            "%s:%s/%s" % (b, d.get("active", 0), b)
            for b, d in sorted((r.get("buckets") or {}).items(),
                               key=lambda kv: int(kv[0]))
            if d.get("warm")) or "-"
        detail = str(r.get("detail", ""))
        if r.get("standby"):
            # held out of dispatch until the autoscaler admits it
            detail = "STANDBY " + detail
        if r.get("outlier"):
            # the federation sweep's verdict: this replica's serve p99
            # diverges from the fleet median — the flagged row the
            # cxxnet_fleet_outlier gauge and fleet_outlier event name
            detail = ("OUTLIER (p99 %.1fms vs fleet) " % r["p99_ms"]
                      if r.get("p99_ms") is not None
                      else "OUTLIER ") + detail
        # paged-KV pool level (ADMIN stats kv_blocks_free/total):
        # "-" on dense/pre-paging replicas (None in the snapshot —
        # absence is the capability signal, never rendered as 0/0)
        blks = ("%s/%s" % (r.get("kv_blocks_free"),
                           r.get("kv_blocks_total"))
                if r.get("kv_blocks_total") is not None else "-")
        # retained conversation cache (ADMIN stats
        # kv_retained_blocks/kv_retained_hits): parked blocks and
        # lifetime revivals — "-" on pre-retention replicas (None in
        # the snapshot; absence is the capability signal)
        ret = ("%s:%s" % (r.get("kv_retained_blocks"),
                          r.get("kv_retained_hits"))
               if r.get("kv_retained_blocks") is not None else "-")
        # warm-grid readiness (ADMIN stats warm_programs/
        # expected_programs): compiled fraction of the replica's
        # expected program grid — "-" when it declares no grid (None
        # in the snapshot; absence is the capability signal)
        warm = ("%.0f%% (%s/%s)" % (r["warm_pct"],
                                    r.get("warm_programs"),
                                    r.get("expected_programs"))
                if r.get("warm_pct") is not None else "-")
        parts.append(fmt % (
            esc(r.get("name", "?")), esc(r.get("state", "?")),
            "yes" if r.get("hold") else "-", r.get("queue_depth", 0),
            r.get("in_flight", 0), r.get("outstanding", 0),
            r.get("lost", 0),
            esc(bks), esc(blks), esc(ret), esc(warm),
            r.get("ejections", 0),
            "never" if age is None else "%.1fs" % age,
            esc(detail)))
    parts.append("</pre><h2>router</h2><pre>")
    stats = snap.get("stats") or {}
    parts.append(" ".join("%s=%s" % kv for kv in
                          sorted(stats.items())))
    if stats.get("lost_contact") or stats.get("hedges"):
        # the failover account, interpreted: how many losses the
        # replay machinery recovered vs surfaced, and the hedge win
        # rate — the at-a-glance line behind the
        # cxxnet_fleet_failover_* series
        parts.append("failover: %s lost-contact, %s replayed, %s "
                     "denied; %s hedged, %s hedge wins; %s late "
                     "duplicate answer(s) discarded"
                     % (stats.get("lost_contact", 0),
                        stats.get("replays", 0),
                        stats.get("replay_denied", 0),
                        stats.get("hedges", 0),
                        stats.get("hedge_wins", 0),
                        stats.get("discarded_late", 0)))
    fed = snap.get("federation")
    if fed:
        parts.append("</pre><h2>federated fleet metrics</h2><pre>")
        parts.append("%d replica(s) federated, %.1fs ago"
                     % (fed.get("replicas", 0), fed.get("age_s", 0.0)))
        for name, h in sorted((fed.get("series") or {}).items()):
            parts.append("%-28s n=%-8d p50=%s p99=%s"
                         % (esc(name), h.get("count", 0),
                            _ms(h.get("p50_ms")), _ms(h.get("p99_ms"))))
        fslo = fed.get("slo")
        if fslo is not None:
            parts.append("fleet slo: %d requests, %d bad, burn %.2fx%s"
                         % (fslo.get("requests", 0),
                            fslo.get("bad", 0),
                            fslo.get("burn_rate", 0.0),
                            "  BURNING" if fslo.get("alert") else ""))
        dec = fed.get("decode")
        if dec:
            pct = dec.get("kv_live_pct")
            parts.append("decode kv (%d replica(s)): %s MiB allocated, "
                         "%s MiB live (%s%%)%s"
                         % (dec.get("replicas", 0),
                            _mib(dec.get("kv_bytes")),
                            _mib(dec.get("kv_live_bytes")),
                            "n/a" if pct is None else "%.1f" % pct,
                            "  CONVOY on %d replica(s)"
                            % dec["convoy_replicas"]
                            if dec.get("convoy_replicas") else ""))
            pl = dec.get("pool")
            if pl:
                hr = pl.get("prefix_hit_rate")
                rr = pl.get("retained_hit_rate")
                parts.append("paged kv (%d replica(s)): %s/%s blocks "
                             "free, %s retained (%s revival(s), hit "
                             "rate %s%%), prefix hit rate %s%%, %s "
                             "exhaustion defer(s)%s"
                             % (pl.get("replicas", 0),
                                pl.get("blocks_free", 0),
                                pl.get("blocks_total", 0),
                                pl.get("blocks_retained", 0),
                                pl.get("retained_hits", 0),
                                "n/a" if rr is None else "%.1f" % rr,
                                "n/a" if hr is None else "%.1f" % hr,
                                pl.get("kv_defers", 0),
                                "  PRESSURE on %d replica(s)"
                                % pl["pressure_replicas"]
                                if pl.get("pressure_replicas") else ""))
    scale = snap.get("scale")
    if scale:
        parts.append("</pre><h2>autoscaler</h2><pre>")
        parts.append("target %d replicas (bounds %d..%d, %d standby); "
                     "%d scale event(s); up at burn>=%gx, retire after "
                     "%gs idle, cooldown %gs"
                     % (scale.get("target_replicas", 0),
                        scale.get("min", 0), scale.get("max", 0),
                        scale.get("standby", 0),
                        scale.get("events", 0),
                        scale.get("up_burn", 0.0),
                        scale.get("down_idle_s", 0.0),
                        scale.get("cooldown_s", 0.0)))
        for ev in scale.get("recent") or []:
            # warm_pct: the replica's compiled fraction at the scale
            # decision — a 0% scale-up is "admitted but paying every
            # compile cliff ahead" (serve_scale_up_to_first_token_s)
            wp = ev.get("warm_pct")
            parts.append("%-4s %-21s -> %d active%s  (%s)"
                         % (esc(ev.get("action", "?")),
                            esc(ev.get("replica", "?")),
                            ev.get("active", 0),
                            "" if wp is None
                            else ", %.0f%% warm" % wp,
                            esc(ev.get("reason", ""))))
    tenants = snap.get("tenants")
    if tenants:
        parts.append("</pre><h2>tenants (weighted-fair QoS)</h2><pre>")
        cols = ("tenant", "weight", "accepted", "served", "shed",
                "errors", "fleet p99", "slo burn")
        tfmt = "%-16s %6s %9s %9s %9s %9s %10s %9s"
        parts.append(tfmt % cols)
        for t, d in sorted(tenants.items()):
            ro = d.get("router") or {}
            slo = d.get("slo") or {}
            parts.append(tfmt % (
                esc(t), "%g" % d.get("weight", 1.0),
                ro.get("accepted", 0), ro.get("served", 0),
                ro.get("shed", 0), ro.get("errors", 0),
                _ms(d.get("p99_ms")),
                ("%.2fx%s" % (slo["burn_rate"],
                              " BURNING" if slo.get("alert") else "")
                 if slo.get("burn_rate") is not None else "n/a")))
    wins = snap.get("windows") or []
    if wins:
        parts.append("</pre><h2>rolling-reload drain windows</h2><pre>")
        for w in wins:
            parts.append("%-21s out %.3f -> back %.3f (%.3fs)"
                         % (esc(w.get("replica", "?")), w["out_s"],
                            w["back_s"], w["back_s"] - w["out_s"]))
    parts.append("</pre><p><a href='/fleetz?json=1'>json</a> "
                 "<a href='/statusz'>statusz</a></p></body></html>")
    return "\n".join(parts)


def requestz_html(recs: List[dict], total: int, cap: int,
                  limit: int) -> str:
    """Render a flight-recorder listing as the /requestz page — one
    row per request, newest first. Handles BOTH record shapes: a servd
    replica's phase-attributed records and a router's attempt records
    (utils/routerd.py), so the same page works on every process.
    Pure function of its inputs — validated socket-free in tests."""
    esc = html.escape
    parts = ["<html><head><title>cxxnet requestz</title></head>"
             "<body><h1>request flight recorder</h1><pre>"]
    parts.append("%d of last %d requests recorded%s"
                 % (total, cap,
                    "  (showing newest %d — ?n=<k> to change)"
                    % len(recs) if limit > 0 and total > len(recs)
                    else ""))
    parts.append("</pre><pre>")
    cols = ("request", "outcome", "total", "ttft", "tok", "detail")
    fmt = "%-24s %-14s %9s %9s %5s  %s"
    parts.append(fmt % cols)
    for r in recs:
        total_s = r.get("total_s")
        ttft_s = r.get("ttft_s")
        if r.get("attempts") is not None:
            # router shape: the routing life in one cell
            detail = " -> ".join(
                "%s:%s%s" % (a.get("replica", "?"),
                             a.get("outcome", "?"),
                             " (retried)" if a.get("retried") else "")
                for a in r["attempts"]) or "(no attempt)"
        else:
            ph = r.get("phases") or {}
            detail = " ".join(
                "%s=%s" % (k, _ms(None if ph.get(k) is None
                                  else ph[k] * 1e3))
                for k in telemetry.REQUEST_PHASES if k in ph)
            if r.get("shed_at"):
                detail = "shed at admission (%s)" % r["shed_at"]
        parts.append(fmt % (
            esc(str(r.get("id", "?"))), esc(str(r.get("outcome", "?"))),
            _ms(None if total_s is None else total_s * 1e3),
            _ms(None if ttft_s is None else ttft_s * 1e3),
            r.get("tokens_out", r.get("retries", 0)),
            esc(detail)))
    if not recs:
        parts.append("(no requests recorded yet)")
    parts.append("</pre><p>one request's Chrome trace: "
                 "<code>/trace?request=&lt;id&gt;</code> "
                 "(on a router: the stitched cross-process trace); "
                 "<a href='/requestz?json=1'>json</a> "
                 "<a href='/statusz'>statusz</a></p></body></html>")
    return "\n".join(parts)


def batchz_html(snap: dict) -> str:
    """Render a ``servd.ServeFrontend.batch_snapshot(ring=...)`` as the
    /batchz page: the KV/occupancy account, the per-bucket table, and
    the newest iteration records of the scheduler flight ring (one row
    per decode iteration: composition, step latency, queue pressure,
    convoy verdict). Pure function of the snapshot — validated
    socket-free in tests."""
    esc = html.escape
    parts = ["<html><head><title>cxxnet batchz</title></head>"
             "<body><h1>decode batch scheduler</h1><pre>"]
    occ = snap.get("mean_occupancy")
    parts.append("iterations: %d (%d slot-iterations, mean occupancy "
                 "%s)   capacity %d, free slots %d, queue depth %d"
                 % (snap.get("iterations", 0),
                    snap.get("slot_iterations", 0),
                    "n/a" if occ is None else "%.2f" % occ,
                    snap.get("capacity", 0), snap.get("free_slots", 0),
                    snap.get("queue_depth", 0)))
    kv_pct = snap.get("kv_live_pct")
    waste = snap.get("slot_waste_pct")
    parts.append("kv cache: %s MiB allocated, %s MiB live (%s%% live"
                 "%s) — the paged-KV reclaim target (ROADMAP item 2)"
                 % (_mib(snap.get("kv_bytes")),
                    _mib(snap.get("kv_live_bytes")),
                    "n/a" if kv_pct is None else "%.1f" % kv_pct,
                    "" if waste is None
                    else ", %.1f%% slot waste" % waste))
    pool = snap.get("pool")
    if pool is not None:
        hr = pool.get("prefix_hit_rate")
        parts.append("paged pool: %s/%s blocks free (%s tokens/block, "
                     "%s MiB pool)   prefix reuse: %s/%s admissions "
                     "hit, %s%% of prompt tokens resident, %s CoW, "
                     "%s exhaustion defers"
                     % (pool.get("blocks_free", 0),
                        pool.get("blocks_total", 0),
                        pool.get("block_tokens", 0),
                        _mib(pool.get("pool_bytes")),
                        pool.get("prefix_hits", 0),
                        pool.get("prefix_queries", 0),
                        "n/a" if hr is None else "%.1f" % hr,
                        pool.get("cow_copies", 0),
                        pool.get("alloc_failures", 0)))
        rr = pool.get("retained_hit_rate")
        parts.append("retained cache: %s block(s) parked (cap %s), "
                     "%s revival(s) (%s%% of prompt tokens), %s "
                     "eviction(s)%s"
                     % (pool.get("blocks_retained", 0),
                        pool.get("retained_cap", 0),
                        pool.get("retained_hits", 0),
                        "n/a" if rr is None else "%.1f" % rr,
                        pool.get("retained_evictions", 0),
                        "   MEMORY PRESSURE (shedding)"
                        if pool.get("pressure") else ""))
    parts.append("convoy: %s (%d episode(s); threshold %d iterations "
                 "pinned with queued work at zero free slots)"
                 % ("ACTIVE" if snap.get("convoy") else "none",
                    snap.get("convoys", 0),
                    snap.get("convoy_iters", 0)))
    parts.append("</pre><h2>buckets</h2><pre>")
    cols = ("bucket", "warm", "active", "kv MiB", "live MiB", "live%")
    fmt = "%-7s %5s %7s %9s %9s %7s"
    if pool is not None:
        cols = cols + ("blocks",)
        fmt += " %7s"
    parts.append(fmt % cols)
    for b, bs in sorted((snap.get("buckets") or {}).items(),
                        key=lambda kv: int(kv[0])):
        kvb = bs.get("kv_bytes", 0)
        row = (esc(str(b)), bs.get("warm", 0), bs.get("active", 0),
               _mib(kvb), _mib(bs.get("kv_live_bytes", 0)),
               "%.1f" % (100.0 * bs.get("kv_live_bytes", 0) / kvb)
               if kvb else "n/a")
        if pool is not None:
            # block-table claims: a shared prefix block counts once
            # per holder, so the column can sum past blocks_used
            row = row + (bs.get("blocks_held", 0),)
        parts.append(fmt % row)
    ring = snap.get("flight") or []
    if ring:
        parts.append("</pre><h2>iteration flight ring (newest %d of "
                     "cap %d)</h2><pre>"
                     % (len(ring), snap.get("flight_cap", 0)))
        cols = ("iter", "bucket", "occ", "step", "queue", "q_age",
                "kv_live%", "slots [slot:id@age]")
        ifmt = "%-8s %6s %4s %9s %6s %8s %8s  %s"
        if pool is not None:
            # block pressure per iteration: next to the queue columns
            # it answers "queued because slots or because blocks?"
            cols = cols[:7] + ("blk_free",) + cols[7:]
            ifmt = "%-8s %6s %4s %9s %6s %8s %8s %8s  %s"
        parts.append(ifmt % cols)
        for it in ring:
            slots = " ".join("%s:%s@%s" % (r[0], r[1], r[2])
                             for r in it.get("slots") or [])
            extra = []
            for rid, slot in it.get("admitted") or []:
                extra.append("+%s" % rid)
            for row in it.get("retired") or []:
                extra.append("-%s" % row[0])
            if it.get("convoy"):
                extra.append("CONVOY")
            if it.get("error"):
                extra.append("ERROR %s" % it["error"])
            if extra:
                slots += "  (" + " ".join(extra) + ")"
            kvp = it.get("kv_live_pct")
            row = (it.get("iter", "?"), it.get("bucket", "?"),
                   it.get("occupancy", 0), _ms(it.get("step_ms")),
                   it.get("queue_depth", 0),
                   _ms(None if it.get("queue_age_s") is None
                       else it["queue_age_s"] * 1e3),
                   "n/a" if kvp is None else "%.1f" % kvp)
            if pool is not None:
                row = row + ("%s/%s" % (it.get("blocks_free", "?"),
                                        it.get("blocks_total", "?")),)
            parts.append(ifmt % (row + (esc(slots),)))
    parts.append("</pre><p>one request's slot-Gantt view: "
                 "<code>/trace?request=&lt;id&gt;</code>; "
                 "<a href='/batchz?json=1'>json</a> "
                 "<a href='/statusz'>statusz</a></p></body></html>")
    return "\n".join(parts)


def why_html(payload: dict) -> str:
    """Render one request's slowdown autopsy (a ``classify_record`` /
    ``classify_route`` verdict, or a router's ``stitch_route`` merge)
    as the /why page: the primary verdict up top, then the cause
    waterfall with seconds and share of wall time, then — on a router —
    each hop's own local verdict. Pure function of the payload —
    validated socket-free in tests."""
    esc = html.escape
    aut = payload.get("autopsy") or {}
    causes = aut.get("causes") or {}
    wall = float(aut.get("wall_s") or 0.0)
    parts = ["<html><head><title>cxxnet why</title></head>"
             "<body><h1>request autopsy: %s</h1><pre>"
             % esc(str(payload.get("id", "?")))]
    parts.append("outcome: %-12s  wall %s   PRIMARY VERDICT: %s"
                 % (esc(str(payload.get("outcome", "?"))),
                    _ms(wall * 1e3),
                    esc(str(aut.get("primary", "?")))))
    parts.append("</pre><h2>cause waterfall</h2><pre>")
    fmt = "%-16s %10s %7s  %s"
    parts.append(fmt % ("cause", "seconds", "share", ""))
    for cause in autopsy.CAUSES:
        s = float(causes.get(cause, 0.0))
        share = (100.0 * s / wall) if wall > 0 else 0.0
        bar = "#" * int(round(share / 4.0))
        mark = " <-- primary" if cause == aut.get("primary") else ""
        parts.append(fmt % (esc(cause), "%.6f" % s,
                            "%.1f%%" % share, bar + mark))
    hops = payload.get("hops") or {}
    if hops:
        parts.append("</pre><h2>hops (each replica's local verdict)"
                     "</h2><pre>")
        hfmt = "%-16s %-16s %10s  %s"
        parts.append(hfmt % ("replica", "primary", "wall", "causes"))
        for name in sorted(hops):
            h = hops[name] or {}
            hc = h.get("causes") or {}
            detail = " ".join(
                "%s=%s" % (c, _ms(hc[c] * 1e3))
                for c in autopsy.CAUSES if hc.get(c, 0.0) > 0.0)
            parts.append(hfmt % (
                esc(str(name)), esc(str(h.get("primary", "?"))),
                _ms(float(h.get("wall_s") or 0.0) * 1e3), esc(detail)))
    parts.append("</pre><p>the raw record: "
                 "<code>/requestz?request=&lt;id&gt;</code>; the Gantt "
                 "view: <code>/trace?request=&lt;id&gt;</code>; "
                 "<a href='/why?request=%s&amp;json=1'>json</a> "
                 "<a href='/eventz'>eventz</a> "
                 "<a href='/statusz'>statusz</a></p></body></html>"
                 % esc(str(payload.get("id", "?"))))
    return "\n".join(parts)


def eventz_html(rows: List[dict], limit: int = 0) -> str:
    """Render the incident timeline (``autopsy.incidents`` rows — on a
    router the fleet-merged feed) as the /eventz page: one wall-clock
    ordered row per transition or point incident, begin rows naming the
    requests whose autopsies cite the episode. Pure function of the
    rows — validated socket-free in tests."""
    esc = html.escape
    parts = ["<html><head><title>cxxnet eventz</title></head>"
             "<body><h1>fleet incident timeline</h1><pre>"]
    parts.append("%d incident row(s)%s"
                 % (len(rows),
                    "  (newest %d — ?n=<k> to change)" % limit
                    if limit > 0 else ""))
    parts.append("</pre><pre>")
    fmt = "%-12s %-10s %-14s %-6s %-24s %s"
    parts.append(fmt % ("t+", "process", "kind", "state", "requests",
                        "detail"))
    for r in rows:
        ev = r.get("event") or {}
        detail = " ".join(
            "%s=%s" % (k, ev[k]) for k in sorted(ev)
            if k not in ("ev", "ts") and not isinstance(ev[k], (dict,
                                                               list)))
        reqs = ",".join(str(x) for x in (r.get("requests") or ())) \
            or "-"
        parts.append(fmt % (
            "%.3fs" % float(r.get("ts") or 0.0),
            esc(str(r.get("process", "-"))), esc(str(r.get("kind", "?"))),
            esc(str(r.get("state", "?"))), esc(reqs), esc(detail)))
    if not rows:
        parts.append("(no incidents recorded — a quiet fleet)")
    parts.append("</pre><p>each begin row names the requests whose "
                 "<code>/why?request=&lt;id&gt;</code> autopsies cite "
                 "the episode; "
                 "<a href='/eventz?json=1'>json</a> "
                 "<a href='/statusz'>statusz</a></p></body></html>")
    return "\n".join(parts)


class _HTTPServer(ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True
    statusd: "StatusServer"


class _Endpoint(BaseHTTPRequestHandler):
    server_version = "cxxnet-statusd/1"
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):   # quiet: no per-scrape stderr spam
        pass

    def _reply(self, code: int, ctype: str, body: bytes) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):   # noqa: N802 (BaseHTTPRequestHandler contract)
        srv = self.server.statusd
        path, _, query = self.path.partition("?")
        try:
            if path == "/metrics":
                if parse_qs(query).get("json"):
                    # the RAW registry snapshot (+ SLO window): the
                    # fleet router's federation feed — exact bucket
                    # counts, so the fleet merge is bucket addition
                    # with no text-format round trip (routerd
                    # federate_now; doc/observability.md "Fleet
                    # observability")
                    body = {"metrics": srv.registry.metrics_snapshot(),
                            "slo": srv.slo.snapshot()
                            if srv.slo is not None else None,
                            "slo_tenants": {
                                t: tr.snapshot() for t, tr in
                                sorted(srv.slo_tenants.items())}
                            if srv.slo_tenants else None,
                            # the decode KV/convoy account rides the
                            # federation feed: the router sums the
                            # byte accounts into cxxnet_fleet_decode_*
                            "batch": srv.batch.batch_snapshot()
                            if srv.batch is not None else None}
                    self._reply(200, "application/json",
                                json.dumps(body).encode("utf-8"))
                else:
                    self._reply(
                        200,
                        "text/plain; version=0.0.4; charset=utf-8",
                        srv.metrics_text().encode("utf-8"))
            elif path == "/healthz":
                fails = srv.health_failures()
                if fails:
                    body = "unhealthy\n" + "".join(
                        "%s: %s\n" % (n, d) for n, d in fails)
                    self._reply(503, "text/plain; charset=utf-8",
                                body.encode("utf-8"))
                else:
                    self._reply(200, "text/plain; charset=utf-8", b"ok\n")
            elif path == "/livez":
                fails = srv.health_failures(liveness_only=True)
                if fails:
                    body = "dead\n" + "".join(
                        "%s: %s\n" % (n, d) for n, d in fails)
                    self._reply(503, "text/plain; charset=utf-8",
                                body.encode("utf-8"))
                else:
                    self._reply(200, "text/plain; charset=utf-8",
                                b"alive\n")
            elif path in ("/", "/statusz"):
                self._reply(200, "text/html; charset=utf-8",
                            srv.statusz_html().encode("utf-8"))
            elif path == "/trace":
                # keep_blank_values: "?request=" with an empty id must
                # 404 like any other unknown id, not silently fall
                # through to the whole-ring event trace
                rid = (parse_qs(query, keep_blank_values=True)
                       .get("request") or [None])[0]
                if rid is not None:
                    if srv.fleet is not None and hasattr(
                            srv.fleet, "stitched_trace"):
                        # router process: ONE cross-process trace —
                        # the router's attempt lane plus each touched
                        # replica's phase lanes, fetched live over
                        # their statusd and clock-aligned on the
                        # shared wall epoch (routerd.stitched_trace)
                        trace = srv.fleet.stitched_trace(rid)
                        if trace is None:
                            self._reply(
                                404, "text/plain; charset=utf-8",
                                ("no routed request %r in the router "
                                 "flight ring; see /requestz\n" % rid)
                                .encode("utf-8"))
                        else:
                            self._reply(200, "application/json",
                                        json.dumps(trace)
                                        .encode("utf-8"))
                        return
                    # one request's flight record as a Chrome trace
                    fr = srv.flight
                    rec = fr.get(rid) if fr is not None else None
                    if rec is None:
                        detail = ("no flight record for request %r"
                                  % rid) if fr is not None else \
                            "no flight recorder registered (serving off?)"
                        self._reply(404, "text/plain; charset=utf-8",
                                    (detail + "; see /requestz\n")
                                    .encode("utf-8"))
                    else:
                        # on a batching replica, merge the request's
                        # scheduler iterations in as slot-Gantt lanes
                        # (which iterations it shared, and with whom)
                        ring = getattr(srv.batch, "batch_flight", None)\
                            if srv.batch is not None else None
                        iters = ring.for_request(rid) \
                            if ring is not None else None
                        self._reply(
                            200, "application/json",
                            json.dumps(telemetry.request_chrome_trace(
                                rec, batch_iters=iters))
                            .encode("utf-8"))
                else:
                    trace = telemetry.events_to_chrome(
                        srv.registry.recent_events())
                    self._reply(200, "application/json",
                                json.dumps(trace).encode("utf-8"))
            elif path == "/requestz":
                q = parse_qs(query, keep_blank_values=True)
                fr = srv.flight
                rid = (q.get("request") or [None])[0]
                if rid is not None:
                    # ONE raw flight record by id — the cross-process
                    # stitch fetches a replica's half of a routed
                    # request through this (routerd.stitched_trace)
                    rec = fr.get(rid) if fr is not None else None
                    if rec is None:
                        self._reply(404, "text/plain; charset=utf-8",
                                    ("no flight record for request %r\n"
                                     % rid).encode("utf-8"))
                    else:
                        self._reply(200, "application/json",
                                    json.dumps(rec).encode("utf-8"))
                    return
                try:
                    # ?n=<k>: the ring default (256 records) is an
                    # unreadable wall in a browser — bound the listing
                    n = int((q.get("n") or ["0"])[0])
                except ValueError:
                    self._reply(400, "text/plain; charset=utf-8",
                                b"n must be an integer\n")
                    return
                recs = fr.list() if fr is not None else []
                total = len(recs)
                if n > 0:
                    recs = recs[:n]
                if q.get("json"):
                    body = {"requests": recs,
                            "capacity": fr.cap if fr is not None else 0,
                            "total": total, "shown": len(recs)}
                    self._reply(200, "application/json",
                                json.dumps(body).encode("utf-8"))
                else:
                    # HTML by default, ?json=1 for the raw snapshot —
                    # the same contract as /fleetz and /programz
                    self._reply(200, "text/html; charset=utf-8",
                                requestz_html(
                                    recs, total,
                                    fr.cap if fr is not None else 0,
                                    n).encode("utf-8"))
            elif path == "/batchz":
                fe = srv.batch
                q = parse_qs(query)
                try:
                    # ?n=<k>: iteration-ring rows shown (default 64 —
                    # the full ring is an unreadable wall)
                    n = int((q.get("n") or ["64"])[0])
                except ValueError:
                    self._reply(400, "text/plain; charset=utf-8",
                                b"n must be an integer\n")
                    return
                # ONE snapshot per request: it takes the frontend's
                # admission lock, so the probe must not pay it twice
                snap = fe.batch_snapshot(ring=max(0, n)) \
                    if fe is not None else None
                if snap is None:
                    self._reply(404, "text/plain; charset=utf-8",
                                b"no batching frontend registered "
                                b"(serve_buckets unset, or this "
                                b"process is not serving)\n")
                elif q.get("json"):
                    self._reply(200, "application/json",
                                json.dumps(snap).encode("utf-8"))
                else:
                    self._reply(200, "text/html; charset=utf-8",
                                batchz_html(snap).encode("utf-8"))
            elif path == "/programz":
                lg = srv.perf
                q = parse_qs(query)
                try:
                    # ?n=<k>: program cards shown (default all — the
                    # grid is small; floods of shapes are not). The
                    # query contract outranks the subsystem check: a
                    # malformed ?n is 400 even with no ledger wired.
                    n = int((q.get("n") or ["0"])[0])
                except ValueError:
                    self._reply(400, "text/plain; charset=utf-8",
                                b"n must be an integer\n")
                    return
                if lg is None:
                    self._reply(404, "text/plain; charset=utf-8",
                                b"no performance ledger registered "
                                b"(perf_ledger=0?)\n")
                else:
                    snap = lg.snapshot()
                    if n > 0:
                        snap = dict(snap)
                        snap["cards"] = (snap.get("cards") or [])[:n]
                    if q.get("json"):
                        self._reply(200, "application/json",
                                    json.dumps(snap).encode("utf-8"))
                    else:
                        self._reply(200, "text/html; charset=utf-8",
                                    programz_html(snap).encode("utf-8"))
            elif path == "/compilez":
                lg = srv.perf
                q = parse_qs(query)
                try:
                    # ?n=<k>: compile-ring rows shown (default 64).
                    # Contract first: malformed ?n is 400, ledger or not.
                    n = int((q.get("n") or ["64"])[0])
                except ValueError:
                    self._reply(400, "text/plain; charset=utf-8",
                                b"n must be an integer\n")
                    return
                if lg is None:
                    self._reply(404, "text/plain; charset=utf-8",
                                b"no performance ledger registered "
                                b"(perf_ledger=0?)\n")
                else:
                    recs = lg.recent_compiles()
                    total = len(recs)
                    if n > 0:
                        recs = recs[:n]
                    body = {"compiles": recs, "total": total,
                            "shown": len(recs),
                            "readiness": lg.readiness()}
                    if q.get("json"):
                        self._reply(200, "application/json",
                                    json.dumps(body).encode("utf-8"))
                    else:
                        self._reply(200, "text/html; charset=utf-8",
                                    compilez_html(body).encode("utf-8"))
            elif path == "/fleetz":
                fl = srv.fleet
                q = parse_qs(query)
                try:
                    # ?n=<k>: replica rows shown (default all).
                    # Contract first: malformed ?n is 400, fleet or not.
                    n = int((q.get("n") or ["0"])[0])
                except ValueError:
                    self._reply(400, "text/plain; charset=utf-8",
                                b"n must be an integer\n")
                    return
                if fl is None:
                    self._reply(404, "text/plain; charset=utf-8",
                                b"no fleet registered (this process is "
                                b"not a router; task = route wires "
                                b"one)\n")
                else:
                    snap = fl.fleet_snapshot()
                    if n > 0:
                        snap = dict(snap)
                        snap["replicas"] = \
                            (snap.get("replicas") or [])[:n]
                    if q.get("json"):
                        self._reply(200, "application/json",
                                    json.dumps(snap).encode("utf-8"))
                    else:
                        self._reply(200, "text/html; charset=utf-8",
                                    fleetz_html(snap).encode("utf-8"))
            elif path == "/why":
                q = parse_qs(query, keep_blank_values=True)
                rid = (q.get("request") or [None])[0]
                if rid is None:
                    self._reply(400, "text/plain; charset=utf-8",
                                b"which request? /why?request=<id> "
                                b"(ids on /requestz)\n")
                    return
                if srv.fleet is not None and hasattr(
                        srv.fleet, "stitched_why"):
                    # router process: the cross-process verdict — the
                    # router's own lane decomposition with the winning
                    # replica's books stitched into the latency lane
                    # (routerd.stitched_why)
                    payload = srv.fleet.stitched_why(rid)
                else:
                    fr = srv.flight
                    rec = fr.get(rid) if fr is not None else None
                    payload = None if rec is None else {
                        "id": rec.get("id"),
                        "outcome": rec.get("outcome"),
                        # replicas stamp the verdict at record time
                        # (servd._observe_request); classify on the
                        # fly for records that predate the autopsy
                        "autopsy": rec.get("autopsy")
                        or autopsy.classify_record(rec),
                        "hops": {}}
                if payload is None:
                    self._reply(404, "text/plain; charset=utf-8",
                                ("no flight record for request %r; "
                                 "see /requestz\n" % rid)
                                .encode("utf-8"))
                elif q.get("json"):
                    self._reply(200, "application/json",
                                json.dumps(payload).encode("utf-8"))
                else:
                    self._reply(200, "text/html; charset=utf-8",
                                why_html(payload).encode("utf-8"))
            elif path == "/eventz":
                q = parse_qs(query)
                try:
                    # ?n=<k>: newest incident rows shown (default all —
                    # the transition streams are sparse by design)
                    n = int((q.get("n") or ["0"])[0])
                except ValueError:
                    self._reply(400, "text/plain; charset=utf-8",
                                b"n must be an integer\n")
                    return
                if srv.fleet is not None and hasattr(
                        srv.fleet, "fleet_eventz"):
                    # router process: the fleet-merged timeline — this
                    # router's incidents plus every replica's own
                    # /eventz feed under one wall clock
                    rows = srv.fleet.fleet_eventz(
                        n if n > 0 else None)
                else:
                    fr = srv.flight
                    rows = autopsy.incidents(
                        srv.registry.recent_events(),
                        t0_wall=getattr(srv.registry, "t0_wall", 0.0),
                        records=fr.list() if fr is not None else None,
                        n=n if n > 0 else None)
                if q.get("json"):
                    body = {"rows": rows, "shown": len(rows)}
                    self._reply(200, "application/json",
                                json.dumps(body).encode("utf-8"))
                else:
                    self._reply(200, "text/html; charset=utf-8",
                                eventz_html(rows, n).encode("utf-8"))
            elif path == "/profilez":
                prof = srv.profiler
                if prof is None:
                    self._reply(404, "text/plain; charset=utf-8",
                                b"no profiler registered (learn_task "
                                b"runs wire one whenever status_port "
                                b"is set; embedders call "
                                b"statusd.set_profiler)\n")
                else:
                    secs = (parse_qs(query).get("secs")
                            or ["2"])[0]
                    try:
                        secs = float(secs)
                    except ValueError:
                        self._reply(400, "text/plain; charset=utf-8",
                                    b"secs must be a number\n")
                        return
                    # a PREVIOUS capture's failure surfaces on the next
                    # request (the 200 goes out before a capture runs)
                    prev_err = getattr(prof, "last_error", None)
                    ok, detail = prof.start(secs)
                    if ok:
                        body = ("profiling for %gs into %s\n(xprof/"
                                "TensorBoard-profile format; summarize "
                                "with tools/summarize_trace.py)\n"
                                % (secs, detail))
                        if prev_err:
                            body += ("WARNING: previous capture FAILED: "
                                     "%s\n" % prev_err)
                        self._reply(200, "text/plain; charset=utf-8",
                                    body.encode("utf-8"))
                    else:
                        code = 409 if "in progress" in detail else 400
                        self._reply(code, "text/plain; charset=utf-8",
                                    (detail + "\n").encode("utf-8"))
            else:
                # the endpoint table IS the list — a new endpoint that
                # skips ENDPOINTS is invisible here and fails the
                # parametrized contract test
                self._reply(404, "text/plain; charset=utf-8",
                            ("not found; endpoints: %s\n"
                             % " ".join(p for p, _, _ in ENDPOINTS))
                            .encode("utf-8"))
        except Exception as e:    # a broken probe must not kill the server
            try:
                self._reply(500, "text/plain; charset=utf-8",
                            ("internal error: %r\n" % e).encode("utf-8"))
            except Exception:
                pass


class StatusServer:
    """The live-introspection HTTP server. Construct + ``start()`` binds
    a daemon thread; ``stop()`` shuts it down. One per process (the
    module-level ``start``/``stop`` manage the singleton the learn task
    uses); tests build isolated instances against private registries."""

    def __init__(self, port: int = 0, host: str = "",
                 registry=None):
        self.registry = registry if registry is not None else telemetry._REG
        self.run_info: Dict[str, object] = {}
        self.progress: Dict[str, object] = {}
        # serving wiring (set_flight_recorder / set_slo): the per-request
        # flight ring behind /trace?request= and /requestz, and the SLO
        # tracker behind the cxxnet_slo_* gauges and the /statusz section
        self.flight: Optional[telemetry.FlightRecorder] = None
        self.slo: Optional[SLOTracker] = None
        # per-tenant SLO trackers ({tenant: SLOTracker}) — the
        # cxxnet_slo_tenant_* label rows, the /statusz tenant lines,
        # and the slo_tenants half of the /metrics?json=1 federation
        # feed (doc/serving.md "Multi-tenant QoS")
        self.slo_tenants: Dict[str, SLOTracker] = {}
        # performance-ledger wiring (set_perf / set_profiler): the
        # perf.Ledger behind /programz and the cxxnet_program_* series,
        # and the perf.ProfilerCapture behind /profilez
        self.perf = None
        self.profiler = None
        # batching wiring (set_batch): the ServeFrontend whose
        # batch_snapshot()/batch_flight back /batchz, the
        # cxxnet_decode_* series, the /metrics?json=1 federation feed,
        # and the /trace slot-Gantt lanes
        self.batch = None
        # fleet wiring (set_fleet): the routerd.Router behind /fleetz
        # and the cxxnet_fleet_* series (task = route registers it)
        self.fleet = None
        # the conservation-law auditor behind cxxnet_books_* — the
        # PROCESS-wide one by default (servd/routerd register their
        # laws there), swappable for isolation via set_auditor(None)
        self.auditor = telemetry.auditor()
        # (name, probe_fn, liveness): see register_probe
        self.probes: List[Tuple[str, Callable[[], Tuple[bool, str]],
                                bool]] = []
        # loopback by default: /statusz exposes the full run config (data
        # and model paths included), so wide exposure is OPT-IN —
        # status_host=0.0.0.0 for a cross-host Prometheus scrape
        self._httpd = _HTTPServer((host or "127.0.0.1", int(port)),
                                  _Endpoint)
        self._httpd.statusd = self
        self.host = self._httpd.server_address[0]
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None
        # cxxlint: disable=wallclock — rendered via localtime on /statusz
        self.t0_wall = time.time()

    # -- lifecycle -----------------------------------------------------
    def start(self) -> "StatusServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="cxn-statusd",
            daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def __enter__(self) -> "StatusServer":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.stop()
        return False

    # -- wiring --------------------------------------------------------
    def register_probe(self, name: str,
                       fn: Callable[[], Tuple[bool, str]],
                       liveness: bool = False) -> None:
        """``fn() -> (ok, detail)``; a False (or raising) probe flips
        /healthz (readiness) to 503 with the detail in the body.
        ``liveness=True`` probes additionally flip /livez — reserve those
        for "restart me" conditions (dead thread), not "don't route to
        me" ones (draining, breaker open, rollback in flight)."""
        self.probes.append((name, fn, bool(liveness)))

    def wire_health(self, recovery=None) -> None:
        """Wire the standard health sources: the watchdog heartbeat
        channels are always consulted (health.channel_status); a
        RecoveryPolicy adds the unresolved-anomaly probe — 503 from the
        moment an anomaly decides rollback/abort until the driver calls
        ``recovery.resolve()`` after the restore."""
        if recovery is not None:
            def _probe():
                a = recovery.pending
                if a is None:
                    return True, "no unresolved anomaly"
                return False, "unresolved anomaly: " + a.describe()
            self.register_probe("anomaly", _probe)

    def all_failures(self, channels: Optional[list] = None) \
            -> Tuple[List[Tuple[str, str]], List[Tuple[str, str]]]:
        """ONE evaluation of every heartbeat channel and probe ->
        ``(readiness_failures, liveness_failures)`` — so a scrape that
        needs both views (the cxxnet_healthy and cxxnet_live gauges)
        runs each probe once and the two lists can never disagree about
        a single evaluation. An overdue heartbeat fails BOTH: a hung
        process is neither routable nor worth keeping; probe failures
        are readiness-only unless registered with ``liveness=True``."""
        if channels is None:
            channels = health_mod.channel_status()
        ready: List[Tuple[str, str]] = []
        live: List[Tuple[str, str]] = []
        for ch, age, timeout, overdue in channels:
            if overdue:
                f = ("watchdog:" + ch,
                     "heartbeat silent %.2fs (timeout %.2fs)"
                     % (age, timeout))
                ready.append(f)
                live.append(f)
        for name, fn, liveness in list(self.probes):
            try:
                ok, detail = fn()
            except Exception as e:
                ok, detail = False, "probe raised: %r" % e
            if not ok:
                ready.append((name, detail))
                if liveness:
                    live.append((name, detail))
        return ready, live

    def health_failures(self, channels: Optional[list] = None,
                        liveness_only: bool = False) \
            -> List[Tuple[str, str]]:
        """Readiness failures by default; ``liveness_only=True`` gives
        the /livez view (overdue heartbeats + liveness probes)."""
        ready, live = self.all_failures(channels)
        return live if liveness_only else ready

    # -- renderers -----------------------------------------------------
    def metrics_text(self) -> str:
        # ONE heartbeat snapshot and ONE probe pass per scrape: the
        # healthy/live gauges and the per-channel age rows must agree
        # within a single response
        channels = health_mod.channel_status()
        ready, live = self.all_failures(channels)
        books = None
        if self.auditor is not None:
            # EVERY scrape sweeps: a violation can never hide between
            # daemon periods, and the latched account below is at most
            # one scrape old
            self.auditor.sweep()
            books = self.auditor.snapshot()
        return prometheus_metrics(
            self.registry.metrics_snapshot(),
            progress=dict(self.progress),
            health_failures=ready,
            channels=channels,
            live_failures=live,
            slo=self.slo.snapshot() if self.slo is not None else None,
            slo_tenants={t: tr.snapshot()
                         for t, tr in sorted(self.slo_tenants.items())}
            if self.slo_tenants else None,
            perf=self.perf.snapshot() if self.perf is not None else None,
            batch=self.batch.batch_snapshot()
            if self.batch is not None else None,
            fleet=self.fleet.fleet_snapshot()
            if self.fleet is not None else None,
            books=books)

    def statusz_html(self) -> str:
        reg = self.registry
        snap = reg.metrics_snapshot()
        s = reg.summary()
        esc = html.escape
        parts = ["<html><head><title>cxxnet statusz</title></head>"
                 "<body><h1>cxxnet_tpu statusz</h1>"]

        def table(title, rows):
            if not rows:
                return
            parts.append("<h2>%s</h2><pre>" % esc(title))
            w = max(len(str(k)) for k, _ in rows)
            for k, v in rows:
                parts.append("%-*s  %s" % (w, esc(str(k)), esc(str(v))))
            parts.append("</pre>")

        info = [(k, v) for k, v in self.run_info.items() if k != "config"]
        info.append(("uptime", "%.1fs" % snap["uptime_s"]))
        info.append(("process", snap["process"]))
        info.append(("started", time.strftime(
            "%Y-%m-%d %H:%M:%S", time.localtime(self.t0_wall))))
        table("run", info)
        prog = sorted(self.progress.items())
        table("progress", prog)

        channels = health_mod.channel_status()
        fails, live_fails = self.all_failures(channels)
        rows = [("healthz (ready)", "503 UNHEALTHY" if fails
                 else "200 ok"),
                ("livez (alive)", "503 DEAD" if live_fails
                 else "200 alive")]
        rows += [("probe " + n, d) for n, d in fails]
        for ch, age, timeout, overdue in channels:
            rows.append(("heartbeat " + ch, "%.2fs ago (timeout %.1fs)%s"
                         % (age, timeout, " OVERDUE" if overdue else "")))
        table("health", rows)

        if self.slo is not None:
            sn = self.slo.snapshot()
            obj = sn["objectives"]
            objs = []
            if obj["ttft_ms"] > 0:
                objs.append("ttft<=%gms" % obj["ttft_ms"])
            if obj["p99_ms"] > 0:
                objs.append("latency<=%gms" % obj["p99_ms"])
            objs.append("availability>=%g" % obj["availability"])
            reasons = " ".join("%s=%d" % kv
                               for kv in sorted(sn["by_reason"].items()))
            table("slo", [
                ("objectives", "  ".join(objs)),
                ("window", "%.0fs: %d requests, %d bad%s"
                 % (sn["window_s"], sn["requests"], sn["bad"],
                    ("  (" + reasons + ")") if reasons else "")),
                ("error budget", "%.4f%% of requests may be bad"
                 % (100 * sn["budget"])),
                ("burn rate", "%.2fx%s" % (sn["burn_rate"],
                                           "  BURNING" if sn["alert"]
                                           else ""))])
        if self.fleet is not None:
            fsnap = self.fleet.fleet_snapshot()
            by: Dict[str, int] = {}
            for r in fsnap.get("replicas") or []:
                by[r.get("state", "?")] = by.get(r.get("state", "?"),
                                                 0) + 1
            table("fleet", [
                ("replicas", "%d configured, %d eligible (%s) — see "
                 "/fleetz" % (len(fsnap.get("replicas") or []),
                              fsnap.get("eligible", 0),
                              " ".join("%s=%d" % kv
                                       for kv in sorted(by.items()))
                              or "none")),
                ("router", " ".join(
                    "%s=%s" % kv
                    for kv in sorted((fsnap.get("stats")
                                      or {}).items())))])

        if self.flight is not None and len(self.flight):
            latest = self.flight.list()[0]
            table("requests", [
                ("flight recorder", "%d of last %d requests recorded"
                 % (len(self.flight), self.flight.cap)),
                ("latest", "id=%s outcome=%s total=%s"
                 % (latest.get("id"), latest.get("outcome"),
                    _ms(None if latest.get("total_s") is None
                        else latest["total_s"] * 1e3)))])

        # continuous-batching occupancy: the honest weighted mean over
        # decode iterations (serve.batch_slot_iterations /
        # serve.batch_iterations — a last-write gauge scraped between
        # batches lies); 1.00 means every pass served one sequence
        iters = snap["counters"].get("serve.batch_iterations", 0)
        if iters:
            slots = snap["counters"].get("serve.batch_slot_iterations",
                                         0)
            rows = [
                ("mean occupancy", "%.2f sequences/pass over %d decode "
                 "iterations" % (slots / float(iters), iters)),
                ("last pass", snap["gauges"].get(
                    "serve.batch_occupancy", "n/a"))]
            bsnap = self.batch.batch_snapshot() \
                if self.batch is not None else None
            if bsnap:
                kv_pct = bsnap.get("kv_live_pct")
                rows.append(
                    ("kv cache", "%s MiB allocated, %s%% live — see "
                     "/batchz" % (_mib(bsnap.get("kv_bytes")),
                                  "n/a" if kv_pct is None
                                  else "%.1f" % kv_pct)))
                rows.append(
                    ("convoy", "%s (%d episode(s))"
                     % ("ACTIVE" if bsnap.get("convoy") else "none",
                        bsnap.get("convoys", 0))))
            table("batching", rows)

        if self.perf is not None:
            psnap = self.perf.snapshot()
            hbm = psnap.get("hbm") or {}
            prows = [
                ("cards", "%d compiled programs (see /programz)"
                 % len(psnap.get("cards") or [])),
                ("hbm peak", "%s MiB (headroom %s MiB)"
                 % (_mib(hbm.get("peak_bytes")),
                    _mib(hbm.get("headroom_bytes"))))]
            if hbm.get("decode_kv_bytes") is not None:
                prows.append(("hbm decode kv", "%s MiB (live decode "
                              "cache — a first-class HBM consumer)"
                              % _mib(hbm["decode_kv_bytes"])))
            table("program ledger", prows)

        ck = reg.last_event("ckpt_save")
        if ck is not None and "ts" in ck:
            table("checkpoint", [
                ("last save", ck.get("path", "?")),
                ("age", "%.1fs" % (snap["uptime_s"] - ck["ts"])),
                ("bytes", ck.get("bytes", "?"))])

        hist_rows = []
        for name, a in sorted(s.get("hists", {}).items(),
                              key=lambda kv: -kv[1]["sum_s"]):
            # a declared-but-never-fired series (TTFT before the first
            # request) renders "n/a", not a 0.00ms lie
            hist_rows.append((name, "n=%d p50=%s p90=%s p99=%s"
                              % (a["count"], _ms(a["p50_ms"]),
                                 _ms(a["p90_ms"]), _ms(a["p99_ms"]))))
        table("latency histograms", hist_rows)

        comp = s.get("compiles", {})
        if comp.get("count"):
            table("recompiles", [("count", comp["count"]),
                                 ("total_s", comp["total_s"])] +
                  sorted(comp.get("by_cause", {}).items()))
        table("counters", sorted(snap["counters"].items()))
        table("gauges", sorted(snap["gauges"].items()))

        cfg = self.run_info.get("config")
        if cfg:
            parts.append("<details><summary>config (%d keys)</summary><pre>"
                         % len(cfg))
            for k, v in cfg:
                parts.append("%s = %s" % (esc(str(k)), esc(str(v))))
            parts.append("</pre></details>")
        parts.append("<p>endpoints: %s</p></body></html>"
                     % " ".join("<a href='%s'>%s</a>" % (p, p)
                                for p, _, _ in ENDPOINTS))
        return "\n".join(parts)


# ----------------------------------------------------------------------
# module-level singleton surface (the learn-task wiring); every function
# is a cheap no-op while no server is running, so instrumented call
# sites (per-batch progress updates) cost one attribute test by default
_SERVER: Optional[StatusServer] = None


def start(port: int = 0, host: str = "", registry=None) -> StatusServer:
    global _SERVER
    stop()
    _SERVER = StatusServer(port, host=host, registry=registry).start()
    # the continuous half of the conservation-law auditor: scrapes
    # sweep on demand (metrics_text), the daemon sweeps between them —
    # an unwatched process still latches cxxnet_books_broken
    telemetry.auditor().start(0.5)
    return _SERVER


def stop() -> None:
    global _SERVER
    if _SERVER is not None:
        s, _SERVER = _SERVER, None
        s.stop()
        telemetry.auditor().stop()


def active() -> Optional[StatusServer]:
    return _SERVER


def set_run_info(**kv) -> None:
    s = _SERVER
    if s is not None:
        s.run_info.update(kv)


def update_progress(**kv) -> None:
    s = _SERVER
    if s is not None:
        s.progress.update(kv)


def register_probe(name: str, fn, liveness: bool = False) -> None:
    s = _SERVER
    if s is not None:
        s.register_probe(name, fn, liveness=liveness)


def wire_health(recovery=None) -> None:
    s = _SERVER
    if s is not None:
        s.wire_health(recovery)


def set_flight_recorder(fr) -> None:
    """Attach a telemetry.FlightRecorder — /trace?request=<id> and
    /requestz serve from it. No-op without a running server."""
    s = _SERVER
    if s is not None:
        s.flight = fr


def set_slo(tracker: Optional[SLOTracker]) -> None:
    """Attach an SLOTracker — /metrics exports its cxxnet_slo_* gauges
    and /statusz renders the budget account. No-op without a server."""
    s = _SERVER
    if s is not None:
        s.slo = tracker


def set_batch(frontend) -> None:
    """Attach a batching ServeFrontend (or any object exposing
    ``batch_snapshot(ring=...)`` and ``batch_flight``) — /batchz, the
    cxxnet_decode_* /metrics families, the /metrics?json=1 federation
    feed, and the /trace slot-Gantt lanes serve from it. None clears
    (a reload that swapped to a solo frontend)."""
    s = _SERVER
    if s is not None:
        s.batch = frontend


def set_slo_tenants(trackers) -> None:
    """Attach the per-tenant SLOTracker map ({tenant: tracker}) —
    /metrics exports cxxnet_slo_tenant_* label rows and the
    /metrics?json=1 federation feed carries each tenant's window for
    the fleet-wide per-tenant merge. None/empty clears."""
    s = _SERVER
    if s is not None:
        s.slo_tenants = dict(trackers or {})


def set_perf(ledger) -> None:
    """Attach a perf.Ledger — /programz and the cxxnet_program_* /
    cxxnet_hbm_* series serve from it. No-op without a server."""
    s = _SERVER
    if s is not None:
        s.perf = ledger


def set_profiler(capture) -> None:
    """Attach a perf.ProfilerCapture — /profilez?secs=N starts captures
    through its one-at-a-time guard. No-op without a server."""
    s = _SERVER
    if s is not None:
        s.profiler = capture


def set_fleet(router) -> None:
    """Attach a routerd.Router — /fleetz and the cxxnet_fleet_* series
    serve from its fleet_snapshot(). No-op without a server."""
    s = _SERVER
    if s is not None:
        s.fleet = router


def set_auditor(aud) -> None:
    """Swap the conservation-law auditor behind the cxxnet_books_*
    series (the process-wide telemetry.auditor() by default). None
    stops exporting books state. No-op without a server."""
    s = _SERVER
    if s is not None:
        s.auditor = aud


# ----------------------------------------------------------------------
def selftest(verbose: bool = False) -> int:
    """Serve on port 0, scrape every endpoint over a real socket,
    validate the Prometheus text format, flip /healthz with a failing
    probe, shut down. Jax-free; ``make check`` gates on it. Runs with
    runtime lock-order enforcement on for the registry/SLO/flight
    locks (utils/lockrank.py)."""
    with lockrank.enforced():
        return _selftest_body(verbose)


def _selftest_body(verbose: bool = False) -> int:
    from urllib.request import urlopen
    from urllib.error import HTTPError

    reg = telemetry._Registry()
    reg.enable()                       # in-memory sink
    with reg.span("selftest.step"):
        time.sleep(0.001)
    reg.count("selftest.requests", 3)
    reg.gauge("selftest.level", 7)
    reg.hist("selftest.latency", 0.012)
    reg.declare_hist("selftest.never_fired")   # -> "n/a", empty buckets

    srv = StatusServer(0, host="127.0.0.1", registry=reg).start()
    srv.slo = SLOTracker(ttft_ms=50.0, availability=0.999,
                         min_requests=3, window_s=60.0)
    srv.flight = telemetry.FlightRecorder(cap=8)
    srv.flight.record({"id": "7", "outcome": "served", "tokens_in": 4,
                       "tokens_out": 8, "total_s": 0.061, "ttft_s": 0.02,
                       "phases": {"queue_wait": 0.001, "dispatch": 0.0005,
                                  "prefill": 0.02, "decode": 0.04},
                       "recompiles": []})
    try:
        base = "http://127.0.0.1:%d" % srv.port

        metrics = urlopen(base + "/metrics", timeout=5).read().decode()
        for line in metrics.splitlines():
            if not line or line.startswith("#"):
                continue
            assert PROM_LINE_RE.match(line), \
                "invalid Prometheus line: %r" % line
        assert "cxxnet_selftest_requests_total" in metrics
        assert 'cxxnet_selftest_step_seconds_bucket' in metrics
        assert 'le="+Inf"' in metrics
        # a declared-but-empty series still exports (zeroed) buckets
        assert "cxxnet_selftest_never_fired_seconds_bucket" in metrics
        # the SLO account: healthy window -> burn gauge 0
        assert 'cxxnet_slo_burn{process="0"} 0' in metrics
        assert "cxxnet_slo_burn_rate" in metrics

        # per-request flight recorder: HTML by default (the ?json=1
        # contract /fleetz and /programz follow), listable as JSON,
        # ?n=<k> bounded, one raw record by ?request=<id> (the
        # cross-process stitch feed)
        rpage = urlopen(base + "/requestz", timeout=5).read().decode()
        assert "flight recorder" in rpage and ">7<" not in rpage
        reqz = json.loads(urlopen(base + "/requestz?json=1",
                                  timeout=5).read())
        assert reqz["requests"] and reqz["requests"][0]["id"] == "7"
        srv.flight.record({"id": "8", "outcome": "shed",
                           "shed_at": "queue", "total_s": 0.0,
                           "phases": {}, "recompiles": []})
        lim = json.loads(urlopen(base + "/requestz?json=1&n=1",
                                 timeout=5).read())
        assert lim["shown"] == 1 and lim["total"] == 2 \
            and lim["requests"][0]["id"] == "8"
        one = json.loads(urlopen(base + "/requestz?request=7",
                                 timeout=5).read())
        assert one["id"] == "7" and one["outcome"] == "served"
        try:
            urlopen(base + "/requestz?request=nope", timeout=5)
            raise AssertionError("unknown request id should 404")
        except HTTPError as e:
            assert e.code == 404
        try:
            urlopen(base + "/requestz?n=x", timeout=5)
            raise AssertionError("non-integer n should 400")
        except HTTPError as e:
            assert e.code == 400
        # the federation feed: raw registry snapshot + SLO window
        mj = json.loads(urlopen(base + "/metrics?json=1",
                                timeout=5).read())
        assert mj["metrics"]["counters"]["selftest.requests"] == 3
        assert "selftest.latency" in mj["metrics"]["hists"]
        assert mj["slo"]["min_requests"] == 3
        rtrace = json.loads(urlopen(
            base + "/trace?request=7", timeout=5).read())
        names = [t["name"] for t in rtrace["traceEvents"]
                 if t.get("ph") == "X"]
        assert names == ["queue_wait", "dispatch", "prefill", "decode"]
        try:
            urlopen(base + "/trace?request=nope", timeout=5)
            raise AssertionError("unknown request id should 404")
        except HTTPError as e:
            assert e.code == 404
        # request autopsy: /why decomposes the record's wall time into
        # named causes, exactly ONE primary verdict, and the attributed
        # seconds tile >= 95% of wall_s
        why = json.loads(urlopen(base + "/why?request=7&json=1",
                                 timeout=5).read())
        aut = why["autopsy"]
        assert aut["primary"] == "decode_baseline", aut
        assert sum(aut["causes"].values()) >= 0.95 * aut["wall_s"], aut
        wpage = urlopen(base + "/why?request=7",
                        timeout=5).read().decode()
        assert "PRIMARY VERDICT" in wpage and "decode_baseline" in wpage
        try:
            urlopen(base + "/why?request=nope", timeout=5)
            raise AssertionError("unknown request id should 404")
        except HTTPError as e:
            assert e.code == 404
        try:
            urlopen(base + "/why", timeout=5)
            raise AssertionError("missing request id should 400")
        except HTTPError as e:
            assert e.code == 400
        # incident timeline: a transition pair and a point event merge
        # into wall-clock-ordered rows on /eventz
        reg.record({"ev": "kv_pressure", "pressure": 1, "ts": 0.01})
        reg.record({"ev": "kv_pressure", "pressure": 0, "ts": 0.05})
        reg.record({"ev": "serve_drain", "ts": 0.06})
        evz = json.loads(urlopen(base + "/eventz?json=1",
                                 timeout=5).read())
        kinds = [r["kind"] for r in evz["rows"]]
        assert "kv_pressure" in kinds and "serve_drain" in kinds, kinds
        walls = [r["t_wall"] for r in evz["rows"]]
        assert walls == sorted(walls)
        lim2 = json.loads(urlopen(base + "/eventz?json=1&n=1",
                                  timeout=5).read())
        assert lim2["shown"] == 1
        epage = urlopen(base + "/eventz", timeout=5).read().decode()
        assert "incident timeline" in epage
        try:
            urlopen(base + "/eventz?n=x", timeout=5)
            raise AssertionError("non-integer n should 400")
        except HTTPError as e:
            assert e.code == 400
        # SLO burn flips under a flood of objective-violating requests
        for _ in range(5):
            srv.slo.observe(ok=True, ttft_s=0.5)     # >> 50ms objective
        m2 = urlopen(base + "/metrics", timeout=5).read().decode()
        assert 'cxxnet_slo_burn{process="0"} 1' in m2

        assert urlopen(base + "/healthz", timeout=5).status == 200
        assert urlopen(base + "/livez", timeout=5).status == 200
        srv.register_probe("boom", lambda: (False, "injected failure"))
        try:
            urlopen(base + "/healthz", timeout=5)
            raise AssertionError("healthz should be 503")
        except HTTPError as e:
            assert e.code == 503
            assert "injected failure" in e.read().decode()
        # a readiness failure is NOT a liveness failure: /livez stays 200
        assert urlopen(base + "/livez", timeout=5).status == 200
        m = urlopen(base + "/metrics", timeout=5).read().decode()
        assert 'cxxnet_healthy{process="0"} 0' in m
        assert 'cxxnet_live{process="0"} 1' in m
        srv.register_probe("dead", lambda: (False, "worker died"),
                           liveness=True)
        try:
            urlopen(base + "/livez", timeout=5)
            raise AssertionError("livez should be 503")
        except HTTPError as e:
            assert e.code == 503
            assert "worker died" in e.read().decode()
        srv.probes.clear()

        # fleet surfaces: 404 before a router registers, then the
        # /fleetz page + cxxnet_fleet_* series from a snapshot-shaped
        # fake (the real Router drives these in the routerd selftest)
        try:
            urlopen(base + "/fleetz", timeout=5)
            raise AssertionError("fleetz without a fleet should 404")
        except HTTPError as e:
            assert e.code == 404

        class _FakeFleet:
            def fleet_snapshot(self):
                return {"replicas": [
                    {"name": "127.0.0.1:7001", "state": "up",
                     "hold": False, "queue_depth": 2, "in_flight": 1,
                     "outstanding": 1, "ejections": 0,
                     "probe_fails": 0, "last_probe_age_s": 0.1,
                     "detail": "ready"},
                    {"name": "127.0.0.1:7002", "state": "dead",
                     "hold": False, "queue_depth": 0, "in_flight": 0,
                     "outstanding": 0, "ejections": 3,
                     "probe_fails": 3, "last_probe_age_s": None,
                     "detail": "statusd unreachable"},
                    {"name": "127.0.0.1:7003", "state": "up",
                     "standby": True, "hold": False,
                     "queue_depth": 0, "in_flight": 0,
                     "outstanding": 0, "ejections": 0,
                     "probe_fails": 0, "last_probe_age_s": 0.1,
                     "detail": "ready"}],
                    "eligible": 1, "draining": False,
                    "reloading": False,
                    "windows": [{"replica": "127.0.0.1:7001",
                                 "out_s": 1.0, "back_s": 1.5}],
                    "stats": {"accepted": 5, "served": 4, "shed": 1,
                              "errors": 0, "deadline": 0,
                              "retries": 1, "admin": 0,
                              "client_gone": 0}}

        srv.fleet = _FakeFleet()
        fz = urlopen(base + "/fleetz", timeout=5).read().decode()
        assert "127.0.0.1:7001" in fz and "dead" in fz
        assert "drain windows" in fz
        fj = json.loads(urlopen(base + "/fleetz?json=1",
                                timeout=5).read())
        assert fj["eligible"] == 1 and len(fj["replicas"]) == 3
        mf = urlopen(base + "/metrics", timeout=5).read().decode()
        for line in mf.splitlines():
            if line and not line.startswith("#"):
                assert PROM_LINE_RE.match(line), \
                    "invalid Prometheus line: %r" % line
        assert 'cxxnet_fleet_replicas{process="0"} 3' in mf
        assert 'cxxnet_fleet_replicas_eligible{process="0"} 1' in mf
        assert ('cxxnet_fleet_state{process="0",state="dead"} 1'
                in mf)
        # a held-out standby is its OWN state and NOT "up"/routable —
        # a probe-state "up" must not leak into the replica_up gauge
        assert ('cxxnet_fleet_state{process="0",state="standby"} 1'
                in mf)
        assert ('cxxnet_fleet_state{process="0",state="up"} 1'
                in mf)
        assert ('cxxnet_fleet_replica_up{process="0",'
                'replica="127.0.0.1:7003"} 0' in mf)
        assert ('cxxnet_fleet_replica_up{process="0",'
                'replica="127.0.0.1:7002"} 0' in mf)
        assert ('cxxnet_fleet_replica_queue_depth{process="0",'
                'replica="127.0.0.1:7001"} 2' in mf)

        page = urlopen(base + "/statusz", timeout=5).read().decode()
        assert "statusz" in page and "selftest.requests" in page
        assert "fleet" in page and "eligible" in page
        srv.fleet = None
        # never-fired series renders n/a, not 0.00ms; SLO section shows
        assert "selftest.never_fired" in page and "n/a" in page
        assert "burn rate" in page and "BURNING" in page
        trace = json.loads(urlopen(base + "/trace", timeout=5).read())
        assert any(t.get("ph") == "X" for t in trace["traceEvents"])

        # conservation-law auditor: a law that cannot reconcile latches
        # cxxnet_books_broken on the next scrape (metrics_text sweeps),
        # sticky until an operator resets the auditor
        telemetry.audit_register("selftest.books",
                                 lambda: "debit 3 != credit 2")
        try:
            mb = urlopen(base + "/metrics", timeout=5).read().decode()
            for line in mb.splitlines():
                if line and not line.startswith("#"):
                    assert PROM_LINE_RE.match(line), \
                        "invalid Prometheus line: %r" % line
            assert ('cxxnet_books_broken{process="0",'
                    'law="selftest.books"} 1' in mb)
            assert "cxxnet_books_laws" in mb
            # the latch is sticky: a clean follow-up sweep cannot clear
            mb2 = urlopen(base + "/metrics", timeout=5).read().decode()
            assert ('cxxnet_books_broken{process="0",'
                    'law="selftest.books"} 1' in mb2)
        finally:
            telemetry.audit_unregister("selftest.books")
            telemetry.auditor().reset()

        try:
            urlopen(base + "/nope", timeout=5)
            raise AssertionError("unknown path should 404")
        except HTTPError as e:
            assert e.code == 404
            # the 404 body derives from the ENDPOINTS table
            body = e.read().decode()
            for p, _, _ in ENDPOINTS:
                assert p in body, (p, body)
    finally:
        srv.stop()
        reg.disable()
    if verbose:
        print("statusd selftest: /metrics /healthz /livez /statusz "
              "/trace /requestz /why /eventz ok (Prometheus format "
              "valid, readiness vs liveness flips, per-request trace, "
              "autopsy verdict + incident timeline, books latch, SLO "
              "burn flip, empty-series n/a, 404)")
    return 0


if __name__ == "__main__":
    if "--selftest" in sys.argv[1:]:
        sys.exit(selftest(verbose=True))
    print(__doc__)
    sys.exit(1)
