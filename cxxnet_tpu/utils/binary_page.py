"""BinaryPage: the reference's packed-image page format, byte-compatible.

Layout (reference: src/utils/io.h:254-327): a page is one fixed-size block of
``page_ints`` int32 little-endian words (reference kPageSize = 64<<18 words =
64 MiB). Word 0 is the object count n; words 1..n+1 are cumulative object
sizes (word 1 is always 0); object r's bytes occupy
``[page_bytes - cum[r+1], page_bytes - cum[r])`` — payloads pack backward
from the end of the page.

page_ints is parameterizable here (tests use small pages); the default is the
reference's constant, and files written with it are interchangeable with
im2bin output.
"""

from __future__ import annotations

import struct
from typing import BinaryIO, List, Optional

KPAGE_INTS = 64 << 18  # reference kPageSize (number of int32 words)


class BinaryPage:
    def __init__(self, page_ints: int = KPAGE_INTS):
        self.page_ints = page_ints
        self.page_bytes = page_ints * 4
        self.clear()

    def clear(self) -> None:
        self.objs: List[bytes] = []
        self.used_payload = 0

    def size(self) -> int:
        return len(self.objs)

    def _free_bytes(self) -> int:
        return (self.page_ints - (len(self.objs) + 2)) * 4 - self.used_payload

    def push(self, data: bytes) -> bool:
        """Append one object; False if the page is full (reference Push)."""
        if self._free_bytes() < len(data) + 4:
            return False
        self.objs.append(bytes(data))
        self.used_payload += len(data)
        return True

    def __getitem__(self, r: int) -> bytes:
        return self.objs[r]

    def save(self, f: BinaryIO) -> None:
        buf = bytearray(self.page_bytes)
        n = len(self.objs)
        struct.pack_into("<i", buf, 0, n)
        cum = 0
        pos = 4  # word index 1
        struct.pack_into("<i", buf, pos, 0)
        for r, obj in enumerate(self.objs):
            cum += len(obj)
            struct.pack_into("<i", buf, 4 * (r + 2), cum)
            start = self.page_bytes - cum
            buf[start: start + len(obj)] = obj
        f.write(bytes(buf))

    @classmethod
    def load(cls, f: BinaryIO,
             page_ints: int = KPAGE_INTS) -> Optional["BinaryPage"]:
        raw = f.read(page_ints * 4)
        if len(raw) < page_ints * 4:
            return None
        page = cls(page_ints)
        n = struct.unpack_from("<i", raw, 0)[0]
        cums = struct.unpack_from("<%di" % (n + 1), raw, 4)
        for r in range(n):
            start = page.page_bytes - cums[r + 1]
            end = page.page_bytes - cums[r]
            page.objs.append(raw[start:end])
            page.used_payload += end - start
        return page
