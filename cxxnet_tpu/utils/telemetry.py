"""Process-wide structured telemetry: spans, counters, recompile detection.

The reference reported progress with bare printfs per round
(src/cxxnet_main.cpp:330-360); production training systems stand on
first-class runtime instrumentation (TF's system paper, arxiv 1605.08695)
and per-region timing is what drives every subsequent optimization (TVM,
arxiv 1802.04799). This module is that measurement substrate:

* **span timers** — ``with telemetry.span("io.decode"):`` records wall time
  per named region; spans nest (a per-thread stack tracks depth/parent) and
  are safe to emit from worker threads (the decode pool, the prefetcher).
* **counters / gauges** — ``telemetry.count("train.images", n)`` accumulates
  monotonically; ``telemetry.gauge("device.bytes_in_use", v)`` records the
  latest value of a level. ``sample_device_memory()`` snapshots the
  accelerator's allocator stats where the backend exposes them.
* **recompile detector** — ``jit_watch(fn, name, cause=...)`` wraps a jitted
  callable and records a ``compile`` event (with its cause and compile
  seconds) whenever the underlying jit cache grows: exactly once per
  genuinely new (signature, shape) key, never on cache hits.
* **histograms** — ``telemetry.hist("serve.request", seconds)`` feeds a
  fixed LOG-SPACED bucket histogram (``HIST_BUCKETS``: 4 buckets per
  decade, 1µs..1000s, identical in every process), so merging shards from
  a multihost run is exact bucket-count addition — never re-binning.
  Every span duration additionally feeds the histogram of its span name,
  which is what /metrics serves as Prometheus ``_bucket`` series
  (utils/statusd.py) and what bench.py's p50/p90/p99 come from.

Sinks:

* a JSONL run log (one event per line; ``enable(path)``), flushed
  incrementally so a crashed run still leaves its telemetry behind;
* a Chrome-trace / Perfetto JSON export built from the span tree
  (``write_chrome_trace`` or ``chrome_trace``), loadable in
  chrome://tracing or https://ui.perfetto.dev;
* an aggregate ``summary()`` dict (per-span totals, counters, compiles,
  step-time percentiles) — printed by learn_task at end of run and
  attached to bench.py's emitted JSON.

Disabled (the default) the module is near-zero overhead: ``span()`` returns
a shared no-op context manager (no allocation), counters are one
thread-local read plus a branch (the read keeps per-request attribution
working inside a trace context even when disabled), and no events are
ever buffered. Everything is process-global by design —
one training job per process (the Trainer model), one telemetry stream.

Multihost runs get one stream PER PROCESS: ``enable(path, process_index=i)``
substitutes a ``%d`` rank placeholder in the log path (so shards never
clobber each other), tags every event with ``"p": i``, and
``tools/telemetry_report.py --merge shard*.jsonl`` re-aligns the shards on
the shared wall-clock epoch for one cross-host report.

Request attribution (the serving datapath's measurement contract):

* **trace contexts** — ``with telemetry.trace_context(request_id) as tc:``
  tags every span/event recorded on the same thread underneath it with
  ``"req": request_id``, and accumulates per-request counter deltas and
  recompile events on ``tc`` itself — so one served request's telemetry
  can be pulled apart from everything around it. ``telemetry.mark(name)``
  timestamps a named boundary on the active context (the trainer marks
  ``first_token`` at the prefill/decode split — the TTFT boundary).
  Contexts are thread-local and work even with telemetry DISABLED (the
  marks/attribution still flow; only event emission is gated), because
  the serving SLO layer needs TTFT regardless of whether a JSONL log was
  configured.
* **flight recorder** — ``FlightRecorder`` keeps a bounded ring of the
  last N completed request traces (phase split, token counts, outcome,
  recompiles); statusd serves one as a Chrome trace at
  ``/trace?request=<id>`` (``request_chrome_trace``) and lists the ring
  at ``/requestz``.
"""

from __future__ import annotations

import bisect
import io
import json
import os
import sys
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from . import lockrank

__all__ = [
    "enable", "disable", "enabled", "reset", "span", "count", "gauge",
    "hist", "event", "record_compile", "jit_watch",
    "sample_device_memory",
    "flush", "finish", "summary", "brief_summary", "events",
    "recent_events", "last_event", "wall_epoch", "span_event",
    "percentile", "count_by",
    "chrome_trace", "events_to_chrome", "write_chrome_trace",
    "Histogram", "HIST_BUCKETS", "trace_context", "current_trace", "mark",
    "declare_hist", "TraceContext", "FlightRecorder",
    "request_chrome_trace", "REQUEST_PHASES",
    "CompileWindow", "compile_window", "current_compile_window",
    "BooksAuditor", "auditor", "audit_register", "audit_unregister",
    "audit_sweep",
]

# per-span-name duration history kept for live percentiles (the JSONL log
# keeps everything; this only bounds in-memory state on week-long runs)
_DUR_CAP = 8192
# in-memory event buffer bound when NO log sink drains it (bench/library
# mode): oldest events drop past this; aggregates (summary) are unaffected
_PENDING_CAP = 65536
# recent-event ring kept even WITH a log sink — the /trace endpoint's
# snapshot source (statusd serves a live Chrome trace from it)
_RING_CAP = 4096

# Fixed log-spaced histogram bucket upper bounds (seconds): 4 per decade,
# 1µs .. 1000s. FIXED for every histogram in every process by design —
# cross-process/shard merging is then exact bucket-count addition (the
# property Prometheus `le` buckets and telemetry_report --merge rely on).
HIST_BUCKETS = tuple(round(10.0 ** (e / 4.0), 10) for e in range(-24, 13))


def fmt_ms(v) -> str:
    """Render a millisecond figure, turning the empty-series sentinel
    (None — ``Histogram`` on zero observations) into "n/a". The ONE
    renderer of the sentinel, shared by /statusz and the report tools
    so the format cannot drift between them."""
    return "n/a" if v is None else "%.2fms" % v


class Histogram:
    """Fixed-bucket latency histogram (see HIST_BUCKETS). ``counts[i]``
    holds observations with value <= HIST_BUCKETS[i] (and > the previous
    bound); the final slot is the +Inf overflow. Mergeable exactly."""

    __slots__ = ("counts", "sum", "n")

    def __init__(self):
        self.counts = [0] * (len(HIST_BUCKETS) + 1)
        self.sum = 0.0
        self.n = 0

    def observe(self, value: float) -> None:
        v = float(value)
        self.counts[bisect.bisect_left(HIST_BUCKETS, v)] += 1
        self.sum += v
        self.n += 1

    def percentile(self, p: float) -> float:
        """Estimated percentile: walk the cumulative counts to the target
        rank, interpolate linearly inside the bucket. Error is bounded by
        the bucket width (~78% per log-spaced step) — exact enough for
        p50/p90/p99 dashboards, and identical no matter how many shards
        were merged to produce the counts. Ranks landing in the +Inf
        overflow slot are CLAMPED to the last bound (1000s): the result
        must stay finite (strict-JSON logs, bench lines), so a tail past
        1000s reads as exactly 1000s — the overflow bucket's count is
        the tell.

        An EMPTY histogram returns None (never NaN, never a fake 0.0):
        a series that was declared but never fired — TTFT on a run that
        served zero requests — has no percentiles, and 0.0ms would read
        as an impossibly fast tail on /statusz and in bench lines. The
        renderers turn None into "n/a"; JSON sinks carry it as null."""
        if self.n == 0:
            return None
        rank = (p / 100.0) * self.n
        cum = 0
        for i, c in enumerate(self.counts):
            if c == 0:
                continue
            prev = cum
            cum += c
            if cum >= rank:
                lo = HIST_BUCKETS[i - 1] if i > 0 else 0.0
                hi = HIST_BUCKETS[i] if i < len(HIST_BUCKETS) \
                    else HIST_BUCKETS[-1]
                frac = min(1.0, max(0.0, (rank - prev) / c))
                return lo + (hi - lo) * frac
        return HIST_BUCKETS[-1]

    def to_dict(self) -> dict:
        """JSON-friendly sparse snapshot (only nonzero buckets)."""
        return {"buckets": {str(i): c for i, c in enumerate(self.counts)
                            if c},
                "sum": round(self.sum, 9), "count": self.n}

    def merge_dict(self, d: dict) -> "Histogram":
        """Fold a ``to_dict`` snapshot in — EXACT because every histogram
        shares HIST_BUCKETS (shard merge = bucket-count addition). An
        out-of-range bucket index means the snapshot came from a build
        with DIFFERENT buckets (or a corrupted log): merging it would be
        silently wrong, so it raises ValueError for the caller to report."""
        for i, c in (d.get("buckets") or {}).items():
            i = int(i)
            if not 0 <= i < len(self.counts):
                raise ValueError(
                    "histogram bucket index %d out of range (%d buckets) "
                    "— snapshot from a mismatched HIST_BUCKETS version or "
                    "a corrupted log" % (i, len(self.counts)))
            self.counts[i] += int(c)
        self.sum += float(d.get("sum", 0.0))
        self.n += int(d.get("count", 0))
        return self

    def stats(self) -> dict:
        """Summary dict; the percentile fields are None (rendered "n/a",
        serialized null) when the histogram never observed anything."""
        if self.n == 0:
            return {"count": 0, "sum_s": 0.0,
                    "p50_ms": None, "p90_ms": None, "p99_ms": None}
        return {"count": self.n, "sum_s": round(self.sum, 6),
                "p50_ms": round(1e3 * self.percentile(50), 4),
                "p90_ms": round(1e3 * self.percentile(90), 4),
                "p99_ms": round(1e3 * self.percentile(99), 4)}


class _NullSpan:
    """Shared no-op context manager returned by span() when disabled."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("reg", "name", "attrs", "t0", "depth")

    def __init__(self, reg: "_Registry", name: str, attrs):
        self.reg = reg
        self.name = name
        self.attrs = attrs

    def __enter__(self):
        stack = self.reg._stack()
        self.depth = len(stack)
        stack.append(self.name)
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dur = time.perf_counter() - self.t0
        stack = self.reg._stack()
        if stack and stack[-1] is self.name:
            stack.pop()
        self.reg._record_span(self.name, self.t0, dur, self.depth,
                              self.attrs)
        return False


class TraceContext:
    """One request's attribution scope (``with trace_context(rid):``).

    While active on a thread, every span/event that thread records is
    tagged ``"req": request_id``, counter deltas are mirrored into
    ``self.counts``, recompile events into ``self.compiles``, and
    ``mark(name)`` timestamps named boundaries into ``self.marks``
    (perf_counter stamps — the serving worker turns the trainer's
    ``first_token`` mark into TTFT). Contexts nest (innermost wins) and
    deliberately work with telemetry DISABLED: attribution costs a
    thread-local read, and the SLO layer needs the marks whether or not
    a JSONL sink exists."""

    __slots__ = ("reg", "request_id", "marks", "counts", "compiles", "t0")

    def __init__(self, reg: "_Registry", request_id):
        self.reg = reg
        self.request_id = str(request_id)
        self.marks: Dict[str, float] = {}
        self.counts: Dict[str, float] = {}
        self.compiles: List[dict] = []
        self.t0: Optional[float] = None

    def __enter__(self) -> "TraceContext":
        self.t0 = time.perf_counter()
        self.reg._ctx_stack().append(self)
        return self

    def __exit__(self, *exc):
        stack = self.reg._ctx_stack()
        if stack and stack[-1] is self:
            stack.pop()
        return False

    def mark(self, name: str) -> None:
        self.marks[name] = time.perf_counter()


class CompileWindow:
    """Collects the compile records observed on this thread while
    active — the batching dispatcher's stall-attribution bracket around
    work that runs OUTSIDE any request's trace context (warm-session
    creation, the batch-wide decode step): a compile inside the window
    stalled every request aboard the batch, so the dispatcher fans
    ``window.compiles`` out to their flight records as
    ``compile_stall_s``. Like TraceContext it works with telemetry
    DISABLED (thread-local append, no sink needed) and nests — every
    active window on the thread sees the compile. The label also rides
    the perf ledger's compile flight ring as the trigger context."""

    __slots__ = ("reg", "label", "compiles")

    def __init__(self, reg: "_Registry", label):
        self.reg = reg
        self.label = str(label)
        self.compiles: List[dict] = []

    def __enter__(self) -> "CompileWindow":
        self.reg._win_stack().append(self)
        return self

    def __exit__(self, *exc):
        stack = self.reg._win_stack()
        if stack and stack[-1] is self:
            stack.pop()
        return False

    @property
    def stall_s(self) -> float:
        return round(sum(c["dur"] for c in self.compiles), 6)


class _Registry:
    """The process-wide telemetry state. Use the module-level functions;
    the class exists so tests can build isolated instances."""

    def __init__(self):
        self.enabled = False
        self.log_path: Optional[str] = None
        self._log_f: Optional[io.TextIOBase] = None
        # innermost rank by design: every subsystem records telemetry,
        # so nothing may be acquired while this is held
        self._lock = lockrank.lock("telemetry.registry")
        self._tls = threading.local()
        self.process_index = 0
        # the performance ledger's compile hook (utils/perf.py):
        # called by JitWatch with the compiled callable + call args on
        # every detected compile. Survives reset()/enable() — bench
        # resets telemetry between rows without re-wiring the ledger.
        self.compile_hook = None
        self.reset()

    # -- lifecycle -----------------------------------------------------
    def reset(self) -> None:
        with self._lock:
            self._pending: List[dict] = []
            self.counters: Dict[str, float] = {}
            self.gauges: Dict[str, float] = {}
            self.span_agg: Dict[str, list] = {}   # name -> [n, total, max]
            self.span_durs: Dict[str, deque] = {}
            self.hists: Dict[str, Histogram] = {}
            self.compiles: List[dict] = []
            self._flushed_counters: Dict[str, float] = {}
            self._flushed_hist_n: Dict[str, int] = {}
            # recent-event ring (kept even with a log sink): the /trace
            # endpoint's snapshot + last-event-by-kind for /statusz
            self._recent: deque = deque(maxlen=_RING_CAP)
            self.last_by_kind: Dict[str, dict] = {}
            self.t0_perf = time.perf_counter()
            # cxxlint: disable=wallclock — the shard-merge epoch: --merge
            # re-bases shards on the shared wall clock, never a duration
            self.t0_wall = time.time()

    def enable(self, log_path: Optional[str] = None,
               process_index: Optional[int] = None) -> None:
        self.reset()
        if process_index is None:
            # env fallback for library users under the multihost launcher.
            # Deliberately NOT PS_RANK: that var also selects an io shard
            # in single-process debugging (doc/io.md), where redirecting
            # the telemetry log by rank would be wrong.
            v = os.environ.get("CXXNET_WORKER_RANK")
            if v is not None:
                try:
                    process_index = int(v)
                except ValueError:
                    pass
        self.process_index = int(process_index or 0)
        path = log_path or None
        if path and "%d" in path:
            # the multihost shard contract: each rank writes its own file
            path = path.replace("%d", str(self.process_index))
        elif path and self.process_index:
            # no placeholder on a non-zero rank: suffix rather than
            # silently clobber rank 0's shard
            sys.stderr.write(
                "WARNING: telemetry_log %r has no %%d rank placeholder in "
                "a multi-process run; writing %s.%d instead so shard 0 is "
                "not clobbered\n" % (path, path, self.process_index))
            path = "%s.%d" % (path, self.process_index)
        self.log_path = path
        if self._log_f is not None:
            self._log_f.close()
            self._log_f = None
        if self.log_path:
            d = os.path.dirname(os.path.abspath(self.log_path))
            if d:
                os.makedirs(d, exist_ok=True)
            self._log_f = open(self.log_path, "w")
        self.enabled = True
        self.record({"ev": "meta", "pid": os.getpid(),
                     "t0_wall": self.t0_wall})

    def disable(self) -> None:
        self.enabled = False
        if self._log_f is not None:
            self._log_f.close()
            self._log_f = None
        self.log_path = None

    # -- recording -----------------------------------------------------
    def _stack(self) -> list:
        s = getattr(self._tls, "stack", None)
        if s is None:
            s = self._tls.stack = []
        return s

    def _ctx_stack(self) -> list:
        s = getattr(self._tls, "ctx", None)
        if s is None:
            s = self._tls.ctx = []
        return s

    def _win_stack(self) -> list:
        s = getattr(self._tls, "win", None)
        if s is None:
            s = self._tls.win = []
        return s

    def trace_context(self, request_id) -> TraceContext:
        return TraceContext(self, request_id)

    def current_trace(self) -> Optional[TraceContext]:
        s = getattr(self._tls, "ctx", None)
        return s[-1] if s else None

    def compile_window(self, label) -> CompileWindow:
        return CompileWindow(self, label)

    def current_compile_window(self) -> Optional[CompileWindow]:
        s = getattr(self._tls, "win", None)
        return s[-1] if s else None

    def mark(self, name: str) -> None:
        """Timestamp a named boundary on this thread's active trace
        context (no-op without one); with telemetry enabled the boundary
        is also recorded as a ``mark`` event in the stream."""
        tc = self.current_trace()
        if tc is not None:
            tc.mark(name)
        if self.enabled:
            self.record({"ev": "mark", "name": name})

    def _ts(self, t_perf: float) -> float:
        return t_perf - self.t0_perf

    def record(self, ev: dict) -> None:
        """Append one raw event (already-shaped dict). No-op if disabled."""
        if not self.enabled:
            return
        if "ts" not in ev:
            ev["ts"] = round(self._ts(time.perf_counter()), 6)
        with self._lock:
            self._append(ev)

    def _append(self, ev: dict) -> None:
        # lock held. Without a sink nothing drains _pending: bound it so
        # an enabled-without-log run (bench mode) cannot leak per-step
        if "p" not in ev:
            ev["p"] = self.process_index
        if "req" not in ev:
            # request attribution: the recording thread's active trace
            # context tags the event (thread-local read — safe under the
            # registry lock, never contended)
            tc = self.current_trace()
            if tc is not None:
                ev["req"] = tc.request_id
        self._pending.append(ev)
        self._recent.append(ev)
        self.last_by_kind[ev.get("ev", "?")] = ev
        if self._log_f is None and len(self._pending) > _PENDING_CAP:
            del self._pending[: _PENDING_CAP // 2]

    def span(self, name: str, **attrs):
        if not self.enabled:
            return _NULL_SPAN
        return _Span(self, name, attrs or None)

    def span_event(self, name: str, start_perf: float, dur: float,
                   **attrs) -> None:
        """Record a span from explicit perf_counter timings — for call
        sites that must time regardless of telemetry (the train loop's
        probes) or that only know post hoc whether the interval counts."""
        if not self.enabled:
            return
        self._record_span(name, start_perf, dur, len(self._stack()),
                          attrs or None)

    def _record_span(self, name, t0, dur, depth, attrs) -> None:
        if not self.enabled:     # disabled mid-span: drop silently
            return
        ev = {"ev": "span", "name": name, "ts": round(self._ts(t0), 6),
              "dur": round(dur, 6), "depth": depth,
              "tid": threading.get_ident()}
        if attrs:
            ev.update(attrs)
        with self._lock:
            self._append(ev)
            agg = self.span_agg.get(name)
            if agg is None:
                agg = self.span_agg[name] = [0, 0.0, 0.0]
                self.span_durs[name] = deque(maxlen=_DUR_CAP)
            agg[0] += 1
            agg[1] += dur
            if dur > agg[2]:
                agg[2] = dur
            self.span_durs[name].append(dur)
            # every span feeds the mergeable fixed-bucket histogram of its
            # name — the /metrics latency series and the shard-merge feed
            h = self.hists.get(name)
            if h is None:
                h = self.hists[name] = Histogram()
            h.observe(dur)

    def count(self, name: str, n=1) -> None:
        tc = self.current_trace()
        if tc is not None:
            # per-request attribution rides the thread-local context even
            # with telemetry disabled (the flight recorder's counter view)
            tc.counts[name] = tc.counts.get(name, 0) + n
        if not self.enabled:
            return
        with self._lock:
            self.counters[name] = self.counters.get(name, 0) + n

    def hist(self, name: str, value: float) -> None:
        """Observe one value (seconds) into the named fixed-bucket
        histogram — for latencies measured outside a span (or values that
        are not span-shaped at all)."""
        if not self.enabled:
            return
        with self._lock:
            h = self.hists.get(name)
            if h is None:
                h = self.hists[name] = Histogram()
            h.observe(value)

    def declare_hist(self, name: str) -> None:
        """Register a histogram series with zero observations, so
        /metrics exports its (empty) bucket series from scrape one and
        /statusz shows it as "n/a" — a dashboard watching serve_ttft
        must see the series exist BEFORE the first request, not appear
        mid-run."""
        if not self.enabled:
            return
        with self._lock:
            self.hists.setdefault(name, Histogram())

    def gauge(self, name: str, value) -> None:
        if not self.enabled:
            return
        with self._lock:
            self.gauges[name] = value
            self._append(
                {"ev": "gauge", "name": name, "value": value,
                 "ts": round(self._ts(time.perf_counter()), 6)})

    def record_compile(self, name: str, cause: str, seconds: float,
                       key=None) -> None:
        tc = self.current_trace()
        if tc is not None:
            # attribute the compile to the request that paid the cliff;
            # "off" = compile start relative to the context entry (the
            # backend call), so the trace export draws the bar inside
            # the phase that actually paid it — a fresh decode-program
            # compile runs in the decode phase, not prefill
            entry = {"name": name, "cause": cause,
                     "dur": round(seconds, 6)}
            if tc.t0 is not None:
                entry["off"] = round(
                    time.perf_counter() - seconds - tc.t0, 6)
            tc.compiles.append(entry)
        wins = getattr(self._tls, "win", None)
        if wins:
            # every active compile window on the thread sees the
            # compile — the batching dispatcher's batch-wide stall
            # attribution (a step compile stalls ALL slots aboard)
            wentry = {"name": name, "cause": cause,
                      "dur": round(seconds, 6)}
            if key is not None:
                wentry["key"] = str(key)
            for w in wins:
                w.compiles.append(dict(wentry))
        if not self.enabled:
            return
        ev = {"ev": "compile", "name": name, "cause": cause,
              "dur": round(seconds, 6),
              "ts": round(self._ts(time.perf_counter()) - seconds, 6),
              "tid": threading.get_ident()}
        if key is not None:
            ev["key"] = str(key)
        with self._lock:
            self._append(ev)
            self.compiles.append(ev)

    # -- sinks ---------------------------------------------------------
    def flush(self) -> None:
        """Write pending events to the JSONL log (if one is attached),
        plus a counters snapshot when any counter moved since the last
        flush — so a crashed run keeps its counters too, not only its
        spans. Without a log path events stay buffered in memory (the
        bench / library mode — summary() and chrome_trace() read them
        there)."""
        if self._log_f is None:
            return
        with self._lock:
            batch, self._pending = self._pending, []
            counters = None
            if self.counters != self._flushed_counters:
                counters = dict(self.counters)
                self._flushed_counters = dict(counters)
            hists = None
            hist_n = {k: h.n for k, h in self.hists.items()}
            if hist_n != self._flushed_hist_n:
                hists = {k: h.to_dict() for k, h in self.hists.items()}
                self._flushed_hist_n = hist_n
            ts = round(self._ts(time.perf_counter()), 6)
            p = self.process_index
        for ev in batch:
            self._log_f.write(json.dumps(ev) + "\n")
        if counters is not None:
            self._log_f.write(json.dumps(
                {"ev": "counters", "counters": counters,
                 "ts": ts, "p": p}) + "\n")
        if hists is not None:
            # cumulative snapshot, last-wins on re-read — like counters,
            # so a crashed run keeps its histograms to the last flush
            self._log_f.write(json.dumps(
                {"ev": "hists", "hists": hists, "ts": ts, "p": p}) + "\n")
        self._log_f.flush()

    def events(self) -> List[dict]:
        with self._lock:
            return list(self._pending)

    def recent_events(self) -> List[dict]:
        """The last ~_RING_CAP events regardless of sink — the /trace
        endpoint's snapshot source."""
        with self._lock:
            return list(self._recent)

    def last_event(self, kind: str) -> Optional[dict]:
        """Most recent event of the given ``ev`` kind (e.g. "ckpt_save"
        for /statusz's checkpoint-age line)."""
        with self._lock:
            return self.last_by_kind.get(kind)

    def metrics_snapshot(self) -> dict:
        """One consistent point-in-time copy of everything /metrics
        serves: counters, gauges, raw histogram buckets, compile totals,
        uptime — taken under the lock so a scrape mid-step never sees a
        half-updated histogram."""
        with self._lock:
            return {
                "counters": dict(self.counters),
                "gauges": dict(self.gauges),
                "hists": {k: h.to_dict() for k, h in self.hists.items()},
                "compiles": len(self.compiles),
                "compile_s": round(sum(c["dur"] for c in self.compiles), 6),
                "uptime_s": time.perf_counter() - self.t0_perf,
                "process": self.process_index,
            }

    def summary(self) -> dict:
        """Aggregate view: per-span totals, counters, gauges, compiles,
        and p50/p90/p99 duration percentiles per span name."""
        with self._lock:
            spans = {}
            for name, (n, total, mx) in self.span_agg.items():
                durs = sorted(self.span_durs[name])
                spans[name] = {
                    "count": n, "total_s": round(total, 6),
                    "mean_ms": round(1e3 * total / n, 4),
                    "max_ms": round(1e3 * mx, 4),
                    "p50_ms": round(1e3 * percentile(durs, 50), 4),
                    "p90_ms": round(1e3 * percentile(durs, 90), 4),
                    "p99_ms": round(1e3 * percentile(durs, 99), 4),
                }
            return {
                "spans": spans,
                "counters": dict(self.counters),
                "gauges": dict(self.gauges),
                "hists": {name: h.stats()
                          for name, h in self.hists.items()},
                "compiles": {
                    "count": len(self.compiles),
                    "total_s": round(sum(c["dur"] for c in self.compiles),
                                     6),
                    "by_cause": count_by(self.compiles, "cause"),
                    "by_name": count_by(self.compiles, "name"),
                },
            }

    def brief_summary(self, top: int = 8,
                      summary: Optional[dict] = None) -> dict:
        """Compact per-phase breakdown for embedding in one-line JSON
        (the bench.py contract): top spans by total time + compile cost.
        Pass a precomputed ``summary()`` to avoid re-sorting every span's
        duration history."""
        s = summary if summary is not None else self.summary()
        ranked = sorted(s["spans"].items(),
                        key=lambda kv: -kv[1]["total_s"])[:top]
        out = {"spans": {name: {"count": a["count"],
                                "total_s": a["total_s"],
                                "p50_ms": a["p50_ms"],
                                "p90_ms": a["p90_ms"],
                                "p99_ms": a["p99_ms"]}
                         for name, a in ranked},
               "compiles": s["compiles"]["count"],
               "compile_s": s["compiles"]["total_s"]}
        if s["counters"]:
            out["counters"] = s["counters"]
        return out

    def finish(self, close: bool = False) -> Optional[dict]:
        """Record the end-of-run summary event, flush the log, and (with a
        log path) write the Chrome-trace export next to it. Returns the
        summary dict (None if disabled)."""
        if not self.enabled:
            return None
        s = self.summary()
        if self.log_path:
            self.flush()   # drain events + counters snapshot first, so
            #                the summary below stays the log's last line
        self.record({"ev": "summary", "summary": s,
                     "ts": round(self._ts(time.perf_counter()), 6)})
        if self.log_path:
            self.flush()
            try:
                self.write_chrome_trace(self.log_path + ".trace.json")
            except Exception:
                pass
        if close:
            self.disable()
        return s

    # -- chrome trace ----------------------------------------------------
    def _all_events(self) -> List[dict]:
        """Everything recorded so far: the log file's lines (events already
        flushed) plus the in-memory pending buffer."""
        evs: List[dict] = []
        if self.log_path and os.path.exists(self.log_path):
            if self._log_f is not None:
                self.flush()
            with open(self.log_path) as f:
                for line in f:
                    line = line.strip()
                    if line:
                        evs.append(json.loads(line))
            return evs
        return self.events()

    def chrome_trace(self) -> dict:
        return events_to_chrome(self._all_events())

    def write_chrome_trace(self, path: str) -> str:
        with open(path, "w") as f:
            json.dump(self.chrome_trace(), f)
        return path


def percentile(sorted_vals: list, p: float) -> float:
    """Nearest-rank percentile of an ascending-sorted list (shared with
    tools/telemetry_report.py so live and offline numbers agree)."""
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, int(round((p / 100.0)
                                            * (len(sorted_vals) - 1))))
    return sorted_vals[i]


def count_by(evs: List[dict], key: str) -> Dict[str, int]:
    """Histogram of ``ev[key]`` over a list of event dicts."""
    out: Dict[str, int] = {}
    for e in evs:
        k = e.get(key, "?")
        out[k] = out.get(k, 0) + 1
    return out



def events_to_chrome(evs: List[dict]) -> dict:
    """Build a chrome://tracing / Perfetto 'traceEvents' JSON object from a
    list of telemetry events (live or re-read from a JSONL log). Spans and
    compiles become complete ('X') events; gauges become counter ('C')
    tracks. Timestamps are microseconds relative to run start."""
    trace = []
    tids = {}

    def tid_of(ev):
        t = ev.get("tid", 0)
        if t not in tids:
            tids[t] = len(tids)
            trace.append({"ph": "M", "name": "thread_name", "pid": 0,
                          "tid": tids[t],
                          "args": {"name": "thread-%d" % tids[t]}})
        return tids[t]

    for ev in evs:
        kind = ev.get("ev")
        if kind == "span":
            trace.append({
                "ph": "X", "name": ev["name"], "pid": 0,
                "tid": tid_of(ev),
                "ts": round(ev["ts"] * 1e6, 1),
                "dur": round(ev["dur"] * 1e6, 1),
            })
        elif kind == "compile":
            trace.append({
                "ph": "X", "name": "compile:" + ev["name"], "pid": 0,
                "tid": tid_of(ev),
                "ts": round(max(ev.get("ts", 0.0), 0.0) * 1e6, 1),
                "dur": round(ev["dur"] * 1e6, 1),
                "args": {"cause": ev.get("cause", "?")},
            })
        elif kind == "gauge":
            trace.append({
                "ph": "C", "name": ev["name"], "pid": 0,
                "ts": round(ev["ts"] * 1e6, 1),
                "args": {"value": ev.get("value", 0)},
            })
    return {"traceEvents": trace, "displayTimeUnit": "ms"}


# the canonical request-phase order (doc/observability.md glossary):
# queue_wait (accept -> worker pop), dispatch (pop -> backend call),
# prefill (backend call -> first token: TTFT's server-side share),
# decode (first token -> last token). The phases TILE the request's
# wall-clock — their sum is the request's total by construction.
REQUEST_PHASES = ("queue_wait", "dispatch", "prefill", "decode")


class FlightRecorder:
    """Bounded ring of the last N completed request traces — the
    per-request black box the serving frontend fills and statusd serves
    (``/trace?request=<id>`` as a Chrome trace, ``/requestz`` as a
    list). A record is one plain dict::

        {"id": "17", "outcome": "served", "tokens_in": 8, "tokens_out":
         16, "t_wall": <arrival unix time>, "total_s": 0.213,
         "ttft_s": 0.041, "tokens_per_s": 93.1,
         "phases": {"queue_wait": .., "dispatch": .., "prefill": ..,
                    "decode": ..},
         "recompiles": [{"name": "jit.decode_prefill", "cause":
                         "new_signature", "dur": 1.2}, ...],
         "counts": {<per-request counter deltas>}}

    Bounded and lock-guarded; eviction is oldest-first (deque maxlen).
    Jax-free and registry-independent, so it works with telemetry
    disabled — a flight record must survive a run that configured no
    JSONL log."""

    def __init__(self, cap: int = 256):
        self.cap = max(1, int(cap))
        self._lock = lockrank.lock("telemetry.flight")
        self._ring: deque = deque(maxlen=self.cap)

    def record(self, rec: dict) -> None:
        with self._lock:
            self._ring.append(rec)

    def get(self, request_id) -> Optional[dict]:
        rid = str(request_id)
        with self._lock:
            # newest-first: a repeated id resolves to the most recent
            # flight. Repeats happen across frontend restarts feeding
            # one recorder, and with client-chosen TRACE ids — a
            # client that reuses an id (or picks one colliding with a
            # local dense id) shadows the older record here; that is
            # the documented contract (doc/serving.md: choose unique
            # trace ids), not a lookup guarantee
            for rec in reversed(self._ring):
                if rec.get("id") == rid:
                    return rec
        return None

    def list(self) -> List[dict]:
        """Newest-first snapshot of the ring."""
        with self._lock:
            return list(reversed(self._ring))

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)


def request_chrome_trace(rec: dict, batch_iters=None) -> dict:
    """One flight record -> a Chrome-trace / Perfetto JSON object: the
    phases as back-to-back complete ('X') events on one lane (they tile
    the request's wall-clock), recompiles on a second lane inside the
    phase that paid them. Timestamps are µs relative to request accept,
    so the trace opens in ui.perfetto.dev showing exactly where this
    request's milliseconds went.

    ``batch_iters`` (optional, oldest-first) are the batching
    dispatcher's per-iteration scheduler records containing this
    request (``servd.BatchFlightRecorder.for_request``): they render as
    slot-Gantt lanes — one lane per decode slot, one bar per occupant
    run — aligned on the shared wall epoch (each iteration record's
    ``t_wall`` minus the request's), so the request's bar shows exactly
    which iterations it shared its decode with, and with whom."""
    rid = str(rec.get("id", "?"))
    trace: List[dict] = [
        {"ph": "M", "name": "process_name", "pid": 0,
         "args": {"name": "cxxnet-request %s" % rid}},
        {"ph": "M", "name": "thread_name", "pid": 0, "tid": 0,
         "args": {"name": "phases"}},
    ]
    phases = rec.get("phases") or {}
    t = 0.0
    args = {"request": rid, "outcome": rec.get("outcome", "?"),
            "tokens_in": rec.get("tokens_in", 0),
            "tokens_out": rec.get("tokens_out", 0)}
    for name in REQUEST_PHASES:
        dur = float(phases.get(name, 0.0) or 0.0)
        if dur <= 0.0:
            continue
        trace.append({"ph": "X", "name": name, "pid": 0, "tid": 0,
                      "ts": round(t * 1e6, 1), "dur": round(dur * 1e6, 1),
                      "args": args})
        t += dur
    if t == 0.0:
        # no positive phase at all — an admission shed (honest zero
        # phases: nothing was dequeued or dispatched). The lane must
        # still be VISIBLE in a stitched cross-process trace (the
        # retried-request case renders the shed hop next to the served
        # one), so draw a 1µs marker named for the outcome.
        name = str(rec.get("outcome", "?"))
        if rec.get("shed_at"):
            name += "(%s)" % rec["shed_at"]
        trace.append({"ph": "X", "name": name, "pid": 0, "tid": 0,
                      "ts": 0.0, "dur": 1.0, "args": args})
    comp_t0 = float(phases.get("queue_wait", 0.0) or 0.0) \
        + float(phases.get("dispatch", 0.0) or 0.0)
    if rec.get("recompiles"):
        trace.append({"ph": "M", "name": "thread_name", "pid": 0,
                      "tid": 1, "args": {"name": "recompiles"}})
        ct = comp_t0
        for c in rec["recompiles"]:
            dur = float(c.get("dur", 0.0))
            off = c.get("off")
            # "off" places the bar where the compile actually ran
            # (relative to the backend call = prefill start) — a fresh
            # decode-program compile lands in the decode lane section,
            # matching the phase accounting; records without it (older
            # logs) fall back to stacking from prefill start
            ts = comp_t0 + max(0.0, float(off)) if off is not None \
                else ct
            trace.append({"ph": "X", "name": "compile:%s"
                          % c.get("name", "?"), "pid": 0, "tid": 1,
                          "ts": round(ts * 1e6, 1),
                          "dur": round(dur * 1e6, 1),
                          "args": {"cause": c.get("cause", "?"),
                                   "request": rid}})
            ct = ts + dur
    t0_wall = rec.get("t_wall")
    if batch_iters and t0_wall is not None:
        # slot-Gantt lanes: per slot, contiguous runs of the same
        # occupant merge into one bar (a straggler shows as one long
        # bar next to the short bars of the batchmates that came and
        # went). Each iteration spans [t_wall - step, t_wall] on the
        # shared wall epoch; clock skew vs the request's own accept
        # epoch is sub-ms on one host — good enough for a Gantt.
        runs: Dict[int, dict] = {}       # slot -> open run
        bars: List[tuple] = []           # (slot, closed run)
        for it in batch_iters:
            it_wall = it.get("t_wall")
            if it_wall is None:
                continue
            step_s = float(it.get("step_ms") or 0.0) / 1e3
            start = it_wall - t0_wall - step_s
            end = it_wall - t0_wall
            seen = set()
            for row in it.get("slots") or []:
                slot, occupant = int(row[0]), str(row[1])
                seen.add(slot)
                run = runs.get(slot)
                if run is not None and run["rid"] == occupant:
                    run["end"] = end
                    run["iters"][1] = it.get("iter")
                    continue
                if run is not None:
                    bars.append((slot, run))
                runs[slot] = {"rid": occupant, "start": start,
                              "end": end,
                              "iters": [it.get("iter"),
                                        it.get("iter")]}
            for slot in [s for s in runs if s not in seen]:
                bars.append((slot, runs.pop(slot)))
        bars.extend(runs.items())
        if bars:
            lanes = sorted({slot for slot, _ in bars})
            for slot in lanes:
                trace.append({"ph": "M", "name": "thread_name",
                              "pid": 0, "tid": 10 + slot,
                              "args": {"name": "batch slot %d" % slot}})
            for slot, run in bars:
                trace.append({
                    "ph": "X",
                    "name": run["rid"] if run["rid"] != rid
                    else "%s (this request)" % rid,
                    "pid": 0, "tid": 10 + slot,
                    "ts": round(run["start"] * 1e6, 1),
                    "dur": round(max(run["end"] - run["start"],
                                     1e-6) * 1e6, 1),
                    "args": {"occupant": run["rid"],
                             "iterations": "%s..%s" % tuple(run["iters"]),
                             "request": rid}})
    return {"traceEvents": trace, "displayTimeUnit": "ms"}


class JitWatch:
    """Recompile detector: wraps a jitted callable and records a compile
    event whenever the wrapped jit cache grows — i.e. exactly when XLA
    traced + compiled for a genuinely new (signature, shape) key, and
    never on cache hits. The first detected compile is attributed to
    ``cause`` (what the call site knows: new_signature, rebuild_after_clear,
    decode_cache_drop); later growth on the same program means the inputs'
    shapes/shardings changed ("shape_change")."""

    __slots__ = ("_fn", "_name", "_cause_next", "_reg", "_key")

    def __init__(self, fn, name: str, cause: str = "new_signature",
                 registry: Optional[_Registry] = None, key=None):
        self._fn = fn
        self._name = name
        self._cause_next = cause
        self._reg = registry or _REG
        # the caller's program key (the trainer's jit-cache key): rides
        # the compile event and the perf ledger's ProgramCard
        self._key = key

    def __call__(self, *args, **kwargs):
        reg = self._reg
        if not reg.enabled and reg.current_trace() is None \
                and reg.current_compile_window() is None \
                and reg.compile_hook is None:
            # an active trace context or compile window wants its
            # recompiles attributed (the flight recorder works with
            # telemetry disabled too), and the perf ledger wants its
            # cards either way
            return self._fn(*args, **kwargs)
        try:
            before = self._fn._cache_size()
        except Exception:
            before = None
        t0 = time.perf_counter()
        out = self._fn(*args, **kwargs)
        dt = time.perf_counter() - t0
        if before is not None:
            try:
                grew = self._fn._cache_size() > before
            except Exception:
                grew = False
            if grew:
                reg.record_compile(self._name, self._cause_next, dt,
                                   key=self._key)
                hook = reg.compile_hook
                if hook is not None:
                    # the perf ledger (utils/perf.py): hand it the
                    # compiled callable + the triggering args so it can
                    # card the program. Supervised — a ledger bug must
                    # not kill the train step that compiled
                    try:
                        hook(self._name, self._cause_next, dt,
                             fn=self._fn, args=args, kwargs=kwargs,
                             key=self._key)
                    except Exception:
                        pass
                self._cause_next = "shape_change"
        return out

    def __getattr__(self, name):
        # forward lower()/trace()/cache introspection to the jitted fn
        return getattr(self._fn, name)


class BooksAuditor:
    """Conservation-law registry: named invariants over the serving
    books — "accepted = served + shed + errors + deadline + abandoned",
    "blocks total = free + live + retained", "tenant charges sum to the
    door books", "fleet sums = Σ replica feeds" — checked on a daemon
    sweep and at every /metrics scrape, so every number the request
    autopsy and the bench rows cite is provably reconciled.

    A law is a callable ``fn() -> Optional[str]``: ``None`` means the
    books reconcile (or the law could not take a consistent snapshot —
    inconclusive PASSES; a law must never false-latch off a racy read:
    use a stable-snapshot double-read and return None when the bracket
    moved), a string is the violation detail. The first violation
    LATCHES the law sticky-broken (``cxxnet_books_broken{law=...}``
    stays 1 until ``reset()``), emits exactly one ``books_broken``
    transition event (``broken: 1`` carrying the detail; ``reset()``
    emits the matching ``broken: 0`` clear), and bumps the
    ``books.violations`` counter — a single bad snapshot can never flap
    the gauge, and telemetry_report's exit-2 gate sees the latch even
    if every later sweep reconciles.

    Laws run OUTSIDE the auditor lock (a law reads other subsystems'
    locked state; rank "telemetry.audit" keeps the latch bookkeeping
    below only the registry itself), and the transition events are
    emitted outside it too. A law that RAISES is counted
    (``law_errors``) but treated as inconclusive: laws are registered
    at start() and unregistered at drain(), and a transient exception
    during concurrent teardown must not break the books."""

    def __init__(self, registry: Optional["_Registry"] = None):
        self._lock = lockrank.lock("telemetry.audit")
        self._registry = registry
        self._laws: Dict[str, object] = {}
        self._broken: Dict[str, str] = {}
        self.violations = 0          # cumulative latches (survives reset)
        self.sweeps = 0
        self.law_errors = 0
        self._thread: Optional[threading.Thread] = None
        self._stop_ev = threading.Event()

    def _reg(self) -> "_Registry":
        return self._registry if self._registry is not None else _REG

    def register(self, name: str, fn) -> None:
        """Install (or replace) the named law."""
        with self._lock:
            self._laws[str(name)] = fn

    def unregister(self, name: str) -> None:
        """Remove the named law. A latch it already tripped STAYS
        latched — a violation observed just before drain must still
        fail the next scrape."""
        with self._lock:
            self._laws.pop(str(name), None)

    def sweep(self) -> Dict[str, Optional[str]]:
        """Evaluate every registered law once. Returns {law: detail}
        (None = reconciled/inconclusive) for this sweep; latch state is
        cumulative and read via snapshot()."""
        with self._lock:
            laws = list(self._laws.items())
        results: Dict[str, Optional[str]] = {}
        errors = 0
        for name, fn in laws:
            try:
                detail = fn()
            except Exception:
                errors += 1
                detail = None
            results[name] = None if detail is None else str(detail)
        newly: List[tuple] = []
        with self._lock:
            self.sweeps += 1
            self.law_errors += errors
            for name, detail in results.items():
                if detail is not None and name not in self._broken:
                    self._broken[name] = detail
                    self.violations += 1
                    newly.append((name, detail))
        reg = self._reg()
        for name, detail in newly:
            reg.count("books.violations")
            reg.record({"ev": "books_broken", "law": name, "broken": 1,
                        "detail": detail})
        return results

    def snapshot(self) -> dict:
        """Point-in-time view for /metrics and bench rows."""
        with self._lock:
            return {"laws": sorted(self._laws),
                    "broken": dict(self._broken),
                    "violations": self.violations,
                    "sweeps": self.sweeps,
                    "law_errors": self.law_errors}

    def reset(self) -> None:
        """Clear every latch, emitting the ``broken: 0`` transition for
        each — the operator's acknowledge. ``violations`` stays
        cumulative (the bench-row feed)."""
        with self._lock:
            cleared = sorted(self._broken)
            self._broken.clear()
        reg = self._reg()
        for name in cleared:
            reg.record({"ev": "books_broken", "law": name, "broken": 0})

    def start(self, period_s: float = 1.0) -> None:
        """Start the daemon sweep loop (idempotent)."""
        with self._lock:
            if self._thread is not None:
                return
            self._stop_ev.clear()
            t = threading.Thread(target=self._run,
                                 args=(max(0.05, float(period_s)),),
                                 name="books-auditor", daemon=True)
            self._thread = t
        t.start()

    def _run(self, period_s: float) -> None:
        while not self._stop_ev.wait(period_s):
            try:
                self.sweep()
            except Exception:
                pass

    def stop(self) -> None:
        with self._lock:
            t, self._thread = self._thread, None
        if t is not None:
            self._stop_ev.set()
            t.join(timeout=2.0)


# ----------------------------------------------------------------------
# module-level singleton surface
_REG = _Registry()


def enable(log_path: Optional[str] = None,
           process_index: Optional[int] = None) -> None:
    _REG.enable(log_path, process_index=process_index)


def disable() -> None:
    _REG.disable()


def enabled() -> bool:
    return _REG.enabled


def reset() -> None:
    _REG.reset()


def span(name: str, **attrs):
    return _REG.span(name, **attrs)


def span_event(name: str, start_perf: float, dur: float, **attrs) -> None:
    _REG.span_event(name, start_perf, dur, **attrs)


def count(name: str, n=1) -> None:
    _REG.count(name, n)


def gauge(name: str, value) -> None:
    _REG.gauge(name, value)


def hist(name: str, value: float) -> None:
    _REG.hist(name, value)


def declare_hist(name: str) -> None:
    _REG.declare_hist(name)


def trace_context(request_id) -> TraceContext:
    return _REG.trace_context(request_id)


def current_trace() -> Optional[TraceContext]:
    return _REG.current_trace()


def compile_window(label) -> CompileWindow:
    """A stall-attribution bracket for work outside any request's trace
    context (``with compile_window("step:b4") as w:`` — then read
    ``w.compiles`` / ``w.stall_s``). Works with telemetry disabled."""
    return _REG.compile_window(label)


def current_compile_window() -> Optional[CompileWindow]:
    return _REG.current_compile_window()


def mark(name: str) -> None:
    _REG.mark(name)


def event(ev: dict) -> None:
    _REG.record(ev)


def record_compile(name: str, cause: str, seconds: float, key=None) -> None:
    _REG.record_compile(name, cause, seconds, key)


def jit_watch(fn, name: str, cause: str = "new_signature",
              key=None) -> JitWatch:
    return JitWatch(fn, name, cause=cause, key=key)


def flush() -> None:
    _REG.flush()


def finish(close: bool = False) -> Optional[dict]:
    return _REG.finish(close=close)


def summary() -> dict:
    return _REG.summary()


def brief_summary(top: int = 8, summary: Optional[dict] = None) -> dict:
    return _REG.brief_summary(top=top, summary=summary)


def events() -> List[dict]:
    return _REG.events()


def recent_events() -> List[dict]:
    return _REG.recent_events()


def wall_epoch() -> float:
    """The registry's wall-clock epoch: event ``ts`` seconds are
    relative to this, so cross-process alignment (the /eventz incident
    merge, --merge shard re-basing) is ``t0_wall + ts``."""
    return _REG.t0_wall


def last_event(kind: str) -> Optional[dict]:
    return _REG.last_event(kind)


def chrome_trace() -> dict:
    return _REG.chrome_trace()


def write_chrome_trace(path: str) -> str:
    return _REG.write_chrome_trace(path)


# the process-wide conservation-law auditor: subsystems register laws
# at start() (servd's door books, kvblocks' block conservation, routerd's
# federation sums) and unregister them at drain(); statusd sweeps at
# every scrape and exports the latches as cxxnet_books_broken{law=...}
_AUDITOR = BooksAuditor()


def auditor() -> BooksAuditor:
    return _AUDITOR


def audit_register(name: str, fn) -> None:
    _AUDITOR.register(name, fn)


def audit_unregister(name: str) -> None:
    _AUDITOR.unregister(name)


def audit_sweep() -> Dict[str, Optional[str]]:
    return _AUDITOR.sweep()


def sample_device_memory() -> Optional[dict]:
    """Record the first local device's allocator stats as gauges (device
    memory high-water). Backends without memory_stats (CPU, some tunneled
    runtimes) make this a silent no-op."""
    if not _REG.enabled:
        return None
    try:
        import jax
        stats = jax.local_devices()[0].memory_stats()
    except Exception:
        return None
    if not stats:
        return None
    for k in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit"):
        if k in stats:
            gauge("device." + k, int(stats[k]))
    return stats
