"""Versioned framing for exported serving artifacts.

The reference versions its model blobs so a stale file fails with a
message instead of undefined behavior (src/nnet/nnet_config.h:126-145 —
net_type/reserved fields checked on load). Our serving artifacts
(export_forward / export_decode StableHLO bytes) bake in a cache-layout
contract (_decode_cache_specs) that can change across framework
versions, so they get the same guard: a fixed magic, a format version,
and a JSON header carrying the artifact kind plus a fingerprint of the
layout contract. Loaders fail with a framework message on mismatch
instead of whatever jax.export.deserialize does with alien bytes.

Frame layout: b"CXTF" | uint32 version | uint32 header_len |
header JSON (utf-8) | payload (raw jax.export serialization).
"""

import hashlib
import json
import struct

MAGIC = b"CXTF"
VERSION = 1


def frame(kind: str, meta: dict, payload: bytes) -> bytes:
    header = dict(meta)
    header["kind"] = kind
    hb = json.dumps(header, sort_keys=True).encode("utf-8")
    return MAGIC + struct.pack("<II", VERSION, len(hb)) + hb + payload


def unframe(data: bytes, expect_kind: str):
    """-> (meta, payload); raises ValueError with a framework message on
    any mismatch (wrong magic / future version / wrong artifact kind /
    truncated frame)."""
    if len(data) < 12 or data[:4] != MAGIC:
        raise ValueError(
            "not a cxxnet_tpu serving artifact (bad magic): this file is "
            "either corrupt or a pre-versioning export — re-export it "
            "with this framework version")
    version, hlen = struct.unpack("<II", data[4:12])
    if version > VERSION:
        raise ValueError(
            "serving artifact format v%d is newer than this framework "
            "supports (v%d): upgrade the framework or re-export"
            % (version, VERSION))
    if len(data) < 12 + hlen:
        raise ValueError("serving artifact truncated (header)")
    try:
        meta = json.loads(data[12:12 + hlen].decode("utf-8"))
    except ValueError:
        raise ValueError("serving artifact header is not valid JSON "
                         "(corrupt file)")
    kind = meta.get("kind")
    if kind != expect_kind:
        raise ValueError(
            "serving artifact kind mismatch: file holds %r, loader "
            "expects %r (did you swap the prefill/step files?)"
            % (kind, expect_kind))
    return meta, data[12 + hlen:]


def cache_fingerprint(cache_keys, cache_shapes, cache_dtype) -> str:
    """Stable digest of the decode cache-layout contract: the prefill and
    step artifacts of one export share it, and a loader refuses to pair
    artifacts whose layouts disagree."""
    desc = repr((list(cache_keys),
                 [tuple(int(d) for d in sh) for sh in cache_shapes],
                 str(cache_dtype)))
    return hashlib.sha1(desc.encode("utf-8")).hexdigest()
