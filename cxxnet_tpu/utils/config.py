"""key=value config reader, token-compatible with the reference config format.

Mirrors the tokenizer semantics of cxxnet's ConfigReaderBase
(reference: src/utils/config.h:20-141):

* tokens are separated by spaces / tabs / newlines
* ``#`` starts a comment that runs to end of line
* ``"..."`` is a quoted string token; ``\\`` escapes the next char; a newline
  inside a double-quoted string is an error
* ``'...'`` is a multi-line quoted string token
* ``=`` always delimits its own token (``a=b`` tokenizes as ``a``, ``=``, ``b``)
* the stream is consumed as (name, '=', value) triples

The result is an ordered list of (name, value) pairs — order matters for the
netconfig DSL and iterator sections, and keys may repeat.
"""

from __future__ import annotations

from typing import Iterator, List, Tuple


class ConfigError(ValueError):
    pass


def _tokenize(text: str) -> Iterator[str]:
    i, n = 0, len(text)
    tok: List[str] = []

    def flush():
        if tok:
            yield_val = "".join(tok)
            tok.clear()
            return yield_val
        return None

    while i < n:
        c = text[i]
        if c == "#":
            out = flush()
            if out is not None:
                yield out
            while i < n and text[i] not in "\r\n":
                i += 1
        elif c == '"':
            if tok:
                raise ConfigError("ConfigReader: token followed directly by string")
            i += 1
            s: List[str] = []
            while True:
                if i >= n:
                    raise ConfigError("ConfigReader: unterminated string")
                ch = text[i]
                if ch == "\\":
                    i += 1
                    if i < n:
                        s.append(text[i])
                    i += 1
                elif ch == '"':
                    i += 1
                    break
                elif ch in "\r\n":
                    raise ConfigError("ConfigReader: unterminated string")
                else:
                    s.append(ch)
                    i += 1
            yield "".join(s)
        elif c == "'":
            if tok:
                raise ConfigError("ConfigReader: token followed directly by string")
            i += 1
            s = []
            while True:
                if i >= n:
                    raise ConfigError("ConfigReader: unterminated string")
                ch = text[i]
                if ch == "\\":
                    i += 1
                    if i < n:
                        s.append(text[i])
                    i += 1
                elif ch == "'":
                    i += 1
                    break
                else:
                    s.append(ch)
                    i += 1
            yield "".join(s)
        elif c == "=":
            out = flush()
            if out is not None:
                yield out
            yield "="
            i += 1
        elif c in " \t\r\n":
            out = flush()
            if out is not None:
                yield out
            i += 1
        else:
            tok.append(c)
            i += 1
    out = flush()
    if out is not None:
        yield out


def parse_config_string_py(text: str) -> List[Tuple[str, str]]:
    """Pure-Python parse: the fallback path and the parity reference for the
    native tokenizer (tests/test_native.py)."""
    toks = list(_tokenize(text))
    cfg: List[Tuple[str, str]] = []
    i = 0
    while i < len(toks):
        name = toks[i]
        if name == "=":
            raise ConfigError("ConfigReader: stray '='")
        if i + 1 >= len(toks) or toks[i + 1] != "=":
            raise ConfigError("ConfigReader: expected '=' after %r" % name)
        if i + 2 >= len(toks) or toks[i + 2] == "=":
            raise ConfigError("ConfigReader: expected value after %r =" % name)
        cfg.append((name, toks[i + 2]))
        i += 3
    return cfg


def parse_config_string(text: str) -> List[Tuple[str, str]]:
    """Parse config text into an ordered list of (name, value) pairs.

    Uses the native tokenizer (src/core/config.cc via
    lib/libcxxnet_tpu_core.so) when built; pure Python otherwise."""
    from . import native
    if native.load() is not None:
        out = native.parse_config_string(text)
        if out is not None:
            return out
    return parse_config_string_py(text)


def parse_config_file(fname: str) -> List[Tuple[str, str]]:
    with open(fname, "r") as f:
        return parse_config_string(f.read())


class ConfigIterator:
    """Iterator over (name, value) pairs of a config file.

    Equivalent of the reference's utils::ConfigIterator
    (src/utils/config.h:169-189), including argv-style overrides appended at
    the end (src/cxxnet_main.cpp:63-72).
    """

    def __init__(self, fname: str, argv_overrides: List[str] = ()):  # type: ignore[assignment]
        self.pairs = parse_config_file(fname)
        for arg in argv_overrides:
            if "=" not in arg:
                raise ConfigError("override must be key=value, got %r" % arg)
            k, v = arg.split("=", 1)
            self.pairs.append((k.strip(), v.strip()))

    def __iter__(self):
        return iter(self.pairs)
