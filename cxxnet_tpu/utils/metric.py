"""Evaluation metrics: error / rmse / logloss / rec@n, and MetricSet.

Behavioral counterpart of the reference's src/utils/metric.h:
* error  — argmax mismatch; for 1-wide predictions, thresholds at 0
  (metric.h MetricError)
* rmse   — mean of per-row squared-error sums (metric.h MetricRMSE; note the
  reference returns sum of squared diffs per row averaged over rows, without
  a square root — we reproduce that)
* logloss — negative log of the predicted probability of the target class,
  clipped to [1e-15, 1-1e-15] (metric.h MetricLogloss)
* rec@n  — fraction of the row's label set hit in the top-n scores
  (metric.h MetricRecall)

MetricSet aggregates several metrics, each bound to a label field
(``metric[field] = name`` config syntax), and prints
``\\t{evname}-{metric}[{field}]:{value}`` per metric (metric.h:220-231).

Two execution paths:
* host path (``add_eval``) — numpy, used by the eval-iterator loop where
  predictions are fetched anyway;
* device path (``device_stats`` + ``absorb``) — each metric reduces to a
  (sum, count) sufficient-statistic pair with jnp inside the jitted train
  step, the trainer accumulates the (n_metrics, 2) array on device, and the
  host only fetches it at round boundaries. This is what keeps
  ``eval_train=1`` from forcing a device→host sync every batch (the
  reference overlapped metric evaluation in its per-GPU worker threads,
  nnet_impl-inl.hpp:174-180; here the whole computation stays inside the
  compiled step).
"""

from __future__ import annotations

import re
from typing import List, Optional

import numpy as np


class IMetric:
    name = "none"

    def clear(self) -> None:
        self.sum_metric = 0.0
        self.cnt_inst = 0

    def add_eval(self, pred: np.ndarray, labels: np.ndarray) -> None:
        """pred: (n, k) scores; labels: (n, label_width) label field."""
        raise NotImplementedError

    def device_stats(self, pred, labels):
        """jnp sufficient statistics (sum_metric, cnt_inst) for one batch;
        traceable inside jit. Same numerics as add_eval."""
        raise NotImplementedError

    def get(self) -> float:
        return self.sum_metric / max(self.cnt_inst, 1)


class MetricError(IMetric):
    name = "error"

    def __init__(self):
        self.clear()

    def add_eval(self, pred, labels):
        pred = np.asarray(pred)
        if pred.shape[1] != 1:
            maxidx = np.argmax(pred, axis=1)
        else:
            maxidx = (pred[:, 0] > 0.0).astype(np.int64)
        self.sum_metric += float(np.sum(maxidx != labels[:, 0].astype(np.int64)))
        self.cnt_inst += pred.shape[0]

    def device_stats(self, pred, labels):
        import jax.numpy as jnp
        if pred.shape[1] != 1:
            maxidx = jnp.argmax(pred, axis=1)
        else:
            maxidx = (pred[:, 0] > 0.0).astype(jnp.int32)
        wrong = jnp.sum(maxidx != labels[:, 0].astype(jnp.int32))
        return wrong.astype(jnp.float32), jnp.float32(pred.shape[0])


class MetricRMSE(IMetric):
    name = "rmse"

    def __init__(self):
        self.clear()

    def add_eval(self, pred, labels):
        pred = np.asarray(pred)
        if pred.shape != labels.shape:
            raise ValueError("rmse: pred and label shape mismatch")
        diff = np.sum((pred - labels) ** 2, axis=1)
        self.sum_metric += float(np.sum(diff))
        self.cnt_inst += pred.shape[0]

    def device_stats(self, pred, labels):
        import jax.numpy as jnp
        if pred.shape != labels.shape:
            raise ValueError("rmse: pred and label shape mismatch")
        s = jnp.sum(jnp.square(pred - labels))
        return s.astype(jnp.float32), jnp.float32(pred.shape[0])


class MetricLogloss(IMetric):
    name = "logloss"

    def __init__(self):
        self.clear()

    def add_eval(self, pred, labels):
        pred = np.asarray(pred)
        n = pred.shape[0]
        if pred.shape[1] != 1:
            tgt = labels[:, 0].astype(np.int64)
            p = np.clip(pred[np.arange(n), tgt], 1e-15, 1.0 - 1e-15)
            res = -np.log(p)
        else:
            p = np.clip(pred[:, 0], 1e-15, 1.0 - 1e-15)
            y = labels[:, 0]
            res = -(y * np.log(p) + (1.0 - y) * np.log(1.0 - p))
        bad = ~np.isfinite(res)
        if bad.any():
            # non-finite rows (NaN predictions/labels) are excluded from
            # both sum and count, surfaced as a health event (warn +
            # health/nonfinite_metric counter) — the jit path below can't
            # raise, so the reference's host-only FloatingPointError was
            # an inconsistent contract
            from . import health
            health.note_nonfinite("logloss", int(bad.sum()))
            res = res[~bad]
        self.sum_metric += float(np.sum(res))
        self.cnt_inst += int(res.shape[0])

    def device_stats(self, pred, labels):
        # no in-trace NaN raise (jit can't); NaNs surface at absorb()
        # time as the same health event the host path emits
        import jax.numpy as jnp
        n = pred.shape[0]
        if pred.shape[1] != 1:
            tgt = labels[:, 0].astype(jnp.int32)
            p = jnp.clip(pred[jnp.arange(n), tgt], 1e-15, 1.0 - 1e-15)
            s = -jnp.sum(jnp.log(p))
        else:
            p = jnp.clip(pred[:, 0], 1e-15, 1.0 - 1e-15)
            y = labels[:, 0]
            s = -jnp.sum(y * jnp.log(p) + (1.0 - y) * jnp.log(1.0 - p))
        return s.astype(jnp.float32), jnp.float32(n)


class MetricRecall(IMetric):
    def __init__(self, name: str):
        m = re.match(r"rec@(\d+)$", name)
        if not m:
            raise ValueError("must specify n for rec@n")
        self.topn = int(m.group(1))
        self.name = name
        self.clear()

    def add_eval(self, pred, labels):
        pred = np.asarray(pred)
        n, k = pred.shape
        if k < self.topn:
            raise ValueError(
                "rec@%d meaningless for prediction list of length %d" % (self.topn, k))
        # top-n indices by score (ties broken arbitrarily, matching the
        # reference's shuffled sort)
        top = np.argpartition(-pred, self.topn - 1, axis=1)[:, : self.topn]
        for i in range(n):
            lab = labels[i].astype(np.int64)
            hit = np.isin(lab, top[i]).sum()
            self.sum_metric += float(hit) / lab.shape[0]
        self.cnt_inst += n

    def device_stats(self, pred, labels):
        import jax
        import jax.numpy as jnp
        n, k = pred.shape
        if k < self.topn:
            raise ValueError(
                "rec@%d meaningless for prediction list of length %d"
                % (self.topn, k))
        top = jax.lax.top_k(pred, self.topn)[1]           # (n, topn)
        lab = labels.astype(jnp.int32)                    # (n, lw)
        hit = (lab[:, :, None] == top[:, None, :]).any(-1)  # (n, lw)
        s = jnp.sum(hit.mean(axis=1, dtype=jnp.float32))
        return s, jnp.float32(n)


def create_metric(name: str) -> Optional[IMetric]:
    if name == "rmse":
        return MetricRMSE()
    if name == "error":
        return MetricError()
    if name == "logloss":
        return MetricLogloss()
    if name.startswith("rec@"):
        return MetricRecall(name)
    return None


class MetricSet:
    """A set of evaluators, each bound to a label field name."""

    def __init__(self):
        self.evals: List[IMetric] = []
        self.label_fields: List[str] = []

    def add_metric(self, name: str, field: str = "label") -> None:
        m = create_metric(name)
        if m is None:
            raise ValueError("Metric: unknown metric name: %s" % name)
        self.evals.append(m)
        self.label_fields.append(field)

    def clear(self) -> None:
        for e in self.evals:
            e.clear()

    def add_eval(self, predscores: List[np.ndarray], label_info) -> None:
        """predscores: one prediction array per metric; label_info: LabelInfo."""
        assert len(predscores) == len(self.evals), \
            "number of predict scores must equal number of metrics"
        for i, e in enumerate(self.evals):
            field = self.label_fields[i]
            e.add_eval(predscores[i], label_info.field(field))

    def device_stats(self, predscores, label_info):
        """(n_metrics, 2) jnp array of (sum_metric, cnt_inst) per metric;
        traceable inside the jitted train step."""
        import jax.numpy as jnp
        assert len(predscores) == len(self.evals), \
            "number of predict scores must equal number of metrics"
        rows = []
        for i, e in enumerate(self.evals):
            s, c = e.device_stats(predscores[i],
                                  label_info.field(self.label_fields[i]))
            rows.append(jnp.stack([s, c]))
        return jnp.stack(rows)

    def absorb(self, stats) -> None:
        """Fold a fetched (n_metrics, 2) stats array (the on-device
        accumulator) into the host counters. A non-finite device sum is
        kept (the printed value shows nan — visible) but routed through
        the same health event the host path emits, so the jit path no
        longer passes NaNs SILENTLY."""
        stats = np.asarray(stats)
        for i, e in enumerate(self.evals):
            s = float(stats[i, 0])
            if not np.isfinite(s):
                from . import health
                health.note_nonfinite("train-metric:%s" % e.name)
            e.sum_metric += s
            e.cnt_inst += int(round(float(stats[i, 1])))

    def print_str(self, evname: str) -> str:
        out = []
        for i, e in enumerate(self.evals):
            s = "\t%s-%s" % (evname, e.name)
            if self.label_fields[i] != "label":
                s += "[%s]" % self.label_fields[i]
            s += ":%g" % e.get()
            out.append(s)
        return "".join(out)

    def __len__(self):
        return len(self.evals)
