"""Free-list KV-block allocator for the paged decode cache.

The HOST half of the paged KV cache (doc/performance.md "Decode KV
cache"): the device side — per-layer block pools and the gather/
writeback programs — lives in ``nnet/trainer.py`` (``KVBlockPool`` /
the paged ``DecodeSession``); this module owns every allocation
decision and is deliberately jax-free so the allocator invariants are
testable in milliseconds (``tests/test_kvblocks.py``).

Model
-----
The pool is ``blocks`` fixed-size blocks of ``block_size`` cache rows
(tokens) each. Block id 0 is RESERVED as the scratch block: the padding
entry of every block table, and the landing pad for a retired slot's
runaway device writes — it is never allocated and never meaningfully
read (attention masks every position past a slot's live extent, and a
gathered scratch block only ever covers masked positions).

* ``admit(toks, n_new)`` reserves every block a sequence can ever
  write — ``ceil((plen + n_new - 1) / block_size)`` — up front, so a
  mid-decode allocation failure cannot exist: admission either holds
  all its blocks or defers (servd's deterministic queue-wait). The
  prompt's full blocks are first matched against the prefix trie;
  matched blocks are SHARED (refcount incremented, prefilled by
  whoever loaded them — the prefill-once contract) and only the
  remainder comes off the free list.
* Shared-prefix matching is content-keyed at block granularity: the
  trie maps ``(previous block id, the block's token tuple)`` to a
  resident block, so two prompts share exactly their common full-block
  prefix. A partial tail block is never shared.
* Copy-on-write: a sequence never writes into a block with refcount
  > 1. The only write into the shared region is the block-aligned
  full-coverage case (the whole prompt matched): the last prompt
  position must be recomputed for its first-token logits, so the last
  matched block is demoted to a GATHER source and a fresh block
  becomes the write target — the device writeback copies the old
  content through the gathered view (``cow_copies`` counts these).
  Every other write lands past the shared prefix in exclusively-owned
  blocks by construction.
* ``free(ids)`` decrements refcounts; a REGISTERED block reaching zero
  moves to the **retained pool** instead of the free list, keeping its
  trie key — the cross-request conversation cache (doc/robustness.md
  "Memory governance"): turn N+1 of a conversation revives the blocks
  turn N computed (refcount 0 -> 1, a *retained* hit) instead of
  re-prefilling them. Unregistered blocks (a faulted prefill's, or any
  block with ``prefix_reuse`` off) still free instantly. Accounting
  stays exact at every instant: ``live + retained + free == pool``,
  always (``check()`` asserts it).
* Eviction is **cost-to-recompute LRU, deepest-suffix first**: the
  free list is served first; when it runs dry the allocator evicts the
  least-recently-retired retained LEAF — a block with no trie-resident
  descendant. Leaf-only eviction is a correctness rule, not a policy:
  a trie child's key names its parent's block id, so evicting a parent
  whose descendant is still resident would let a recycled id serve
  stale KV under new content. (A retained block can never have a LIVE
  descendant — ``admit`` refcounts the whole shared chain from the
  root, so a live block's ancestors are all live — which also means a
  nonempty retained pool always has an evictable leaf: eviction can
  always make progress, and exhaustion can never deadlock a
  reserve-up-front admission.)
* Evict-before-defer: ``admit`` reserves against free PLUS evictable
  retained blocks — it returns None (servd's deterministic queue-wait)
  only when live + reserved blocks alone exceed the pool. Eviction and
  reservation happen atomically under the allocator's admission lock.

Thread model: single mutating owner (servd's worker thread drives
every admit/free through the session); the published account travels
through servd's admission-lock snapshot (``_publish_batch_state``).
The mutating entry points (``admit``/``free``/``register``/
``evict_retained``) additionally serialize under one ranked lock,
``kvblocks.evict`` (lockrank.RANKS rank 15) — it nests INSIDE servd's
admission lock (``servd.queue``, rank 10) and never the reverse, so a
pressure shed issued from the dispatcher while coalescing a batch
cannot invert against an in-flight reservation; ``CXXNET_LOCKRANK=1``
(the chaos harness) asserts the order at runtime. Read-only queries
(``match_prefix``/``fresh_need``/``reservable``/``account``) stay
lockless under the single-owner model.
"""

from typing import Dict, List, Optional, Sequence, Tuple

from . import lockrank

__all__ = ["BlockAllocator", "AdmitTicket", "KVPoolExhausted"]


class KVPoolExhausted(RuntimeError):
    """Transient block-pool exhaustion at admission: the request fits
    the pool but not RIGHT NOW. Raised by a paged
    ``DecodeSession.prefill`` before any device work (the session
    stays open); servd's block-budgeted ``_gather`` makes it all but
    unreachable on the serving path, and its ``_admit_one`` catches it
    as a REQUEUE (the request returns to the queue head: a
    deterministic wait, never an error, never a device OOM). Lives
    here (not trainer.py) so the jax-free serving frontend can catch
    it by type."""


class AdmitTicket:
    """One admission's block reservation.

    ``ids``         every block the sequence holds (refcounted), in
                    position order: ``ids[j]`` backs cache rows
                    ``[j*bs, (j+1)*bs)``.
    ``gather_ids``  the ids to GATHER content from, same order —
                    identical to ``ids`` except at a copy-on-write
                    index, where it names the shared source block
                    whose content the device writeback copies.
    ``p0``          first position the suffix prefill must compute
                    (0 = no reuse; the positions [0, p0) are already
                    resident in the shared blocks).
    """

    __slots__ = ("ids", "gather_ids", "p0")

    def __init__(self, ids: List[int], gather_ids: List[int], p0: int):
        self.ids = ids
        self.gather_ids = gather_ids
        self.p0 = p0


class BlockAllocator:
    """Free-list allocator with refcounted shared-prefix blocks."""

    def __init__(self, blocks: int, block_size: int,
                 prefix_reuse: bool = True,
                 retained_frac: float = 1.0):
        if blocks < 2:
            raise ValueError("kvblocks: need >= 2 blocks "
                             "(one is the reserved scratch block)")
        if block_size < 1:
            raise ValueError("kvblocks: block_size must be >= 1")
        self.blocks = int(blocks)
        self.bs = int(block_size)
        self.prefix_reuse = bool(prefix_reuse)
        # retained-pool cap as a fraction of the usable pool
        # (serve_retained_frac). 0 restores the PR 15 free-instantly
        # contract; the default retains everything reclaimable —
        # retained blocks are evictable headroom, not a commitment
        self.retained_frac = max(0.0, min(1.0, float(retained_frac)))
        self.retained_cap = int(self.retained_frac * (self.blocks - 1))
        # ascending allocation order (pop() from the tail): determinism
        # the tests and the flight ring rely on
        self._free: List[int] = list(range(self.blocks - 1, 0, -1))
        self._ref = [0] * self.blocks
        # (prev block id | 0 at the root, block token tuple) -> block id
        self._trie: Dict[Tuple[int, tuple], int] = {}
        self._key_of: Dict[int, Tuple[int, tuple]] = {}
        # refcount-0 blocks still resident in the trie: block id ->
        # retire stamp (monotonic clock; min stamp = LRU). A chain
        # retires parent-before-child, so the LRU leaf is the oldest
        # conversation's deepest suffix — the eviction order
        self._retained: Dict[int, int] = {}
        self._rclock = 0
        # trie-resident children per parent block id (leaf test for
        # eviction); root (0) is not tracked
        self._children: Dict[int, int] = {}
        # serializes reservation+eviction+release (see module doc:
        # rank 15, nests inside servd.queue)
        self._lock = lockrank.lock("kvblocks.evict")
        # lifetime tallies (the cxxnet_decode_kv_block_* series) —
        # counted at admission SUCCESS only: a deferred ask retries
        # and must tally once, not once per attempt (alloc_failures
        # counts the defers), and the hit-rate denominator
        # (prompt_tokens) must hold only tokens that actually admitted
        self.prefix_queries = 0      # admissions completed
        self.prefix_hits = 0         # admissions that reused >= 1 token
        self.prefix_hit_tokens = 0   # prompt tokens NOT re-prefilled
        self.prompt_tokens = 0       # prompt tokens admitted
        self.cow_copies = 0          # copy-on-write block demotions
        self.alloc_failures = 0      # admissions deferred on exhaustion
        self.retained_hits = 0       # admissions served from retained
        self.retained_hit_tokens = 0  # hit tokens beyond the live chain
        self.retained_evictions = 0  # retained blocks recycled

    # -- geometry ------------------------------------------------------
    @property
    def usable(self) -> int:
        """Allocatable blocks (the scratch block excluded)."""
        return self.blocks - 1

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def used_blocks(self) -> int:
        """Blocks not on the free list (live + retained)."""
        return self.usable - len(self._free)

    @property
    def retained_blocks(self) -> int:
        return len(self._retained)

    @property
    def live_blocks(self) -> int:
        """Blocks held by a live (refcount > 0) sequence."""
        return self.used_blocks - len(self._retained)

    @property
    def available_blocks(self) -> int:
        """Admissible headroom: free plus cascade-evictable retained
        blocks. The per-request form (``reservable``) subtracts the
        request's OWN pinned chain — the shared blocks it is about to
        revive and its CoW gather source fund nothing."""
        return len(self._free) + len(self._retained)

    def blocks_for(self, plen: int, n_new: int) -> int:
        """Blocks a (prompt, budget) sequence can ever write: cache
        rows [0, plen + n_new - 1) — the final generated token is
        returned but its K/V row is never written (no later step reads
        it)."""
        rows = max(1, int(plen) + max(1, int(n_new)) - 1)
        return -(-rows // self.bs)

    def fits(self, plen: int, n_new: int) -> bool:
        """Whether the sequence can EVER be admitted (vs the whole
        pool) — False is a deterministic request defect, not a wait."""
        return self.blocks_for(plen, n_new) <= self.usable

    # -- prefix trie ---------------------------------------------------
    def match_prefix(self, toks: Sequence[int]) -> List[int]:
        """Resident blocks covering the prompt's full-block prefix —
        the chain of content-matched FULL blocks from the root. No
        refcounts move (``admit`` does that)."""
        if not self.prefix_reuse:
            return []
        out: List[int] = []
        prev = 0
        bs = self.bs
        for j in range(len(toks) // bs):
            key = (prev, tuple(int(t) for t in toks[j * bs:(j + 1) * bs]))
            b = self._trie.get(key)
            if b is None:
                break
            out.append(b)
            prev = b
        return out

    def fresh_need(self, plen: int, n_new: int,
                   toks: Optional[Sequence[int]] = None) -> int:
        """Blocks ``admit`` would pull OFF THE FREE LIST right now —
        total need minus the resident shared prefix (with ``toks``),
        CoW demotion included. servd's gather loop budgets queue pops
        against this (single mutating owner, so check-then-admit is
        race-free)."""
        shared = len(self.match_prefix(toks)) if toks is not None else 0
        need = self.blocks_for(plen, n_new)
        if shared * self.bs >= plen:
            shared -= 1       # the CoW demotion needs a fresh target
        return need - max(0, shared)

    def _pinned(self, shared: List[int], cow_src: Optional[int]) -> int:
        """Retained blocks this admission itself pins: the chain it is
        about to revive plus a retained CoW gather source — they cannot
        be evicted to fund the same admission's fresh need."""
        n = sum(1 for b in shared if b in self._retained)
        if cow_src is not None and cow_src in self._retained:
            n += 1
        return n

    def reservable(self, plen: int, n_new: int,
                   toks: Optional[Sequence[int]] = None) -> bool:
        """Whether ``admit`` would succeed RIGHT NOW — the admission
        gate. With ``toks`` the shared prefix is credited. Retained
        blocks count as headroom (evict-before-defer): False means
        live + reserved blocks alone exceed the pool."""
        shared = self.match_prefix(toks) if toks is not None else []
        cow_src = None
        if shared and len(shared) * self.bs >= plen:
            cow_src = shared.pop()
        need = self.blocks_for(plen, n_new) - len(shared)
        return need <= (len(self._free) + len(self._retained)
                        - self._pinned(shared, cow_src))

    # -- reserve / release ---------------------------------------------
    def admit(self, toks: Sequence[int],
              n_new: int) -> Optional[AdmitTicket]:
        """Reserve every block for (prompt, generation budget): shared
        full-prefix blocks are refcounted (a retained match is REVIVED:
        refcount 0 -> 1, a retained hit), the rest come off the free
        list — evicting retained LRU leaves when it runs dry, atomically
        under the admission lock. Returns None only when live + reserved
        blocks alone exceed the pool (nothing moves — the caller defers:
        servd's deterministic queue-wait, never a device OOM)."""
        with self._lock:
            return self._admit(toks, n_new)

    def _admit(self, toks: Sequence[int],
               n_new: int) -> Optional[AdmitTicket]:
        plen = len(toks)
        if plen < 1:
            raise ValueError("kvblocks: empty prompt")
        need = self.blocks_for(plen, n_new)
        if need > self.usable:
            raise ValueError(
                "kvblocks: sequence needs %d blocks, pool holds %d — "
                "gate this at admits() (it can never fit)"
                % (need, self.usable))
        shared = self.match_prefix(toks)
        cow_src = None
        if shared and len(shared) * self.bs >= plen:
            # block-aligned full coverage: the last prompt position
            # must be recomputed (its first-token logits are not
            # stored), and that write may not land in a shared block —
            # demote the last match to a gather source (CoW)
            cow_src = shared.pop()
        fresh_need = need - len(shared)
        if fresh_need > (len(self._free) + len(self._retained)
                         - self._pinned(shared, cow_src)):
            self.alloc_failures += 1
            return None
        self.prefix_queries += 1
        p0 = (plen - 1) if cow_src is not None else len(shared) * self.bs
        if p0 > 0:
            self.prefix_hits += 1
            self.prefix_hit_tokens += p0
        # retained sub-source of the hit: tokens of [0, p0) beyond the
        # LIVE-held chain came from retained content (revived blocks
        # and/or a retained CoW source). Live blocks form a chain
        # PREFIX — a live block's ancestors are all live — so the
        # boundary is the first retained block in the chain.
        chain = shared + ([cow_src] if cow_src is not None else [])
        n_live = 0
        for b in chain:
            if b in self._retained:
                break
            n_live += 1
        rtoks = max(0, p0 - n_live * self.bs)
        if rtoks > 0:
            self.retained_hits += 1
            self.retained_hit_tokens += rtoks
        if cow_src is not None:
            self.cow_copies += 1
        self.prompt_tokens += plen
        for b in shared:
            if b in self._retained:
                del self._retained[b]     # revival: refcount 0 -> 1
            self._ref[b] += 1
        fresh: List[int] = []
        for _ in range(fresh_need):
            if not self._free:
                # evict-before-defer: recycle the LRU retained leaf.
                # The revived chain already left the retained pool;
                # only the CoW gather source still needs pinning (its
                # content is gathered by THIS admission's prefill).
                self._evict_one(exclude=cow_src)
            fresh.append(self._free.pop())
        for b in fresh:
            self._ref[b] = 1
        ids = shared + fresh
        gather_ids = list(ids)
        if cow_src is not None:
            # gather the shared content, write back to the fresh copy
            gather_ids[len(shared)] = cow_src
        return AdmitTicket(ids, gather_ids, p0)

    def register(self, ticket: AdmitTicket,
                 toks: Sequence[int]) -> None:
        """Publish the admission's FULL prompt blocks into the trie
        (call after its prefill succeeded — a faulted prefill's blocks
        hold garbage and must stay unfindable). An existing entry wins:
        a copy-on-write twin is not re-registered under the same
        content (its source already serves lookups)."""
        if not self.prefix_reuse:
            return
        with self._lock:
            prev = 0
            bs = self.bs
            for j in range(len(toks) // bs):
                b = ticket.ids[j]
                key = (prev,
                       tuple(int(t) for t in toks[j * bs:(j + 1) * bs]))
                cur = self._trie.get(key)
                if cur is None:
                    self._trie[key] = b
                    self._key_of[b] = key
                    if prev:
                        self._children[prev] = \
                            self._children.get(prev, 0) + 1
                    cur = b
                prev = cur

    def free(self, ids: Sequence[int]) -> None:
        """Release one holder's blocks (retire / deadline-evict /
        close): refcounts drop; a REGISTERED block reaching zero moves
        to the retained pool (trie key kept — the conversation cache),
        an unregistered one returns to the free list. The account is
        exact at every instant: live + retained + free == pool."""
        with self._lock:
            for b in ids:
                if not 1 <= b < self.blocks:
                    raise ValueError("kvblocks: bad block id %r" % (b,))
                self._ref[b] -= 1
                if self._ref[b] < 0:
                    raise ValueError(
                        "kvblocks: double free of block %d" % b)
                if self._ref[b] != 0:
                    continue
                if self.retained_cap > 0 and b in self._key_of:
                    # retain: keep the trie entry, stamp the LRU clock
                    # (ids arrive in position order, so a chain stamps
                    # parent-before-child and the LRU leaf is the
                    # oldest conversation's deepest suffix)
                    self._rclock += 1
                    self._retained[b] = self._rclock
                else:
                    self._drop_key(b)
                    self._free.append(b)
            # cap AFTER the whole release landed: a parent is never
            # dropped from the trie before its child is accounted, so
            # the leaf rule sees the finished chain
            while len(self._retained) > self.retained_cap:
                self._evict_one()

    # -- retained pool --------------------------------------------------
    def _drop_key(self, b: int) -> None:
        """Remove ``b``'s trie entry (if any) and its parent's child
        count — the bookkeeping shared by instant-free and eviction."""
        key = self._key_of.pop(b, None)
        if key is None:
            return
        if self._trie.get(key) == b:
            del self._trie[key]
        prev = key[0]
        if prev:
            c = self._children.get(prev, 0) - 1
            if c > 0:
                self._children[prev] = c
            else:
                self._children.pop(prev, None)

    def _evict_one(self, exclude: Optional[int] = None) -> int:
        """Recycle the LRU retained LEAF (no trie-resident descendant)
        onto the free list. Always succeeds on a nonempty retained pool
        (minus ``exclude``): retained blocks never have live
        descendants, so every retained chain bottoms out in a retained
        leaf — eviction cannot wedge against reservation."""
        best = None
        best_stamp = 0
        for b, stamp in self._retained.items():
            if b == exclude or self._children.get(b, 0):
                continue
            if best is None or stamp < best_stamp:
                best, best_stamp = b, stamp
        if best is None:
            raise AssertionError(
                "kvblocks: no evictable retained leaf (%d retained) — "
                "the leaf invariant is broken" % len(self._retained))
        del self._retained[best]
        self._drop_key(best)
        self._free.append(best)
        self.retained_evictions += 1
        return best

    def evict_retained(self, n: Optional[int] = None,
                       target_free: Optional[int] = None) -> int:
        """Proactively shed retained mass (servd's low-headroom
        pressure latch): evict LRU leaves until ``n`` blocks are
        recycled and/or the free list reaches ``target_free`` (with
        neither bound, drain the whole retained pool). Returns the
        number of blocks evicted."""
        with self._lock:
            done = 0
            while self._retained:
                if n is not None and done >= n:
                    break
                if target_free is not None \
                        and len(self._free) >= target_free:
                    break
                self._evict_one()
                done += 1
            return done

    # -- account / invariants ------------------------------------------
    def account(self) -> dict:
        return {"blocks_total": self.usable,
                "blocks_free": len(self._free),
                "blocks_used": self.used_blocks,
                "blocks_live": self.live_blocks,
                "blocks_retained": len(self._retained),
                "retained_cap": self.retained_cap,
                "block_tokens": self.bs,
                "prefix_queries": self.prefix_queries,
                "prefix_hits": self.prefix_hits,
                "prefix_hit_tokens": self.prefix_hit_tokens,
                "prompt_tokens": self.prompt_tokens,
                "cow_copies": self.cow_copies,
                "alloc_failures": self.alloc_failures,
                "retained_hits": self.retained_hits,
                "retained_hit_tokens": self.retained_hit_tokens,
                "retained_evictions": self.retained_evictions}

    def books_law(self) -> Optional[str]:
        """Conservation law for the auditor (telemetry.BooksAuditor):
        ``live + retained + free == pool``, evaluated atomically under
        the allocator lock. Returns None when the books reconcile, a
        detail string when they do not — never raises (the auditor
        treats exceptions as inconclusive, but a broken pool equation
        is a definite violation and must latch)."""
        with self._lock:
            free = len(self._free)
            retained = len(self._retained)
            live = sum(1 for b in range(1, self.blocks)
                       if self._ref[b] > 0)
            if live + retained + free == self.usable:
                return None
            return ("kv blocks leak: live %d + retained %d + free %d "
                    "!= pool %d" % (live, retained, free, self.usable))

    def check(self) -> None:
        """Assert every structural invariant (the test suite's oracle
        after chaos-ordered admit/free interleavings)."""
        assert self._ref[0] == 0, "scratch block acquired a refcount"
        free = set(self._free)
        assert len(free) == len(self._free), "free list duplicates"
        assert 0 not in free, "scratch block on the free list"
        retained = set(self._retained)
        assert not (free & retained), \
            "blocks both free and retained: %r" % sorted(free & retained)
        live = 0
        for b in range(1, self.blocks):
            if b in free:
                assert self._ref[b] == 0, \
                    "block %d free with refcount %d" % (b, self._ref[b])
            elif b in retained:
                assert self._ref[b] == 0, \
                    "retained block %d holds refcount %d" \
                    % (b, self._ref[b])
                assert b in self._key_of, \
                    "retained block %d has no trie key" % b
            else:
                assert self._ref[b] > 0, \
                    "block %d leaked (neither free, retained nor held)" \
                    % b
                live += 1
        # the books reconcile, always: live + retained + free == pool
        assert live + len(retained) + len(free) == self.usable, \
            "books broken: live %d + retained %d + free %d != pool %d" \
            % (live, len(retained), len(free), self.usable)
        assert len(retained) <= self.retained_cap, \
            "retained pool over cap: %d > %d" \
            % (len(retained), self.retained_cap)
        children: Dict[int, int] = {}
        for key, b in self._trie.items():
            assert self._ref[b] > 0 or b in retained, \
                "trie points at dead block %d" % b
            assert self._key_of.get(b) == key, \
                "trie/_key_of disagree on block %d" % b
            prev = key[0]
            if prev:
                # chain integrity: a resident child's parent must be
                # resident too (the leaf-only eviction rule's contract)
                assert prev in self._key_of, \
                    "block %d's trie parent %d left the trie" % (b, prev)
                children[prev] = children.get(prev, 0) + 1
                # and a live child can never hang off a retained
                # parent (admit refcounts the whole chain)
                if self._ref[b] > 0:
                    assert prev not in retained, \
                        "live block %d under retained parent %d" \
                        % (b, prev)
        assert children == self._children, \
            "child counts drifted: %r != %r" % (children, self._children)
        for b, key in self._key_of.items():
            assert self._trie.get(key) == b
