"""Free-list KV-block allocator for the paged decode cache.

The HOST half of the paged KV cache (doc/performance.md "Decode KV
cache"): the device side — per-layer block pools and the gather/
writeback programs — lives in ``nnet/trainer.py`` (``KVBlockPool`` /
the paged ``DecodeSession``); this module owns every allocation
decision and is deliberately jax-free so the allocator invariants are
testable in milliseconds (``tests/test_kvblocks.py``).

Model
-----
The pool is ``blocks`` fixed-size blocks of ``block_size`` cache rows
(tokens) each. Block id 0 is RESERVED as the scratch block: the padding
entry of every block table, and the landing pad for a retired slot's
runaway device writes — it is never allocated and never meaningfully
read (attention masks every position past a slot's live extent, and a
gathered scratch block only ever covers masked positions).

* ``admit(toks, n_new)`` reserves every block a sequence can ever
  write — ``ceil((plen + n_new - 1) / block_size)`` — up front, so a
  mid-decode allocation failure cannot exist: admission either holds
  all its blocks or defers (servd's deterministic queue-wait). The
  prompt's full blocks are first matched against the prefix trie;
  matched blocks are SHARED (refcount incremented, prefilled by
  whoever loaded them — the prefill-once contract) and only the
  remainder comes off the free list.
* Shared-prefix matching is content-keyed at block granularity: the
  trie maps ``(previous block id, the block's token tuple)`` to a
  resident block, so two prompts share exactly their common full-block
  prefix. A partial tail block is never shared.
* Copy-on-write: a sequence never writes into a block with refcount
  > 1. The only write into the shared region is the block-aligned
  full-coverage case (the whole prompt matched): the last prompt
  position must be recomputed for its first-token logits, so the last
  matched block is demoted to a GATHER source and a fresh block
  becomes the write target — the device writeback copies the old
  content through the gathered view (``cow_copies`` counts these).
  Every other write lands past the shared prefix in exclusively-owned
  blocks by construction.
* ``free(ids)`` decrements refcounts; a block reaching zero leaves the
  trie and returns to the free list in the same step — accounting is
  exact at every instant (no deferred reclamation, no leak: after the
  last holder frees, ``blocks_free`` equals the usable pool and the
  trie is empty).

Thread model: single mutating owner (servd's worker thread drives
every admit/free through the session). The published account travels
through servd's admission-lock snapshot (``_publish_batch_state``) —
the allocator itself takes no lock, so the cxxlint lock graph is
untouched.
"""

from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ["BlockAllocator", "AdmitTicket", "KVPoolExhausted"]


class KVPoolExhausted(RuntimeError):
    """Transient block-pool exhaustion at admission: the request fits
    the pool but not RIGHT NOW. Raised by a paged
    ``DecodeSession.prefill`` before any device work (the session
    stays open); servd's block-budgeted ``_gather`` makes it all but
    unreachable on the serving path, and its ``_admit_one`` catches it
    as a REQUEUE (the request returns to the queue head: a
    deterministic wait, never an error, never a device OOM). Lives
    here (not trainer.py) so the jax-free serving frontend can catch
    it by type."""


class AdmitTicket:
    """One admission's block reservation.

    ``ids``         every block the sequence holds (refcounted), in
                    position order: ``ids[j]`` backs cache rows
                    ``[j*bs, (j+1)*bs)``.
    ``gather_ids``  the ids to GATHER content from, same order —
                    identical to ``ids`` except at a copy-on-write
                    index, where it names the shared source block
                    whose content the device writeback copies.
    ``p0``          first position the suffix prefill must compute
                    (0 = no reuse; the positions [0, p0) are already
                    resident in the shared blocks).
    """

    __slots__ = ("ids", "gather_ids", "p0")

    def __init__(self, ids: List[int], gather_ids: List[int], p0: int):
        self.ids = ids
        self.gather_ids = gather_ids
        self.p0 = p0


class BlockAllocator:
    """Free-list allocator with refcounted shared-prefix blocks."""

    def __init__(self, blocks: int, block_size: int,
                 prefix_reuse: bool = True):
        if blocks < 2:
            raise ValueError("kvblocks: need >= 2 blocks "
                             "(one is the reserved scratch block)")
        if block_size < 1:
            raise ValueError("kvblocks: block_size must be >= 1")
        self.blocks = int(blocks)
        self.bs = int(block_size)
        self.prefix_reuse = bool(prefix_reuse)
        # ascending allocation order (pop() from the tail): determinism
        # the tests and the flight ring rely on
        self._free: List[int] = list(range(self.blocks - 1, 0, -1))
        self._ref = [0] * self.blocks
        # (prev block id | 0 at the root, block token tuple) -> block id
        self._trie: Dict[Tuple[int, tuple], int] = {}
        self._key_of: Dict[int, Tuple[int, tuple]] = {}
        # lifetime tallies (the cxxnet_decode_kv_block_* series) —
        # counted at admission SUCCESS only: a deferred ask retries
        # and must tally once, not once per attempt (alloc_failures
        # counts the defers), and the hit-rate denominator
        # (prompt_tokens) must hold only tokens that actually admitted
        self.prefix_queries = 0      # admissions completed
        self.prefix_hits = 0         # admissions that reused >= 1 token
        self.prefix_hit_tokens = 0   # prompt tokens NOT re-prefilled
        self.prompt_tokens = 0       # prompt tokens admitted
        self.cow_copies = 0          # copy-on-write block demotions
        self.alloc_failures = 0      # admissions deferred on exhaustion

    # -- geometry ------------------------------------------------------
    @property
    def usable(self) -> int:
        """Allocatable blocks (the scratch block excluded)."""
        return self.blocks - 1

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def used_blocks(self) -> int:
        return self.usable - len(self._free)

    def blocks_for(self, plen: int, n_new: int) -> int:
        """Blocks a (prompt, budget) sequence can ever write: cache
        rows [0, plen + n_new - 1) — the final generated token is
        returned but its K/V row is never written (no later step reads
        it)."""
        rows = max(1, int(plen) + max(1, int(n_new)) - 1)
        return -(-rows // self.bs)

    def fits(self, plen: int, n_new: int) -> bool:
        """Whether the sequence can EVER be admitted (vs the whole
        pool) — False is a deterministic request defect, not a wait."""
        return self.blocks_for(plen, n_new) <= self.usable

    # -- prefix trie ---------------------------------------------------
    def match_prefix(self, toks: Sequence[int]) -> List[int]:
        """Resident blocks covering the prompt's full-block prefix —
        the chain of content-matched FULL blocks from the root. No
        refcounts move (``admit`` does that)."""
        if not self.prefix_reuse:
            return []
        out: List[int] = []
        prev = 0
        bs = self.bs
        for j in range(len(toks) // bs):
            key = (prev, tuple(int(t) for t in toks[j * bs:(j + 1) * bs]))
            b = self._trie.get(key)
            if b is None:
                break
            out.append(b)
            prev = b
        return out

    def fresh_need(self, plen: int, n_new: int,
                   toks: Optional[Sequence[int]] = None) -> int:
        """Blocks ``admit`` would pull OFF THE FREE LIST right now —
        total need minus the resident shared prefix (with ``toks``),
        CoW demotion included. servd's gather loop budgets queue pops
        against this (single mutating owner, so check-then-admit is
        race-free)."""
        shared = len(self.match_prefix(toks)) if toks is not None else 0
        need = self.blocks_for(plen, n_new)
        if shared * self.bs >= plen:
            shared -= 1       # the CoW demotion needs a fresh target
        return need - max(0, shared)

    def reservable(self, plen: int, n_new: int,
                   toks: Optional[Sequence[int]] = None) -> bool:
        """Whether ``admit`` would succeed RIGHT NOW — the admission
        gate. With ``toks`` the shared prefix is credited."""
        return self.fresh_need(plen, n_new, toks) <= len(self._free)

    # -- reserve / release ---------------------------------------------
    def admit(self, toks: Sequence[int],
              n_new: int) -> Optional[AdmitTicket]:
        """Reserve every block for (prompt, generation budget): shared
        full-prefix blocks are refcounted, the rest come off the free
        list. Returns None when the free list cannot cover the fresh
        need (nothing moves — the caller defers: servd's deterministic
        queue-wait, never a device OOM)."""
        plen = len(toks)
        if plen < 1:
            raise ValueError("kvblocks: empty prompt")
        need = self.blocks_for(plen, n_new)
        if need > self.usable:
            raise ValueError(
                "kvblocks: sequence needs %d blocks, pool holds %d — "
                "gate this at admits() (it can never fit)"
                % (need, self.usable))
        shared = self.match_prefix(toks)
        cow_src = None
        if shared and len(shared) * self.bs >= plen:
            # block-aligned full coverage: the last prompt position
            # must be recomputed (its first-token logits are not
            # stored), and that write may not land in a shared block —
            # demote the last match to a gather source (CoW)
            cow_src = shared.pop()
        fresh_need = need - len(shared)
        if fresh_need > len(self._free):
            self.alloc_failures += 1
            return None
        self.prefix_queries += 1
        p0 = (plen - 1) if cow_src is not None else len(shared) * self.bs
        if p0 > 0:
            self.prefix_hits += 1
            self.prefix_hit_tokens += p0
        if cow_src is not None:
            self.cow_copies += 1
        self.prompt_tokens += plen
        for b in shared:
            self._ref[b] += 1
        fresh = [self._free.pop() for _ in range(fresh_need)]
        for b in fresh:
            self._ref[b] = 1
        ids = shared + fresh
        gather_ids = list(ids)
        if cow_src is not None:
            # gather the shared content, write back to the fresh copy
            gather_ids[len(shared)] = cow_src
        return AdmitTicket(ids, gather_ids, p0)

    def register(self, ticket: AdmitTicket,
                 toks: Sequence[int]) -> None:
        """Publish the admission's FULL prompt blocks into the trie
        (call after its prefill succeeded — a faulted prefill's blocks
        hold garbage and must stay unfindable). An existing entry wins:
        a copy-on-write twin is not re-registered under the same
        content (its source already serves lookups)."""
        if not self.prefix_reuse:
            return
        prev = 0
        bs = self.bs
        for j in range(len(toks) // bs):
            b = ticket.ids[j]
            key = (prev, tuple(int(t) for t in toks[j * bs:(j + 1) * bs]))
            cur = self._trie.setdefault(key, b)
            if cur == b:
                self._key_of[b] = key
            prev = cur

    def free(self, ids: Sequence[int]) -> None:
        """Release one holder's blocks (retire / deadline-evict /
        close): refcounts drop, a block reaching zero leaves the trie
        and returns to the free list immediately — the account is
        exact at every instant."""
        for b in ids:
            if not 1 <= b < self.blocks:
                raise ValueError("kvblocks: bad block id %r" % (b,))
            self._ref[b] -= 1
            if self._ref[b] < 0:
                raise ValueError("kvblocks: double free of block %d" % b)
            if self._ref[b] == 0:
                key = self._key_of.pop(b, None)
                if key is not None and self._trie.get(key) == b:
                    del self._trie[key]
                self._free.append(b)

    # -- account / invariants ------------------------------------------
    def account(self) -> dict:
        return {"blocks_total": self.usable,
                "blocks_free": len(self._free),
                "blocks_used": self.used_blocks,
                "block_tokens": self.bs,
                "prefix_queries": self.prefix_queries,
                "prefix_hits": self.prefix_hits,
                "prefix_hit_tokens": self.prefix_hit_tokens,
                "prompt_tokens": self.prompt_tokens,
                "cow_copies": self.cow_copies,
                "alloc_failures": self.alloc_failures}

    def check(self) -> None:
        """Assert every structural invariant (the test suite's oracle
        after chaos-ordered admit/free interleavings)."""
        assert self._ref[0] == 0, "scratch block acquired a refcount"
        free = set(self._free)
        assert len(free) == len(self._free), "free list duplicates"
        assert 0 not in free, "scratch block on the free list"
        for b in range(1, self.blocks):
            if b in free:
                assert self._ref[b] == 0, \
                    "block %d free with refcount %d" % (b, self._ref[b])
            else:
                assert self._ref[b] > 0, \
                    "block %d leaked (neither free nor held)" % b
        for key, b in self._trie.items():
            assert self._ref[b] > 0, "trie points at dead block %d" % b
            assert self._key_of.get(b) == key, \
                "trie/_key_of disagree on block %d" % b
        for b, key in self._key_of.items():
            assert self._trie.get(key) == b
