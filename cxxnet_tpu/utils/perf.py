"""Live program performance ledger: per-compiled-program cost/memory
cards, MFU & roofline-efficiency gauges, HBM headroom accounting, and an
on-demand profiler capture guard.

The offline tools already knew how to compute "is it fast / does it
fit": ``tools/roofline.py`` counts analytic FLOPs against the chip peak,
``tools/memory_report.py`` compiles a step and reads XLA's
``memory_analysis()``, ``tools/profile_bench.py`` captures an xprof
trace. None of that fed the *running* system — production ML infra
treats cost models as first-class runtime objects (TF's system paper,
arXiv:1605.08695) and compile-time cost metadata as the optimization
currency (TVM, arXiv:1802.04799). This module is that runtime spine:

* **DeviceSpec** — the shared peak-FLOP/s + HBM-bandwidth + HBM-capacity
  table per platform (``DEVICE_SPECS``). The offline tools consume the
  SAME table (``tools/roofline.py`` delegates ``peak_flops()`` /
  ``peak_hbm_bytes()`` here), so live and offline numbers can never
  disagree. A ``cpu`` entry (nominal, documented) makes every gauge
  testable without the TPU tunnel; ``CXXNET_PEAK_TFLOPS`` /
  ``CXXNET_PEAK_HBM_GBS`` / ``CXXNET_HBM_CAPACITY_GIB`` override any
  entry, ``PALLAS_AXON_TPU_GEN`` picks the TPU generation.

* **ProgramCard** — one card per (program name, input-shapes signature)
  the trainer compiles. The recompile detector
  (``telemetry.jit_watch``) already sees every compile; with the ledger
  enabled it hands the compiled callable + its call arguments to
  ``Ledger.on_compile``, which records the compile wall time
  immediately and queues an analysis job. The **carder thread**
  completes the card off the hot path: ``fn.lower(shapes)`` (the trace
  is cached from the triggering call — milliseconds) yields XLA
  ``cost_analysis()`` FLOPs + bytes accessed; ``lowered.compile()``
  (a real second compile — the reason this runs on a background
  thread, never inside a serving request) yields ``memory_analysis()``
  argument/temp/output bytes per device. A roofline-predicted
  execution time falls out: ``max(flops/peak_flops, bytes/hbm_bw)``.

* **live gauges** — ``snapshot()`` joins each card against the
  program's *measured* latency histogram (``MEASURED_SERIES``: the
  telemetry series the trainer already feeds — ``train.step``,
  ``decode.prefill``, ``decode.decode``, ...):
  ``mfu_pct`` = flops / (measured p50 x peak), ``roofline_eff_pct`` =
  predicted / measured p50 (under 100 = slower than the hardware
  allows; over 100 usually means the measured series times DISPATCH,
  not execution — flagged in doc/performance.md). Aggregates:
  ``hbm_peak_bytes`` (max per-device peak over cards — the number the
  paged-KV allocator will be sized against) and ``hbm_headroom_bytes``
  vs the spec capacity. statusd renders all of it: ``/programz`` (the
  per-program table), ``/metrics`` (``cxxnet_program_*`` /
  ``cxxnet_hbm_*`` series), and each completed card lands in the
  telemetry JSONL as a ``program_card`` event for
  ``tools/telemetry_report.py``'s program-ledger section.

* **ProfilerCapture** — the guard behind statusd's ``/profilez?secs=N``:
  one jax.profiler trace capture at a time into a run-scoped directory
  (conf key ``profilez_dir``), so a live slow replica can be xprof'd
  without restarting it. Injectable trace function keeps it testable
  (and the selftest) jax-free.

Jax-free at import (like servd/statusd/health): jax is imported lazily
inside the capture paths, which only run after a jitted call already
proved jax present. ``python -m cxxnet_tpu.utils.perf --selftest``
exercises card math, gauge rendering, /programz + /profilez over a real
socket, and the capture guard with faked analyses; ``make check`` gates
on it. Enabled via the conf key ``perf_ledger`` (learn_task wires it
whenever telemetry is on); disabled, the only cost is the recompile
detector's existing bookkeeping.
"""

from __future__ import annotations

import os
import threading
import time
import zlib
from collections import deque
from typing import Dict, List, Optional, Tuple

from . import lockrank
from . import telemetry

__all__ = [
    "DeviceSpec", "DEVICE_SPECS", "device_spec", "offline_spec",
    "current_device_spec", "MEASURED_SERIES", "Ledger", "ProfilerCapture",
    "ledger", "enable", "disable", "enabled", "drain", "reset",
    "decode_bound_tokens_per_s", "shapes_signature", "predicted_seconds",
    "footprint_bytes", "selftest",
]


class DeviceSpec:
    """One platform's roofline constants: peak matmul FLOP/s (bf16),
    HBM bandwidth (bytes/s), and per-device HBM capacity (bytes). The
    single source the live ledger AND the offline tools read."""

    __slots__ = ("name", "peak_flops", "hbm_bw", "hbm_capacity")

    def __init__(self, name: str, peak_flops: float, hbm_bw: float,
                 hbm_capacity: float):
        self.name = name
        self.peak_flops = float(peak_flops)
        self.hbm_bw = float(hbm_bw)
        self.hbm_capacity = float(hbm_capacity)

    def to_dict(self) -> dict:
        return {"name": self.name, "peak_flops": self.peak_flops,
                "hbm_bw": self.hbm_bw, "hbm_capacity": self.hbm_capacity}

    def __repr__(self):
        return ("DeviceSpec(%s, %.0f GFLOP/s, %.0f GB/s, %.1f GiB)"
                % (self.name, self.peak_flops / 1e9, self.hbm_bw / 1e9,
                   self.hbm_capacity / 2**30))


# bf16 peak / HBM bandwidth / per-device HBM capacity per chip
# generation (v5e = "v5 lite"). The ``cpu`` entry is NOMINAL — a
# few-core container has no single honest peak — chosen so MFU%/headroom
# stay meaningful (and overridable) in tunnel-down CPU runs; every field
# yields to the CXXNET_PEAK_* env overrides below.
DEVICE_SPECS: Dict[str, DeviceSpec] = {
    "v5e": DeviceSpec("v5e", 197.0e12, 819.0e9, 16 * 2.0**30),
    "v5lite": DeviceSpec("v5lite", 197.0e12, 819.0e9, 16 * 2.0**30),
    "v4": DeviceSpec("v4", 275.0e12, 1228.0e9, 32 * 2.0**30),
    "v6e": DeviceSpec("v6e", 918.0e12, 1638.0e9, 32 * 2.0**30),
    "cpu": DeviceSpec("cpu", 0.2e12, 25.0e9, 16 * 2.0**30),
}


def device_spec(gen: Optional[str] = None) -> DeviceSpec:
    """The spec for a generation name (default: the offline tools'
    ``PALLAS_AXON_TPU_GEN`` convention, v5e when unset), with the env
    overrides applied: ``CXXNET_PEAK_TFLOPS``, ``CXXNET_PEAK_HBM_GBS``,
    ``CXXNET_HBM_CAPACITY_GIB``. Unknown generations fall back to v5e
    (the fleet default), like tools/roofline.py always did."""
    if gen is None:
        gen = os.environ.get("PALLAS_AXON_TPU_GEN", "v5e").lower()
    base = DEVICE_SPECS.get(gen, DEVICE_SPECS["v5e"])
    name, peak, bw, cap = base.name, base.peak_flops, base.hbm_bw, \
        base.hbm_capacity
    env = os.environ.get("CXXNET_PEAK_TFLOPS")
    if env:
        peak = float(env) * 1e12
    env = os.environ.get("CXXNET_PEAK_HBM_GBS")
    if env:
        bw = float(env) * 1e9
    env = os.environ.get("CXXNET_HBM_CAPACITY_GIB")
    if env:
        cap = float(env) * 2.0**30
    if (peak, bw, cap) != (base.peak_flops, base.hbm_bw,
                           base.hbm_capacity):
        return DeviceSpec(name + "+env", peak, bw, cap)
    return base


def offline_spec() -> DeviceSpec:
    """The chip the OFFLINE tools model (roofline.py, memory_report.py):
    always a TPU generation — an analysis run on a CPU box is still
    asking "how would this do on the chip"."""
    return device_spec()


def current_device_spec() -> DeviceSpec:
    """The spec for the platform THIS process actually runs on: the cpu
    entry under ``JAX_PLATFORMS=cpu`` (so live gauges are testable with
    the tunnel down), the REAL chip generation (device_kind) on an
    accelerator backend — ``PALLAS_AXON_TPU_GEN`` still overrides —
    and the cpu fallback when jax is absent entirely (jax-free tests).

    CONTRACT: call only after the backend is up (a jit ran, a device
    was probed) — ``jax.default_backend()`` initializes the platform,
    and doing that before the trainer's platform selection would
    re-introduce the tunnel-down hang doc/performance.md warns about.
    The ledger therefore resolves its spec LAZILY at first card
    completion, never at enable() time."""
    try:
        import jax
        backend = jax.default_backend()
    except Exception:
        backend = "cpu"
    if backend == "cpu":
        return device_spec("cpu")
    if not os.environ.get("PALLAS_AXON_TPU_GEN"):
        try:
            kind = jax.devices()[0].device_kind.lower()
        except Exception:
            kind = ""
        # "TPU v5 lite" / "TPU v4" / "TPU v6 lite" -> table keys
        for token, gen in (("v6", "v6e"), ("v5", "v5e"), ("v4", "v4")):
            if token in kind:
                return device_spec(gen)
    return offline_spec()


# program name -> the telemetry histogram that MEASURES its executions
# (the join key between a card's predicted time and reality). These are
# the series the trainer already feeds; doc/observability.md notes
# which ones time dispatch rather than execution.
MEASURED_SERIES = {
    "jit.train_step": "train.step",
    "jit.eval_fwd": "eval.forward",
    "jit.predict": "predict",
    "jit.decode_prefill": "decode.prefill",
    "jit.decode_step": "decode.decode",
    "jit.beam_decode": "decode.beam",
}

_DTYPE_SHORT = {
    "float32": "f32", "float64": "f64", "float16": "f16",
    "bfloat16": "bf16", "int32": "i32", "int64": "i64", "int8": "i8",
    "uint8": "u8", "uint32": "u32", "bool": "b1",
}


def _leaves(obj):
    """Jax-free pytree leaf walk (list/tuple/dict containers — the only
    shapes the trainer's call signatures use)."""
    if isinstance(obj, (list, tuple)):
        for v in obj:
            yield from _leaves(v)
    elif isinstance(obj, dict):
        for k in sorted(obj, key=str):
            yield from _leaves(obj[k])
    else:
        yield obj


def shapes_signature(args, kwargs=None) -> Tuple[str, str]:
    """(display, hash) signature of a call's input shapes/dtypes —
    the card key's second half. Duck-typed (``.shape``/``.dtype``), so
    fakes work jax-free; non-array leaves (None, python scalars) are
    folded in by repr. The display form is truncated for tables; the
    crc32 hash is the stable key."""
    toks: List[str] = []
    for leaf in _leaves((args, kwargs or {})):
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is not None and dtype is not None:
            toks.append("%s[%s]" % (
                _DTYPE_SHORT.get(str(dtype), str(dtype)),
                ",".join(str(int(d)) for d in shape)))
        elif leaf is None:
            continue
        else:
            toks.append(repr(leaf)[:16])
    full = ",".join(toks)
    h = "%08x" % (zlib.crc32(full.encode("utf-8", "replace"))
                  & 0xffffffff)
    if len(full) > 56:
        disp = "%s..(%d args)#%s" % (full[:40], len(toks), h)
    else:
        disp = full or "()"
    return disp, h


def _mem_field(mem, name):
    """Read one memory_analysis field from either the XLA stats object
    (attributes) or a faked dict (tests)."""
    if mem is None:
        return None
    if isinstance(mem, dict):
        v = mem.get(name)
    else:
        v = getattr(mem, name, None)
    return int(v) if v is not None else None


def predicted_seconds(flops, bytes_accessed,
                      spec: DeviceSpec) -> Optional[float]:
    """THE roofline execution-time bound: max(flops/peak, bytes/bw) —
    one definition shared by the live ledger and bench's analytic rows
    so the two can never disagree. None when neither term is known."""
    bounds = []
    if flops is not None and spec.peak_flops > 0:
        bounds.append(float(flops) / spec.peak_flops)
    if bytes_accessed is not None and spec.hbm_bw > 0:
        bounds.append(float(bytes_accessed) / spec.hbm_bw)
    return max(bounds) if bounds else None


def footprint_bytes(mem) -> Optional[int]:
    """THE per-device program footprint: XLA argument+temp+output bytes
    (the total tools/memory_report.py prints) — shared definition, same
    reason as ``predicted_seconds``. Accepts the XLA stats object or a
    faked dict; None when no field is present."""
    parts = [_mem_field(mem, k) for k in
             ("argument_size_in_bytes", "temp_size_in_bytes",
              "output_size_in_bytes")]
    if all(v is None for v in parts):
        return None
    return sum(v or 0 for v in parts)


# bound on the compile flight ring (per-compile records with trigger
# attribution) — sized like telemetry.FlightRecorder's request ring:
# the full grid of a serving run fits with room for reload rebuilds
COMPILE_RING_CAP = 256


class Ledger:
    """The program performance ledger: cards keyed by (program name,
    shapes hash), completed asynchronously by the carder thread, joined
    against measured latency histograms at snapshot time. One per
    process (the module singleton); tests build isolated instances
    against private telemetry registries."""

    def __init__(self, registry=None, spec: Optional[DeviceSpec] = None,
                 compile_ring_cap: int = COMPILE_RING_CAP):
        # ranked between telemetry.flight and telemetry.registry: card
        # completion emits the program_card event under this lock (the
        # SLOTracker precedent — completion order must match log order)
        self._cond = lockrank.condition("perf.ledger")
        self._registry = registry
        self.spec = spec
        self.enabled = False
        self._cards: Dict[Tuple[str, str], dict] = {}
        self._order: List[Tuple[str, str]] = []
        self._jobs: deque = deque()
        self._busy = 0
        self._thread: Optional[threading.Thread] = None
        # the compile flight recorder (doc/performance.md "Compile
        # cliff"): a bounded ring of per-compile records with trigger
        # attribution (which request / dispatcher window paid the
        # cliff), plus the warm-grid readiness account — the expected
        # program grid vs the keys compiled so far. One lock guards
        # both (rank perf.compiles); the program_compile JSONL event is
        # emitted OUTSIDE it (the IO-outside-the-lock rule).
        self._clock = lockrank.lock("perf.compiles")
        self._ring: deque = deque(maxlen=max(1, int(compile_ring_cap)))
        self._compile_seq = 0
        self._expected: Dict[str, str] = {}   # key str -> bucket label
        self._warm: set = set()               # key strs compiled so far
        # set_decode_kv: a callable returning the serving frontend's
        # live decode KV-cache bytes — the decode cache is persistent
        # device state BETWEEN program executions, so the HBM headroom
        # account must charge it next to the peak program footprint
        self._decode_kv_fn = None

    def _reg(self):
        return self._registry if self._registry is not None \
            else telemetry._REG

    # -- lifecycle -----------------------------------------------------
    def enable(self, spec: Optional[DeviceSpec] = None) -> "Ledger":
        """Arm the ledger and hook the recompile detector. The spec
        stays UNRESOLVED unless given: enable() runs before the trainer
        selects a platform, and probing jax here would initialize the
        wrong backend (or hang on a down tunnel). It resolves lazily —
        via ``current_device_spec()`` — at first card completion /
        snapshot, when a jit provably already ran."""
        with self._cond:
            if spec is not None:
                self.spec = spec
            self.enabled = True
        self._reg().compile_hook = self.on_compile
        return self

    def disable(self, join_timeout: float = 20.0) -> None:
        """Unhook, drop queued jobs, and JOIN the carder thread
        (bounded): a daemon thread still inside a native XLA compile at
        interpreter teardown segfaults the process — the same crash
        class ProfilerCapture.shutdown() guards against."""
        reg = self._reg()
        if reg.compile_hook == self.on_compile:
            reg.compile_hook = None
        with self._cond:
            self.enabled = False
            self._jobs.clear()
            self._cond.notify_all()
            t = self._thread
        if t is not None and t.is_alive():
            t.join(join_timeout)

    def reset(self) -> None:
        with self._cond:
            self._cards.clear()
            del self._order[:]
            self._jobs.clear()
        with self._clock:
            self._ring.clear()
            self._warm.clear()
            # the expected grid survives: it is conf-derived wiring
            # (like the compile hook), not per-run measurement state

    # -- capture -------------------------------------------------------
    def on_compile(self, name: str, cause: str, seconds: float,
                   fn=None, args=(), kwargs=None, key=None) -> None:
        """The recompile detector's hook: called once per genuinely new
        (program, signature) compile with the jitted callable and the
        triggering call's arguments. Records compile wall time NOW;
        queues the cost/memory analysis for the carder thread (the
        memory tier pays a real second compile — never on this, the
        hot, thread). Never raises: a ledger bug must not kill a train
        step or a served request."""
        try:
            if not self.enabled:
                return
            disp, h = shapes_signature(args, kwargs)
            with self._cond:
                existing = self._cards.get((name, h))
                need = fn is not None and (existing is None
                                           or existing["status"] == "new")
            # abstractify OUTSIDE the lock (the work-outside-the-lock
            # rule the carder follows): shape/dtype/sharding metadata
            # survives donation, the buffers may not, and a big params
            # pytree walk must not block a /metrics scrape — and only
            # for a card that still needs analysis (a reload's
            # rebuild_after_clear re-compiles already-carded programs)
            structs = self._abstractify(args, kwargs) if need else None
            with self._cond:
                card = self._cards.get((name, h))
                if card is None:
                    card = self._new_card(name, h, disp, cause, key)
                    self._cards[(name, h)] = card
                    self._order.append((name, h))
                card["compiles"] += 1
                card["compile_s"] = round(card["compile_s"]
                                          + float(seconds), 6)
                if card["status"] == "new" and fn is not None:
                    if structs is not None:
                        card["status"] = "pending"
                        self._jobs.append((name, h, fn, structs[0],
                                           structs[1]))
                        self._cond.notify()
                        self._ensure_thread()
                    else:
                        card["status"] = "error"
                        card["error"] = "could not abstract call args"
            self._record_flight(name, cause, seconds, disp, h, key)
            reg = self._reg()
            reg.count("perf.compile_hooks")
        except Exception:
            reg = self._reg()
            reg.count("perf.capture_errors")

    def _record_flight(self, name, cause, seconds, disp, h, key) -> None:
        """One compile into the flight ring + the warm-grid account,
        with trigger attribution: the active trace context (a serving
        request paying the cliff at prefill) and/or the active compile
        window (the dispatcher's session-creation / batch-step bracket,
        a bench phase). Emits the transition-style ``program_compile``
        JSONL event OUTSIDE the ring lock."""
        reg = self._reg()
        tc = reg.current_trace()
        win = reg.current_compile_window()
        ks = str(key) if key is not None else None
        rec = {"name": name, "key": ks, "cause": cause,
               "shapes": disp, "sig": h,
               "seconds": round(float(seconds), 6),
               # the compile STARTED seconds ago (same convention as
               # the telemetry compile event's ts)
               "ts": round(reg._ts(time.perf_counter()) - seconds, 6),
               "trigger_request": tc.request_id if tc is not None
               else None,
               "trigger_context": win.label if win is not None else None}
        with self._clock:
            self._compile_seq += 1
            rec["seq"] = self._compile_seq
            self._ring.append(dict(rec))
            if ks is not None:
                self._warm.add(ks)
            expected = len(self._expected)
            warm = sum(1 for k in self._expected if k in self._warm)
        ev = {"ev": "program_compile"}
        ev.update(rec)
        if expected:
            # the readiness transition rides the event: the offline
            # report replays warm-up as a 0 -> 100 trajectory
            ev["warm_programs"] = warm
            ev["expected_programs"] = expected
            ev["ready_pct"] = round(100.0 * warm / expected, 2)
        reg.record(ev)

    def recent_compiles(self, n: Optional[int] = None) -> List[dict]:
        """Newest-first snapshot of the compile flight ring."""
        with self._clock:
            out = [dict(r) for r in self._ring]
        out.reverse()
        return out[:n] if n else out

    def set_expected_grid(self, entries) -> None:
        """Register the EXPECTED program grid (the warm-grid readiness
        denominator): an iterable of ``(key, bucket_label)`` pairs — or
        bare keys — where ``key`` is the trainer's jit-cache key for a
        program conf implies will compile (``Trainer.
        expected_decode_grid`` enumerates the serving grid). Replaces
        any previous grid; keys are matched by ``str()`` against the
        keys the recompile detector reports."""
        exp: Dict[str, str] = {}
        for e in entries or ():
            if isinstance(e, (tuple, list)) and len(e) == 2 \
                    and isinstance(e[1], str):
                exp[str(e[0])] = e[1]
            else:
                exp[str(e)] = ""
        with self._clock:
            self._expected = exp

    def readiness(self) -> dict:
        """The warm-grid account: expected vs warm program counts,
        headline ``ready_pct`` (None when no grid is registered —
        absence is the capability signal, like every federation field)
        and the per-bucket-label breakdown."""
        with self._clock:
            exp = dict(self._expected)
            warm_set = set(self._warm)
        buckets: Dict[str, dict] = {}
        warm = 0
        for k, label in sorted(exp.items()):
            st = buckets.setdefault(label or "all",
                                    {"expected": 0, "warm": 0})
            st["expected"] += 1
            if k in warm_set:
                st["warm"] += 1
                warm += 1
        for st in buckets.values():
            st["ready_pct"] = round(100.0 * st["warm"] / st["expected"],
                                    2)
        return {"expected": len(exp), "warm": warm,
                "ready_pct": round(100.0 * warm / len(exp), 2)
                if exp else None,
                "cold_keys": sorted(k for k in exp
                                    if k not in warm_set)[:16],
                "buckets": buckets}

    @staticmethod
    def _new_card(name, h, disp, cause, key) -> dict:
        return {"name": name, "shapes": disp, "sig": h,
                "key": str(key) if key is not None else None,
                "cause": cause, "compiles": 0, "compile_s": 0.0,
                "flops": None, "bytes_accessed": None,
                "arg_bytes": None, "temp_bytes": None, "out_bytes": None,
                "gen_code_bytes": None, "peak_bytes": None,
                "predicted_s": None, "status": "new", "error": None}

    @staticmethod
    def _abstractify(args, kwargs):
        """jax.ShapeDtypeStruct pytrees mirroring the call's arguments
        (shape + dtype + sharding — metadata that survives donated
        buffers being consumed). None on any surprise."""
        try:
            import jax

            def struct(a):
                shape = getattr(a, "shape", None)
                dtype = getattr(a, "dtype", None)
                if shape is None or dtype is None:
                    return a          # python scalar / None: pass through
                sharding = getattr(a, "sharding", None)
                try:
                    return jax.ShapeDtypeStruct(shape, dtype,
                                                sharding=sharding)
                except Exception:
                    return jax.ShapeDtypeStruct(shape, dtype)

            def walk(o):
                if isinstance(o, (list, tuple)):
                    return type(o)(walk(v) for v in o)
                if isinstance(o, dict):
                    return {k: walk(v) for k, v in o.items()}
                return struct(o)

            return walk(list(args)), walk(dict(kwargs or {}))
        except Exception:
            return None

    def _ensure_thread(self) -> None:
        # under the lock
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._carder, name="cxn-perf-carder", daemon=True)
            self._thread.start()

    def _carder(self) -> None:
        """Background card completion: one analysis job at a time, the
        lower/compile work OUTSIDE the lock (a compile in here must
        never block a scrape or the next on_compile)."""
        while True:
            with self._cond:
                while not self._jobs and self.enabled:
                    self._cond.wait(timeout=1.0)
                if not self._jobs:
                    if not self.enabled:
                        return
                    continue
                name, h, fn, sargs, skwargs = self._jobs.popleft()
                self._busy += 1
            cost = mem = None
            err = None
            try:
                cost, mem = self._capture(fn, sargs, skwargs)
            except Exception as e:
                err = "%s: %s" % (type(e).__name__, e)
            try:
                self.complete_card(name, h, cost=cost, mem=mem, error=err)
            finally:
                with self._cond:
                    self._busy -= 1
                    self._cond.notify_all()

    @staticmethod
    def _capture(fn, sargs, skwargs):
        """(cost_analysis dict, memory stats) of the program, from a
        re-lower (cheap: the trace cache is warm from the triggering
        call) + a second compile (the expensive half — why this runs on
        the carder thread)."""
        lowered = fn.lower(*sargs, **skwargs)
        cost = lowered.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        mem = lowered.compile().memory_analysis()
        return (dict(cost) if cost else {}), mem

    def complete_card(self, name: str, sig: str, cost=None, mem=None,
                      error: Optional[str] = None) -> Optional[dict]:
        """Fill a card's analysis fields (XLA dicts/objects or faked
        test dicts), compute the roofline prediction, and publish the
        ``program_card`` telemetry event. Public so jax-free tests (and
        the selftest) can exercise the math with faked analyses."""
        spec = self.spec or current_device_spec()
        with self._cond:
            card = self._cards.get((name, sig))
            if card is None:
                card = self._new_card(name, sig, sig, "unknown", None)
                self._cards[(name, sig)] = card
                self._order.append((name, sig))
            if error is not None:
                card["status"] = "error"
                card["error"] = error[:200]
            else:
                card["status"] = "ready"
                if cost:
                    f = cost.get("flops")
                    b = cost.get("bytes accessed")
                    card["flops"] = float(f) if f is not None else None
                    card["bytes_accessed"] = float(b) if b is not None \
                        else None
                card["arg_bytes"] = _mem_field(mem,
                                               "argument_size_in_bytes")
                card["temp_bytes"] = _mem_field(mem, "temp_size_in_bytes")
                card["out_bytes"] = _mem_field(mem, "output_size_in_bytes")
                card["gen_code_bytes"] = _mem_field(
                    mem, "generated_code_size_in_bytes")
                card["peak_bytes"] = footprint_bytes(mem)
                card["predicted_s"] = predicted_seconds(
                    card["flops"], card["bytes_accessed"], spec)
            # the spec's peaks ride the event so the offline report can
            # recompute MFU/eff joins without guessing the chip
            ev = {"ev": "program_card", "spec": spec.name,
                  "spec_peak_flops": spec.peak_flops,
                  "spec_hbm_bw": spec.hbm_bw}
            ev.update({k: card[k] for k in (
                "name", "shapes", "sig", "key", "cause", "compiles",
                "compile_s", "flops", "bytes_accessed", "arg_bytes",
                "temp_bytes", "out_bytes", "peak_bytes", "predicted_s",
                "status", "error")})
            reg = self._reg()
            reg.count("perf.cards")
            reg.record(ev)
            return dict(card)

    def drain(self, timeout: float = 10.0) -> bool:
        """Wait for queued analysis jobs to finish (bench rows and the
        end-of-run flush want complete cards). True when idle."""
        deadline = time.monotonic() + timeout
        with self._cond:
            while self._jobs or self._busy:
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._cond.wait(timeout=min(0.2, left))
        return True

    # -- views ---------------------------------------------------------
    def cards(self) -> List[dict]:
        """Insertion-ordered card copies."""
        with self._cond:
            return [dict(self._cards[k]) for k in self._order]

    def card(self, name: str) -> Optional[dict]:
        """The most recent card for a program name (any signature)."""
        with self._cond:
            for k in reversed(self._order):
                if k[0] == name:
                    return dict(self._cards[k])
        return None

    def snapshot(self) -> dict:
        """Everything the surfaces render: the spec, the cards joined
        against their measured latency histograms (mfu_pct /
        roofline_eff_pct / measured p50+p99), and the HBM account
        (peak = max card footprint; headroom vs spec capacity)."""
        spec = self.spec or current_device_spec()
        cards = self.cards()
        needed = {MEASURED_SERIES.get(c["name"]) for c in cards}
        needed.discard(None)
        reg = self._reg()
        stats: Dict[str, dict] = {}
        if needed:
            with reg._lock:
                for s in needed:
                    hist = reg.hists.get(s)
                    if hist is not None and hist.n:
                        stats[s] = hist.stats()
        by_name: Dict[str, int] = {}
        for c in cards:
            by_name[c["name"]] = by_name.get(c["name"], 0) + 1
        peak = None
        for c in cards:
            series = MEASURED_SERIES.get(c["name"])
            st = stats.get(series) if series else None
            c["measured_series"] = series
            # the measured histogram is per program NAME: with several
            # live signatures (decode buckets, train-shape variants)
            # each card's mfu/eff joins a p50 that AGGREGATES its
            # siblings — flagged so /programz readers and the report
            # interpret multi-signature joins accordingly
            c["series_shared_by"] = by_name[c["name"]]
            c["measured_n"] = st["count"] if st else 0
            c["measured_p50_ms"] = st["p50_ms"] if st else None
            c["measured_p99_ms"] = st["p99_ms"] if st else None
            c["mfu_pct"] = c["roofline_eff_pct"] = None
            if st and st["p50_ms"]:
                p50_s = st["p50_ms"] / 1e3
                if c["flops"] is not None and spec.peak_flops > 0:
                    c["mfu_pct"] = round(
                        100.0 * c["flops"] / (p50_s * spec.peak_flops), 2)
                if c["predicted_s"] is not None:
                    c["roofline_eff_pct"] = round(
                        100.0 * c["predicted_s"] / p50_s, 2)
            if c["peak_bytes"] is not None:
                peak = max(peak or 0, c["peak_bytes"])
        decode_kv = None
        fn = self._decode_kv_fn
        if fn is not None:
            try:
                decode_kv = int(fn())
            except Exception:
                decode_kv = None    # the account never kills a scrape
        hbm = {"capacity_bytes": spec.hbm_capacity,
               "peak_bytes": peak,
               # the live decode KV cache is a first-class HBM
               # consumer: persistent device state held BETWEEN
               # program executions, so headroom charges it on top of
               # the peak program footprint. Under the PAGED layout
               # decode_kv_bytes is the block pool's REAL array nbytes
               # (block-exact, pinned by test_perf). The HEADROOM row
               # stays conservative in BOTH layouts: the decode-step
               # card's argument bytes already include the cache the
               # decode_kv row charges again — one session's worth
               # dense, up to the whole pool paged (the step program
               # donates the pool arrays). It can only understate
               # free HBM, never overstate it, and the ledger cannot
               # tell which card bytes are the pool's to exclude them.
               "decode_kv_bytes": decode_kv,
               "headroom_bytes":
               (spec.hbm_capacity - peak - (decode_kv or 0))
               if peak is not None else None}
        return {"spec": spec.to_dict(), "enabled": self.enabled,
                "cards": cards, "hbm": hbm,
                # the warm-grid readiness account (ready_pct None until
                # an expected grid is registered) — statusd exports it
                # as cxxnet_ready_programs_pct (+ per-bucket rows)
                "readiness": self.readiness()}

    def decode_pool_cap_bytes(self,
                              frac: float = 0.5) -> Optional[int]:
        """Byte budget for the PAGED decode KV pool (ROADMAP item 2:
        "sized from the live HBM account"): ``frac`` of what the spec's
        HBM capacity leaves after the peak program footprint measured
        so far. The decode-KV hook is deliberately NOT charged here —
        the pool REPLACES the dense caches that hook reports, so
        charging them would double-count the very bytes being sized.
        None when the ledger is off (the pool falls back to
        dense-equivalent sizing). Conservative by construction: cards
        land as programs compile, so a pool sized at serving start sees
        the train/prefill peak, and ``Trainer.decode_kv_pool`` still
        floors the result at one max-length sequence."""
        if not self.enabled:
            return None
        spec = self.spec or current_device_spec()
        peak = 0
        with self._cond:
            for c in self._cards.values():
                pb = c.get("peak_bytes")
                if pb is not None:
                    peak = max(peak, int(pb))
        room = spec.hbm_capacity - peak
        if room <= 0:
            return None
        return int(max(0.0, min(1.0, float(frac))) * room)

    def set_decode_kv(self, fn) -> None:
        """Register the decode KV-cache account hook (``fn() ->
        bytes``; None clears) — servd's batching frontend wires its
        ``decode_kv_bytes`` here so /programz, /statusz and the
        ``cxxnet_hbm_headroom_bytes`` gauge charge the live decode
        cache against HBM (what ROADMAP item 2's paged allocator will
        size against)."""
        self._decode_kv_fn = fn


class ProfilerCapture:
    """The /profilez guard: at most ONE jax.profiler trace capture at a
    time, each into a fresh numbered subdirectory of the run-scoped
    ``outdir`` (conf key ``profilez_dir``). ``start(secs)`` returns
    (ok, detail) immediately — the capture itself runs on a daemon
    thread so the HTTP handler never blocks for the capture window.
    ``trace_fn(secs, path)`` is injectable for jax-free tests; the
    default imports jax and brackets ``start_trace``/``stop_trace``."""

    MAX_SECS = 120.0

    def __init__(self, outdir: str, trace_fn=None):
        self.outdir = outdir
        self._trace_fn = trace_fn or self._jax_trace
        self._lock = lockrank.lock("perf.profilez")
        self._busy = False
        # shutdown() sets _stop to cut an in-flight capture short (the
        # default trace fn polls it between sleep slices) and LATCHES
        # _shutdown so a racing /profilez request cannot start a fresh
        # capture thread into interpreter teardown
        self._stop = threading.Event()
        self._shutdown = False
        self._thread: Optional[threading.Thread] = None
        self.captures = 0
        self.last_path: Optional[str] = None
        self.last_error: Optional[str] = None

    def _jax_trace(self, secs: float, path: str) -> None:
        import jax
        jax.profiler.start_trace(path)
        try:
            # sliced sleep so shutdown() can end the capture early (a
            # preemption must not wait out a 120s window)
            deadline = time.monotonic() + secs
            while time.monotonic() < deadline \
                    and not self._stop.is_set():
                time.sleep(min(0.2, max(0.0,
                                        deadline - time.monotonic())))
        finally:
            jax.profiler.stop_trace()

    def start(self, secs: float) -> Tuple[bool, str]:
        try:
            secs = float(secs)
        except (TypeError, ValueError):
            return False, "secs must be a number"
        if not (0 < secs <= self.MAX_SECS):
            return False, ("secs must be in (0, %g]" % self.MAX_SECS)
        with self._lock:
            if self._shutdown:
                return False, "profiler shut down (process exiting)"
            if self._busy:
                return False, ("capture already in progress (into %s); "
                               "one at a time" % (self.last_path or "?"))
            self._busy = True
            self.captures += 1
            path = os.path.join(self.outdir,
                                "capture_%03d" % self.captures)
            self.last_path = path
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, args=(secs, path),
                name="cxn-profilez", daemon=True)
            # started under the lock: shutdown() can never observe
            # _busy without also seeing a joinable thread
            self._thread.start()
        telemetry.count("perf.profilez_captures")
        telemetry.event({"ev": "profilez", "secs": secs, "path": path})
        return True, path

    def _run(self, secs: float, path: str) -> None:
        err = None
        try:
            os.makedirs(path, exist_ok=True)
            self._trace_fn(secs, path)
        except Exception as e:
            err = "%s: %s" % (type(e).__name__, e)
        with self._lock:
            self._busy = False
            self.last_error = err
        if err:
            # the HTTP 200 went out before the capture ran: make the
            # failure visible — counted, logged, and echoed by the
            # NEXT /profilez response (statusd reads last_error)
            telemetry.count("perf.profilez_errors")
            telemetry.event({"ev": "profilez_error", "path": path,
                             "error": err[:200]})

    def busy(self) -> bool:
        with self._lock:
            return self._busy

    def shutdown(self, timeout: float = 20.0) -> bool:
        """Cut short any in-flight capture and JOIN its thread. MUST run
        before process teardown (learn_task's exit path does): a daemon
        capture thread still inside native profiler code — or the
        first capture's ~10s lazy profiler import — while the
        interpreter exits SEGFAULTS the process (observed rc -11),
        which would turn servd's clean SIGTERM drain into a crash.
        True when the capture finished within the timeout. Latches: a
        /profilez request racing the drain is refused from here on."""
        with self._lock:
            # latch AND set the stop flag under the lock: start() holds
            # it across its _stop.clear() + thread launch, so a racing
            # start either completes first (its thread then sees the
            # flag) or observes the latch and refuses — it can never
            # clear the flag after this set
            self._shutdown = True
            self._stop.set()
            t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout)
        with self._lock:
            return not self._busy

    def wait(self, timeout: float = 30.0) -> bool:
        """Poll until the in-flight capture (if any) finishes — tests
        and the acceptance drive need a join point."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if not self.busy():
                return True
            time.sleep(0.02)
        return not self.busy()


# ----------------------------------------------------------------------
# module-level singleton surface (the learn-task / bench wiring)
_LEDGER = Ledger()


def ledger() -> Ledger:
    return _LEDGER


def enable(spec: Optional[DeviceSpec] = None) -> Ledger:
    return _LEDGER.enable(spec=spec)


def disable() -> None:
    _LEDGER.disable()


def enabled() -> bool:
    return _LEDGER.enabled


def drain(timeout: float = 10.0) -> bool:
    return _LEDGER.drain(timeout)


def reset() -> None:
    _LEDGER.reset()


def set_decode_kv(fn) -> None:
    """Module-level form of ``Ledger.set_decode_kv`` (the learn-task
    serve wiring)."""
    _LEDGER.set_decode_kv(fn)


def decode_bound_tokens_per_s(ntok: int) -> Optional[float]:
    """The decode-step roofline bound for a served request: the scan
    program generates ntok-1 of the request's tokens (the first came
    from prefill), so the hardware-allowed rate is (ntok-1) / the
    program's predicted execution time. None until a decode-step card
    is ready — callers (servd's flight recorder) stay null-safe."""
    if ntok is None or ntok < 2 or not _LEDGER.enabled:
        return None
    card = _LEDGER.card("jit.decode_step")
    if card is None or not card.get("predicted_s"):
        return None
    return round((ntok - 1) / card["predicted_s"], 3)


# ----------------------------------------------------------------------
def selftest(verbose: bool = False) -> int:
    """Jax-free: card math from faked analyses, MFU/headroom joins
    against a private telemetry registry, /programz + /profilez over a
    real socket, the one-capture-at-a-time guard. ``make check`` gates
    on it. Runs under runtime lock-rank enforcement."""
    with lockrank.enforced():
        return _selftest_body(verbose)


def _selftest_body(verbose: bool = False) -> int:
    import json
    from urllib.request import urlopen
    from urllib.error import HTTPError

    reg = telemetry._Registry()
    reg.enable()
    spec = DeviceSpec("test", 100e12, 500e9, 8 * 2.0**30)
    lg = Ledger(registry=reg, spec=spec).enable()
    assert reg.compile_hook == lg.on_compile

    # a faked train-step compile + analysis: flops-bound program
    class _A:
        def __init__(self, shape, dtype="float32"):
            self.shape, self.dtype = shape, dtype
    disp, sig = shapes_signature(([_A((8, 128)), {"w": _A((128, 64))}],),
                                 None)
    lg.on_compile("jit.train_step", "new_signature", 1.25, fn=None,
                  args=([_A((8, 128)), {"w": _A((128, 64))}],), key="k1")
    card = lg.complete_card(
        "jit.train_step", sig,
        cost={"flops": 2.0e12, "bytes accessed": 1.0e9},
        mem={"argument_size_in_bytes": 3 * 2**30,
             "temp_size_in_bytes": 2**30,
             "output_size_in_bytes": 2**20})
    # flops-bound: 2e12/100e12 = 20ms > 1e9/500e9 = 2ms
    assert abs(card["predicted_s"] - 0.02) < 1e-9, card
    assert card["peak_bytes"] == 3 * 2**30 + 2**30 + 2**20
    assert card["status"] == "ready" and card["compile_s"] == 1.25
    # the JSONL event landed
    evs = [e for e in reg.events() if e.get("ev") == "program_card"]
    assert evs and evs[-1]["flops"] == 2.0e12

    # measured join: feed the train.step histogram at ~40ms -> MFU 50%
    for _ in range(10):
        reg.hist("train.step", 0.040)
    snap = lg.snapshot()
    c = [c for c in snap["cards"] if c["name"] == "jit.train_step"][0]
    assert c["measured_n"] == 10
    assert c["mfu_pct"] is not None and 35.0 < c["mfu_pct"] < 65.0, c
    assert c["roofline_eff_pct"] is not None \
        and 35.0 < c["roofline_eff_pct"] < 65.0
    assert snap["hbm"]["peak_bytes"] == card["peak_bytes"]
    assert snap["hbm"]["headroom_bytes"] == \
        spec.hbm_capacity - card["peak_bytes"]

    # an error completion keeps the card visible, fields null
    lg.on_compile("jit.predict", "new_signature", 0.2, fn=None,
                  args=(_A((4, 4)),))
    _, sig2 = shapes_signature((_A((4, 4)),), None)
    bad = lg.complete_card("jit.predict", sig2, error="boom")
    assert bad["status"] == "error" and bad["flops"] is None

    # decode bound: needs a ready decode-step card
    assert decode_bound_tokens_per_s(16) is None     # module ledger off
    _, sig3 = shapes_signature((_A((1, 8)),), None)
    lg.on_compile("jit.decode_step", "new_signature", 0.5, fn=None,
                  args=(_A((1, 8)),))
    lg.complete_card("jit.decode_step", sig3,
                     cost={"flops": 1.0e9, "bytes accessed": 5.0e8})
    cardd = lg.card("jit.decode_step")
    assert cardd["predicted_s"] == 5.0e8 / 500e9

    # compile flight ring: per-compile records with trigger
    # attribution (a trace context = the request whose prefill
    # compiled in-band; a compile window = the dispatcher's bracket
    # around batch-wide work) + the warm-grid readiness account
    lg.set_expected_grid([(("sess_step", 2, 0.0, 0), "2"),
                          (("sess_admit", 2), "2"),
                          (("sess_prefill", 8, 0.0, 0), "prefill")])
    rd = lg.readiness()
    assert rd["expected"] == 3 and rd["warm"] == 0 \
        and rd["ready_pct"] == 0.0, rd
    # mirror JitWatch's cache-growth sequence: record_compile (feeds
    # the innermost trace context / every open compile window) then
    # the supervised ledger hook (feeds the ring)
    with reg.trace_context("req-7") as tc7:
        reg.record_compile("jit.decode_prefill", "new_signature", 0.3,
                           key=("sess_prefill", 8, 0.0, 0))
        lg.on_compile("jit.decode_prefill", "new_signature", 0.3,
                      fn=None, args=(_A((1, 8)),),
                      key=("sess_prefill", 8, 0.0, 0))
    assert tc7.compiles and tc7.compiles[0]["dur"] == 0.3
    with reg.compile_window("session:b2") as cwin:
        reg.record_compile("jit.decode_step", "new_signature", 0.7,
                           key=("sess_step", 2, 0.0, 0))
        lg.on_compile("jit.decode_step", "new_signature", 0.7,
                      fn=None, args=(_A((2, 8)),),
                      key=("sess_step", 2, 0.0, 0))
    assert cwin.stall_s == 0.7, cwin.compiles
    assert reg.current_compile_window() is None
    recs = lg.recent_compiles(2)          # newest first
    assert recs[0]["key"] == str(("sess_step", 2, 0.0, 0))
    assert recs[0]["trigger_context"] == "session:b2" \
        and recs[0]["trigger_request"] is None, recs[0]
    assert recs[1]["trigger_request"] == "req-7" \
        and recs[1]["trigger_context"] is None, recs[1]
    assert recs[0]["seq"] > recs[1]["seq"] > 0
    assert recs[0]["seconds"] == 0.7 and recs[0]["shapes"]
    rd = lg.readiness()
    assert rd["warm"] == 2 and rd["ready_pct"] == 66.67, rd
    assert rd["buckets"]["2"] == {"expected": 2, "warm": 1,
                                  "ready_pct": 50.0}, rd
    assert rd["buckets"]["prefill"]["ready_pct"] == 100.0
    assert rd["cold_keys"] == [str(("sess_admit", 2))], rd
    cevs = [e for e in reg.events()
            if e.get("ev") == "program_compile"]
    assert cevs and cevs[-1]["trigger_context"] == "session:b2" \
        and cevs[-1]["warm_programs"] == 2 \
        and cevs[-1]["expected_programs"] == 3, cevs[-1]
    assert lg.snapshot()["readiness"]["ready_pct"] == 66.67

    # /programz + /metrics + /profilez over a real socket
    from . import statusd
    srv = statusd.StatusServer(0, host="127.0.0.1", registry=reg).start()
    srv.perf = lg
    started = []

    def fake_trace(secs, path):
        started.append(path)
        time.sleep(secs)

    import tempfile
    prof = ProfilerCapture(tempfile.mkdtemp(prefix="cxn-perf-selftest-"),
                           trace_fn=fake_trace)
    srv.profiler = prof
    try:
        base = "http://127.0.0.1:%d" % srv.port
        page = urlopen(base + "/programz", timeout=5).read().decode()
        assert "jit.train_step" in page and "MFU" in page
        doc = json.loads(urlopen(base + "/programz?json=1",
                                 timeout=5).read())
        assert doc["hbm"]["peak_bytes"] == card["peak_bytes"]
        assert any(c["name"] == "jit.train_step" for c in doc["cards"])
        m = urlopen(base + "/metrics", timeout=5).read().decode()
        for line in m.splitlines():
            if line and not line.startswith("#"):
                assert statusd.PROM_LINE_RE.match(line), line
        assert 'cxxnet_program_mfu_pct{process="0",program=' in m
        assert "cxxnet_hbm_peak_bytes" in m
        assert "cxxnet_hbm_headroom_bytes" in m
        assert "cxxnet_ready_programs_pct" in m
        assert 'cxxnet_ready_programs_bucket_pct{process="0"' \
               ',bucket="2"} 50.0' in m
        # /compilez: the flight ring + readiness render, json contract
        page = urlopen(base + "/compilez", timeout=5).read().decode()
        assert "compile flight recorder" in page \
            and "session:b2" in page and "66.7% ready" in page, page
        doc = json.loads(urlopen(base + "/compilez?json=1&n=2",
                                 timeout=5).read())
        assert doc["shown"] == 2 and doc["total"] >= 4
        assert doc["readiness"]["ready_pct"] == 66.67
        assert doc["compiles"][0]["trigger_context"] == "session:b2"
        try:
            urlopen(base + "/compilez?n=nope", timeout=5)
            raise AssertionError("bad n should 400")
        except HTTPError as e:
            assert e.code == 400
        # profilez: capture starts, a concurrent second one is refused
        r = urlopen(base + "/profilez?secs=0.5", timeout=5)
        assert r.status == 200 and b"capture_001" in r.read()
        try:
            urlopen(base + "/profilez?secs=0.5", timeout=5)
            raise AssertionError("concurrent capture should 409")
        except HTTPError as e:
            assert e.code == 409
        prof.wait(5.0)
        assert started and started[0].endswith("capture_001")
        ok, detail = prof.start(0.01)      # guard released after finish
        assert ok, detail
        prof.wait(5.0)
        try:
            urlopen(base + "/profilez?secs=nope", timeout=5)
            raise AssertionError("bad secs should 400")
        except HTTPError as e:
            assert e.code == 400
        srv.profiler = None
        try:
            urlopen(base + "/profilez?secs=1", timeout=5)
            raise AssertionError("no profiler registered should 404")
        except HTTPError as e:
            assert e.code == 404
        srv.perf = None
        try:
            urlopen(base + "/compilez", timeout=5)
            raise AssertionError("no ledger registered should 404")
        except HTTPError as e:
            assert e.code == 404
    finally:
        srv.stop()
        lg.disable()
        reg.disable()
    if verbose:
        print("perf selftest: card math, MFU/headroom joins, compile "
              "ring + readiness, /programz, /compilez, /metrics "
              "program series, /profilez guard ok")
    return 0


if __name__ == "__main__":
    import sys
    if "--selftest" in sys.argv[1:]:
        sys.exit(selftest(verbose=True))
    print(__doc__)
    sys.exit(1)
