"""Foundation utilities: config parsing, metrics, binary serialization.

TPU-native counterpart of the reference's src/utils/ module
(config.h, metric.h, io.h). The device-side pieces of src/utils
(thread.h, thread_buffer.h) map to the io prefetcher in cxxnet_tpu.io.
"""

from .config import ConfigIterator, parse_config_string, parse_config_file  # noqa: F401
from .metric import MetricSet, create_metric  # noqa: F401
from . import serializer  # noqa: F401
