"""Foundation utilities: config parsing, metrics, binary serialization.

TPU-native counterpart of the reference's src/utils/ module
(config.h, metric.h, io.h). The device-side pieces of src/utils
(thread.h, thread_buffer.h) map to the io prefetcher in cxxnet_tpu.io.
"""

from .config import ConfigIterator, parse_config_string, parse_config_file  # noqa: F401
from .metric import MetricSet, create_metric  # noqa: F401
from . import serializer  # noqa: F401
from . import telemetry  # noqa: F401


def enable_compile_cache(path=None):
    """Point jax at a persistent compilation cache so repeated bench/
    sweep/quality runs skip the 20-40s first-compile of each train step
    (a big deal through a remote-compile tunnel). Safe no-op when the
    backend does not support caching. Opt-in: the CLI tools call this;
    library users call it themselves or set CXXNET_COMPILE_CACHE."""
    import os
    import jax
    d = path or os.environ.get(
        "CXXNET_COMPILE_CACHE",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))), ".jax_cache"))
    try:
        jax.config.update("jax_compilation_cache_dir", d)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 5.0)
    except Exception:
        pass
    return d
