"""Request slowdown autopsy + fleet incident timeline (pure functions).

The fleet records everything — phase-split flight records (servd), batch
iteration rings, the compile flight ring (perf), KV-pressure and convoy
transition events, router attempt lists — but answering "why was request
X slow?" still meant joining five endpoints by hand. This module is the
join, written once as a DETERMINISTIC classifier over the records
themselves:

* ``classify_record(rec)`` — one replica flight record (the shape
  ``servd._observe_request`` builds) -> an **autopsy**: the request's
  wall time decomposed into named causes, seconds attributed to each,
  and exactly one *primary* verdict. The decomposition is a waterfall
  that tiles ``wall_s`` by construction:

    - the queue pool (``queue_wait`` + ``dispatch`` phases) is claimed
      first by ``convoy_victim`` (overlap with a decode-convoy episode,
      stamped by servd as ``convoy_overlap_s``), then by ``kv_defer``
      (the request was bounced by KV exhaustion at least once —
      ``kv_defers`` > 0), and the remainder is honest ``queue_wait``;
    - the work pool (``prefill`` + ``decode`` phases) is claimed first
      by ``compile_stall`` (the PR 16 per-request attribution,
      ``compile_stall_s``), then by ``eviction_storm`` (overlap with a
      latched KV-pressure episode, ``kv_pressure_overlap_s``), and the
      remainder — plus the wall-vs-phase residual — is
      ``decode_baseline``: the time the model legitimately took.

* ``classify_route(rec)`` — one ROUTER flight record (attempt list) ->
  the router-side autopsy: time before the winning attempt launched is
  ``hedge_replay`` when failover machinery caused it (a retry, replay
  or hedge lane won) and router ``queue_wait`` otherwise; the winning
  attempt's latency is ``decode_baseline`` until a replica hop record
  refines it.

* ``stitch_route(rec, hops)`` — the cross-process join (the ``/why``
  router path, exactly the ``/trace`` stitch shape): the winning
  attempt's latency lane is replaced by the replica's own autopsy plus
  ``slow_replica`` — the part of the router-observed latency the
  replica cannot account for (network + a replica slower than its own
  books admit).

* ``incidents(events, ...)`` — the fleet incident timeline behind
  ``/eventz``: every transition-only event stream merged into one
  wall-clock-aligned list of begin/end/point rows, each begin row
  carrying the requests whose autopsies cite its cause (a burn episode
  links to the convoy that caused it).

Everything here is a pure function of dicts — jax-free, IO-free,
lock-free — so servd/routerd/statusd stamp and render, the offline
``tools/telemetry_report.py`` re-derives, and the unit suite
(tests/test_autopsy.py) drives synthetic records through every cause
class. ``python -m cxxnet_tpu.utils.autopsy --selftest`` is the
embedded smoke check.
"""

from __future__ import annotations

import sys
from typing import Dict, List, Optional, Tuple

__all__ = ["CAUSES", "classify_record", "classify_route",
           "stitch_route", "TRANSITION_EVENTS", "POINT_EVENTS",
           "INCIDENT_CAUSES", "incidents", "selftest"]

# The cause taxonomy (doc/observability.md "Request autopsy & incident
# timeline"). Order is the primary-verdict tie-break: a named cause
# beats decode_baseline at equal seconds, and earlier names win ties —
# deterministic, so the same record always gets the same verdict.
CAUSES = ("queue_wait", "compile_stall", "convoy_victim", "kv_defer",
          "eviction_storm", "hedge_replay", "slow_replica",
          "decode_baseline")


def _f(v) -> float:
    try:
        return max(0.0, float(v))
    except (TypeError, ValueError):
        return 0.0


def _finish(causes: Dict[str, float], wall: float) -> dict:
    primary = CAUSES[0]
    best = causes.get(primary, 0.0)
    for c in CAUSES:
        if causes.get(c, 0.0) > best:
            primary, best = c, causes[c]
    return {"primary": primary,
            "causes": {c: round(causes.get(c, 0.0), 6) for c in CAUSES},
            "wall_s": round(wall, 6)}


def classify_record(rec: dict) -> dict:
    """One replica flight record -> its autopsy. Deterministic, total:
    a record missing every optional input (a pre-autopsy record, a shed
    with zero phases) still classifies — everything unexplained lands
    in ``queue_wait``/``decode_baseline``, never in a named cause."""
    phases = rec.get("phases") or {}
    queue_pool = _f(phases.get("queue_wait")) + _f(phases.get("dispatch"))
    work_pool = _f(phases.get("prefill")) + _f(phases.get("decode"))
    wall = rec.get("wall_s")
    if wall is None:
        wall = rec.get("total_s")
    wall = _f(wall)
    causes = {c: 0.0 for c in CAUSES}
    # queue pool waterfall: convoy overlap first (the request waited
    # behind a pinned slot), then KV-defer (it was bounced back to the
    # queue head by pool exhaustion), remainder is plain queue_wait
    convoy = min(queue_pool, _f(rec.get("convoy_overlap_s")))
    causes["convoy_victim"] = convoy
    queue_pool -= convoy
    if int(rec.get("kv_defers") or 0) > 0:
        causes["kv_defer"] = queue_pool
    else:
        causes["queue_wait"] = queue_pool
    # work pool waterfall: compile stall (the PR 16 per-request
    # attribution — exactly 0.0 on a warm bucket), then eviction-storm
    # overlap, remainder plus the wall-vs-phases residual is baseline
    stall = min(work_pool, _f(rec.get("compile_stall_s")))
    causes["compile_stall"] = stall
    work_pool -= stall
    storm = min(work_pool, _f(rec.get("kv_pressure_overlap_s")))
    causes["eviction_storm"] = storm
    phase_sum = (_f(phases.get("queue_wait")) + _f(phases.get("dispatch"))
                 + _f(phases.get("prefill")) + _f(phases.get("decode")))
    causes["decode_baseline"] = (work_pool - storm
                                 + max(0.0, wall - phase_sum))
    return _finish(causes, max(wall, phase_sum))


def classify_route(rec: dict) -> dict:
    """One ROUTER flight record (``routerd._record_request`` shape) ->
    the router-side autopsy over ``total_s``. The winning attempt is
    the last one (the response the client got); everything before its
    launch is ``hedge_replay`` when the failover machinery caused the
    delay (more than one attempt, or the winner is a replay/hedge
    lane) and router ``queue_wait`` otherwise; the winner's latency is
    ``decode_baseline`` until ``stitch_route`` refines it with the
    replica's own books."""
    total = _f(rec.get("total_s"))
    atts = rec.get("attempts") or []
    causes = {c: 0.0 for c in CAUSES}
    if not atts:
        # door shed / proto error / router-side deadline: the router
        # alone produced the answer
        causes["queue_wait"] = total
        return _finish(causes, total)
    win = atts[-1]
    t_off = min(total, _f(win.get("t_off_s")))
    lat = min(total - t_off, _f(win.get("latency_s")))
    failover = len(atts) > 1 or win.get("cls") in ("replay", "hedge")
    causes["hedge_replay" if failover else "queue_wait"] += t_off
    causes["decode_baseline"] = lat
    causes["queue_wait"] += total - t_off - lat
    return _finish(causes, total)


def stitch_route(rec: dict, hops) -> dict:
    """The cross-process autopsy (the ``/why`` router path): ``hops``
    is ``[(replica_name, replica_flight_record), ...]`` exactly like
    the ``/trace`` stitch. The winning attempt's latency lane is
    replaced by the replica's own cause decomposition plus
    ``slow_replica`` — the slice of router-observed latency the
    replica's books cannot account for (network, connect, or a replica
    slower than it admits). The result still tiles the router's
    ``total_s``. Returns the full ``/why`` payload: merged autopsy
    plus the router-lane and per-hop breakdowns."""
    base = rec.get("autopsy") or classify_route(rec)
    causes = {c: 0.0 for c in CAUSES}
    causes.update(base.get("causes") or {})
    hop_auts: Dict[str, dict] = {}
    atts = rec.get("attempts") or []
    win_name = atts[-1].get("replica") if atts else None
    for name, rrec in hops or []:
        if isinstance(rrec, dict):
            hop_auts[str(name)] = rrec.get("autopsy") \
                or classify_record(rrec)
    win_aut = hop_auts.get(win_name) if win_name else None
    if win_aut is not None:
        lane = causes.get("decode_baseline", 0.0)
        hop_causes = win_aut.get("causes") or {}
        hop_sum = sum(_f(v) for v in hop_causes.values())
        # clock-skew guard: the replica's books may claim (slightly)
        # more than the router observed — scale them down to fit the
        # lane so the stitched causes still tile total_s exactly
        scale = 1.0 if hop_sum <= lane or hop_sum <= 0.0 \
            else lane / hop_sum
        causes["decode_baseline"] = 0.0
        claimed = 0.0
        for c in CAUSES:
            add = _f(hop_causes.get(c)) * scale
            causes[c] += add
            claimed += add
        causes["slow_replica"] += max(0.0, lane - claimed)
    merged = _finish(causes, base.get("wall_s", 0.0))
    return {"id": rec.get("id"), "outcome": rec.get("outcome"),
            "autopsy": merged, "router": base, "hops": hop_auts}


# ----------------------------------------------------------------------
# fleet incident timeline (/eventz + telemetry_report --incidents)

# transition-only event kinds -> the latch field whose truthiness says
# begin (latched) vs end (cleared). "state" fields accept both the
# numeric (slo_burn: 0/1) and the named (serve_breaker: open/closed)
# convention.
TRANSITION_EVENTS = {
    "decode_convoy": "convoy",
    "kv_pressure": "pressure",
    "fleet_outlier": "outlier",
    "slo_burn": "state",
    "serve_breaker": "state",
    "books_broken": "broken",
}
# point kinds: one row each, no begin/end pairing
POINT_EVENTS = ("fleet_scale", "serve_batch_rescue", "serve_drain",
                "serve_reload", "route_reload", "route_drain",
                "route_replica", "route_discarded_late",
                "route_hedge_mismatch")
# incident kind -> the autopsy causes that cite it (the causal links:
# a begin row carries the requests whose autopsies blame its episode)
INCIDENT_CAUSES = {
    "decode_convoy": ("convoy_victim",),
    "kv_pressure": ("kv_defer", "eviction_storm"),
    "slo_burn": ("queue_wait", "compile_stall", "convoy_victim",
                 "kv_defer", "eviction_storm", "hedge_replay",
                 "slow_replica"),
}


def _latched(kind: str, ev: dict) -> bool:
    field = TRANSITION_EVENTS[kind]
    v = ev.get(field)
    if isinstance(v, str):
        return v.lower() in ("open", "burning", "1", "true")
    return bool(v)


def _incident_key(ev: dict) -> tuple:
    return (ev.get("ev"), ev.get("replica"), ev.get("law"),
            ev.get("slot"), ev.get("process"))


def incidents(events, t0_wall: float = 0.0, records=None,
              n: Optional[int] = None, process=None) -> List[dict]:
    """Transition/point events -> the incident timeline, oldest first.
    ``events`` carry registry-relative ``ts`` seconds; ``t0_wall`` is
    the registry's wall epoch, so rows align across processes on
    ``t_wall``. ``records`` (flight records WITH autopsies, any order)
    feeds the causal links: a begin row lists up to 8 request ids whose
    autopsy cites one of the incident's causes and whose flight window
    overlaps the episode. ``n`` bounds the output to the NEWEST rows.
    Rows: ``{"kind", "state" (begin|end|point), "ts", "t_wall",
    "requests"?, "process"?, "event"}``."""
    rows: List[dict] = []
    for ev in events or []:
        kind = ev.get("ev")
        if kind in TRANSITION_EVENTS:
            state = "begin" if _latched(kind, ev) else "end"
        elif kind in POINT_EVENTS:
            state = "point"
        else:
            continue
        ts = _f(ev.get("ts"))
        row = {"kind": kind, "state": state, "ts": round(ts, 6),
               "t_wall": round(t0_wall + ts, 6), "event": dict(ev)}
        if process is not None:
            row["process"] = process
        rows.append(row)
    rows.sort(key=lambda r: r["t_wall"])
    # pair begins with ends (same kind+subject) to bound each episode's
    # window, then attach the requests whose autopsies cite it
    if records:
        open_at: Dict[tuple, dict] = {}
        windows: List[Tuple[dict, float, float]] = []
        for row in rows:
            if row["state"] == "begin":
                open_at[_incident_key(row["event"])] = row
            elif row["state"] == "end":
                beg = open_at.pop(_incident_key(row["event"]), None)
                if beg is not None:
                    windows.append((beg, beg["t_wall"], row["t_wall"]))
        for beg in open_at.values():           # still-latched episodes
            windows.append((beg, beg["t_wall"], float("inf")))
        for beg, w0, w1 in windows:
            wanted = INCIDENT_CAUSES.get(beg["kind"])
            if not wanted:
                continue
            hits = []
            for rec in records:
                aut = rec.get("autopsy")
                if not aut:
                    continue
                c = aut.get("causes") or {}
                if not any(_f(c.get(w)) > 0 for w in wanted):
                    continue
                r0 = rec.get("t_wall")
                if r0 is None:
                    continue
                r1 = float(r0) + _f(rec.get("wall_s")
                                    if rec.get("wall_s") is not None
                                    else rec.get("total_s"))
                if r1 >= w0 and float(r0) <= w1:
                    hits.append(rec.get("id"))
            if hits:
                beg["requests"] = hits[:8]
    if n is not None and n >= 0:
        rows = rows[-n:] if n else []
    return rows


# ----------------------------------------------------------------------
def selftest(verbose: bool = False) -> int:
    # a plain served record: everything is decode_baseline
    rec = {"id": "a", "outcome": "served", "wall_s": 1.0,
           "total_s": 1.0,
           "phases": {"queue_wait": 0.1, "dispatch": 0.0,
                      "prefill": 0.2, "decode": 0.7}}
    a = classify_record(rec)
    assert a["primary"] == "decode_baseline", a
    assert abs(sum(a["causes"].values()) - 1.0) < 1e-6, a
    # compile stall claims the work pool
    a = classify_record(dict(rec, compile_stall_s=0.8))
    assert a["primary"] == "compile_stall", a
    assert abs(sum(a["causes"].values()) - 1.0) < 1e-6
    # kv defer claims the queue pool
    a = classify_record(dict(rec, kv_defers=2,
                             phases={"queue_wait": 0.8, "dispatch": 0.0,
                                     "prefill": 0.1, "decode": 0.1}))
    assert a["primary"] == "kv_defer", a
    # a record with NO optional inputs still classifies
    a = classify_record({"id": "bare"})
    assert a["primary"] == "queue_wait" and a["wall_s"] == 0.0
    # router record: single clean attempt
    rr = {"id": "r", "outcome": "served", "total_s": 0.5,
          "attempts": [{"replica": "x", "t_off_s": 0.01,
                        "latency_s": 0.48, "status": "ok"}]}
    ra = classify_route(rr)
    assert ra["primary"] == "decode_baseline"
    assert abs(sum(ra["causes"].values()) - 0.5) < 1e-6
    # failover: two attempts -> the pre-winner time is hedge_replay
    rr2 = {"id": "r2", "outcome": "served", "total_s": 1.0,
           "attempts": [{"replica": "x", "t_off_s": 0.0,
                         "latency_s": 0.4, "status": "lost"},
                        {"replica": "y", "t_off_s": 0.45,
                         "latency_s": 0.5, "status": "ok",
                         "cls": "replay"}]}
    ra2 = classify_route(rr2)
    assert ra2["causes"]["hedge_replay"] > 0.4, ra2
    # the stitch: replica books replace the latency lane; slow_replica
    # absorbs what the replica cannot account for
    hop = {"id": "r", "outcome": "served", "wall_s": 0.4,
           "total_s": 0.4,
           "phases": {"queue_wait": 0.0, "dispatch": 0.0,
                      "prefill": 0.1, "decode": 0.3}}
    sw = stitch_route(rr, [("x", hop)])
    m = sw["autopsy"]
    assert abs(sum(m["causes"].values()) - 0.5) < 1e-6, m
    assert abs(m["causes"]["slow_replica"] - 0.08) < 1e-6, m
    # incident timeline: begin/end pairing + causal request link
    evs = [{"ev": "decode_convoy", "convoy": 1, "ts": 1.0, "slot": 0},
           {"ev": "decode_convoy", "convoy": 0, "ts": 3.0, "slot": 0},
           {"ev": "fleet_scale", "action": "up", "ts": 2.0}]
    recs = [{"id": "v", "t_wall": 101.5, "wall_s": 1.0,
             "autopsy": {"primary": "convoy_victim",
                         "causes": {"convoy_victim": 0.9},
                         "wall_s": 1.0}}]
    rows = incidents(evs, t0_wall=100.0, records=recs)
    assert [r["kind"] for r in rows] == ["decode_convoy", "fleet_scale",
                                         "decode_convoy"]
    assert rows[0]["requests"] == ["v"], rows[0]
    if verbose:
        print("autopsy selftest: record/route/stitch/incident "
              "classification ok (%d causes)" % len(CAUSES))
    return 0


if __name__ == "__main__":
    if "--selftest" in sys.argv[1:]:
        sys.exit(selftest(verbose=True))
    print(__doc__)
    sys.exit(1)
