"""ctypes loader for the native runtime core (lib/libcxxnet_tpu_core.so).

The native library implements the host-side runtime the reference keeps in
C++ (config tokenizer, BinaryPage packing, a background-threaded page
reader — reference: src/utils/config.h, src/utils/io.h:254,
src/utils/thread_buffer.h). Build with `make` at the repo root. Everything
here degrades gracefully: when the .so is absent (or CXXNET_TPU_NATIVE=0),
callers use the pure-Python implementations instead.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import List, Optional, Tuple

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_LIB_PATH = os.path.join(_REPO_ROOT, "lib", "libcxxnet_tpu_core.so")

_lib = None
_load_attempted = False


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    lib.CXNCoreVersion.restype = ctypes.c_int64
    lib.CXNConfigParse.restype = ctypes.c_void_p
    lib.CXNConfigParse.argtypes = [ctypes.c_char_p,
                                   ctypes.POINTER(ctypes.c_char_p)]
    lib.CXNConfigCount.restype = ctypes.c_int64
    lib.CXNConfigCount.argtypes = [ctypes.c_void_p]
    lib.CXNConfigGet.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                 ctypes.POINTER(ctypes.c_char_p),
                                 ctypes.POINTER(ctypes.c_char_p)]
    lib.CXNConfigFree.argtypes = [ctypes.c_void_p]

    lib.CXNPageCreate.restype = ctypes.c_void_p
    lib.CXNPageCreate.argtypes = [ctypes.c_int64]
    lib.CXNPagePush.restype = ctypes.c_int
    lib.CXNPagePush.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                ctypes.c_int64]
    lib.CXNPageCount.restype = ctypes.c_int64
    lib.CXNPageCount.argtypes = [ctypes.c_void_p]
    lib.CXNPageClear.argtypes = [ctypes.c_void_p]
    lib.CXNPageSave.restype = ctypes.c_int
    lib.CXNPageSave.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                ctypes.c_int]
    lib.CXNPageFree.argtypes = [ctypes.c_void_p]

    lib.CXNPageReaderCreate.restype = ctypes.c_void_p
    lib.CXNPageReaderCreate.argtypes = [
        ctypes.POINTER(ctypes.c_char_p), ctypes.c_int64, ctypes.c_int64,
        ctypes.c_int64]
    lib.CXNPageReaderBeforeFirst.argtypes = [ctypes.c_void_p]
    lib.CXNPageReaderNext.restype = ctypes.c_int64
    lib.CXNPageReaderNext.argtypes = [ctypes.c_void_p,
                                      ctypes.POINTER(ctypes.c_void_p)]
    lib.CXNPageReaderFree.argtypes = [ctypes.c_void_p]

    lib.CXNJpegDims.restype = ctypes.c_int
    lib.CXNJpegDims.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                                ctypes.POINTER(ctypes.c_int64),
                                ctypes.POINTER(ctypes.c_int64),
                                ctypes.POINTER(ctypes.c_int64)]
    lib.CXNJpegDecodeF32.restype = ctypes.c_int
    lib.CXNJpegDecodeF32.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                                     ctypes.POINTER(ctypes.c_float),
                                     ctypes.c_int64, ctypes.c_int64]
    return lib


def load() -> Optional[ctypes.CDLL]:
    """Load (once) and return the native library, or None."""
    global _lib, _load_attempted
    if _load_attempted:
        return _lib
    _load_attempted = True
    if os.environ.get("CXXNET_TPU_NATIVE", "1") == "0":
        return None
    path = os.environ.get("CXXNET_TPU_NATIVE_LIB", _LIB_PATH)
    if not os.path.exists(path):
        return None
    try:
        _lib = _bind(ctypes.CDLL(path))
    except OSError:
        _lib = None
    return _lib


def build(quiet: bool = True) -> bool:
    """Compile the native library via `make` (used by tests/dev). True on
    success."""
    global _load_attempted
    try:
        subprocess.run(
            ["make", "lib/libcxxnet_tpu_core.so"], cwd=_REPO_ROOT,
            check=True,
            stdout=subprocess.DEVNULL if quiet else None,
            stderr=subprocess.DEVNULL if quiet else None)
    except (OSError, subprocess.CalledProcessError):
        return False
    _load_attempted = False  # allow re-load
    return load() is not None


def parse_config_string(text: str) -> Optional[List[Tuple[str, str]]]:
    """Native config parse; None if the library is unavailable.
    Raises ValueError on malformed config (same cases as the Python
    tokenizer in cxxnet_tpu.utils.config)."""
    lib = load()
    if lib is None:
        return None
    err = ctypes.c_char_p()
    h = lib.CXNConfigParse(text.encode("utf-8"), ctypes.byref(err))
    if not h:
        from .config import ConfigError
        raise ConfigError((err.value or b"parse error").decode())
    try:
        n = lib.CXNConfigCount(h)
        out = []
        name = ctypes.c_char_p()
        val = ctypes.c_char_p()
        for i in range(n):
            lib.CXNConfigGet(h, i, ctypes.byref(name), ctypes.byref(val))
            out.append((name.value.decode(), val.value.decode()))
        return out
    finally:
        lib.CXNConfigFree(h)


def decode_jpeg_chw(buf: bytes):
    """Decode JPEG bytes to a float32 (3, h, w) RGB array with the native
    decoder — the whole call (libjpeg decode + float CHW conversion) runs
    outside the GIL, so a Python thread pool of these parallelizes for
    real. Returns None if the library is unavailable or the stream is not a
    JPEG the native path can handle (caller falls back to cv2)."""
    import numpy as np
    lib = load()
    if lib is None:
        return None
    h = ctypes.c_int64()
    w = ctypes.c_int64()
    c = ctypes.c_int64()
    n = len(buf)
    if not lib.CXNJpegDims(buf, n, ctypes.byref(h), ctypes.byref(w),
                           ctypes.byref(c)):
        return None
    out = np.empty((3, h.value, w.value), np.float32)
    ok = lib.CXNJpegDecodeF32(
        buf, n, out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        h.value, w.value)
    if not ok:
        return None
    return out


class NativePageReader:
    """Iterates objects from a chain of BinaryPage .bin files with a C++
    read-ahead thread. Drop-in for the sequential Python page loop in
    ImagePageIterator."""

    def __init__(self, paths: List[str], page_ints: int, lookahead: int = 4):
        lib = load()
        if lib is None:
            raise RuntimeError("native library not available")
        self._lib = lib
        arr = (ctypes.c_char_p * len(paths))(
            *[p.encode("utf-8") for p in paths])
        self._h = lib.CXNPageReaderCreate(arr, len(paths), page_ints,
                                          lookahead)
        if not self._h:
            raise IOError("cannot open bin files: %s" % paths)

    def before_first(self) -> None:
        self._lib.CXNPageReaderBeforeFirst(self._h)

    def next_obj(self) -> Optional[bytes]:
        """Next object's bytes, or None at end of data."""
        out = ctypes.c_void_p()
        sz = self._lib.CXNPageReaderNext(self._h, ctypes.byref(out))
        if sz == -1:
            return None
        if sz < 0:
            raise IOError("native page reader: read/parse error")
        return ctypes.string_at(out, sz)

    def close(self) -> None:
        if self._h:
            self._lib.CXNPageReaderFree(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
