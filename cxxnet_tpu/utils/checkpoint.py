"""Preemption-tolerant checkpoint IO: atomic writes, integrity, recovery.

The reference's restart story is "scan models/%04d.model and reload"
(src/cxxnet_main.cpp:135-157) — written in place, no integrity check, no
tolerance for a task kill mid-write. On a preemptible fleet the checkpoint
path IS the fault-tolerance mechanism (TensorFlow makes user-level
checkpoint/restore the sole recovery primitive for exactly this reason,
arxiv 1605.08695 §4.2), so this module gives every model file:

* **durable atomic writes** — payload goes to ``<name>.tmp``, is fsync'd,
  and renamed over the final name; the directory entry is fsync'd too.
  A kill at ANY point leaves either the old file or the new file, never
  a torn one. Flaky-filesystem writes (NFS, GCS-fuse) retry with
  exponential backoff (``retry_io``).
* **integrity framing** — new files are ``CXCKHDR1 + payload + footer``
  where the 20-byte footer is ``<IQ8s``: CRC32(payload), payload length,
  magic ``CXCKPT01``. The header magic distinguishes a *truncated new
  file* (header present, footer gone -> corrupt) from a *legacy seed
  checkpoint* (no framing at all -> loaded trusted, flagged by fsck).
  The first payload byte of a legacy file is a small int32 net_type, so
  the 8-byte header can never be confused with legacy content.
* **recovery helpers** — gap-tolerant directory scans, quarantine of
  corrupt files to ``<name>.corrupt`` (telemetry event ``ckpt_corrupt``),
  stale-tmp GC, and a ``keep_last``/``keep_every`` retention policy.
* **preemption** — ``PreemptionGuard`` converts SIGTERM/SIGINT into a
  "checkpoint at the next step boundary then exit cleanly" flag; a second
  signal falls through to the default handler (hard kill still works).

``tools/ckpt_fsck.py`` builds its offline verifier on these primitives and
``tests/faultinject.py`` + ``tests/test_checkpoint_faults.py`` prove every
failure mode (kill mid-write, truncation, bit flip, rename failure, disk
full, stale tmp) either recovers or fails loudly — never loads garbage.
"""

from __future__ import annotations

import errno
import os
import re
import signal
import struct
import sys
import time
import zlib
from typing import List, Optional, Tuple

from . import serializer
from . import telemetry

HEADER_MAGIC = b"CXCKHDR1"
FOOTER_MAGIC = b"CXCKPT01"
# magic of the versioned training-state section learn_task/trainer append
# INSIDE the payload (rng counter, grad accum, iterator cursor); defined
# here so peek_state and fsck can find it without importing the trainer
STATE_MAGIC = b"CXTSTA01"

_FOOTER_FMT = "<IQ8s"   # crc32(payload), payload length, FOOTER_MAGIC
FOOTER_SIZE = struct.calcsize(_FOOTER_FMT)

_NAME_RE = re.compile(r"^(\d+)\.model$")
EMERGENCY_NAME = "emergency.model"


class CheckpointError(Exception):
    """Base class for checkpoint IO failures."""


class CheckpointCorruptError(CheckpointError):
    """The file's integrity framing does not validate (truncated / torn /
    bit-flipped). Callers must NOT fall back to loading the raw bytes."""


# ----------------------------------------------------------------------
# integrity framing
def crc32(payload: bytes) -> int:
    return zlib.crc32(payload) & 0xFFFFFFFF


def frame(payload: bytes) -> bytes:
    """Wrap a serialized model payload in the v1 integrity framing."""
    return (HEADER_MAGIC + payload
            + struct.pack(_FOOTER_FMT, crc32(payload), len(payload),
                          FOOTER_MAGIC))


def split_footer(blob: bytes) -> Tuple[bytes, str]:
    """Strip and verify the integrity framing.

    Returns ``(payload, fmt)`` with fmt ``"v1"`` (framed, CRC verified) or
    ``"legacy"`` (footer-less seed checkpoint, returned as-is). Raises
    CheckpointCorruptError when the framing is present but inconsistent —
    a framed file can never be silently demoted to legacy by truncation,
    because the header magic survives at the front.
    """
    has_header = blob.startswith(HEADER_MAGIC)
    body = blob[len(HEADER_MAGIC):] if has_header else blob
    if len(body) >= FOOTER_SIZE and body.endswith(FOOTER_MAGIC):
        crc, plen, _ = struct.unpack(_FOOTER_FMT, body[-FOOTER_SIZE:])
        payload = body[:-FOOTER_SIZE]
        if plen != len(payload):
            raise CheckpointCorruptError(
                "footer declares %d payload bytes but %d are present "
                "(truncated or torn write)" % (plen, len(payload)))
        actual = crc32(payload)
        if actual != crc:
            raise CheckpointCorruptError(
                "CRC mismatch: footer %08x != payload %08x (bit "
                "corruption)" % (crc, actual))
        return payload, "v1"
    if has_header:
        raise CheckpointCorruptError(
            "header magic present but footer missing or invalid "
            "(truncated / torn write)")
    return blob, "legacy"


def verify_blob(blob: bytes):
    """Classify checkpoint bytes without raising: returns
    ``(status, reason, payload_or_None)`` with status ``ok`` (v1, CRC
    verified), ``legacy`` (unverifiable seed format), or ``corrupt``."""
    try:
        payload, fmt = split_footer(blob)
    except CheckpointCorruptError as e:
        return "corrupt", str(e), None
    return ("ok" if fmt == "v1" else "legacy"), "", payload


def peek_state(payload: bytes) -> Optional[dict]:
    """Read the training-state metadata dict (round counter, batch cursor,
    rng counter, ...) out of a verified payload WITHOUT building the net.

    The state section is the last section of the payload, so a valid hit
    must end exactly at the payload end; earlier spurious occurrences of
    the magic inside tensor data are rejected by that length check."""
    import json
    end = len(payload)
    i = payload.rfind(STATE_MAGIC)
    while i >= 0:
        try:
            r = serializer.Reader(payload[i + len(STATE_MAGIC):])
            nbytes = r.read_uint64()
            if i + len(STATE_MAGIC) + 8 + nbytes == end:
                meta = json.loads(r.read_string())
                if isinstance(meta, dict):
                    return meta
        except Exception:
            pass
        i = payload.rfind(STATE_MAGIC, 0, i)
    return None


# ----------------------------------------------------------------------
# durable IO
# OSErrors that no amount of retrying fixes: fail them immediately so a
# mistyped path surfaces at once (and doesn't pollute the ckpt.io_retry
# counter that exists to measure genuinely flaky mounts)
_NON_TRANSIENT_ERRNO = frozenset(
    e for e in (errno.ENOENT, errno.EISDIR, errno.ENOTDIR) if e is not None)


def backoff_delay(attempt: int, base_delay: float = 0.05,
                  cap: float = 30.0) -> float:
    """The shared exponential-backoff schedule: ``base_delay`` doubled
    per attempt, capped. Used by ``retry_io`` below and by the serving
    frontend's circuit-breaker cooldown (utils/servd.py) — one curve for
    every "try again later" in the stack."""
    return min(cap, base_delay * (2.0 ** max(0, int(attempt))))


def retry_io(fn, retries: int = 2, base_delay: float = 0.05,
             retriable=(OSError,)):
    """Run ``fn`` with exponential-backoff retries on transient IO errors
    (flaky NFS / GCS-fuse mounts). ``retries`` is the number of RE-tries;
    the last failure re-raises; permanent errors (missing path, not a
    file) are never retried."""
    for attempt in range(retries + 1):
        try:
            return fn()
        except retriable as e:
            if getattr(e, "errno", None) in _NON_TRANSIENT_ERRNO \
                    or attempt >= retries:
                raise
            telemetry.count("ckpt.io_retry")
            time.sleep(backoff_delay(attempt, base_delay))


def _fsync_dir(dirname: str) -> None:
    """fsync the directory entry so the rename itself is durable; some
    filesystems don't support opening a directory — best effort."""
    try:
        fd = os.open(dirname or ".", os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def atomic_write(path: str, data, fsync: bool = True,
                 retries: int = 2, base_delay: float = 0.05) -> None:
    """Write ``data`` (bytes, or a sequence of byte-like chunks, written
    in order) to ``path`` atomically: tmp file, fsync, rename.

    A crash/kill at any instant leaves either the previous ``path``
    contents or the complete new contents — never a partial file. The
    tmp file is removed on failure; transient OSErrors retry with
    backoff. Chunks are written sequentially so callers never have to
    concatenate a multi-GB payload into one extra host-RAM copy."""
    tmp = path + ".tmp"
    chunks = data if isinstance(data, (list, tuple)) else (data,)

    def _once():
        try:
            with open(tmp, "wb") as f:
                for c in chunks:
                    f.write(c)
                if fsync:
                    f.flush()
                    os.fsync(f.fileno())
            os.replace(tmp, path)
        except BaseException:
            try:
                if os.path.exists(tmp):
                    os.remove(tmp)
            except OSError:
                pass
            raise
        if fsync:
            _fsync_dir(os.path.dirname(os.path.abspath(path)))

    retry_io(_once, retries=retries, base_delay=base_delay)


def write_checkpoint(path: str, payload, fsync: bool = True,
                     retries: int = 2, base_delay: float = 0.05) -> None:
    """Frame ``payload`` (bytes or memoryview; header + CRC footer) and
    atomic-write it without building the framed blob in RAM."""
    footer = struct.pack(_FOOTER_FMT, crc32(payload), len(payload),
                         FOOTER_MAGIC)
    atomic_write(path, (HEADER_MAGIC, payload, footer), fsync=fsync,
                 retries=retries, base_delay=base_delay)


def read_verified(path: str, retries: int = 0,
                  base_delay: float = 0.05) -> Tuple[bytes, str]:
    """Read a checkpoint file and verify/strip its framing. Returns
    ``(payload, fmt)``; raises CheckpointCorruptError (with the path in
    the message) when the framing does not validate."""
    def _read():
        with open(path, "rb") as f:
            return f.read()

    blob = retry_io(_read, retries=retries, base_delay=base_delay) \
        if retries > 0 else _read()
    try:
        return split_footer(blob)
    except CheckpointCorruptError as e:
        raise CheckpointCorruptError("%s: %s" % (path, e)) from None


# ----------------------------------------------------------------------
# directory hygiene: scan / quarantine / GC / retention
def scan_checkpoints(model_dir: str) -> List[Tuple[int, str]]:
    """All ``<counter>.model`` files in ``model_dir``, sorted ascending by
    counter. Tolerates gaps in the numbering (save_period > 1) — unlike
    the reference's stop-at-first-hole scan."""
    out: List[Tuple[int, str]] = []
    try:
        names = os.listdir(model_dir)
    except OSError:
        return out
    for nm in names:
        m = _NAME_RE.match(nm)
        if m:
            out.append((int(m.group(1)), os.path.join(model_dir, nm)))
    out.sort()
    return out


def quarantine(path: str, reason: str = "") -> Optional[str]:
    """Move a corrupt checkpoint aside to ``<path>.corrupt`` (never
    deleted: the operator may want forensics) and emit the
    ``ckpt_corrupt`` telemetry event. Returns the new path."""
    dst = path + ".corrupt"
    n = 0
    while os.path.exists(dst):
        n += 1
        dst = "%s.corrupt.%d" % (path, n)
    try:
        os.replace(path, dst)
    except OSError:
        return None
    telemetry.event({"ev": "ckpt_corrupt", "path": path,
                     "reason": str(reason)[:300], "quarantined_to": dst})
    sys.stderr.write("WARNING: corrupt checkpoint %s (%s) quarantined "
                     "to %s\n" % (path, reason, dst))
    return dst


def gc_stale_tmp(model_dir: str) -> List[str]:
    """Remove ``*.tmp`` leftovers from writes that died before their
    rename. Call only from the single live writer of ``model_dir``."""
    removed = []
    try:
        names = os.listdir(model_dir)
    except OSError:
        return removed
    for nm in names:
        if nm.endswith(".tmp"):
            p = os.path.join(model_dir, nm)
            try:
                os.remove(p)
                removed.append(p)
            except OSError:
                pass
    if removed:
        telemetry.event({"ev": "ckpt_gc_tmp", "removed": len(removed)})
    return removed


def apply_retention(model_dir: str, keep_last: int = 0,
                    keep_every: int = 0, protect=()) -> List[str]:
    """Delete old numbered checkpoints: keep the newest ``keep_last``,
    plus every counter divisible by ``keep_every`` (long-horizon anchors),
    plus anything in ``protect``. ``keep_last <= 0`` disables retention
    entirely (keep everything — the reference behavior)."""
    if keep_last <= 0:
        return []
    ckpts = scan_checkpoints(model_dir)
    keep = {c for c, _ in ckpts[-keep_last:]}
    if keep_every > 0:
        keep |= {c for c, _ in ckpts if c % keep_every == 0}
    keep |= set(protect)
    removed = []
    for c, p in ckpts:
        if c in keep:
            continue
        try:
            os.remove(p)
            removed.append(p)
        except OSError:
            pass
    if removed:
        telemetry.event({"ev": "ckpt_retention", "removed": len(removed),
                         "keep_last": keep_last, "keep_every": keep_every})
    return removed


# ----------------------------------------------------------------------
# preemption handling
class PreemptionGuard:
    """Convert SIGTERM/SIGINT into a cooperative "checkpoint then exit"
    request.

    While installed, the FIRST signal sets ``requested`` (the train loop
    checks it at step boundaries, takes one emergency checkpoint and
    exits cleanly) and immediately restores the previous handlers, so a
    second signal gets default handling — an operator can still hard-kill
    a hung save. Installing outside the main thread is a silent no-op
    (signal.signal is main-thread-only); ``enabled=False`` builds an
    inert guard so call sites need no branching."""

    def __init__(self, signals=None, enabled: bool = True):
        self.signals = tuple(signals) if signals is not None else \
            (signal.SIGTERM, signal.SIGINT)
        self.enabled = enabled
        self.requested = False
        self.signum: Optional[int] = None
        self._old = {}

    def __enter__(self) -> "PreemptionGuard":
        if not self.enabled:
            return self
        try:
            for s in self.signals:
                self._old[s] = signal.signal(s, self._handle)
        except ValueError:        # not the main thread
            self._restore()
        return self

    def _handle(self, signum, frame) -> None:
        # async-signal-safe by construction: ONLY set flags. The handler
        # runs on the main thread between bytecodes — calling into
        # telemetry here could deadlock on its non-reentrant lock if the
        # signal lands inside a span/counter critical section (the train
        # loop holds it every batch). The train loop emits the telemetry
        # event when it observes `requested`.
        self.requested = True
        self.signum = int(signum)
        self._restore()

    def _restore(self) -> None:
        for s, h in self._old.items():
            try:
                signal.signal(s, h)
            except (ValueError, OSError):
                pass
        self._old = {}

    def __exit__(self, *exc) -> bool:
        self._restore()
        return False
