"""Production serving frontend: admission control, deadlines, breaker, drain.

The only online surface until now was ``task = serve`` — a single-threaded
stdin loop where an unexpected backend exception killed the process, a slow
decode stalled every queued client, and SIGTERM dropped in-flight requests
on the floor. This module is the overload-robustness layer large-scale
serving systems put in front of the model (the TensorFlow-Serving-era
playbook, arxiv 1605.08695): a stdlib-only concurrent frontend that wraps
the cached ``generate``/``predict`` programs behind a TCP line protocol
and is also the engine behind the stdin ``task = serve`` loop, keeping
every program on the compiled decode-cache fast path (recompiles are the
latency cliff — cf. TVM, arxiv 1802.04799).

What a request gets on the way to the backend:

* **admission control** — a bounded queue (``serve_queue``); when it is
  full the request is fast-rejected ``ERR busy`` from the reader thread
  (never queued, never stalls the worker) and counted (``serve.shed``).
  Load past capacity degrades into cheap rejections, not latency collapse.
* **deadlines** — ``serve_deadline_ms`` default, or a per-request
  ``DEADLINE <ms>`` prefix; a request whose deadline expired while queued
  is answered ``ERR deadline`` BEFORE dispatch (the backend never burns
  decode time on an answer nobody is waiting for) and counted.
* **backend supervision** — any backend exception is caught, answered
  ``ERR backend``, counted, and fed to a **circuit breaker**: after
  ``serve_breaker_fails`` consecutive failures it opens and requests shed
  instantly (no queue wait, no backend call); after an exponential-backoff
  cooldown (the shared ``checkpoint.backoff_delay`` schedule) ONE request
  goes through as a half-open probe — success closes the breaker, failure
  reopens it with a doubled cooldown.
* **graceful drain** — ``drain()`` (the driver calls it off the
  ``PreemptionGuard`` SIGTERM/SIGINT flag) stops accepting, finishes every
  accepted request within ``serve_drain_ms``, answers whatever is left
  ``ERR draining``, flushes telemetry, and returns the final stats —
  exactly one response line per accepted request, always.
* **hot reload** — ``ADMIN reload`` (or SIGHUP in the driver) sets a flag
  the worker honors BETWEEN requests: the reload callback swaps in the
  newest valid checkpoint without dropping the queue.

Wire protocol (one line per request, one line per response, utf-8):

    <tok> <tok> ...                 -> <id> <id> ...        (continuation)
    DEADLINE <ms> <tok> ...         -> same, with a per-request deadline
    TRACE <id> [DEADLINE <ms>] ...  -> same, request adopts the caller's
                                       fleet-wide trace id (see below)
    TENANT <id> [DEADLINE <ms>] ... -> same, request runs as tenant <id>
                                       (after TRACE, before DEADLINE)
    ADMIN reload                    -> OK reload scheduled
    ADMIN stats                     -> OK accepted=.. served=.. ...
    (anything else)                 -> ERR <class> <detail>

``TRACE <id>`` is the cross-process trace-propagation prefix (the
Dapper idea: ONE id names a request on every process that touched it).
The fleet router (utils/routerd.py) mints an id per client request and
stamps it on every forward attempt; this frontend adopts it as the
request id its ``telemetry.trace_context`` / flight record / ``/trace
?request=<id>`` surface uses — so a request retried across replicas is
findable on each of them under the same id. The prefix composes with
``DEADLINE`` (TRACE first), is optional (a TRACE-less client gets a
locally minted id, exactly as before), and is validated: the id must
be 1..``TRACE_ID_MAX`` chars of ``[A-Za-z0-9._:-]``; anything else is
answered ``ERR proto trace ...`` (class ``proto``: a protocol-level
violation, deterministic, never dispatched).

**Multi-tenant weighted-fair QoS** (doc/serving.md "Multi-tenant
QoS"): a ``tenants`` table (``parse_tenants("free:1,paid:4")`` — give
every process in the fleet the SAME value) makes the admission queue
per-tenant weighted-fair (``_FairQueue``: stride-scheduled pops, fair
shares of the queue bound with borrow-then-evict capacity fairness),
adds per-tenant books to ``ADMIN stats`` (``tenant.<id>.<key>=N``,
reconciling per tenant), per-tenant latency histograms
(``serve.tenant.<t>.request`` — the federation merges them into fleet
p99s) and per-tenant SLO windows (``slo_tenants``). The ``TENANT``
prefix names the request's tenant; prefix-less clients run as
``tenant_default``. A tenant at/over its fair share of a full queue is
shed ``ERR busy tenant ...`` — third token wire format: the fleet
router relays it WITHOUT retry (the verdict holds fleet-wide).

Error classes: ``empty`` (blank request — visible instead of a silently
missing response), ``parse`` (non-integer token, token outside vocab, bad
DEADLINE), ``proto`` (malformed TRACE or TENANT prefix, unknown
tenant), ``busy`` (queue full, breaker open, or tenant over fair
share: shed), ``deadline``,
``backend``, ``draining``. The THIRD token of an error line is a
machine-readable detail token — the retryability contract the fleet
router (utils/routerd.py) dispatches on, so these are wire format, not
prose (the full vocabulary is ONE table in doc/serving.md "Error
vocabulary"): ``ERR busy queue ...`` (admission queue full — the request
never
dispatched, instantly retryable on another replica) vs ``ERR busy
breaker ...`` (circuit breaker open — also never dispatched, retryable
elsewhere, but the replica should leave rotation); ``ERR draining
server ...`` (refused at the door) and ``ERR draining shutdown ...``
(queued, never dispatched) are retryable, ``ERR draining backend ...``
(the in-flight request drain gave up on) may have dispatched and is
NOT. Counters reconcile:
``accepted == served + errors + shed + deadline``. A request arriving
AFTER drain began is refused (``ERR draining``) without entering the
accounting — it was never accepted, so drain's final stats stay final.
Responses leave each connection in request order (the protocol pairs
them positionally), even when a rejection is produced instantly while
earlier requests are still queued.

Observability — every accepted request gets a **request id** and its
life is decomposed into phase-attributed telemetry (the measurement
contract the batching/paging/prewarm throughput arc is graded against):

* **phases** (they tile accept->answer wall-clock): ``queue_wait``
  (accept -> worker pop), ``dispatch`` (pop -> backend call),
  ``prefill`` (backend call -> first token — the worker runs the
  backend under ``telemetry.trace_context(request_id)``, and the
  trainer marks ``first_token`` at its prefill/decode split), and
  ``decode`` (first token -> last token, i.e. per-token time).
* **series**: counters ``serve.accepted/requests/errors/shed/deadline/
  empty/client_gone/backend_errors/breaker_*/reloads/tokens``, gauges
  ``serve.queue_depth`` / ``serve.in_flight`` /
  ``serve.tokens_per_second`` / ``serve.batch_occupancy`` (sequences in
  the most recent decode pass — reads 1 today, the headline once
  batching lands), histograms ``serve.request`` (end-to-end),
  ``serve.queue_wait``, ``serve.ttft`` (accept -> first token) and
  ``serve.decode_per_token`` — declared at start() so /metrics exports
  the bucket series from scrape one.
* **flight recorder**: the last ``flight_cap`` dequeued requests keep
  their full trace (phase split, tokens, outcome, the recompiles they
  paid) in ``self.flight`` (telemetry.FlightRecorder) — statusd serves
  one as a Chrome trace at ``/trace?request=<id>`` and lists the ring
  at ``/requestz``; each also emits a ``serve_request_done`` event
  (tools/telemetry_report.py's request-breakdown section).
* **SLOs**: pass ``slo=statusd.SLOTracker(...)`` and every completed
  request feeds the rolling error-budget account behind the
  ``cxxnet_slo_burn`` alert gauge.

``health_probe`` (readiness: 503 while draining
or breaker-open) and ``liveness_probe`` (worker thread death) plug into
statusd ``/healthz`` / ``/livez``; the accept and worker threads beat the
``serve.accept`` / ``serve.worker`` watchdog channels (paused across idle
periods so an empty queue is not a hang).

**Continuous batching** (doc/serving.md "Continuous batching"): pass a
``slot_backend`` and the worker becomes an iteration-granularity
batching dispatcher instead of the one-request-per-pass loop. The slot
backend owns bucketed decode sessions (``buckets`` = slot counts, e.g.
``1,2,4,8``; ``session(bucket)`` opens one; a session exposes
``prefill(slot, toks, seq) -> (first_token, done)``, ``step() ->
[(slot, token, done), ...]``, ``retire(slot)`` and optionally
``close()`` / the backend ``admits(toks) -> error-detail-or-None``
compatibility check) — ``Trainer.decode_session`` is the real one, the
chaos tests inject jax-free fakes (tests/faultinject.py). Scheduling:

* **coalesce** — up to ``batch_max`` queued requests are drained within
  a ``batch_window_ms`` gather window and admitted into the smallest
  bucket that fits; the window applies only when STARTING a batch —
  requests already decoding never stall on it.
* **iteration granularity** — each loop turn advances every active slot
  one token; a finished sequence retires its slot and the next queued
  request joins MID-DECODE (its ``queue_wait`` ends at slot admission).
* **per-iteration deadlines** — an expired sequence retires with ``ERR
  deadline`` between iterations; the others keep decoding.
* **contracts kept** — exactly-once ``_finish`` per request (drain
  mid-batch answers every in-flight slot), breaker semantics (a
  prefill/step failure that CLOSED the session — the device-fault
  signal of the session contract — counts ONE breaker failure however
  many requests die of it, and a step failure fails the whole batch
  ``ERR backend``; a prefill that raised with the session left OPEN
  never touched device state — pre-dispatch validation — and is a
  deterministic request defect the breaker ignores), honest
  per-request phases (prefill
  is the request's own admission prefill; decode is ITS first->last
  token wall; ``occupancy_at_dispatch`` rides the flight record), and
  hot reload deferred until the in-flight batch finishes (the slot
  caches hold the old model's K/V; sessions are closed, then reloaded).
* **occupancy is measured, not asserted** — every iteration feeds
  ``serve.batch_occupancy`` (gauge: last iteration) plus the honest
  weighted-mean pair ``serve.batch_iterations`` /
  ``serve.batch_slot_iterations`` (mean occupancy = slots/iterations —
  a last-write gauge scraped between batches lies), and ``ADMIN stats``
  reports ``free_slots`` (bucket capacity − active) plus per-bucket
  ``batch_buckets`` / ``bucket.<b>.warm`` / ``bucket.<b>.active`` so
  the fleet router can prefer the replica that can batch a request in.
* **block-aware admission (paged KV)** — a backend exposing the
  paged-pool hooks (``kv_free_blocks`` / ``kv_fresh_blocks`` /
  ``kv_pool_account``; doc/performance.md "Decode KV cache") gets a
  block-budgeted gather: a queued request is popped only when the
  pool covers its prompt + generation blocks RIGHT NOW, head-of-queue
  order, no skip-ahead — pool exhaustion is a deterministic FIFO
  queue-wait, never an error, never a device OOM. A retirement
  returns its blocks mid-decode and the next turn's gather admits
  into them; the rare budget race (``kvblocks.KVPoolExhausted`` from
  a prefill that ran no device work) REQUEUES at the queue head.
  ``ADMIN stats`` gains ``kv_blocks_total``/``kv_blocks_free`` +
  ``bucket.<b>.blocks_held``, and ``batch_snapshot()`` the ``pool``
  sub-dict (free-list level, prefix-reuse/CoW tallies, block-exact
  ``pool_bytes`` — what ``decode_kv_bytes`` reports under paging).
* **the scheduler is observed per ITERATION** (doc/observability.md
  "Decode datapath") — every decode iteration lands in the
  ``BatchFlightRecorder`` ring (``batch_flight_cap``): bucket, step
  latency, the slots aboard with each occupant's request id and age,
  admissions/retirements, queue depth + head-of-queue age (also the
  ``serve.queue_age`` histogram), live-KV utilization, convoy verdict.
  statusd serves it at ``/batchz`` and renders a request's iterations
  as slot-Gantt lanes inside its ``/trace?request=<id>`` trace;
  ``batch_iteration`` JSONL events fire on composition CHANGES only
  (never per token). The per-bucket KV account (``batch_snapshot``,
  joined from each session's ``kv_account()``) publishes
  ``cxxnet_decode_kv_bytes{bucket=}`` / ``cxxnet_decode_kv_live_pct``
  / ``cxxnet_decode_slot_waste_pct`` — the padding+dead-slot waste a
  paged KV cache (ROADMAP item 2) would reclaim — and feeds the perf
  ledger's HBM headroom account (``decode_kv_bytes``). A **convoy**
  — a sequence aboard >= ``convoy_iters`` iterations while queued work
  waits at zero free slots — latches ``cxxnet_decode_convoy``, counts
  ``serve.convoys``, and emits ONE transition-only ``decode_convoy``
  event per episode: the starvation signal the disaggregation
  scheduler and the autoscaler's pressure pass consume.

Deliberately jax-free (like health.py and statusd.py): the backend is an
injected callable, so ``python -m cxxnet_tpu.utils.servd --selftest``
proves the whole admission/deadline/breaker/drain machinery over a real
socket on a box with no accelerator stack (``make check`` gates on it),
and ``--stub`` runs a standalone echo server the chaos tests drive as a
subprocess (SIGTERM drain, floods, exploding backends).
"""

from __future__ import annotations

import re
import socket
import sys
import threading
import time
from collections import deque
from typing import Callable, List, Optional, Tuple

from . import autopsy
from . import checkpoint as ckpt
from . import health
from . import kvblocks
from . import lockrank
from . import perf
from . import statusd
from . import telemetry

__all__ = ["CircuitBreaker", "ServeFrontend", "BatchFlightRecorder",
           "embed_vocab",
           "TRACE_ID_MAX", "valid_trace_id", "TENANT_ID_MAX",
           "valid_tenant_id", "parse_tenants", "selftest"]

# the TRACE prefix's id bound: long enough for any reasonable minting
# scheme (router prefix + counter, uuid hex), short enough that a
# garbage line cannot smuggle kilobytes into every flight record and
# JSONL event the id is stamped on
TRACE_ID_MAX = 64
_TRACE_ID_RE = re.compile(r"[A-Za-z0-9._:-]{1,%d}$" % TRACE_ID_MAX)


def valid_trace_id(tid: str) -> bool:
    """The TRACE id charset/length contract, shared with the router (it
    validates before forwarding, and mints ids that pass): 1..64 chars
    of ``[A-Za-z0-9._:-]`` — safe in URLs (``/trace?request=<id>``),
    label values, and log lines without escaping."""
    return bool(_TRACE_ID_RE.match(tid))


def parse_trace_prefix(parts: List[str]):
    """Strip a leading ``TRACE <id>`` from a token list ->
    ``(trace_id, proto_detail, rest)``. ``trace_id`` is None when no
    prefix was present; ``proto_detail`` (None when valid) is the
    detail text of the ``ERR proto`` line — ONE implementation of the
    wire-format check, shared by servd's parser and the router's (the
    two must never desynchronize on what a malformed prefix is)."""
    if parts[:1] != ["TRACE"]:
        return None, None, parts
    if len(parts) < 2:
        return None, "trace prefix needs an id", parts
    if not valid_trace_id(parts[1]):
        return None, ("trace id must be 1..%d chars of "
                      "[A-Za-z0-9._:-]" % TRACE_ID_MAX), parts
    return parts[1], None, parts[2:]


# the TENANT prefix's id bound: tenant names are CONFIG identifiers
# (route_tenants / serve_tenant_default), not free-form client strings —
# short, and ':' is excluded (it is the weight separator in the conf
# value "free:1,paid:4")
TENANT_ID_MAX = 32
_TENANT_ID_RE = re.compile(r"[A-Za-z0-9._-]{1,%d}$" % TENANT_ID_MAX)


def valid_tenant_id(tid: str) -> bool:
    """The TENANT id charset/length contract, shared with the router
    (it validates before forwarding): 1..32 chars of ``[A-Za-z0-9._-]``
    — safe in metric names, label values, and the conf syntax."""
    return bool(_TENANT_ID_RE.match(tid))


def parse_tenant_prefix(parts: List[str]):
    """Strip a leading ``TENANT <id>`` from a token list ->
    ``(tenant, proto_detail, rest)``. ``tenant`` is None when no prefix
    was present; ``proto_detail`` (None when valid) is the detail text
    of the ``ERR proto`` line — ONE implementation of the wire-format
    check, shared by servd's parser and the router's (the
    parse_trace_prefix discipline: the two must never desynchronize)."""
    if parts[:1] != ["TENANT"]:
        return None, None, parts
    if len(parts) < 2:
        return None, "tenant prefix needs an id", parts
    if not valid_tenant_id(parts[1]):
        return None, ("tenant id must be 1..%d chars of "
                      "[A-Za-z0-9._-]" % TENANT_ID_MAX), parts
    return parts[1], None, parts[2:]


def parse_tenants(spec):
    """``route_tenants`` conf value -> ``{tenant: weight}``.
    ``"free:1,paid:4"`` (comma/whitespace separated, ``name[:weight]``,
    weight defaults to 1). Empty/None -> ``{}`` (single-tenant mode:
    every fairness path short-circuits to pre-tenant behavior). Shared
    by servd, routerd, and the driver so the tenant table cannot drift
    between the processes enforcing it."""
    if not spec:
        return {}
    if isinstance(spec, dict):
        out = {str(k): float(v) for k, v in spec.items()}
    else:
        out = {}
        for item in re.split(r"[,\s]+", str(spec).strip()):
            if not item:
                continue
            name, _, w = item.partition(":")
            out[name] = float(w) if w else 1.0
    for name, w in out.items():
        if not valid_tenant_id(name):
            raise ValueError("tenant name %r is not 1..%d chars of "
                             "[A-Za-z0-9._-]" % (name, TENANT_ID_MAX))
        if not (w > 0):
            raise ValueError("tenant %r needs a positive weight, got %r"
                             % (name, w))
    return out


def embed_vocab(net) -> int:
    """The vocab bound for parse-time token validation: the largest
    embed layer's vocab_size in a built net (0 = no embed layer, no
    bound). Shared by the learn-task and api serving surfaces so the
    check cannot drift between them. Pure attribute access — jax-free."""
    return max((lay.vocab_size for lay in net.layers
                if getattr(lay, "type_name", "") == "embed"), default=0)


class CircuitBreaker:
    """Consecutive-failure circuit breaker with half-open probes.

    States: ``closed`` (healthy) → ``open`` after ``fails`` consecutive
    backend failures (every dispatch shed instantly) → ``half_open`` once
    the cooldown elapses (exactly ONE request goes through as a probe) →
    ``closed`` on probe success, or back to ``open`` with a doubled
    cooldown on probe failure. The cooldown follows the shared
    ``checkpoint.backoff_delay`` exponential schedule, so a backend that
    stays broken is probed ever more rarely instead of hammered.

    Thread-safe; every transition emits a ``serve_breaker`` telemetry
    event and a ``serve.breaker_<state>`` counter (what
    tools/telemetry_report.py's serving section and its unresolved-open
    exit-2 gate read).
    """

    def __init__(self, fails: int = 5, cooldown: float = 1.0,
                 max_cooldown: float = 30.0, clock=time.monotonic):
        self.fails = max(1, int(fails))
        self.cooldown = float(cooldown)
        self.max_cooldown = float(max_cooldown)
        self._clock = clock
        self._lock = lockrank.lock("servd.breaker")
        self.state = "closed"
        self.consecutive = 0      # consecutive backend failures
        self.opens = 0            # open transitions since last close
        #                           (the backoff exponent)
        self.transitions = 0
        self.reopen_at = 0.0

    def _transition(self, state: str, delay: Optional[float] = None):
        # lock held by the caller
        self.state = state
        self.transitions += 1
        telemetry.count("serve.breaker_%s" % state)
        ev = {"ev": "serve_breaker", "state": state,
              "consecutive_fails": self.consecutive}
        if delay is not None:
            ev["retry_in_s"] = round(delay, 3)
        telemetry.event(ev)

    def blocked(self) -> bool:
        """Admission-time fast check: True while open and still cooling —
        the caller sheds instantly without queueing."""
        with self._lock:
            return self.state == "open" and self._clock() < self.reopen_at

    def allow(self) -> bool:
        """Dispatch-time gate: True to call the backend. While open, the
        first call after the cooldown becomes the half-open probe; until
        that probe resolves every other dispatch is refused."""
        with self._lock:
            if self.state == "closed":
                return True
            if self.state == "open" and self._clock() >= self.reopen_at:
                self._transition("half_open")
                return True
            return False

    def success(self) -> None:
        with self._lock:
            self.consecutive = 0
            if self.state != "closed":
                self.opens = 0
                self._transition("closed")

    def failure(self) -> None:
        with self._lock:
            self.consecutive += 1
            if self.state == "half_open" or (
                    self.state == "closed"
                    and self.consecutive >= self.fails):
                delay = ckpt.backoff_delay(self.opens,
                                           base_delay=self.cooldown,
                                           cap=self.max_cooldown)
                self.opens += 1
                self.reopen_at = self._clock() + delay
                self._transition("open", delay=delay)

    def describe(self) -> str:
        return ("%s (%d consecutive failures)"
                % (self.state, self.consecutive))


class _ConnState:
    """Per-connection response state: slot-ordered reply buffer + the
    count of filled-but-untransmitted responses (what drain waits on)."""

    __slots__ = ("cond", "slots", "dead", "eof", "unsent")

    def __init__(self):
        self.cond = lockrank.condition("servd.conn")
        self.slots: deque = deque()    # [text or None] per submitted line
        self.dead = False              # send failed: connection torn down
        self.eof = False               # reader saw client EOF
        self.unsent = 0                # filled slots not yet transmitted


# _admit_one's "block pool could not cover this admission" verdict —
# distinct from None (rejected / failed / finished at prefill) so the
# worker loop can requeue the request and its unadmitted batchmates
_KV_DEFER = object()


class _Request:
    __slots__ = ("toks", "deadline", "t_arrival", "t_wall", "reply",
                 "done", "seq", "id", "tenant", "_alock", "answered",
                 "kv_defers")

    def __init__(self, toks: List[int], deadline: Optional[float], reply,
                 tenant: Optional[str] = None):
        self.toks = toks
        self.tenant = tenant
        self.t_arrival = time.monotonic()
        # cxxlint: disable=wallclock — flight-record arrival epoch, never
        # subtracted: durations in this class all come from t_arrival
        self.t_wall = time.time()
        self.id = "?"                # assigned under the admission lock
        # deadline arrives relative (seconds); stored absolute monotonic
        self.deadline = None if deadline is None \
            else self.t_arrival + deadline
        self.reply = reply
        self.done = threading.Event()
        self.seq = -1
        # exactly-once answer guard: drain can give up on a request
        # whose backend wedged past the budget while the worker might
        # still answer it later — only the first answer goes out
        self._alock = lockrank.lock("servd.request")
        self.answered = False
        # block-pool admission defers this request ate (the paged-KV
        # requeue path) — the autopsy's kv_defer attribution signal
        self.kv_defers = 0


class _SlotState:
    """Per-slot request state on the batching dispatcher: the admitted
    request, its trace context (first_token mark, recompiles), its
    phase timestamps (queue_wait ended at slot admission), the tokens
    produced so far, the batch occupancy at its admission, and its
    scheduling coordinates (bucket, slot, first/last step-iteration
    ordinal — what lets /requestz answer "who did this request share
    its decode with" without the iteration ring)."""

    __slots__ = ("req", "tc", "queue_wait", "t_pop", "t_back", "toks",
                 "occ", "slot", "bucket", "first_iter", "last_iter",
                 "stall_s")

    def __init__(self, req, tc, queue_wait, t_pop, t_back, toks, occ,
                 slot, bucket):
        self.req = req
        self.tc = tc
        self.queue_wait = queue_wait
        self.t_pop = t_pop
        self.t_back = t_back
        self.toks = toks
        self.occ = occ
        self.slot = slot
        self.bucket = bucket
        # step-iteration ordinals this sequence was aboard for (None
        # until its first step: an n_new == 1 request finishes at
        # prefill and never shares a decode pass)
        self.first_iter = None
        self.last_iter = None
        # compile seconds this request sat through OUTSIDE its own
        # trace context — batch-wide cliffs (warm-session creation,
        # the shared decode step) the dispatcher's compile window
        # attributed to every sequence aboard; its own prefill's
        # recompiles already land on tc.compiles
        self.stall_s = 0.0


class _FairQueue:
    """Per-tenant weighted-fair admission queue (stride scheduling).

    Drop-in for the single admission deque — ``append`` / ``popleft`` /
    ``__len__`` / ``__bool__`` / ``__iter__`` / ``clear`` — except pops
    interleave tenants by WEIGHT instead of arrival order: each tenant
    carries a virtual time advanced by ``1/weight`` per pop, and
    ``popleft`` serves the backlogged tenant furthest behind. A
    weight-4 tenant therefore gets 4 dispatches for every 1 a weight-1
    tenant gets while both are backlogged, and an idle tenant's unused
    share flows to the others (its virtual time is clamped forward to
    the clock when it returns, so idling banks no credit).

    Capacity fairness rides ``over_share``/``evict_over_share``: each
    tenant's fair share of the bound is ``queue_size * w/W`` (floored
    at 1); a tenant may BORROW idle capacity beyond its share, but when
    the queue is full an arrival from an under-share tenant evicts the
    newest queued request of the most-over-share tenant — the shed is
    charged to the tenant over its fair share, never to the victim.
    All methods run under the frontend's admission lock."""

    def __init__(self, weights, queue_size: int):
        total = float(sum(weights.values()))
        self._qs = {t: deque() for t in sorted(weights)}
        self._stride = {t: 1.0 / w for t, w in weights.items()}
        self.shares = {t: max(1, int(queue_size * w / total))
                       for t, w in weights.items()}
        self._vt = {t: 0.0 for t in weights}
        self._vclock = 0.0
        self._n = 0

    def __len__(self) -> int:
        return self._n

    def __bool__(self) -> bool:
        return self._n > 0

    def __iter__(self):
        for t in sorted(self._qs):
            for req in self._qs[t]:
                yield req

    def clear(self) -> None:
        for q in self._qs.values():
            q.clear()
        self._n = 0

    def depth(self, tenant: str) -> int:
        return len(self._qs[tenant])

    def append(self, req) -> None:
        q = self._qs[req.tenant]
        if not q:
            # a tenant returning from idle starts at the clock, not at
            # its stale virtual time — idling must not bank credit that
            # would let it monopolize the worker on return
            self._vt[req.tenant] = max(self._vt[req.tenant],
                                       self._vclock)
        q.append(req)
        self._n += 1

    def popleft(self):
        vt, t = min((self._vt[t], t) for t, q in self._qs.items() if q)
        self._vclock = vt
        self._vt[t] = vt + self._stride[t]
        self._n -= 1
        return self._qs[t].popleft()

    def peek(self):
        """The request ``popleft`` would return RIGHT NOW, no mutation
        — the paged-KV gather gate budgets the NEXT admission's block
        demand (deque-parity: the plain queue peeks its [0])."""
        vt, t = min((self._vt[t], t) for t, q in self._qs.items() if q)
        return self._qs[t][0]

    def appendleft(self, req) -> None:
        """Return a popped request to ITS TENANT's queue head (the
        paged-KV defer/requeue path): the pop's virtual-time charge is
        refunded — a defer costs the tenant no fair-share credit, and
        the refund makes its tenant the furthest-behind again so the
        deferred request is the next pop (deque-parity head semantics).
        No idle clamp: the tenant never left the queue."""
        self._vt[req.tenant] -= self._stride[req.tenant]
        self._qs[req.tenant].appendleft(req)
        self._n += 1

    def oldest_arrival(self):
        """Earliest queued arrival (monotonic), or None when empty —
        the head-of-queue age the convoy detector and the
        serve.queue_age histogram read (deque-parity: the plain queue
        reads its [0])."""
        ts = [q[0].t_arrival for q in self._qs.values() if q]
        return min(ts) if ts else None

    def over_share(self, tenant: str) -> bool:
        return len(self._qs[tenant]) >= self.shares[tenant]

    def evict_over_share(self, exempt: str):
        """The newest queued request of the tenant MOST over its fair
        share (never ``exempt`` — the arriving under-share tenant), or
        None when nobody is over-share. LIFO within the borrower: its
        newest borrowed slot is the one it never fairly held."""
        worst, excess = None, 0
        for t, q in sorted(self._qs.items()):
            if t == exempt:
                continue
            over = len(q) - self.shares[t]
            if over > excess:
                worst, excess = t, over
        if worst is None:
            return None
        self._n -= 1
        return self._qs[worst].pop()


class BatchFlightRecorder:
    """Bounded ring of per-ITERATION batch scheduler records — the
    decode datapath's flight recorder (doc/observability.md "Decode
    datapath"). Where ``telemetry.FlightRecorder`` keeps one record per
    REQUEST, this ring keeps one per decode iteration: wall epoch,
    bucket, step latency, the slots aboard (each occupant's request id
    and age-in-iterations), admissions/retirements since the last
    record, queue depth + head-of-queue age at the iteration, live-KV
    utilization, and the convoy verdict. statusd serves it at
    ``/batchz`` and merges a request's iterations into its
    ``/trace?request=<id>`` Chrome trace as slot-Gantt lanes.

    Jax-free and registry-independent (the FlightRecorder discipline:
    it must survive a run with telemetry disabled). Records are
    appended by the dispatcher OUTSIDE every servd lock, from a
    snapshot taken under the admission lock once per iteration — the
    per-iteration feed must never serialize token decoding against a
    /batchz read. ``iterations``/``slot_iterations`` are LIFETIME
    tallies (the ring evicts, the tallies do not): their ratio is the
    same weighted-mean occupancy the ``serve.batch_iterations`` /
    ``serve.batch_slot_iterations`` counter pair publishes — pinned
    equal by a regression test."""

    def __init__(self, cap: int = 256):
        self.cap = max(1, int(cap))
        self._lock = lockrank.lock("servd.batchflight")
        self._ring: deque = deque(maxlen=self.cap)
        self.iterations = 0
        self.slot_iterations = 0

    def record(self, rec: dict) -> None:
        with self._lock:
            self._ring.append(rec)
            if rec.get("stepped", 1):
                # only DECODE iterations enter the occupancy tallies —
                # a journal-flush record (admissions/retirements on a
                # turn that ran no step, e.g. n_new==1 finishing at
                # prefill) is scheduler history, not a decode pass
                self.iterations += 1
                self.slot_iterations += int(rec.get("occupancy", 0))

    def list(self, n: int = 0) -> List[dict]:
        """Newest-first snapshot of the ring (``n > 0`` bounds it)."""
        with self._lock:
            recs = list(reversed(self._ring))
        return recs[:n] if n > 0 else recs

    def for_request(self, request_id) -> List[dict]:
        """OLDEST-first: every ringed iteration the request was aboard
        — the /trace slot-Gantt feed (which iterations this request
        shared its decode with, and with whom)."""
        rid = str(request_id)
        with self._lock:
            return [rec for rec in self._ring
                    if any(str(row[1]) == rid
                           for row in rec.get("slots") or [])]

    def mean_occupancy(self) -> Optional[float]:
        if not self.iterations:
            return None
        return self.slot_iterations / float(self.iterations)

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)


# stat key -> telemetry counter (serve.requests keeps PR 4's name for the
# successfully-served count so existing dashboards/reports keep working)
_COUNTERS = {
    "accepted": "serve.accepted",
    "served": "serve.requests",
    "errors": "serve.errors",
    "shed": "serve.shed",
    "deadline": "serve.deadline",
    "empty": "serve.empty",
    "admin": "serve.admin",
    "reloads": "serve.reloads",
    "reload_seen": "serve.reload_seen",
    "client_gone": "serve.client_gone",
}
# the stats mirrored into statusd's progress gauges per bump
_PROGRESS_KEYS = ("served", "errors", "shed", "deadline")
# the per-tenant reconciling subset: accepted == served + errors +
# shed + deadline holds PER TENANT exactly as it does frontend-wide
_TENANT_KEYS = ("accepted", "served", "errors", "shed", "deadline")


class ServeFrontend:
    """The concurrent serving frontend around one backend callable.

    ``backend(toks, seq) -> sequence of ints`` runs on the single worker
    thread (batch-1 decode is serial on the accelerator by design — the
    latency-bound case; concurrency buys admission, shedding, and drain,
    not parallel decode). ``seq`` is the dispatch ordinal (the driver
    folds it into the sampling seed so streams differ per request).

    ``reload_fn() -> bool`` (optional) is called between requests when a
    reload was requested; returning False (or raising) keeps the current
    model. ``vocab > 0`` rejects out-of-range token ids at parse time.

    Lifecycle: ``start()`` (worker thread) → optional ``listen(port)``
    (TCP accept thread) → ``submit()`` per request line (the connection
    readers and the driver's stdin pump both land here) → ``drain()``.
    """

    def __init__(self, backend: Callable, queue_size: int = 64,
                 deadline_ms: float = 0.0, drain_ms: float = 5000.0,
                 breaker_fails: int = 5, breaker_cooldown_ms: float = 1000.0,
                 breaker_max_cooldown_ms: float = 30000.0, vocab: int = 0,
                 reload_fn: Optional[Callable] = None,
                 client_timeout: float = 10.0,
                 stall_after_s: float = 120.0,
                 slo=None, flight_cap: int = 256,
                 slot_backend=None, batch_max: int = 0,
                 batch_window_ms: float = 0.0,
                 batch_flight_cap: int = 256, convoy_iters: int = 64,
                 tenants=None, tenant_default: str = "default",
                 slo_tenants=None, kv_pressure_pct: float = 10.0,
                 kv_pressure_clear_pct: float = 25.0):
        self.backend = backend
        # multi-tenant weighted-fair QoS (module docstring): a tenant
        # table turns the admission deque into a _FairQueue and arms
        # per-tenant accounting/SLO; empty = single-tenant mode, every
        # path byte-identical to pre-tenant behavior
        self._tenants = parse_tenants(tenants)
        self.tenant_default = str(tenant_default)
        if self._tenants and self.tenant_default not in self._tenants:
            # the default tenant must have a queue and a weight — a
            # prefix-less client is a first-class tenant, not an error
            self._tenants[self.tenant_default] = 1.0
        # per-tenant SLO trackers (statusd.SLOTracker each): the
        # per-tenant error-budget floors the fleet federation merges
        self.slo_tenants = dict(slo_tenants or {})
        self._tstats = {t: {k: 0 for k in _TENANT_KEYS}
                        for t in self._tenants}
        # continuous batching (module docstring): a slot backend makes
        # the worker an iteration-granularity batching dispatcher;
        # batch_max bounds the coalesced batch (0 = the largest bucket),
        # batch_window_ms is the gather window for a FRESH batch
        self.slot_backend = slot_backend
        self.batch_max = int(batch_max)
        self.batch_window_s = float(batch_window_ms) / 1e3
        self._buckets = []
        if slot_backend is not None:
            self._buckets = sorted(
                {max(1, int(b))
                 for b in (getattr(slot_backend, "buckets", None)
                           or (1,))})
        # per-request observability: the flight ring every dequeued
        # request lands in, and the (optional) SLO error-budget account
        # (statusd.SLOTracker) fed per completed request
        self.flight = telemetry.FlightRecorder(flight_cap)
        self.slo = slo
        self._rid = 0                # request-id counter (admission lock)
        self.queue_size = max(1, int(queue_size))
        self.deadline_ms = float(deadline_ms)
        self.drain_ms = float(drain_ms)
        self.vocab = int(vocab)
        self.reload_fn = reload_fn
        self.client_timeout = float(client_timeout)
        # a backend that BLOCKS (no exception) is invisible to the
        # breaker and to deadlines (the single worker never dispatches
        # again), and the worker heartbeat is deliberately paused across
        # backend calls (compiles). This wall-clock bound on the current
        # dispatch is the wedge detector: readiness fails past it,
        # liveness past twice it. Size it above the worst legitimate
        # call INCLUDING a first compile; 0 disables.
        self.stall_after_s = float(stall_after_s)
        self.breaker = CircuitBreaker(breaker_fails,
                                      cooldown=breaker_cooldown_ms / 1e3,
                                      max_cooldown=breaker_max_cooldown_ms
                                      / 1e3)
        # the admission queue: a plain deque, or the per-tenant
        # weighted-fair queue when a tenant table is configured (same
        # interface — every consumer is tenant-agnostic)
        self._q = (_FairQueue(self._tenants, max(1, int(queue_size)))
                   if self._tenants else deque())
        # ranked locks (utils/lockrank.py): with CXXNET_LOCKRANK=1 the
        # chaos tests assert acquisition order matches the static graph
        self._cond = lockrank.condition("servd.queue")
        self._slock = lockrank.lock("servd.stats")
        self._stats = {k: 0 for k in _COUNTERS}
        self._draining = False
        self._stop = False
        self._reload_flag = False    # plain bool: settable from a signal
        #                              handler without taking any lock
        self._inflight = 0
        self._inflight_req: Optional[_Request] = None
        # batched path: every popped-but-unanswered request (drain's
        # give-up list; _inflight counts these). Mutated by the worker
        # and read by drain/stats under _cond
        self._inflight_reqs: List[_Request] = []
        self._inflight_since: Optional[float] = None
        # batching load/occupancy account: free decode slots (ADMIN
        # stats -> the router's load signal) and the weighted-mean
        # occupancy pair (slot-iterations / iterations). Capacity is
        # known at construction so a stats probe racing worker startup
        # still reads the true idle capacity.
        self._batch_capacity = 0
        if self._buckets:
            self._batch_capacity = (min(self._buckets[-1], self.batch_max)
                                    if self.batch_max > 0
                                    else self._buckets[-1])
        self._batch_free = self._batch_capacity
        self._occ_iters = 0
        self._occ_slots = 0
        # decode-datapath observability (doc/observability.md "Decode
        # datapath"): the per-iteration scheduler flight ring, the
        # per-bucket warm-session/KV account (written by the worker
        # under _cond, read by /batchz, ADMIN stats and the perf
        # ledger's HBM hook), and the convoy detector's latch
        self.batch_flight = (BatchFlightRecorder(batch_flight_cap)
                             if slot_backend is not None else None)
        self.convoy_iters = max(1, int(convoy_iters))
        self._bucket_state = {
            b: {"warm": 0, "active": 0, "kv_bytes": 0,
                "kv_live_bytes": 0, "live_tokens": 0,
                "alloc_tokens": 0}
            for b in self._buckets}
        # paged-KV pool account mirror (worker-written under _cond from
        # the slot backend's kv_pool_account() hook; None on dense/solo
        # backends) — /batchz, ADMIN stats and the /metrics block
        # series read it instead of re-asking the backend
        self._pool_state: Optional[dict] = None
        self._convoy = False         # latched while a convoy holds
        self._convoys = 0            # episodes (0->1 transitions)
        self._convoy_since = 0       # iteration ordinal of the latch
        # KV memory-pressure latch (doc/robustness.md "Memory
        # governance"): latches when the pool's FREE headroom (the
        # block-exact mirror of the HBM ledger's decode headroom —
        # the pool is sized under perf.decode_pool_cap_bytes) drops
        # under kv_pressure_pct percent; while latched the worker
        # sheds retained conversation blocks (kv_shed_retained hook)
        # toward kv_pressure_clear_pct and clears there (hysteresis).
        # 0 disables the latch.
        self.kv_pressure_pct = float(kv_pressure_pct)
        self.kv_pressure_clear_pct = max(float(kv_pressure_clear_pct),
                                         float(kv_pressure_pct))
        self._kv_pressure = False    # latched under low headroom
        self._kv_pressures = 0       # episodes (0->1 transitions)
        self._kv_shed_blocks = 0     # retained blocks shed by the latch
        # autopsy episode windows (utils/autopsy.py): monotonic
        # [t0, t1] spans of the convoy / KV-pressure latches — the
        # classifier intersects a request's [arrival, answer] span
        # with these to attribute convoy_victim / eviction_storm
        # seconds. Single-thread discipline: latch, clear and the
        # _observe_request reads all run on the worker thread
        self._convoy_t0: Optional[float] = None
        self._convoy_episodes: deque = deque(maxlen=64)
        self._kvp_t0: Optional[float] = None
        self._kvp_episodes: deque = deque(maxlen=64)
        self._iter_ord = 0           # lifetime step-iteration ordinal
        self._kv_total = 0           # decode_kv_bytes mirror (worker-
        #                              written, read lock-free)
        # per-turn scheduler journal (worker-thread only): admissions /
        # retirements since the last ringed iteration record
        self._turn_admitted: List[list] = []
        self._turn_retired: List[list] = []
        self._seq = 0
        self._worker_thread: Optional[threading.Thread] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._sock: Optional[socket.socket] = None
        self.port: Optional[int] = None
        # live per-connection writer states (_ConnState): drain waits for
        # their queued responses to reach the kernel before returning —
        # the writer threads are daemons, and a response still buffered
        # at interpreter exit would be a silently dropped answer
        self._conn_lock = lockrank.lock("servd.conns")
        self._conns: set = set()
        # warm-grid readiness account (doc/observability.md "Compile
        # flight recorder"): a readiness callable (perf.Ledger.readiness
        # shaped) plus the gate percentage below which health_probe
        # reports "warming" — unset/0 leaves every path byte-identical
        self._warm_readiness: Optional[Callable] = None
        self._warm_ready_pct = 0.0
        # batch rescue (doc/robustness.md "Failover & hedging"): the
        # session the worker is stepping right now (GIL-atomic store,
        # read by the rescue thread), the rescued flag the worker
        # checks around each step, and the watchdog thread itself
        self._cur_sess = None
        self._batch_rescued = False
        self._rescue_thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------
    def start(self) -> "ServeFrontend":
        telemetry.gauge("serve.queue_depth", 0)
        telemetry.gauge("serve.in_flight", 0)
        telemetry.gauge("serve.batch_occupancy", 0)
        # declare the latency series up front: a dashboard (or the
        # acceptance scrape) must see serve_ttft_seconds buckets exist
        # BEFORE the first request, and /statusz shows them as "n/a"
        for name in ("serve.request", "serve.queue_wait", "serve.ttft",
                     "serve.decode_per_token"):
            telemetry.declare_hist(name)
        if self.slot_backend is not None:
            # the batching dispatcher's queue-age distribution (head-
            # of-queue age sampled once per decode iteration): declared
            # up front like the latency series — the convoy acceptance
            # scrapes its buckets before the first flood
            telemetry.declare_hist("serve.queue_age")
        # conservation laws (doc/observability.md "Metric conservation
        # laws"): the books auditor re-proves the serving invariants
        # continuously — accepted vs outcomes + queue + in-flight,
        # tenant charges vs the door books, and (paged backends) the
        # block-pool equation. Registered here, unregistered at drain;
        # a latched violation survives the unregister by design.
        telemetry.audit_register("serve.books", self._law_books)
        telemetry.audit_register("serve.tenant_books",
                                 self._law_tenant_books)
        pool_law = getattr(getattr(self.slot_backend, "alloc", None),
                           "books_law", None)
        if pool_law is not None:
            telemetry.audit_register("kv.blocks", pool_law)
        target = (self._worker_run_batched if self.slot_backend is not None
                  else self._worker_run)
        self._worker_thread = threading.Thread(
            target=target, name="cxn-servd-worker", daemon=True)
        self._worker_thread.start()
        if self.slot_backend is not None and self.stall_after_s > 0:
            # the batch-rescue watchdog: a dispatch wedged past the
            # stall bound fails the batch and answers ERR backend so
            # the requests become replayable losses upstream instead
            # of hostages (doc/robustness.md "Failover & hedging")
            self._rescue_thread = threading.Thread(
                target=self._rescue_run, name="cxn-servd-rescue",
                daemon=True)
            self._rescue_thread.start()
        return self

    def listen(self, port: int = 0, host: str = "") -> int:
        """Bind the TCP listener (port 0 = ephemeral; loopback unless
        ``host`` widens it — the protocol is unauthenticated) and start
        the accept thread. Returns the bound port."""
        self._sock = socket.create_server((host or "127.0.0.1", int(port)))
        self._sock.settimeout(0.25)
        self.port = self._sock.getsockname()[1]
        self._accept_thread = threading.Thread(
            target=self._accept_run, name="cxn-servd-accept", daemon=True)
        self._accept_thread.start()
        telemetry.event({"ev": "serve_listen", "port": self.port})
        return self.port

    @property
    def listening(self) -> bool:
        return self._sock is not None

    @property
    def draining(self) -> bool:
        return self._draining

    def stats(self) -> dict:
        with self._slock:
            return dict(self._stats)

    def tenant_stats(self) -> dict:
        """Per-tenant counter snapshot ({tenant: {key: n}}): each
        tenant reconciles accepted == served + errors + shed +
        deadline, exactly like the frontend-wide books."""
        with self._slock:
            return {t: dict(st) for t, st in self._tstats.items()}

    def _bump_tenant(self, tenant: Optional[str], *names: str) -> None:
        """Per-tenant half of _bump: the reconciling counter subset,
        mirrored into ``serve.tenant.<t>.<key>`` telemetry counters —
        series the fleet federation sums per tenant exactly like the
        frontend-wide serve.* ones (tenant names are conf-bounded, so
        the series set is bounded too)."""
        if not self._tenants or tenant not in self._tstats:
            return
        keys = [n for n in names if n in _TENANT_KEYS]
        if not keys:
            return
        with self._slock:
            st = self._tstats[tenant]
            for k in keys:
                st[k] += 1
        for k in keys:
            telemetry.count("serve.tenant.%s.%s" % (tenant, k))

    def _slo_observe(self, tenant: Optional[str], ok: bool,
                     ttft_s=None, latency_s=None) -> None:
        """Feed the frontend-wide SLO account AND the request's
        tenant's own tracker — per-tenant error-budget floors are what
        keep a noisy tenant's sheds from burning the victim's budget."""
        if self.slo is not None:
            self.slo.observe(ok=ok, ttft_s=ttft_s, latency_s=latency_s)
        if tenant is not None:
            tr = self.slo_tenants.get(tenant)
            if tr is not None:
                tr.observe(ok=ok, ttft_s=ttft_s, latency_s=latency_s)

    def mean_occupancy(self) -> Optional[float]:
        """Weighted-mean batch occupancy over decode iterations (None
        before the first) — the honest form of ``serve.batch_occupancy``
        (a last-write gauge scraped between batches lies). Solo dispatch
        counts each request as one iteration at occupancy 1."""
        if not self._occ_iters:
            return None
        return self._occ_slots / float(self._occ_iters)

    def decode_kv_bytes(self) -> int:
        """Total allocated decode KV-cache bytes (0 on the solo path)
        — dense: summed across the warm sessions' cache arrays; paged:
        the block pool's REAL nbytes (block-exact, free blocks
        included — they are allocated HBM). The perf ledger's
        HBM-account hook (``perf.set_decode_kv``): the decode cache is a
        first-class HBM consumer next to the program footprints.
        Lock-free (a benign read of the worker's GIL-atomic mirror):
        /metrics renders already take the admission lock once for the
        batch snapshot, and the hook must not take it a second time
        per scrape."""
        return self._kv_total

    def batch_snapshot(self, ring: int = 0) -> Optional[dict]:
        """The decode-datapath observability snapshot (None on the solo
        path): per-bucket warm-session + active-slot + KV accounts, the
        frontend-wide live-vs-allocated cache utilization
        (``kv_live_pct`` — the padding+dead-slot waste a paged KV cache
        would reclaim, ROADMAP item 2), the bucket-rounding
        ``slot_waste_pct`` (warm slots not decoding), the convoy latch
        + episode count, and the lifetime iteration tallies. ``ring >
        0`` appends the newest ``ring`` iteration records. Behind
        statusd ``/batchz``, the ``cxxnet_decode_*`` /metrics families,
        and the ``/metrics?json=1`` federation feed."""
        if self.slot_backend is None:
            return None
        with self._cond:
            buckets = {str(b): dict(bs) for b, bs
                       in sorted(self._bucket_state.items())}
            free = self._batch_free
            qd = len(self._q)
            pool = (dict(self._pool_state)
                    if self._pool_state is not None else None)
        kv = sum(bs["kv_bytes"] for bs in buckets.values())
        kv_live = sum(bs["kv_live_bytes"] for bs in buckets.values())
        warm_slots = sum(int(b) * bs["warm"]
                         for b, bs in buckets.items())
        act = sum(bs["active"] for bs in buckets.values())
        fl = self.batch_flight
        snap = {"buckets": buckets, "capacity": self._batch_capacity,
                "free_slots": free, "queue_depth": qd,
                "kv_bytes": kv, "kv_live_bytes": kv_live,
                "kv_live_pct": round(100.0 * kv_live / kv, 2)
                if kv else None,
                "slot_waste_pct":
                round(100.0 * (warm_slots - act) / warm_slots, 2)
                if warm_slots else None,
                "convoy": 1 if self._convoy else 0,
                "convoys": self._convoys,
                "convoy_iters": self.convoy_iters,
                "iterations": fl.iterations,
                "slot_iterations": fl.slot_iterations,
                "mean_occupancy": self.mean_occupancy(),
                "flight_cap": fl.cap}
        if pool is not None:
            # the paged-KV pool account (block-exact; shared across
            # buckets, so it rides ONCE at the top level, not per
            # bucket). prefix_hit_rate is TOKEN-weighted: the share of
            # admitted prompt tokens served from resident shared
            # blocks instead of being re-prefilled — the bench's
            # prefix-reuse headline.
            pt = pool.get("prompt_tokens", 0)
            pool["prefix_hit_rate"] = (
                round(100.0 * pool.get("prefix_hit_tokens", 0) / pt, 2)
                if pt else None)
            # the RETAINED sub-source of the hit rate (tokens revived
            # from the conversation cache — refcount-0 blocks a new
            # turn re-admitted) and the retained share of the pool:
            # the multi-turn bench's warm-trie evidence
            pool["retained_hit_rate"] = (
                round(100.0 * pool.get("retained_hit_tokens", 0)
                      / pt, 2) if pt else None)
            bt = pool.get("blocks_total", 0)
            pool["kv_retained_pct"] = (
                round(100.0 * pool.get("blocks_retained", 0) / bt, 2)
                if bt else None)
            snap["pool"] = pool
        if ring > 0:
            snap["flight"] = fl.list(ring)
        return snap

    def _eval_convoy(self, bucket: int, free: int, slots_snap,
                     qd: int, qage) -> Optional[int]:
        """The convoy verdict for one iteration (worker thread only):
        a long sequence PINS the bucket — some slot has been aboard >=
        ``convoy_iters`` step iterations — while queued work waits with
        zero free slots. Latched (one ``decode_convoy`` transition
        event per episode, never per-iteration spam; the clearing
        transition carries the episode length); ``serve.convoys``
        counts episodes. Returns the age skew (oldest slot vs the
        median of its batchmates, None without batchmates) for the
        iteration record."""
        ages = [row[2] for row in slots_snap]
        skew = None
        if ages:
            mx = max(ages)
            others = sorted(ages)
            others.remove(mx)
            if others:
                skew = mx - others[len(others) // 2]
        on = bool(qd > 0 and free == 0 and ages
                  and max(ages) >= self.convoy_iters)
        if on and not self._convoy:
            self._convoy = True
            self._convoys += 1
            self._convoy_since = self._iter_ord
            self._convoy_t0 = time.monotonic()
            pinned = max(slots_snap, key=lambda r: r[2])
            telemetry.count("serve.convoys")
            telemetry.event({
                "ev": "decode_convoy", "convoy": 1, "bucket": bucket,
                "pinned": pinned[1], "slot": pinned[0],
                "age_iters": pinned[2], "skew_iters": skew,
                "queue_depth": qd,
                "queue_age_s": round(qage, 6)
                if qage is not None else None})
        elif self._convoy and not on:
            self._convoy = False
            if self._convoy_t0 is not None:
                self._convoy_episodes.append(
                    (self._convoy_t0, time.monotonic()))
                self._convoy_t0 = None
            telemetry.event({
                "ev": "decode_convoy", "convoy": 0,
                "episode_iters": self._iter_ord - self._convoy_since})
        return skew

    def _record_iteration(self, bucket: int, slots_snap, step_s,
                          qd: int, qage, occupancy_after: int = 0,
                          error=None, stepped: bool = True) -> None:
        """File one scheduler turn in the flight ring and feed the
        derived series — called AFTER the admission lock is released,
        from the snapshot ``_publish_batch_state`` took under it.
        ``occupancy_after`` is the composition LEFT after the turn's
        retirements: it holds until the next composition change, which
        is what lets the report reconstruct exact per-iteration
        occupancy from transition-only events (the event at iteration
        N weighs N itself at ``occupancy`` and N+1..next-event-1 at
        ``occupancy_after``). ``stepped=False`` flushes a turn that ran
        NO decode pass (every admission finished at prefill, or every
        sequence deadline-retired) so its admissions/retirements are
        never lost or misattributed to a later iteration; such records
        stay out of the occupancy tallies. The JSONL ``batch_iteration``
        event is transition-only (emitted when the composition changed
        — never per token); the ring keeps every iteration."""
        ads, self._turn_admitted = self._turn_admitted, []
        rets, self._turn_retired = self._turn_retired, []
        skew = self._eval_convoy(bucket, self._batch_free, slots_snap,
                                 qd, qage)
        kv = kv_live = 0
        for bs in self._bucket_state.values():   # worker-owned reads
            kv += bs["kv_bytes"]
            kv_live += bs["kv_live_bytes"]
        rec = {"iter": self._iter_ord,
               # cxxlint: disable=wallclock — iteration epoch aligning
               # the slot-Gantt lanes with request flight records
               # (never subtracted from a monotonic clock)
               "t_wall": round(time.time(), 6),
               "bucket": bucket, "occupancy": len(slots_snap),
               "occupancy_after": int(occupancy_after),
               "step_ms": round(step_s * 1e3, 3)
               if step_s is not None else None,
               "slots": slots_snap,
               "admitted": ads, "retired": rets,
               "queue_depth": qd,
               "queue_age_s": round(qage, 6)
               if qage is not None else None,
               "kv_live_pct": round(100.0 * kv_live / kv, 2)
               if kv else None,
               "age_skew": skew,
               "convoy": 1 if self._convoy else 0}
        ps = self._pool_state             # worker-owned write/read
        if ps is not None:
            # the paged pool's free-list level at this iteration: the
            # /batchz ring's view of block pressure building toward an
            # admission wait (kv_defer) — next to the queue columns it
            # answers "queued because slots or because blocks?"
            rec["blocks_free"] = int(ps.get("blocks_free", 0))
            rec["blocks_total"] = int(ps.get("blocks_total", 0))
        if not stepped:
            rec["stepped"] = 0
        if error is not None:
            rec["error"] = str(error)[:200]
        self.batch_flight.record(rec)
        if qage is not None and stepped:
            telemetry.hist("serve.queue_age", qage)
        if ads or rets or error is not None:
            ev = {"ev": "batch_iteration", "iter": self._iter_ord,
                  "bucket": bucket, "occupancy": len(slots_snap),
                  "occupancy_after": int(occupancy_after),
                  "queue_depth": qd, "step_ms": rec["step_ms"],
                  "admitted": [a[0] for a in ads],
                  "retired": [r[0] for r in rets]}
            if not stepped:
                ev["stepped"] = 0
            if error is not None:
                ev["error"] = rec["error"]
            telemetry.event(ev)

    # -- health (statusd probes) ---------------------------------------
    def _stalled_for(self) -> float:
        """Seconds the CURRENT dispatch has been inside the backend
        (0.0 when idle) — benign unlocked reads of GIL-atomic stores."""
        t0 = self._inflight_since
        if not self._inflight or t0 is None:
            return 0.0
        return time.monotonic() - t0

    def set_warm_account(self, readiness_fn: Callable,
                         ready_pct: float = 0.0) -> None:
        """Register the warm-grid readiness account (a zero-arg
        callable returning ``perf.Ledger.readiness()``-shaped dicts)
        and, optionally, the gate: with ``ready_pct > 0`` the health
        probe reports ``warming`` (503, router state WARMING — probed
        but not routed) until at least that percentage of the expected
        program grid has compiled. 0 keeps the replica routable while
        cold — it serves, it just pays cliffs — but the ADMIN
        ``warm_programs``/``expected_programs`` ints still federate."""
        self._warm_readiness = readiness_fn
        self._warm_ready_pct = float(ready_pct)

    def warm_programs(self) -> Optional[Tuple[int, int, float]]:
        """``(warm, expected, ready_pct)`` from the registered warm
        account, or None when there is no account / no expected grid —
        absence is the capability signal (ADMIN omits the keys, the
        fleet table shows "-")."""
        fn = self._warm_readiness
        if fn is None:
            return None
        try:
            rd = fn() or {}
        except Exception:
            return None
        if rd.get("ready_pct") is None:
            return None
        return (int(rd.get("warm", 0)), int(rd.get("expected", 0)),
                float(rd["ready_pct"]))

    def health_probe(self) -> Tuple[bool, str]:
        """Readiness: NOT ready while draining, while the circuit
        breaker is anything but closed (open, or a half-open probe still
        unresolved), while the warm-grid gate (``set_warm_account``) is
        armed and unmet, or while the current dispatch has been stuck
        inside the backend past ``stall_after_s`` — the "don't route
        traffic here" signal."""
        if self._draining:
            return False, "draining: not accepting new requests"
        st = self.breaker.state
        if st != "closed":
            return False, "circuit breaker %s" % self.breaker.describe()
        if self._warm_ready_pct > 0:
            wp = self.warm_programs()
            if wp is not None and wp[2] < self._warm_ready_pct:
                return False, ("warming: %d/%d programs compiled "
                               "(%.1f%% ready, gate %.0f%%)"
                               % (wp[0], wp[1], wp[2],
                                  self._warm_ready_pct))
        stalled = self._stalled_for()
        if self.stall_after_s > 0 and stalled > self.stall_after_s:
            return False, ("backend stalled: request in flight for "
                           "%.0fs (bound %.0fs)"
                           % (stalled, self.stall_after_s))
        return True, "serving (breaker closed)"

    def liveness_probe(self) -> Tuple[bool, str]:
        """Liveness: the process is still functional — a dead worker
        thread (not a drained one), or a backend wedged past TWICE the
        stall bound (first stop routing, then restart), means restart,
        not just unroutable."""
        t = self._worker_thread
        if t is not None and not t.is_alive() and not self._stop:
            return False, "serve worker thread died"
        stalled = self._stalled_for()
        if self.stall_after_s > 0 and stalled > 2 * self.stall_after_s:
            return False, ("backend wedged: request in flight for %.0fs "
                           "(2x the %.0fs stall bound)"
                           % (stalled, self.stall_after_s))
        return True, "alive"

    # -- conservation laws (telemetry.BooksAuditor) --------------------
    def _law_books(self) -> Optional[str]:
        """``accepted == served + errors + shed + deadline + queued +
        in-flight``, at every instant. A sync rejection bumps accepted
        and its outcome in ONE _slock section, so outcomes can never
        exceed accepted in any snapshot — that direction latches
        immediately. The forward direction has microsecond limbo
        windows (a fair-share eviction and drain leftovers leave the
        queue under the admission lock but are answered OUTSIDE it),
        so a forward violation must PERSIST across several
        stable-snapshot brackets before it returns a detail. A torn
        bracket (the stats moved while the queue was read) is
        inconclusive, never a latch."""
        detail = None
        for _ in range(6):
            with self._slock:
                s1 = dict(self._stats)
            with self._cond:
                depth = len(self._q)
                infl = self._inflight
            with self._slock:
                s2 = dict(self._stats)
            if s1 != s2:
                return None          # the books moved mid-bracket
            a = s1["accepted"]
            o = (s1["served"] + s1["errors"] + s1["shed"]
                 + s1["deadline"])
            if o > a:
                return ("serve books: outcomes %d exceed accepted %d "
                        "(served %d + errors %d + shed %d + deadline "
                        "%d)" % (o, a, s1["served"], s1["errors"],
                                 s1["shed"], s1["deadline"]))
            if a <= o + depth + infl:
                return None
            detail = ("serve books: accepted %d != outcomes %d + "
                      "queued %d + in-flight %d"
                      % (a, o, depth, infl))
            time.sleep(0.005)        # let an in-limbo answer land
        return detail

    def _law_tenant_books(self) -> Optional[str]:
        """Per-tenant charges never exceed the door books, key by key.
        The frontend-wide counter is bumped before the tenant's and
        both live under _slock, so ONE combined snapshot makes
        ``sum_t tenant[k] <= global[k]`` exact — no persistence
        dance needed."""
        if not self._tenants:
            return None
        with self._slock:
            g = dict(self._stats)
            ts = {t: dict(st) for t, st in self._tstats.items()}
        for k in _TENANT_KEYS:
            tot = sum(st[k] for st in ts.values())
            if tot > g[k]:
                return ("tenant books: tenant %s charges sum to %d, "
                        "the door counted %d" % (k, tot, g[k]))
        return None

    # -- accounting ----------------------------------------------------
    def _bump(self, *names: str) -> None:
        """Bump one or more stat counters ATOMICALLY: a synchronously
        rejected request's ``accepted`` and its outcome (errors/shed/
        deadline) land in one critical section, so a concurrent
        ``stats()`` snapshot — drain's final reconciliation — can never
        observe a torn ``accepted > served+errors+shed+deadline``."""
        with self._slock:
            for name in names:
                self._stats[name] += 1
            if any(name in _PROGRESS_KEYS for name in names):
                # applied under the lock: two racing bumps must publish
                # their snapshots in order, or a stale one could make
                # the progress gauges transiently regress
                statusd.update_progress(
                    **{k: self._stats[k] for k in _PROGRESS_KEYS})
        for name in names:
            telemetry.count(_COUNTERS[name])

    def _send(self, reply, text: str) -> bool:
        """Deliver one response line; a reply that raises (client hung up
        mid-request) is counted, never propagated — the server outlives
        every client."""
        try:
            reply(text)
            return True
        except Exception:
            self._bump("client_gone")
            return False

    def _claim(self, req: _Request) -> bool:
        """Claim a request's exactly-once answer slot (see _finish)."""
        with req._alock:
            if req.answered:
                return False
            req.answered = True
            return True

    def _finish(self, req: _Request, text: str, *outcome: str) -> bool:
        """Answer a queued request EXACTLY ONCE, bumping its outcome
        counters only on the winning side — drain can give up on a
        request whose backend wedged past the budget while the worker
        might still complete it later; whoever claims first accounts
        and replies, the loser is a no-op. Returns whether THIS call
        won the answer slot (drain uses it to account the loss)."""
        if not self._claim(req):
            return False
        if outcome:
            self._bump(*outcome)
            self._bump_tenant(req.tenant, *outcome)
        self._send(req.reply, text)
        req.done.set()
        return True

    def _finish_observed(self, req: _Request, text: str, counter: str,
                         outcome: str, tc, queue_wait: float,
                         t_pop: float, t_back: float, t_end: float,
                         wall: float, ntok: int,
                         occupancy: Optional[int] = None,
                         batch=None) -> None:
        """Terminal step for every dequeued request: claim the
        exactly-once answer slot, publish the request's telemetry
        (flight record, SLO account, TTFT series), and only THEN send
        the response — a client synchronized on the response line can
        immediately read /trace?request=<id>. A lost claim means drain
        already answered this request (gave it up as wedged past the
        budget): record outcome "abandoned" — the phases are real work
        the backend did, but the client never received this answer —
        instead of falsely logging a served/ok request."""
        won = self._claim(req)
        self._observe_request(req, tc, outcome if won else "abandoned",
                              queue_wait, t_pop, t_back, t_end, wall,
                              ntok, occupancy=occupancy, batch=batch)
        if won:
            self._bump(counter)
            self._bump_tenant(req.tenant, counter)
            self._send(req.reply, text)
            req.done.set()

    # -- request intake ------------------------------------------------
    def _parse(self, line: str):
        """One request line -> ("req", toks, rel_deadline_s, trace_id,
        tenant) | ("admin", args) | ("err", cls, msg). ``trace_id`` is
        None unless the line carried a valid ``TRACE <id>`` prefix;
        ``tenant`` is the ``TENANT <id>`` prefix, or the configured
        default for prefix-less clients (None in single-tenant mode)."""
        parts = line.split()
        if not parts:
            return ("err", "empty", "request line has no tokens")
        # the cross-process trace id (module docstring): validated by
        # the shared checker, adopted as the request id below.
        # Malformed ids are a protocol violation — deterministic, never
        # dispatched, and distinct from "parse" so an OLD server's
        # rejection of the prefix itself (ERR parse: TRACE is not an
        # integer token) stays distinguishable on the wire
        trace_id, proto_detail, parts = parse_trace_prefix(parts)
        if proto_detail is not None:
            return ("err", "proto", proto_detail)
        if trace_id is not None and not parts:
            return ("err", "empty", "TRACE with no request line")
        # the tenant prefix (TRACE first, then TENANT, then DEADLINE):
        # same validation discipline as TRACE — malformed is a
        # deterministic protocol violation, never dispatched. An
        # unknown tenant on a frontend WITH a tenant table is refused
        # too (the table bounds queue/metric cardinality); without a
        # table the id is recorded for observability and fairness is
        # off — the pre-tenant behavior, byte for byte
        tenant, proto_detail, parts = parse_tenant_prefix(parts)
        if proto_detail is not None:
            return ("err", "proto", proto_detail)
        if tenant is not None and not parts:
            return ("err", "empty", "TENANT with no request line")
        if self._tenants:
            if tenant is None:
                tenant = self.tenant_default
            elif tenant not in self._tenants:
                return ("err", "proto",
                        "tenant %s is not in the configured tenant "
                        "table" % tenant)
        if parts[0] == "ADMIN":
            return ("admin", parts[1:])
        deadline = (self.deadline_ms / 1e3) if self.deadline_ms > 0 \
            else None
        if parts[0] == "DEADLINE":
            if len(parts) < 2:
                return ("err", "parse", "DEADLINE needs a millisecond "
                        "bound")
            try:
                deadline = float(parts[1]) / 1e3
            except ValueError:
                return ("err", "parse", "DEADLINE bound %r is not a "
                        "number" % parts[1])
            if not (0 <= deadline < float("inf")):
                # float() accepts 'nan'/'inf'/negatives; a NaN deadline
                # compares False everywhere and silently DISABLES the
                # deadline — a client framing bug must get ERR parse,
                # not an unbounded request (NaN fails both comparisons)
                return ("err", "parse", "DEADLINE bound %r is not a "
                        "finite non-negative number" % parts[1])
            parts = parts[2:]
            if not parts:
                return ("err", "empty", "DEADLINE with no request tokens")
        try:
            toks = [int(t) for t in parts]
        except ValueError:
            return ("err", "parse", "non-integer token in request")
        if self.vocab and not all(0 <= t < self.vocab for t in toks):
            return ("err", "parse",
                    "token id outside vocab_size %d" % self.vocab)
        return ("req", toks, deadline, trace_id, tenant)

    def submit(self, line: str, reply, wait: bool = False):
        """Admit one request line. ``reply`` is called EXACTLY ONCE with
        the single response line — synchronously for rejections (shed /
        parse / draining: the fast path that never touches the worker),
        from the worker thread otherwise. ``wait=True`` blocks until the
        request is answered (the stdin pump: serial by construction, so
        responses stay in request order). Returns the request's done
        Event, or None when the line was answered synchronously."""
        parsed = self._parse(line)
        if parsed[0] == "admin":
            # the drain check and the scheduling are one critical
            # section with drain()'s flag flip (like the request path):
            # a drained frontend must not promise "OK reload scheduled"
            # for a reload no worker will ever run
            with self._cond:
                if self._draining or self._stop:
                    text = "ERR draining server is shutting down"
                else:
                    self._bump("admin")
                    args = parsed[1]
                    if args and args[0] == "reload":
                        self.request_reload()
                        text = "OK reload scheduled"
                    elif args and args[0] == "stats":
                        # counters plus the LIVE load gauges (the fleet
                        # router's load signal rides here too, not just
                        # /metrics) — read under this lock, so the
                        # snapshot is consistent with the queue
                        live = dict(self.stats(),
                                    queue_depth=len(self._q),
                                    in_flight=self._inflight)
                        # per-tenant books ride the same line as
                        # tenant.<id>.<key>=N — the router's fleet
                        # aggregation sums them like any other key, so
                        # fleet-wide per-tenant reconciliation is free
                        for t, st in self.tenant_stats().items():
                            for k, v in st.items():
                                live["tenant.%s.%s" % (t, k)] = v
                        if self.slot_backend is not None:
                            # free decode slots (bucket capacity −
                            # active): the router's prefer-the-replica-
                            # that-can-batch-it-in signal. Old replicas
                            # simply omit the field — backward
                            # compatible by absence.
                            live["free_slots"] = self._batch_free
                            # per-bucket warm-session + active-slot
                            # counts (bucket.<b>.warm / .active): the
                            # per-bucket load signal the router's
                            # /fleetz shows and disaggregated
                            # scheduling will route on — same
                            # backward-compatibility-by-absence
                            live["batch_buckets"] = len(self._buckets)
                            for b, bs in sorted(
                                    self._bucket_state.items()):
                                live["bucket.%d.warm" % b] = bs["warm"]
                                live["bucket.%d.active" % b] = \
                                    bs["active"]
                                if self._pool_state is not None:
                                    live["bucket.%d.blocks_held" % b] \
                                        = bs.get("blocks_held", 0)
                            if self._pool_state is not None:
                                # paged-KV pool load (process-global —
                                # the pool is shared across buckets, so
                                # these are TOP-level keys the fleet
                                # aggregation can sum exactly; same
                                # absence-is-the-capability-signal
                                # discipline as free_slots)
                                ps = self._pool_state
                                live["kv_blocks_total"] = \
                                    ps.get("blocks_total", 0)
                                live["kv_blocks_free"] = \
                                    ps.get("blocks_free", 0)
                                live["kv_retained_blocks"] = \
                                    ps.get("blocks_retained", 0)
                                live["kv_retained_hits"] = \
                                    ps.get("retained_hits", 0)
                                live["kv_pressure"] = \
                                    1 if self._kv_pressure else 0
                        wp = self.warm_programs()
                        if wp is not None:
                            # warm-grid readiness (the compile-cliff
                            # account): compiled vs expected serving
                            # programs — the router federates these
                            # onto /fleetz as the warm fraction, and
                            # absence (no registered grid) is the
                            # capability signal
                            live["warm_programs"] = wp[0]
                            live["expected_programs"] = wp[1]
                        text = "OK " + " ".join(
                            "%s=%d" % kv for kv in sorted(live.items()))
                    else:
                        text = ("ERR parse unknown ADMIN command %r"
                                % " ".join(args))
            self._send(reply, text)
            return None
        req = None
        shed = False
        shed_rec = None
        evicted = None
        tenant = parsed[4] if parsed[0] == "req" else None
        # admission decision + accounting in ONE critical section with
        # the drain flag: after drain() flips _draining (under this
        # lock) no request can slip an accepted count past its final
        # stats snapshot — a late arrival is refused WITHOUT entering
        # the accounting (it was never accepted; it still gets its one
        # response line). The socket write happens after release.
        with self._cond:
            if self._draining or self._stop:
                text = "ERR draining server is shutting down"
            elif parsed[0] == "err":
                _, cls, msg = parsed
                self._bump(*(("accepted", "empty", "errors")
                             if cls == "empty"
                             else ("accepted", "errors")))
                text = "ERR %s %s" % (cls, msg)
            elif self.breaker.blocked():
                # breaker open: shed instantly — no queue, no backend.
                # Third token "breaker" is wire format (module docstring):
                # retryable elsewhere AND "eject me from rotation"
                self._bump("accepted", "shed")
                self._bump_tenant(tenant, "accepted", "shed")
                shed = True
                shed_rec = self._shed_record(parsed, "breaker")
                text = "ERR busy breaker open (circuit)"
            elif len(self._q) >= self.queue_size \
                    and not (self._tenants
                             and not self._q.over_share(tenant)):
                # full queue, and the arrival holds no fair-share claim
                # (single-tenant mode, or a tenant at/over its share).
                # Third token is wire format: "queue" (genuinely out of
                # capacity — never dispatched, instantly retryable on
                # another replica) vs "tenant" (a fairness verdict that
                # holds fleet-wide under the shared tenant table — the
                # router relays it WITHOUT burning a retry)
                self._bump("accepted", "shed")
                self._bump_tenant(tenant, "accepted", "shed")
                shed = True
                if self._tenants and self._q.over_share(tenant):
                    shed_rec = self._shed_record(parsed, "tenant")
                    text = ("ERR busy tenant %s over fair share "
                            "(%d queued / share %d)"
                            % (tenant, self._q.depth(tenant),
                               self._q.shares[tenant]))
                else:
                    shed_rec = self._shed_record(parsed, "queue")
                    text = "ERR busy queue full (%d)" % self.queue_size
            else:
                if len(self._q) >= self.queue_size:
                    # full queue but the arrival is UNDER its fair
                    # share: the overload is borrowed capacity — evict
                    # the newest queued request of the tenant most over
                    # its share (the shed is charged to the borrower,
                    # answered after the lock) and admit the arrival
                    evicted = self._q.evict_over_share(tenant)
                    if evicted is None:
                        # queue full of in-share traffic: genuine
                        # capacity exhaustion, shed the arrival
                        self._bump("accepted", "shed")
                        self._bump_tenant(tenant, "accepted", "shed")
                        shed = True
                        shed_rec = self._shed_record(parsed, "queue")
                        text = ("ERR busy queue full (%d)"
                                % self.queue_size)
                if not shed:
                    _, toks, deadline, tid, tenant = parsed
                    req = _Request(toks, deadline, reply, tenant=tenant)
                    # the request id that threads through the whole
                    # datapath (trace context, flight record,
                    # /trace?request=<id>): a TRACE-propagated id wins
                    # — the router minted ONE id for this request
                    # fleet-wide, and every replica that touches it
                    # must file its flight record under it. The local
                    # counter still advances so TRACE-less requests
                    # keep their dense local ids either way.
                    self._rid += 1
                    req.id = tid if tid is not None else str(self._rid)
                    self._bump("accepted")
                    self._bump_tenant(tenant, "accepted")
                    self._q.append(req)
                    telemetry.gauge("serve.queue_depth", len(self._q))
                    self._cond.notify()
                    text = None
        if shed_rec is not None:
            # admission sheds land in the flight ring too: a request the
            # fleet router retried elsewhere leaves a record — under its
            # ONE trace id — on EVERY replica that touched it, so the
            # stitched cross-process trace can show the shed attempt
            # next to the served one (phases are honest zeros: nothing
            # was dequeued, nothing dispatched)
            self.flight.record(shed_rec)
            # ... and a serve_request_done event, so the OFFLINE join
            # (telemetry_report --fleet, keyed on the trace id) shows
            # the shed hop too, not just the live stitch. Phases are
            # null like every never-dispatched event — the report's
            # percentile table must not deflate during the overload
            # these events describe
            ev = {
                "ev": "serve_request_done", "req": shed_rec["id"],
                "outcome": "shed", "shed_at": shed_rec["shed_at"],
                "tokens": 0, "total_s": 0.0, "queue_wait_s": None,
                "dispatch_s": None, "prefill_s": None,
                "decode_s": None, "recompiles": 0}
            if shed_rec.get("tenant") is not None:
                ev["tenant"] = shed_rec["tenant"]
            telemetry.event(ev)
        if evicted is not None:
            # the borrower's newest queued request leaves so the
            # under-share arrival can take its place: answered (and
            # charged) OUTSIDE the admission lock — it was already
            # accepted, so the shed keeps its books reconciling, and
            # the shed is the BORROWER's, never the arriving tenant's
            self._shed_queued(evicted, tenant)
        if req is None:
            if shed:
                # an admission shed (queue full / breaker open /
                # fair-share verdict at accept) is an availability
                # failure the error budget must burn for, exactly like
                # a dispatch-time breaker shed — otherwise a
                # total-overload flood that sheds 99% of traffic at the
                # door keeps cxxnet_slo_burn at 0. The burn lands on
                # the SHED tenant's own window — a noisy tenant's sheds
                # must not page the victim's SLO.
                self._slo_observe(tenant, ok=False)
            self._send(reply, text)
            return None
        if wait:
            req.done.wait()
            return None
        return req.done

    def _shed_record(self, parsed, where: str) -> dict:
        """Flight record for a request shed AT ADMISSION (queue full /
        breaker open). Called under the admission lock — it mints from
        the same id counter accepted requests use, so ids stay unique
        per frontend; a TRACE-propagated id wins like everywhere else.
        Phases are honest zeros (nothing was dequeued or dispatched);
        the record exists so the ONE fleet-wide id names this request
        on every replica that touched it, shed attempts included."""
        _, toks, deadline, tid, tenant = parsed
        self._rid += 1
        return {"id": tid if tid is not None else str(self._rid),
                "outcome": "shed", "shed_at": where,
                "tenant": tenant,
                "tokens_in": len(toks), "tokens_out": 0,
                # cxxlint: disable=wallclock — flight-record arrival
                # epoch (the cross-process stitch key), never subtracted
                "t_wall": round(time.time(), 6),
                "total_s": 0.0, "wall_s": 0.0, "ttft_s": None,
                "tokens_per_s": None,
                "phases": {ph: 0.0 for ph in telemetry.REQUEST_PHASES},
                "recompiles": []}

    def _shed_queued(self, req: _Request, for_tenant: str) -> None:
        """Answer a QUEUED request evicted by the fair-share policy
        (charged to its own — borrowing — tenant): exactly-once answer,
        shed accounting, a flight record + serve_request_done event
        under its id (null phases: it never dispatched), and its
        tenant's SLO burn. Called outside the admission lock."""
        won = self._finish(
            req, "ERR busy tenant %s over fair share (evicted for %s)"
            % (req.tenant, for_tenant), "shed")
        if not won:
            return
        self.flight.record({
            "id": req.id, "outcome": "shed", "shed_at": "tenant",
            "tenant": req.tenant,
            "tokens_in": len(req.toks), "tokens_out": 0,
            "t_wall": round(req.t_wall, 6),
            "total_s": 0.0, "wall_s": 0.0, "ttft_s": None,
            "tokens_per_s": None,
            "phases": {ph: 0.0 for ph in telemetry.REQUEST_PHASES},
            "recompiles": []})
        telemetry.event({
            "ev": "serve_request_done", "req": req.id,
            "outcome": "shed", "shed_at": "tenant",
            "tenant": req.tenant,
            "tokens": 0, "total_s": 0.0, "queue_wait_s": None,
            "dispatch_s": None, "prefill_s": None,
            "decode_s": None, "recompiles": 0})
        self._slo_observe(req.tenant, ok=False)

    # -- hot reload ----------------------------------------------------
    def request_reload(self) -> None:
        """Schedule a model reload between requests. Only a plain
        attribute store — safe to call from a SIGHUP handler (taking a
        lock there could deadlock against the interrupted thread); the
        worker notices within its 0.25s idle poll."""
        self._reload_flag = True

    def _do_reload(self) -> None:
        self._reload_flag = False
        # EVERY processed reload request counts here — success, no-op
        # skip (reload_fn False: already serving the newest checkpoint)
        # and failure alike — while `reloads` counts only real swaps.
        # The fleet router's rolling reload waits on THIS delta, so a
        # no-op roll completes in milliseconds instead of burning its
        # whole per-replica timeout out of rotation.
        self._bump("reload_seen")
        if self.reload_fn is None:
            return
        try:
            ok = self.reload_fn()
        except Exception as e:
            telemetry.count("serve.reload_errors")
            telemetry.event({"ev": "serve_reload", "ok": False,
                            "error": repr(e)[:200]})
            sys.stderr.write("WARNING: servd: model reload failed (%s); "
                             "keeping the current model\n" % (e,))
            return
        if ok is not False:
            self._bump("reloads")
            telemetry.event({"ev": "serve_reload", "ok": True})

    # -- worker --------------------------------------------------------
    def _worker_run(self) -> None:
        while True:
            req = None
            with self._cond:
                while not self._q and not self._stop \
                        and not self._reload_flag:
                    # idle is legitimate silence: disarm the watchdog
                    # channel so an empty queue is not a hang
                    health.pause("serve.worker")
                    self._cond.wait(0.25)
                if self._q:
                    req = self._q.popleft()
                    telemetry.gauge("serve.queue_depth", len(self._q))
                    self._inflight = 1
                    self._inflight_req = req
                    self._inflight_since = time.monotonic()
                elif self._stop:
                    break
            health.beat("serve.worker")
            if self._reload_flag:
                # a checkpoint reload is legitimately silent time, like
                # a backend call: disarm the channel so a large-model
                # reload can't false-alarm (or abort) the watchdog
                health.pause("serve.worker")
                self._do_reload()
                health.beat("serve.worker")
                if req is not None:
                    with self._cond:
                        # reload time is not backend time: restart the
                        # stall clock for the dispatch that follows
                        self._inflight_since = time.monotonic()
            if req is None:
                continue
            try:
                self._dispatch(req)
            finally:
                with self._cond:
                    self._inflight = 0
                    self._inflight_req = None
                    self._inflight_since = None
                    self._cond.notify_all()

    def _dispatch(self, req: _Request) -> None:
        now = time.monotonic()
        t_pop = time.perf_counter()
        queue_wait = now - req.t_arrival
        telemetry.hist("serve.queue_wait", queue_wait)
        if req.deadline is not None and now > req.deadline:
            # expired while queued: answered BEFORE dispatch — the
            # backend never decodes an answer nobody is waiting for
            t_end = time.perf_counter()
            wall = time.monotonic() - req.t_arrival
            self._finish_observed(
                req, "ERR deadline expired %.0fms ago"
                % (1e3 * (now - req.deadline)), "deadline", "deadline",
                None, queue_wait, t_pop, t_pop, t_end, wall, 0)
            return
        if not self.breaker.allow():
            t_end = time.perf_counter()
            wall = time.monotonic() - req.t_arrival
            self._finish_observed(
                req, "ERR busy breaker open (circuit)", "shed", "shed",
                None, queue_wait, t_pop, t_pop, t_end, wall, 0)
            return
        req.seq, self._seq = self._seq, self._seq + 1
        telemetry.gauge("serve.in_flight", 1)
        # occupancy accounting: solo dispatch is one whole-request pass
        # at occupancy 1 — the honest weighted-mean pair (iterations /
        # slot-iterations) reads 1.0 here; the batched dispatcher feeds
        # the same series per decode ITERATION
        self._observe_occupancy(1)
        # the backend call is legitimately silent time on the worker
        # channel — a first-request decode-cache compile (or the
        # recompile after a hot reload) can far outlast any sane
        # watchdog_timeout, and PR 3's rule is that compiles never arm
        # heartbeat channels. Slow backends are watched by deadlines and
        # the breaker; a silently WEDGED one by the stall_after_s bound
        # on this dispatch (health/liveness probes above); the heartbeat
        # watches the worker loop itself.
        health.pause("serve.worker")
        # the trace context tags every span/compile the backend records
        # with this request's id and carries the trainer's first_token
        # mark back out — the TTFT boundary
        tc = telemetry.trace_context(req.id)
        t_back = t_pop
        try:
            with tc:
                t_back = time.perf_counter()
                with telemetry.span("serve.request",
                                    tokens=len(req.toks)):
                    out = self.backend(req.toks, req.seq)
                # the conversion is supervised too: a backend returning a
                # non-iterable-of-ints is a backend failure, not a worker
                # death sentence
                outs = [int(t) for t in out]
            text = " ".join(str(t) for t in outs)
        except Exception as e:
            t_end = time.perf_counter()
            wall = time.monotonic() - req.t_arrival
            health.beat("serve.worker")
            telemetry.gauge("serve.in_flight", 0)
            self.breaker.failure()
            telemetry.count("serve.backend_errors")
            telemetry.event({"ev": "serve_backend_error",
                             "error": repr(e)[:200], "req": req.id})
            # one line, whatever the exception said
            self._finish_observed(
                req, "ERR backend " + " ".join(repr(e).split())[:200],
                "errors", "backend_error", tc, queue_wait, t_pop,
                t_back, t_end, wall, 0)
            return
        t_end = time.perf_counter()
        wall = time.monotonic() - req.t_arrival
        health.beat("serve.worker")
        telemetry.gauge("serve.in_flight", 0)
        self.breaker.success()
        self._finish_observed(req, text, "served", "served", tc,
                              queue_wait, t_pop, t_back, t_end, wall,
                              len(outs))

    # -- batching dispatcher (slot_backend path) -----------------------
    def _observe_occupancy(self, n: int) -> None:
        """One decode pass/iteration with ``n`` sequences aboard: the
        last-write gauge (a glance value) plus the honest weighted-mean
        counter pair — mean occupancy = slot_iterations / iterations,
        exact however the scrape interleaves with batches."""
        self._occ_iters += 1
        self._occ_slots += n
        telemetry.gauge("serve.batch_occupancy", n)
        telemetry.count("serve.batch_iterations")
        telemetry.count("serve.batch_slot_iterations", n)

    def _publish_batch_state(self, sess, active, sessions=None):
        """Refresh the load signals after any slot change: the live
        in-flight gauge, the free-slot count ``ADMIN stats`` reports
        (idle = full capacity; an active session = its free slots),
        and the per-bucket warm-session/KV account. Returns ONE
        consistent queue snapshot ``(queue_depth, head_of_queue_age)``
        taken under the same admission-lock acquisition — the
        per-iteration telemetry (queue-age histogram, iteration ring,
        convoy verdict) records FROM this snapshot after the lock is
        released, never re-taking it per token. The KV accounts are
        read from the sessions BEFORE the lock (host metadata
        arithmetic — the work-outside-the-lock rule)."""
        cap = self._batch_capacity
        free = cap if not active else \
            max(0, min(cap, sess.nslots) - len(active))
        accts = {}
        for b, s in (sessions or {}).items():
            fn = getattr(s, "kv_account", None)
            if fn is None:
                continue
            try:
                accts[b] = fn()
            except Exception:
                pass          # an account must never kill the worker
        # the paged-KV pool account (block-exact: pool_bytes IS the
        # device arrays' nbytes) — host metadata arithmetic, read
        # BEFORE the lock like the per-session accounts
        pool = None
        pool_fn = getattr(self.slot_backend, "kv_pool_account", None)
        if pool_fn is not None:
            try:
                pool = pool_fn()
            except Exception:
                pool = None
        if pool is not None:
            pool = self._kv_pressure_tick(pool, pool_fn)
        with self._cond:
            self._batch_free = free
            qd = len(self._q)
            oldest = None
            if qd:
                t0 = (self._q.oldest_arrival()
                      if isinstance(self._q, _FairQueue)
                      else self._q[0].t_arrival)
                if t0 is not None:
                    oldest = max(0.0, time.monotonic() - t0)
            if sessions is not None:
                for b, bs in self._bucket_state.items():
                    a = accts.get(b) or {}
                    warm = 1 if (b in sessions
                                 and not getattr(sessions[b], "closed",
                                                 False)) else 0
                    bs.update(warm=warm,
                              active=int(a.get("active", 0)),
                              kv_bytes=int(a.get("kv_bytes", 0)),
                              kv_live_bytes=int(a.get("kv_live_bytes",
                                                      0)),
                              live_tokens=int(a.get("live_tokens", 0)),
                              alloc_tokens=int(a.get("alloc_tokens",
                                                     0)),
                              blocks_held=int(a.get("blocks_held", 0)))
                self._pool_state = pool
                # plain-int mirror for decode_kv_bytes: the perf
                # ledger's hook reads it per /metrics scrape, and must
                # not pay this (the admission) lock a second time per
                # render — benign GIL-atomic read, worker-only write.
                # Paged backends charge the POOL's real nbytes (the
                # per-bucket kv_bytes are block-table claims: a shared
                # block counts once per holder, and free blocks are
                # still allocated HBM — the PR 13 conservative-by-one-
                # session caveat is gone: this IS the arrays' nbytes)
                self._kv_total = (
                    int(pool.get("pool_bytes", 0)) if pool is not None
                    else sum(bs["kv_bytes"]
                             for bs in self._bucket_state.values()))
        telemetry.gauge("serve.in_flight", len(active))
        return qd, oldest

    def _kv_pressure_tick(self, pool: dict, pool_fn) -> dict:
        """The low-headroom KV pressure latch (worker thread only,
        OUTSIDE the admission lock — shedding is host metadata
        arithmetic on the single mutating owner). Latches when free
        blocks drop under ``kv_pressure_pct`` percent of the pool,
        sheds retained conversation blocks toward
        ``kv_pressure_clear_pct`` (the ``kv_shed_retained`` hook —
        proactive evict-ahead-of-flood, distinct from the allocator's
        own evict-before-defer at admission), and clears only at the
        higher threshold (hysteresis). One transition-only
        ``kv_pressure`` flight event per episode; the latch itself
        travels in the published pool snapshot (``pressure``) to
        /batchz, ADMIN stats and ``cxxnet_decode_kv_pressure``."""
        total = int(pool.get("blocks_total") or 0)
        if total <= 0 or self.kv_pressure_pct <= 0:
            return pool
        free_pct = 100.0 * int(pool.get("blocks_free") or 0) / total
        if not self._kv_pressure and free_pct < self.kv_pressure_pct:
            self._kv_pressure = True
            self._kv_pressures += 1
            self._kvp_t0 = time.monotonic()
            telemetry.count("serve.kv_pressure")
            telemetry.event({
                "ev": "kv_pressure", "pressure": 1,
                "free_pct": round(free_pct, 2),
                "retained": int(pool.get("blocks_retained") or 0)})
        if self._kv_pressure:
            shed_fn = getattr(self.slot_backend, "kv_shed_retained",
                              None)
            if shed_fn is not None \
                    and int(pool.get("blocks_retained") or 0) > 0:
                target = -(-int(self.kv_pressure_clear_pct * total)
                           // 100)
                try:
                    shed = int(shed_fn(target) or 0)
                except Exception:
                    shed = 0      # a shed must never kill the worker
                if shed > 0:
                    self._kv_shed_blocks += shed
                    telemetry.count("serve.kv_shed_blocks", shed)
                    try:
                        pool = pool_fn() or pool
                    except Exception:
                        pass
                    free_pct = (100.0 * int(pool.get("blocks_free")
                                            or 0) / total)
            if free_pct >= self.kv_pressure_clear_pct:
                self._kv_pressure = False
                if self._kvp_t0 is not None:
                    self._kvp_episodes.append(
                        (self._kvp_t0, time.monotonic()))
                    self._kvp_t0 = None
                telemetry.event({
                    "ev": "kv_pressure", "pressure": 0,
                    "free_pct": round(free_pct, 2)})
        pool = dict(pool)
        pool["pressure"] = 1 if self._kv_pressure else 0
        return pool

    def _drop_inflight(self, req: _Request) -> None:
        """A popped request got its final answer: leave drain's
        give-up list (the popped-but-unanswered account)."""
        with self._cond:
            try:
                self._inflight_reqs.remove(req)
            except ValueError:
                pass
            self._inflight = len(self._inflight_reqs)

    def _gather(self, limit: int, fresh: bool) -> List[_Request]:
        """Pop up to ``limit`` queued requests for admission. A FRESH
        batch (no active slots) waits up to the gather window for more
        to coalesce; mid-decode joins take only what is already queued
        — sequences mid-flight must never stall on the window. Popped
        requests enter ``_inflight_reqs`` under the SAME lock as the
        pop, so drain's accounting never sees a request in neither the
        queue nor the in-flight set."""
        out: List[_Request] = []
        if limit <= 0:
            return out
        # paged-KV block budget (doc/performance.md "Decode KV cache"):
        # a request is popped only when the pool can cover its fresh
        # blocks RIGHT NOW — head-of-queue order, no skip-ahead, so
        # exhaustion is a deterministic FIFO wait (retirements return
        # blocks mid-decode and the next turn's gather admits). The
        # budget is decremented per pop because this turn's admissions
        # have not hit the allocator yet (worst-case: same-turn prefix
        # twins are NOT credited — they defer one turn and then share).
        # Hooks absent (dense/solo backend) => no gate.
        kv_free = None
        need_fn = getattr(self.slot_backend, "kv_fresh_blocks", None)
        free_fn = getattr(self.slot_backend, "kv_free_blocks", None)
        if need_fn is not None and free_fn is not None:
            try:
                kv_free = free_fn()
            except Exception:
                kv_free = None    # the gate must never kill the worker
        deadline = None
        with self._cond:
            while True:
                while self._q and len(out) < limit:
                    if kv_free is not None:
                        # the NEXT pop's block demand — peek() on the
                        # tenant fair queue (its head is virtual-time
                        # order, not arrival order), [0] on the deque
                        peek = getattr(self._q, "peek", None)
                        head = peek() if peek is not None else self._q[0]
                        try:
                            need = need_fn(head.toks)
                        except Exception:
                            need = None
                        if need is not None and need > kv_free:
                            break
                        kv_free -= need or 0
                    req = self._q.popleft()
                    out.append(req)
                    self._inflight_reqs.append(req)
                if out:
                    self._inflight = len(self._inflight_reqs)
                    telemetry.gauge("serve.queue_depth", len(self._q))
                if len(out) >= limit or not fresh or not out \
                        or self.batch_window_s <= 0 \
                        or self._draining or self._stop:
                    break
                if deadline is None:
                    deadline = time.monotonic() + self.batch_window_s
                rem = deadline - time.monotonic()
                if rem <= 0:
                    break
                self._cond.wait(min(rem, 0.05))
        return out

    def _finish_popped(self, req: _Request, text: str, counter: str,
                       outcome: str, tc, queue_wait: float, t_pop: float,
                       t_back: float, ntok: int,
                       occupancy: Optional[int] = None,
                       batch=None) -> None:
        """Terminal answer for a popped request on the batched path —
        the observed finish plus the in-flight bookkeeping drop."""
        t_end = time.perf_counter()
        wall = time.monotonic() - req.t_arrival
        self._finish_observed(req, text, counter, outcome, tc,
                              queue_wait, t_pop, t_back, t_end, wall,
                              ntok, occupancy=occupancy, batch=batch)
        self._drop_inflight(req)

    def _retire_info(self, st: _SlotState) -> dict:
        """Journal a slot retirement in the per-turn scheduler log
        (the iteration ring's ``retired`` column) and return the
        record's scheduling coordinates: bucket, slot index, and the
        [first, last] step-iteration ordinals the sequence was aboard
        (None when it never stepped — n_new == 1 finishes at prefill).
        Two records with the same bucket and overlapping iteration
        ranges shared decode passes — the without-the-ring join
        /requestz readers use."""
        self._turn_retired.append([st.req.id, st.slot])
        return {"bucket": st.bucket, "slot": st.slot,
                "iterations": ([st.first_iter, st.last_iter]
                               if st.first_iter is not None else None),
                "stall_s": round(st.stall_s, 6)}

    def _requeue_head(self, reqs) -> None:
        """Return popped-but-unadmitted requests to the queue HEAD in
        their given (arrival) order — the paged-KV defer path: the
        deferred request and everything popped behind it retry before
        anything that arrived later, so FIFO holds under block
        pressure and two defers can never invert each other (the
        admission loop stops at the first). queue_wait keeps running
        (admission, not pop, ends it)."""
        with self._cond:
            for req in reversed(reqs):
                try:
                    self._inflight_reqs.remove(req)
                except ValueError:
                    continue       # already answered (a drain raced)
                self._q.appendleft(req)
            self._inflight = len(self._inflight_reqs)
            telemetry.gauge("serve.queue_depth", len(self._q))

    def _fail_unadmitted(self, reqs, msg: str) -> None:
        """Answer popped-but-never-admitted requests ``ERR backend``
        (they never reached a slot: no phases, no dispatch) — the
        session-creation-failure and closed-session-leftover paths."""
        t_pop = time.perf_counter()
        now = time.monotonic()
        for req in reqs:
            self._finish_popped(req, msg, "errors", "backend_error",
                                None, now - req.t_arrival, t_pop,
                                t_pop, 0)

    def _admit_one(self, sb, sess, active, req: _Request,
                   stall0: float = 0.0):
        """Admit one popped request into a free slot of ``sess`` (its
        ``queue_wait`` ends HERE — slot admission, not queue pop): the
        solo dispatch-time gates first (expired deadline, breaker,
        backend compatibility), then the request's own b=1 prefill runs
        under its trace context — the per-request prefill phase and the
        first_token TTFT mark are per-slot, never per-batch. Returns
        the slot the request now occupies, or None (rejected, failed,
        or already complete — an ``n_new == 1`` request finishes at
        prefill and records its admission-order occupancy: it never
        shares a decode pass, so the batch-wide stamp does not apply)."""
        t_pop = time.perf_counter()
        now = time.monotonic()
        queue_wait = now - req.t_arrival
        telemetry.hist("serve.queue_wait", queue_wait)
        if req.deadline is not None and now > req.deadline:
            self._finish_popped(
                req, "ERR deadline expired %.0fms ago"
                % (1e3 * (now - req.deadline)), "deadline", "deadline",
                None, queue_wait, t_pop, t_pop, 0)
            return
        if not self.breaker.allow():
            self._finish_popped(
                req, "ERR busy breaker open (circuit)", "shed", "shed",
                None, queue_wait, t_pop, t_pop, 0)
            return
        admits = getattr(sb, "admits", None)
        detail = admits(req.toks) if admits is not None else None
        if detail:
            # a deterministic request defect (e.g. prompt too long for
            # the model): answered as a backend-class error (relayed by
            # the router, never retried) but NOT fed to the breaker —
            # the backend is healthy, the request is not
            self._finish_popped(
                req, "ERR backend " + " ".join(str(detail).split())[:200],
                "errors", "backend_error", None, queue_wait, t_pop,
                t_pop, 0)
            return
        slot = sess.free_slots()[0]
        req.seq, self._seq = self._seq, self._seq + 1
        tc = telemetry.trace_context(req.id)
        self._inflight_since = time.monotonic()
        health.pause("serve.worker")     # prefill may compile
        t_back = t_pop
        try:
            with tc:
                t_back = time.perf_counter()
                first, done = sess.prefill(slot, req.toks, req.seq)
        except kvblocks.KVPoolExhausted:
            # transient block-pool exhaustion (the gather budget lost a
            # race it cannot model, e.g. a same-turn batchmate taking
            # the blocks): the session is OPEN and no device work ran.
            # Hand the verdict back to the worker loop (_KV_DEFER) —
            # it requeues this request AND its unadmitted batchmates
            # at the queue head in arrival order (its queue_wait keeps
            # running) to retry after retirements return blocks. A
            # deterministic wait: never an error, never a breaker
            # count, never a device OOM.
            health.beat("serve.worker")
            self._inflight_since = None
            req.kv_defers += 1
            telemetry.count("serve.kv_defer")
            return _KV_DEFER
        except Exception as e:
            health.beat("serve.worker")
            self._inflight_since = None
            # classify by the session's own verdict: a DEVICE-section
            # failure CLOSES the session (the DecodeSession contract) —
            # that is a backend fault and feeds the breaker; a prefill
            # that raised WITHOUT closing never touched device state
            # (pre-dispatch validation, e.g. a prompt too long for a
            # backend with no admits() hook) — a deterministic request
            # defect that must not poison the breaker, exactly like the
            # admits() rejection above
            if getattr(sess, "closed", False):
                self.breaker.failure()
                telemetry.count("serve.backend_errors")
                telemetry.event({"ev": "serve_backend_error",
                                 "error": repr(e)[:200], "req": req.id})
            self._finish_popped(
                req, "ERR backend " + " ".join(repr(e).split())[:200],
                "errors", "backend_error", tc, queue_wait, t_pop,
                t_back, 0)
            return None
        health.beat("serve.worker")
        self._inflight_since = None
        st = _SlotState(req, tc, queue_wait, t_pop, t_back,
                        [int(first)], len(active) + 1, slot,
                        sess.nslots)
        # seed the batch-level stall this request already paid BEFORE
        # its slot existed (the turn's warm-session creation) — set
        # before the done-at-prefill early completion below so an
        # n_new == 1 request carries it too
        st.stall_s = float(stall0)
        active[slot] = st
        self._turn_admitted.append([req.id, slot])
        if done:
            self._complete_slot(sess, active, slot)
            return None
        return slot

    def _complete_slot(self, sess, active, slot) -> None:
        """A sequence produced its last token: answer, retire the slot
        (the next queued request joins here mid-decode), account."""
        st = active.pop(slot)
        sess.retire(slot)
        t_end = time.perf_counter()
        # the request's backend time (prefill -> its own last token)
        # feeds the serve.request histogram like the solo span does
        telemetry.hist("serve.request", max(0.0, t_end - st.t_back))
        self.breaker.success()
        text = " ".join(str(t) for t in st.toks)
        self._finish_popped(st.req, text, "served", "served", st.tc,
                            st.queue_wait, st.t_pop, st.t_back,
                            len(st.toks), occupancy=st.occ,
                            batch=self._retire_info(st))

    def _retire_expired(self, sess, active) -> None:
        """Per-ITERATION deadline enforcement: an expired sequence
        retires with ``ERR deadline`` between iterations — the others
        keep decoding. Its real prefill/decode phases are recorded
        (the backend did burn that time)."""
        now = time.monotonic()
        for slot, st in list(active.items()):
            req = st.req
            if req.deadline is not None and now > req.deadline:
                del active[slot]
                sess.retire(slot)
                self._finish_popped(
                    req, "ERR deadline expired %.0fms ago (mid-decode)"
                    % (1e3 * (now - req.deadline)), "deadline",
                    "deadline", st.tc, st.queue_wait, st.t_pop,
                    st.t_back, len(st.toks), occupancy=st.occ,
                    batch=self._retire_info(st))

    def _fail_batch(self, sess, active, exc: Exception,
                    count_failure: bool = True) -> None:
        """A decode STEP failed: the whole batch is lost — every active
        sequence is answered ``ERR backend`` (exactly once), the
        breaker counts ONE backend failure, the session is dropped.
        ``count_failure=False`` when the underlying fault was already
        counted (a failed PREFILL closed the session: _admit_one's
        except path counted it — the batch dies of that same fault,
        and one fault must cost the breaker AND the backend-error
        series exactly one count; the event still fires, naming the
        requests the fault took down)."""
        if count_failure:
            self.breaker.failure()
            telemetry.count("serve.backend_errors")
        telemetry.event({"ev": "serve_backend_error",
                         "error": repr(exc)[:200],
                         "reqs": [st.req.id for st in active.values()]})
        msg = "ERR backend " + " ".join(repr(exc).split())[:200]
        for slot, st in list(active.items()):
            try:
                sess.retire(slot)
            except Exception:
                pass               # a rescued (closed) session may
                #                    refuse the retire: the slot dies
                #                    with the session either way
            self._finish_popped(st.req, msg, "errors", "backend_error",
                                st.tc, st.queue_wait, st.t_pop,
                                st.t_back, len(st.toks),
                                occupancy=st.occ,
                                batch=self._retire_info(st))
        active.clear()

    def _rescue_run(self) -> None:
        """Batch-rescue watchdog loop (doc/robustness.md "Failover &
        hedging"): a dispatch wedged inside the backend past the stall
        bound gets its batch EVICTED — every aboard request is
        answered ``ERR backend rescued`` (a replayable loss upstream:
        provably no answer left this replica) instead of sitting
        hostage until the router's stall timeout. Poll cadence scales
        with the bound."""
        tick = max(0.01, min(0.25, self.stall_after_s / 4.0))
        while not self._stop:
            if self._stalled_for() > self.stall_after_s \
                    and not self._batch_rescued:
                self._rescue_batch(self._stalled_for())
            time.sleep(tick)

    def _rescue_batch(self, stalled: float) -> None:
        """Evict the wedged batch: answer every in-flight request
        (exactly once — the answer-slot claim), count ONE breaker
        failure + ``serve.batch_rescues``, close the wedged session.
        The worker, still blocked inside ``sess.step()``, observes
        ``_batch_rescued`` when the backend finally returns (or
        raises on the closed session) and runs the slot/journal
        cleanup with ``count_failure=False`` — one fault, one count.
        The in-flight set is NOT dropped here: ``_stalled_for`` keeps
        reporting the wedge to the health probe until the worker
        actually recovers."""
        sess = self._cur_sess
        since0 = self._inflight_since
        with self._cond:
            reqs = list(self._inflight_reqs)
        if not reqs or since0 is None:
            return
        self._batch_rescued = True
        # verify-then-commit: the step may have ended in the window
        # between the trigger check and the flag write — bail (and
        # un-flag) rather than rescue a batch that is not wedged
        if self._cur_sess is not sess or self._inflight_since != since0:
            self._batch_rescued = False
            return
        self.breaker.failure()
        telemetry.count("serve.batch_rescues")
        telemetry.event({"ev": "serve_batch_rescue",
                         "stalled_s": round(stalled, 3),
                         "reqs": [r.id for r in reqs]})
        msg = ("ERR backend rescued batch wedged %.1fs inside the "
               "backend (stall bound %.1fs; replayable: no answer "
               "left this replica)" % (stalled, self.stall_after_s))
        for req in reqs:
            self._finish(req, msg, "errors")
        if sess is not None:
            try:
                close = getattr(sess, "close", None)
                if close is not None:
                    close()
            except Exception:
                pass

    def _worker_run_batched(self) -> None:
        """The iteration-granularity scheduling loop (module docstring
        "Continuous batching"): coalesce -> admit into slots ->
        per-iteration deadlines -> step every active slot one token ->
        retire finished sequences -> repeat, admitting queued requests
        into freed slots MID-DECODE. Sessions are pooled per bucket and
        stay warm (their programs cache per bucket signature — a
        request joining a warm bucket never recompiles); a model reload
        waits for the in-flight batch, then closes every session."""
        sb = self.slot_backend
        buckets = self._buckets
        cap = self._batch_capacity
        sessions = {}                  # bucket -> warm session
        sess = None                    # current session
        active = {}                    # slot -> _SlotState
        last_bucket = 0                # bucket of the most recent
        #                                session: flush records filed
        #                                after a faulted session was
        #                                evicted (sess = None) must
        #                                name the REAL bucket, not 0

        def close_all():
            for s in sessions.values():
                try:
                    close = getattr(s, "close", None)
                    if close is not None:
                        close()
                except Exception:
                    pass
            sessions.clear()

        while True:
            with self._cond:
                while not self._q and not active and not self._stop \
                        and not self._reload_flag:
                    health.pause("serve.worker")
                    self._cond.wait(0.25)
                if self._stop and not active:
                    break
            health.beat("serve.worker")
            if self._reload_flag and not active:
                # reload only BETWEEN batches: the slot caches hold the
                # old model's K/V — close the warm sessions (their
                # programs die with the old trainer), swap, resume
                health.pause("serve.worker")
                close_all()
                sess = None
                self._do_reload()
                health.beat("serve.worker")
                # the closed sessions released their caches: zero the
                # KV account NOW, not at the next admission — /batchz
                # and the HBM headroom hook must not show a freed
                # cache as still allocated across an idle stretch
                self._publish_batch_state(None, {}, sessions)
                continue
            # --- admit: coalesce queued requests into free slots ---
            if not self._reload_flag:
                sess_stall = 0.0
                if not active:
                    batch = self._gather(cap, fresh=True)
                    if batch:
                        b = next((x for x in buckets
                                  if x >= len(batch)), buckets[-1])
                        last_bucket = b
                        sess = sessions.get(b)
                        if sess is None:
                            try:
                                # warm-session creation compiles the
                                # bucket's admit/step programs OUTSIDE
                                # any request's trace context: the
                                # compile window attributes the cliff
                                # to every request admitted this turn
                                # (compile_stall_s on their flight
                                # records)
                                with telemetry.compile_window(
                                        "session:b%d" % b) as cw:
                                    sess = sessions[b] = sb.session(b)
                                sess_stall = cw.stall_s
                            except Exception as e:
                                # the batch never reached a slot: every
                                # drained request is answered, the
                                # breaker counts one failure
                                self.breaker.failure()
                                telemetry.count("serve.backend_errors")
                                telemetry.event(
                                    {"ev": "serve_backend_error",
                                     "error": repr(e)[:200]})
                                self._fail_unadmitted(
                                    batch, "ERR backend "
                                    + " ".join(repr(e).split())[:200])
                                batch = []
                                sess = None
                else:
                    free = min(len(sess.free_slots()),
                               cap - len(active))
                    batch = self._gather(free, fresh=False) \
                        if free > 0 else []
                leftovers = []
                new_slots = []
                for i, req in enumerate(batch):
                    slot = self._admit_one(sb, sess, active, req,
                                           stall0=sess_stall)
                    if slot is _KV_DEFER:
                        # the pool could not cover this admission (the
                        # gather budget's rare blind spot): it and its
                        # unadmitted batchmates go back to the queue
                        # head in arrival order — nothing popped after
                        # the deferred request may admit ahead of it
                        self._requeue_head([req] + list(batch[i + 1:]))
                        break
                    if slot is not None:
                        new_slots.append(slot)
                    if getattr(sess, "closed", False):
                        # a failed prefill closed the session: stop
                        # admitting — every further prefill would raise
                        # "closed" and spuriously count ANOTHER breaker
                        # failure for the same single fault (one fault,
                        # one count: _admit_one's except path had it)
                        leftovers = batch[i + 1:]
                        break
                # every request admitted THIS turn shares its first
                # decode pass with the whole turn's admissions: stamp
                # the final occupancy on all of them — the sequential
                # per-admit stamp would record 1, 2, 3, 4 for a fully
                # coalesced 4-request batch and /requestz would read
                # "not coalesced" for its first member
                for s in new_slots:
                    if s in active:
                        active[s].occ = len(active)
                if sess is not None and getattr(sess, "closed", False):
                    # the session's device state integrity is unknown:
                    # answer everything that died of the one prefill
                    # fault (no further breaker counts) and evict it
                    # from the warm pool — a broken session left pooled
                    # would poison every later batch
                    self._fail_unadmitted(
                        leftovers, "ERR backend decode session closed "
                        "by a failed prefill")
                    if active:
                        self._fail_batch(
                            sess, active, RuntimeError(
                                "decode session closed by a failed "
                                "prefill"), count_failure=False)
                    sessions = {b: s for b, s in sessions.items()
                                if s is not sess}
                    sess = None
            # --- per-iteration deadline retirement ---
            if active:
                self._retire_expired(sess, active)
            if sess is not None:
                last_bucket = sess.nslots
            qd, qage = self._publish_batch_state(sess, active, sessions)
            if not active:
                b0 = sess.nslots if sess is not None else last_bucket
                if self._turn_admitted or self._turn_retired:
                    # a turn with journal entries but NO decode pass
                    # (every admission finished at prefill, or every
                    # sequence deadline-retired): flush it NOW — left
                    # queued, the entries would be misattributed to
                    # whatever iteration comes next (or lost at drain).
                    # The flush also runs the convoy clear.
                    self._record_iteration(b0, [], None, qd, qage,
                                           occupancy_after=0,
                                           stepped=False)
                else:
                    # nothing to step: the convoy latch must still
                    # clear (the straggler retired / queue drained)
                    self._eval_convoy(b0, self._batch_free, [], qd,
                                      qage)
                continue
            # --- one decode iteration: every active slot, one token ---
            self._observe_occupancy(len(active))
            self._iter_ord += 1
            it_ord = self._iter_ord
            for st in active.values():
                if st.first_iter is None:
                    st.first_iter = it_ord
                st.last_iter = it_ord
            # the iteration's slot map (slot, occupant id, age in step
            # iterations) — snapshotted BEFORE the step so the record
            # reflects exactly the composition that decoded together
            slots_snap = [[s, st.req.id, it_ord - st.first_iter]
                          for s, st in sorted(active.items())]
            bucket = sess.nslots
            self._cur_sess = sess          # the rescue watchdog's view
            self._inflight_since = time.monotonic()
            health.pause("serve.worker")   # a fresh bucket may compile
            t_step = time.perf_counter()
            try:
                # the decode step runs with NO trace context (it is
                # batch-wide work): the compile window catches a
                # first-step cliff and the dispatcher fans it out to
                # every sequence that sat through it
                with telemetry.compile_window(
                        "step:b%d" % bucket) as cw:
                    res = sess.step()
            except Exception as e:
                step_s = time.perf_counter() - t_step
                health.beat("serve.worker")
                self._inflight_since = None
                self._cur_sess = None
                if cw.stall_s:
                    for st in active.values():
                        st.stall_s += cw.stall_s
                # a rescued batch already answered its requests and
                # counted the fault (the watchdog): this cleanup pass
                # must not double the breaker count — the finishes
                # below are abandoned no-ops either way (claims taken)
                rescued = self._batch_rescued
                self._batch_rescued = False
                self._fail_batch(sess, active, e,
                                 count_failure=not rescued)
                # the session's state is suspect: drop it from the pool
                sessions = {b: s for b, s in sessions.items()
                            if s is not sess}
                sess = None
                qd, qage = self._publish_batch_state(sess, active,
                                                     sessions)
                # the crash iteration is scheduler history too: ringed
                # with its error so /batchz shows where the batch died
                self._record_iteration(bucket, slots_snap, step_s, qd,
                                       qage, occupancy_after=0,
                                       error=repr(e)[:200])
                continue
            step_s = time.perf_counter() - t_step
            health.beat("serve.worker")
            self._inflight_since = None
            self._cur_sess = None
            if cw.stall_s:
                for st in active.values():
                    st.stall_s += cw.stall_s
            if self._batch_rescued:
                # the wedge cleared just as the watchdog evicted the
                # batch: the requests are already answered upstream —
                # run the same cleanup as a failed step (abandoned
                # no-ops) and drop the closed session
                self._batch_rescued = False
                self._fail_batch(sess, active, RuntimeError(
                    "batch rescued by the stall watchdog"),
                    count_failure=False)
                sessions = {b: s for b, s in sessions.items()
                            if s is not sess}
                sess = None
                qd, qage = self._publish_batch_state(sess, active,
                                                     sessions)
                self._record_iteration(bucket, slots_snap, step_s, qd,
                                       qage, occupancy_after=0,
                                       error="batch rescued")
                continue
            for slot, tok, done in res:
                st = active.get(slot)
                if st is None:
                    continue           # retired this iteration
                st.toks.append(int(tok))
                if done:
                    self._complete_slot(sess, active, slot)
            qd, qage = self._publish_batch_state(sess, active, sessions)
            self._record_iteration(bucket, slots_snap, step_s, qd, qage,
                                   occupancy_after=len(active))
        close_all()
        # the worker is exiting (drain/stop): the closed sessions
        # released their caches — zero the KV account so a /metrics
        # or /programz scrape during the drain window (or a later
        # task in this process reading the perf ledger's decode hook)
        # never reports freed memory as allocated (the reload path's
        # own invariant)
        self._publish_batch_state(None, {}, sessions)

    def _episode_overlap(self, episodes, open_t0, a: float,
                         b: float) -> float:
        """Seconds of the monotonic span [a, b] covered by recorded
        episode windows plus a still-open episode (latched at
        ``open_t0``, not yet cleared). Worker thread only — the
        episode deques have a single writer and a single reader."""
        s = 0.0
        for e0, e1 in episodes:
            s += max(0.0, min(b, e1) - max(a, e0))
        if open_t0 is not None:
            s += max(0.0, b - max(a, open_t0))
        return s

    def _observe_request(self, req: _Request, tc, outcome: str,
                         queue_wait: float, t_pop: float, t_back: float,
                         t_end: float, wall: float, ntok: int,
                         occupancy: Optional[int] = None,
                         batch=None) -> None:
        """Phase-attribute one dequeued request and publish everything
        downstream reads: the TTFT / per-token histograms and
        tokens-per-second gauge, the flight record, the
        ``serve_request_done`` event, and the SLO account. Phases TILE
        the request's accept->answer wall-clock — queue_wait, dispatch
        (pop -> backend call), prefill (call -> first token), decode
        (first -> last token) — so their sum IS the total; a request
        that never reached the backend (deadline, breaker shed) carries
        only queue_wait + dispatch."""
        dispatch = max(0.0, t_back - t_pop)
        prefill = decode = 0.0
        ttft = None
        dispatched = outcome in ("served", "backend_error", "abandoned")
        ft = tc.marks.get("first_token") if tc is not None else None
        if outcome == "deadline" and ft is not None:
            # batched path: a sequence retired MID-DECODE by its
            # deadline really did prefill and decode — record the
            # phases (the never-dispatched deadline keeps tc=None, so
            # the solo expired-in-queue case is unchanged)
            dispatched = True
        if dispatched:
            if ft is not None and t_back <= ft <= t_end:
                prefill = ft - t_back
                decode = t_end - ft
            else:
                # no first-token mark (simple backends, or a failure
                # before one): the whole call is prefill — first token
                # and last token arrive together
                prefill = t_end - t_back
            if outcome == "served":
                ttft = queue_wait + dispatch + prefill
        total = queue_wait + dispatch + prefill + decode
        # ``wall`` is the independently measured accept->last-token
        # wall-clock (one monotonic interval, stamped adjacent to t_end
        # by the caller): the >=95% phase-coverage acceptance is checked
        # against THIS, not against the phase sum itself — a regression
        # that drops or mis-measures a phase moves total in lockstep
        # but cannot move wall
        if ttft is not None:
            telemetry.hist("serve.ttft", ttft)
        if decode > 0 and ntok > 1:
            telemetry.hist("serve.decode_per_token", decode / (ntok - 1))
        tps = None
        gen = prefill + decode
        if outcome == "served" and ntok and gen > 0:
            tps = ntok / gen
            telemetry.gauge("serve.tokens_per_second", round(tps, 3))
            telemetry.count("serve.tokens", ntok)
        if self._tenants and req.tenant is not None:
            # the per-tenant latency account: a serve.* series per
            # tenant (bounded by the conf table), so the fleet
            # federation's exact histogram merge yields per-tenant
            # fleet p99 with no extra wire format — the "victim's p99
            # holds" acceptance is read off exactly this series
            telemetry.hist("serve.tenant.%s.request" % req.tenant,
                           total)
        rec = {"id": req.id, "outcome": outcome,
               "tenant": req.tenant,
               "tokens_in": len(req.toks), "tokens_out": ntok,
               "t_wall": round(req.t_wall, 6),
               "total_s": round(total, 6),
               "wall_s": round(wall, 6),
               "ttft_s": round(ttft, 6) if ttft is not None else None,
               "tokens_per_s": round(tps, 3) if tps is not None else None,
               "phases": {"queue_wait": round(queue_wait, 6),
                          "dispatch": round(dispatch, 6),
                          "prefill": round(prefill, 6),
                          "decode": round(decode, 6)},
               "recompiles": list(tc.compiles) if tc is not None else []}
        # compile seconds this request paid: its OWN prefill's
        # recompiles (tc.compiles) plus the batch-wide cliffs the
        # dispatcher's compile window attributed to its slot
        # (warm-session creation, a first decode step) — exactly 0.0
        # for a request riding warm programs, so TTFT decomposes into
        # "queued" vs "paying the cliff" honestly
        stall = sum(c["dur"] for c in tc.compiles) \
            if tc is not None else 0.0
        if batch is not None:
            stall += batch.get("stall_s") or 0.0
        rec["compile_stall_s"] = round(stall, 6)
        if occupancy is not None:
            # sequences sharing the decode pass when this request was
            # admitted to its slot (itself included): /trace and
            # /requestz show the coalescing, request by request
            rec["occupancy_at_dispatch"] = int(occupancy)
        if batch is not None:
            # the scheduling coordinates (_retire_info): bucket, slot,
            # and [first, last] step-iteration ordinals — /requestz
            # answers "who did this request share its decode with"
            # by joining overlapping ranges, no iteration ring needed
            rec["bucket"] = batch.get("bucket")
            rec["slot"] = batch.get("slot")
            rec["iterations"] = batch.get("iterations")
        if tps is not None:
            # the decode-step roofline bound for THIS token count (the
            # performance ledger's card, null until one is ready):
            # measured tokens/s far under it flags "slower than the
            # hardware allows" per request, right in /requestz
            rec["roofline_bound_tokens_per_s"] = \
                perf.decode_bound_tokens_per_s(ntok)
        if tc is not None and tc.counts:
            rec["counts"] = dict(tc.counts)
        # autopsy inputs + verdict (utils/autopsy.py): seconds of this
        # request's [arrival, answer] span spent inside convoy / KV-
        # pressure episodes, its block-pool defer count, and the
        # classified cause decomposition — /why renders it, the
        # serve_request_done event carries it, and /eventz joins
        # incident rows to the requests whose autopsies cite them
        t1 = req.t_arrival + wall
        rec["convoy_overlap_s"] = round(self._episode_overlap(
            self._convoy_episodes, self._convoy_t0,
            req.t_arrival, t1), 6)
        rec["kv_pressure_overlap_s"] = round(self._episode_overlap(
            self._kvp_episodes, self._kvp_t0, req.t_arrival, t1), 6)
        rec["kv_defers"] = req.kv_defers
        rec["autopsy"] = autopsy.classify_record(rec)
        self.flight.record(rec)
        ev = {"ev": "serve_request_done", "req": req.id,
              "outcome": outcome, "tokens": ntok,
              "total_s": rec["total_s"],
              "recompiles": len(rec["recompiles"]),
              "compile_stall_s": rec["compile_stall_s"]}
        if req.tenant is not None:
            ev["tenant"] = req.tenant
        for ph, v in rec["phases"].items():
            ev[ph + "_s"] = v
        if not dispatched:
            # the flight record's zeros are honest (phases tile the
            # wall-clock), but the report's phase percentiles aggregate
            # these events: a deadline/shed request never HAD a prefill
            # or decode, and hard zeros would deflate the latency table
            # exactly during the overload it triages — null, like ttft
            ev["prefill_s"] = ev["decode_s"] = None
        if ttft is not None:
            ev["ttft_s"] = rec["ttft_s"]
        ev["autopsy"] = rec["autopsy"]
        telemetry.event(ev)
        self._slo_observe(req.tenant, ok=(outcome == "served"),
                          ttft_s=ttft, latency_s=total)

    # -- TCP listener --------------------------------------------------
    def _accept_run(self) -> None:
        sock = self._sock       # local ref: drain() nulls the attribute
        while True:
            with self._cond:
                if self._draining or self._stop:
                    break
            health.beat("serve.accept")
            try:
                conn, _addr = sock.accept()
            except socket.timeout:
                continue
            except OSError:
                break               # listener closed (drain)
            # sends run on the connection's own writer thread, so a
            # stalled reader only wedges itself — and only for this
            # long, then its connection is torn down and counted gone
            conn.settimeout(self.client_timeout)
            threading.Thread(target=self._client_run, args=(conn,),
                             name="cxn-servd-client", daemon=True).start()
        health.pause("serve.accept")

    def _conn_writer(self, conn: socket.socket, st: _ConnState) -> None:
        """Per-connection writer: transmits filled reply slots strictly
        head-first. Sends happen HERE, never on the worker thread — a
        client that stops reading (full TCP window) stalls only its own
        connection for up to ``client_timeout``, not every client's
        dispatch. Exits once the reader saw EOF and every slot is out."""
        while True:
            with st.cond:
                while not ((st.slots and st.slots[0][0] is not None)
                           or (st.eof and not st.slots)):
                    st.cond.wait(0.5)
                if st.eof and not st.slots:
                    return
                s = st.slots.popleft()
            if st.dead:
                # connection torn down: discard quietly, but keep
                # draining slots so the reader's join terminates
                with st.cond:
                    st.unsent -= 1
                    st.cond.notify_all()
                continue
            try:
                conn.sendall((s[0] + "\n").encode("utf-8", "replace"))
            except OSError:
                # a failed/timed-out send may have written PART of a
                # response: the positional stream is unrecoverable —
                # tear the connection down rather than feed a resumed
                # client desynced bytes (socket.timeout is an OSError)
                st.dead = True
                self._bump("client_gone")
                try:
                    conn.close()
                except OSError:
                    pass
            finally:
                with st.cond:
                    st.unsent -= 1
                    st.cond.notify_all()

    def _client_run(self, conn: socket.socket) -> None:
        # responses must leave the socket in REQUEST order — the line
        # protocol pairs them positionally. A synchronous rejection
        # (parse error, shed) is produced by this reader thread while
        # earlier requests may still sit in the queue, so replies are
        # buffered in per-line slots and transmitted strictly head-first
        # by the connection's writer thread: shedding stays instant for
        # the SERVER (no queue entry, no backend), the rejected client
        # just reads its answer in order.
        st = _ConnState()
        with self._conn_lock:
            self._conns.add(st)

        def make_reply(slot):
            def reply(text: str) -> None:
                with st.cond:
                    slot[0] = text
                    st.unsent += 1
                    st.cond.notify_all()
            return reply

        writer = threading.Thread(target=self._conn_writer,
                                  args=(conn, st),
                                  name="cxn-servd-send", daemon=True)
        writer.start()
        try:
            buf = b""
            while True:
                # explicit recv loop (not makefile): a timeout here is
                # an IDLE client — e.g. one waiting out a long queued
                # decode — and must keep the connection, with no
                # partial-line loss
                try:
                    chunk = conn.recv(65536)
                except socket.timeout:
                    continue
                except OSError:
                    break
                eof = not chunk
                if eof and buf:
                    # client EOF with an unterminated final line: still
                    # a request (stdin's `for line in sys.stdin` yields
                    # such a line too — the two surfaces must agree, and
                    # silence is exactly the framing-bug failure ERR
                    # empty exists to prevent)
                    buf += b"\n"
                buf += chunk
                while b"\n" in buf:
                    raw, buf = buf.split(b"\n", 1)
                    line = raw.decode("utf-8", "replace").rstrip("\r")
                    slot = [None]
                    with st.cond:
                        st.slots.append(slot)
                    self.submit(line, make_reply(slot))
                if eof:
                    break
            # client EOF: the writer finishes delivering every answer,
            # however long the requests take — each submitted line gets
            # EXACTLY one reply (the worker's, or drain's ERR), so this
            # join terminates; no budget that could drop a slow answer
            with st.cond:
                st.eof = True
                st.cond.notify_all()
            writer.join()
        finally:
            with self._conn_lock:
                self._conns.discard(st)
            try:
                conn.close()
            except OSError:
                pass

    # -- graceful drain ------------------------------------------------
    def drain(self, timeout_ms: Optional[float] = None) -> dict:
        """Stop accepting, finish every accepted request within the
        budget (``drain_ms`` default), answer any leftovers ``ERR
        draining``, flush telemetry, and return the final stats. Exactly
        one response line per accepted request — a drained shutdown
        loses zero accepted requests. Idempotent."""
        budget = (self.drain_ms if timeout_ms is None
                  else float(timeout_ms)) / 1e3
        t0 = time.monotonic()
        with self._cond:
            self._draining = True
            queued = len(self._q)
            self._cond.notify_all()
        telemetry.event({"ev": "serve_drain", "phase": "begin",
                         "queued": queued})
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=2.0)
            self._accept_thread = None
        deadline = t0 + budget
        with self._cond:
            while (self._q or self._inflight) \
                    and time.monotonic() < deadline:
                self._cond.wait(0.05)
            leftovers = list(self._q)
            self._q.clear()
            telemetry.gauge("serve.queue_depth", 0)
            self._stop = True
            self._cond.notify_all()
        for req in leftovers:
            # budget exhausted: still exactly one response per accepted
            # request — an explicit ERR beats a silent dropped socket
            if self._finish(req, "ERR draining shutdown budget "
                            "exhausted", "errors"):
                # an accepted request the client lost burns error
                # budget like an admission shed — a preemption that
                # drains a full queue as ERR draining must not leave
                # cxxnet_slo_burn reading 0 in the final snapshot (the
                # wedged in-flight case is covered by the worker's
                # "abandoned" observation when the backend returns)
                self._slo_observe(req.tenant, ok=False)
        if self._worker_thread is not None:
            self._worker_thread.join(
                timeout=max(0.5, deadline - time.monotonic() + 1.0))
            if self._worker_thread.is_alive():
                # the backend outlived even the post-budget grace: every
                # in-flight request (ONE on the solo path, the whole
                # popped batch on the batching path) is answered HERE,
                # once — if the wedged backend ever returns, the
                # worker's _finish loses the claim and is a no-op
                with self._cond:
                    reqs = list(self._inflight_reqs)
                    if self._inflight_req is not None:
                        reqs.append(self._inflight_req)
                for req in reqs:
                    self._finish(req, "ERR draining backend exceeded "
                                 "the drain budget", "errors")
        # every accepted request is answered by now, but TCP answers are
        # transmitted by per-connection writer threads (daemons): wait
        # for the buffered ones to reach the kernel, or a response could
        # die with the interpreter — a silently dropped answer, exactly
        # what drain exists to prevent. Bounded: a stalled reader's send
        # times out at client_timeout and counts the client gone.
        flush_by = time.monotonic() + self.client_timeout + 1.0
        while time.monotonic() < flush_by:
            with self._conn_lock:
                conns = list(self._conns)
            if all(c.unsent == 0 for c in conns):
                break
            time.sleep(0.02)
        health.pause("serve.worker")
        health.pause("serve.accept")
        # the laws leave the registry with the frontend; a latch
        # observed before drain survives the unregister (the auditor's
        # contract), so a violation still fails the next scrape
        for law in ("serve.books", "serve.tenant_books", "kv.blocks"):
            telemetry.audit_unregister(law)
        stats = self.stats()
        telemetry.event(dict({"ev": "serve_drain", "phase": "end",
                              "seconds": round(time.monotonic() - t0, 3)},
                             **stats))
        telemetry.flush()
        return stats


# ----------------------------------------------------------------------
def _ask(port: int, line: str, timeout: float = 5.0) -> str:
    """One-shot client (selftest + stub tooling): one request, one
    response line."""
    with socket.create_connection(("127.0.0.1", port),
                                  timeout=timeout) as c:
        c.sendall((line + "\n").encode("utf-8"))
        resp = c.makefile("r", encoding="utf-8").readline()
    return resp.rstrip("\n")


def selftest(verbose: bool = False) -> int:
    """Drive the full admission/deadline/breaker/reload/drain machinery
    over a real loopback socket with an injected backend — jax-free;
    ``make check`` gates on it. Runs with runtime lock-order
    enforcement on (utils/lockrank.py): an inversion anywhere in the
    machinery raises a named LockOrderError instead of deadlocking."""
    with lockrank.enforced():
        return _selftest_body(verbose)


def _selftest_body(verbose: bool = False) -> int:
    boom = {"on": False}
    reloads = []

    def backend(toks, seq):
        if boom["on"]:
            raise RuntimeError("injected backend failure")
        if toks and toks[0] == 42:
            # a real (ms-scale) duration: the phase-coverage assertion
            # compares against an independently stamped wall-clock, and
            # on a µs echo request scheduler noise would dominate
            time.sleep(0.025)
        return [t + 1 for t in toks]

    fe = ServeFrontend(backend, queue_size=4, breaker_fails=2,
                       breaker_cooldown_ms=300.0, drain_ms=2000.0,
                       vocab=100,
                       reload_fn=lambda: reloads.append(1) or True)
    fe.start()
    port = fe.listen(0)
    try:
        # happy path + parse/empty/vocab rejection
        assert _ask(port, "1 2 3") == "2 3 4"
        assert _ask(port, "").startswith("ERR empty")
        assert _ask(port, "1 x 2").startswith("ERR parse")
        assert _ask(port, "1 999").startswith("ERR parse")
        assert _ask(port, "DEADLINE nope 1").startswith("ERR parse")
        # a 0ms deadline has always expired by dispatch time
        assert _ask(port, "DEADLINE 0 1 2").startswith("ERR deadline")
        assert _ask(port, "DEADLINE 5000 7") == "8"
        # TRACE propagation: the caller's fleet-wide id becomes the
        # request id (the flight-record / trace-surface key); malformed
        # ids are a protocol violation, composable with DEADLINE
        assert _ask(port, "TRACE req-a 1 2") == "2 3"
        assert fe.flight.get("req-a")["outcome"] == "served"
        assert _ask(port, "TRACE req-b DEADLINE 5000 3") == "4"
        assert fe.flight.get("req-b") is not None
        assert _ask(port, "TRACE %s 1"
                    % ("x" * (TRACE_ID_MAX + 1))).startswith("ERR proto")
        assert _ask(port, "TRACE bad/id 1").startswith("ERR proto trace")
        # backend supervision: failures answered, loop survives
        boom["on"] = True
        assert _ask(port, "5").startswith("ERR backend")
        assert _ask(port, "5").startswith("ERR backend")
        # 2 consecutive failures: breaker open, sheds instantly
        assert fe.breaker.state == "open"
        assert _ask(port, "5").startswith("ERR busy")
        assert fe.health_probe()[0] is False
        # cooldown elapses, backend healed: half-open probe closes it
        boom["on"] = False
        time.sleep(0.35)
        assert _ask(port, "5") == "6"
        assert fe.breaker.state == "closed" and fe.health_probe()[0]
        # hot reload between requests
        assert _ask(port, "ADMIN reload").startswith("OK")
        assert _ask(port, "9") == "10"
        assert reloads, "reload_fn never ran"
        assert _ask(port, "ADMIN stats").startswith("OK accepted=")
        assert _ask(port, "ADMIN bogus").startswith("ERR parse")
        # request tracing: every dequeued request left a flight record
        # whose phases tile its wall-clock (the /trace?request= source);
        # token 42 makes this one slow enough for robust coverage math
        assert _ask(port, "42") == "43"
        recs = fe.flight.list()
        assert recs, "flight recorder empty after served requests"
        rec = next(r for r in recs if r["outcome"] == "served")
        assert set(rec["phases"]) == set(telemetry.REQUEST_PHASES)
        # coverage is judged against the independently measured
        # accept->observe wall-clock, NOT the phase sum (total_s is the
        # sum by construction — checking against it proves nothing)
        cover = sum(rec["phases"].values())
        assert rec["wall_s"] > 0 and cover >= 0.95 * rec["wall_s"], \
            "phases cover %.0f%% of the request wall-clock" \
            % (100 * cover / rec["wall_s"])
        assert rec["ttft_s"] is not None \
            and rec["ttft_s"] <= rec["total_s"] + 1e-9
        assert fe.flight.get(rec["id"])["id"] == rec["id"]
        ct = telemetry.request_chrome_trace(rec)
        assert any(t.get("name") == "prefill"
                   for t in ct["traceEvents"])
        # outcomes attributed: the exploded requests are in the ring too
        assert any(r["outcome"] == "backend_error" for r in recs)
    finally:
        stats = fe.drain()
    assert stats["accepted"] == (stats["served"] + stats["errors"]
                                 + stats["shed"] + stats["deadline"]), \
        "serve counters do not reconcile: %r" % (stats,)
    assert stats["served"] == 7 and stats["shed"] == 1
    assert stats["deadline"] == 1 and stats["empty"] == 1
    assert fe.health_probe() == (False,
                                 "draining: not accepting new requests")
    assert fe.liveness_probe()[0]

    # SLO error budget: a healthy run keeps the burn gauge 0; a flood of
    # objective-violating requests flips it
    slo = statusd.SLOTracker(ttft_ms=30.0, availability=0.999,
                             min_requests=4, window_s=60.0)
    fe2 = ServeFrontend(lambda toks, seq: list(toks), slo=slo,
                        drain_ms=2000.0)
    fe2.start()
    port2 = fe2.listen(0)
    try:
        for _ in range(4):
            assert _ask(port2, "1 2") == "1 2"
        assert slo.snapshot()["alert"] == 0, "healthy run burned budget"

        def slow(toks, seq):
            time.sleep(0.05)             # >> the 30ms TTFT objective
            return list(toks)

        fe2.backend = slow
        for _ in range(4):
            _ask(port2, "3")
        snap = slo.snapshot()
        assert snap["alert"] == 1 and snap["burn_rate"] >= 1.0, snap
        assert snap["by_reason"].get("ttft", 0) >= 4, snap
    finally:
        fe2.drain()
    if verbose:
        print("servd selftest: admission/deadline/breaker/reload/drain + "
              "request tracing (phases/TTFT/flight recorder) + SLO burn "
              "flip ok (%r)" % (stats,))
    return 0


def _stub_main(argv: List[str]) -> int:
    """``--stub``: a standalone jax-free replica for the chaos harness —
    prints the bound port(s), serves until SIGTERM/SIGINT, drains, prints
    the final stats as JSON, exits 0. Knobs: ``--port N`` ``--delay-ms D``
    (slow backend) ``--explode-every N`` (every Nth dispatch raises)
    ``--queue N`` ``--drain-ms D`` ``--breaker-fails N`` ``--stall-s S``
    (wedged-backend probe bound).

    Fleet knobs (tests/faultinject.py's fleet helpers, the routerd chaos
    suite): ``--status-port N`` starts a statusd sidecar wired to the
    frontend's readiness/liveness probes (what the router polls) and
    prints its port on a second line; the backend answers ``tok +
    version`` where ``version`` starts at 1 and each ``ADMIN reload``
    bumps it (after sleeping ``--reload-ms`` — a stand-in for the decode
    recompile a real reload pays), so a rolling-reload test can SEE which
    model answered; SIGUSR1 wedges the backend (it blocks, heartbeats
    silent — the accept-but-never-answer failure mode from inside) until
    SIGUSR2 unwedges it."""
    import json
    import signal

    def flag(name, default, cast=float):
        if name in argv:
            return cast(argv[argv.index(name) + 1])
        return default

    delay = flag("--delay-ms", 0.0) / 1e3
    explode_every = int(flag("--explode-every", 0))
    reload_s = flag("--reload-ms", 0.0) / 1e3
    model = {"version": 1}
    wedge = {"on": False}

    def backend(toks, seq):
        while wedge["on"]:          # SIGUSR1: block until SIGUSR2
            time.sleep(0.05)
        if explode_every and (seq + 1) % explode_every == 0:
            raise RuntimeError("injected stub explosion")
        if delay:
            time.sleep(delay)
        return [t + model["version"] for t in toks]

    def reload_fn():
        if reload_s:
            time.sleep(reload_s)    # the recompile stand-in
        model["version"] += 1
        return True

    # batched decode mode (--batch-max N): the continuous-batching
    # dispatcher over an inline slot backend — same deterministic
    # answer law as the solo stub continued per token (first token =
    # last prompt token + version, then +1 per decode step), so a
    # kill-mid-decode chaos test can assert token-exact replays while
    # requests are genuinely ABOARD a decode batch when the SIGKILL
    # lands (--per-token-ms paces the steps to hold them there)
    batch_max = int(flag("--batch-max", 0))
    n_new = int(flag("--n-new", 8))
    per_token_s = flag("--per-token-ms", 0.0) / 1e3

    class _StubSession:
        def __init__(self, n):
            self.nslots = n
            self.closed = False
            self.lives: dict = {}

        def free_slots(self):
            return [s for s in range(self.nslots)
                    if s not in self.lives]

        def prefill(self, slot, toks, seq):
            while wedge["on"]:
                time.sleep(0.05)
            if self.closed:
                raise RuntimeError("session closed")
            first = (toks[-1] if toks else 0) + model["version"]
            if n_new <= 1:
                return first, True
            self.lives[slot] = {"next": first + 1,
                                "remaining": n_new - 1}
            return first, False

        def step(self):
            while wedge["on"]:
                time.sleep(0.05)
            if self.closed:
                raise RuntimeError("session closed")
            if per_token_s:
                time.sleep(per_token_s)
            out = []
            for slot, live in list(self.lives.items()):
                tok = live["next"]
                live["next"] += 1
                live["remaining"] -= 1
                done = live["remaining"] <= 0
                if done:
                    self.lives.pop(slot, None)
                out.append((slot, tok, done))
            return out

        def retire(self, slot):
            self.lives.pop(slot, None)

        def close(self):
            self.closed = True
            self.lives.clear()

    class _StubSlotBackend:
        buckets = (batch_max,) if batch_max > 0 else ()

        def session(self, b):
            return _StubSession(b)

    fe = ServeFrontend(backend, queue_size=int(flag("--queue", 64)),
                       drain_ms=flag("--drain-ms", 5000.0),
                       breaker_fails=int(flag("--breaker-fails", 5)),
                       stall_after_s=flag("--stall-s", 120.0),
                       reload_fn=reload_fn,
                       slot_backend=_StubSlotBackend()
                       if batch_max > 0 else None,
                       batch_max=batch_max,
                       # multi-tenant QoS knobs for the fleet chaos
                       # harness (same conf syntax as route_tenants)
                       tenants=flag("--tenants", "", cast=str),
                       tenant_default=flag("--tenant-default",
                                           "default", cast=str))
    # the wedge handlers install BEFORE the port banner: the banner is
    # the chaos harness's spawn synchronization point, and a SIGUSR1
    # sent right after it must wedge the backend — not kill the process
    # via the default action (a real race on fast machines: the fleet
    # wedge tests flaked exactly there)
    for signum, on in ((getattr(signal, "SIGUSR1", None), True),
                       (getattr(signal, "SIGUSR2", None), False)):
        if signum is not None:
            signal.signal(signum,
                          lambda s, f, _on=on: wedge.update(on=_on))
    fe.start()
    port = fe.listen(int(flag("--port", 0)))
    print("servd-stub: listening on port %d" % port, flush=True)
    status_port = int(flag("--status-port", -1))
    if status_port >= 0:
        # the statusd sidecar a real `task = serve` replica runs: the
        # router's probe surface (/healthz readiness + /metrics gauges)
        telemetry.enable()          # in-memory: /metrics needs the reg
        srv = statusd.start(status_port)
        statusd.register_probe("serving", fe.health_probe)
        statusd.register_probe("serving.worker", fe.liveness_probe,
                               liveness=True)
        statusd.set_flight_recorder(fe.flight)
        print("servd-stub: status on port %d" % srv.port, flush=True)
    with ckpt.PreemptionGuard(enabled=True) as guard:
        while not guard.requested:
            time.sleep(0.05)
    stats = fe.drain()
    if status_port >= 0:
        statusd.stop()
    print("servd-stub: drained " + json.dumps(stats), flush=True)
    return 0


if __name__ == "__main__":
    if "--selftest" in sys.argv[1:]:
        sys.exit(selftest(verbose=True))
    if "--stub" in sys.argv[1:]:
        sys.exit(_stub_main(sys.argv[1:]))
    print(__doc__)
    sys.exit(1)
