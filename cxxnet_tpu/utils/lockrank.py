"""Runtime lock-rank enforcement: the dynamic half of tools/cxxlint.py.

The static analyzer (``tools/cxxlint.py``, rule ``lock-cycle``) proves the
lock-acquisition graph it can SEE is acyclic — but callback-driven and
cross-thread acquisitions (a reply closure taking a connection condition,
a probe running on the statusd scrape thread) are invisible to the AST.
This module closes that gap the way large concurrent systems do: every
named lock carries a **rank** derived from the static graph's topological
order, and with ``CXXNET_LOCKRANK=1`` each acquisition asserts that ranks
are taken strictly in increasing order per thread. An inversion the AST
could not see then surfaces as an immediate, named diagnostic in the
existing chaos harness (tests/test_servd.py floods, the servd/statusd
selftests) instead of as a once-a-month production deadlock.

Usage — construct locks through the factories instead of ``threading``::

    self._lock = lockrank.lock("servd.stats")
    self._cond = lockrank.condition("servd.queue")

The factories always return ranked wrappers; whether an acquisition is
CHECKED is decided per-acquire by ``enabled()``, not at construction —
module-level locks (the telemetry registry is built at import time)
would otherwise silently escape enforcement in any process that flips
``CXXNET_LOCKRANK`` on after importing them, which is every pytest
worker and both selftests. With the variable unset (production default)
an acquisition costs one env lookup and otherwise behaves exactly like
the plain primitive. With it set, acquisitions maintain a thread-local
stack of (rank, name, site) and raise ``LockOrderError`` naming BOTH
locks and BOTH acquisition sites on any out-of-order take.
``Condition.wait`` releases and re-takes its lock; the ranked condition
keeps the stack honest across the gap (its inner lock is a RankedLock,
so every method ``threading.Condition.__init__`` binds from it is
ranked). ``enforced()`` is a context manager that sets and restores the
variable around a block — the selftests use it so in-process callers do
not inherit enforcement.

``RANKS`` is the project lock ordering. It must stay a valid topological
order of the static graph — ``tests/test_cxxlint.py`` asserts that every
edge the analyzer extracts from the real package satisfies
``RANKS[a] < RANKS[b]`` (run ``python tools/cxxlint.py --lock-graph`` to
see the edges). Gaps of 10 leave room to slot new locks without
renumbering. A name not in RANKS gets ``DEFAULT_RANK`` (outermost
bucket) and still participates in ordering checks against ranked locks.

Jax-free, stdlib-only; ``python -m cxxnet_tpu.utils.lockrank --selftest``
exercises ordered/inverted/condition-wait paths in-process.
"""

from __future__ import annotations

import os
import re
import sys
import threading
from typing import List, Optional, Tuple

__all__ = ["RANKS", "DEFAULT_RANK", "LockOrderError", "RankedLock",
           "RankedCondition", "lock", "condition", "enabled",
           "enforced", "held", "selftest"]

# The project lock ordering (rank = position in the static lock graph's
# topological order; LOWER = acquired FIRST / outermost). Keep in sync
# with `python tools/cxxlint.py --lock-graph`; tests/test_cxxlint.py
# fails if an edge of the real graph contradicts this table.
RANKS = {
    "routerd.scale": 1,     # Router._scale_lock — autoscaler decisions
    #                         and idle timers (outermost of the router
    #                         locks: a decision may mark replicas under
    #                         the fleet lock; IO — standby probes —
    #                         stays outside it)
    "routerd.fleet": 2,     # Router._lock — replica states/load/windows
    #                         (outermost: held while recording telemetry,
    #                         never under any servd/statusd lock)
    "routerd.stats": 5,     # Router._slock — router counter snapshot
    "routerd.fed": 7,       # Router._fed_lock — federated replica
    #                         metric snapshots + outlier verdicts
    #                         (never nested with fleet/stats; IO stays
    #                         outside it)
    "servd.queue": 10,      # ServeFrontend._cond — admission/worker/drain
    "kvblocks.evict": 15,   # BlockAllocator._lock — KV block
    #                         reservation + retained-pool eviction
    #                         (atomic evict-before-defer). Nests INSIDE
    #                         the admission lock (servd.queue), never
    #                         the reverse: the dispatcher sheds/admits
    #                         while coalescing, the allocator never
    #                         calls back into servd — so exhaustion
    #                         cannot deadlock a reserve-up-front
    #                         admission (tests/test_servd.py chaos
    #                         flood under CXXNET_LOCKRANK=1)
    "servd.conns": 20,      # ServeFrontend._conn_lock — live writer set
    "servd.conn": 30,       # _ConnState.cond — per-connection reply slots
    "servd.request": 40,    # _Request._alock — exactly-once answer claim
    "servd.stats": 50,      # ServeFrontend._slock — stats snapshot
    "servd.breaker": 60,    # CircuitBreaker._lock
    "statusd.slo": 70,      # SLOTracker._lock — emits telemetry under it
    "health.ids": 80,       # health anomaly-id allocation
    "perf.profilez": 85,    # ProfilerCapture._lock — capture guard
    "servd.batchflight": 88,  # BatchFlightRecorder._ring — the
    #                           per-iteration batch scheduler ring
    #                           (appended outside every servd lock,
    #                           read by statusd /batchz)
    "telemetry.flight": 90,   # FlightRecorder._ring
    "perf.compiles": 92,    # Ledger._clock — the compile flight ring +
    #                         warm-grid account (ring append / warm mark
    #                         under it; the program_compile event — IO —
    #                         is emitted OUTSIDE it)
    "perf.ledger": 95,      # Ledger._cond — emits program_card events
    #                         and reads registry hists under it
    "telemetry.audit": 97,  # BooksAuditor._lock — latch bookkeeping
    #                         only: laws are evaluated OUTSIDE it, the
    #                         books_broken event is emitted outside it;
    #                         below everything but the registry
    "telemetry.registry": 100,  # _Registry._lock — innermost by design:
    #                             every subsystem records telemetry, so
    #                             nothing may be acquired under it
}

# unranked names sort OUTERMOST: they may wrap ranked locks but a ranked
# lock holder acquiring an unranked one is an ordering violation —
# conservative, so forgetting to rank a new lock fails loudly in the
# chaos tests instead of silently escaping the ordering discipline
DEFAULT_RANK = 0


class LockOrderError(AssertionError):
    """A lock acquisition out of rank order: names both locks and both
    acquisition sites (the would-be deadlock's two halves)."""


_tls = threading.local()


def enabled() -> bool:
    return os.environ.get("CXXNET_LOCKRANK", "") not in ("", "0")


class enforced:
    """``with lockrank.enforced():`` — enforcement on inside the block,
    prior state restored on exit (selftests and in-process tooling must
    not leak enforcement into their caller's process)."""

    def __enter__(self) -> "enforced":
        self._prev = os.environ.get("CXXNET_LOCKRANK")
        os.environ["CXXNET_LOCKRANK"] = "1"
        return self

    def __exit__(self, *exc) -> bool:
        if self._prev is None:
            os.environ.pop("CXXNET_LOCKRANK", None)
        else:
            os.environ["CXXNET_LOCKRANK"] = self._prev
        return False


def _stack() -> List[Tuple[int, str, str]]:
    s = getattr(_tls, "held", None)
    if s is None:
        s = _tls.held = []
    return s


def held() -> List[Tuple[int, str, str]]:
    """This thread's (rank, name, site) stack, outermost first —
    diagnostics and tests."""
    return list(_stack())


def _site() -> str:
    """path:line of the acquiring frame — first frame outside this
    module AND outside threading (a RankedCondition acquisition passes
    through Condition.__enter__/wait internals; reporting threading.py
    as the site would hide the one thing the operator needs)."""
    f = sys._getframe(2)
    while f is not None and f.f_globals.get("__name__") in (__name__,
                                                            "threading"):
        f = f.f_back
    if f is None:
        return "?"
    return "%s:%d" % (f.f_code.co_filename, f.f_lineno)


def _push(name: str, rank: int, site: str) -> None:
    s = _stack()
    if s:
        top_rank, top_name, top_site = max(s)
        if rank <= top_rank:
            raise LockOrderError(
                "lock order inversion: acquiring %r (rank %d) at %s "
                "while holding %r (rank %d) acquired at %s — the static "
                "order (tools/cxxlint.py --lock-graph, lockrank.RANKS) "
                "requires %r before %r"
                % (name, rank, site, top_name, top_rank, top_site,
                   name, top_name))
    s.append((rank, name, site))


def _pop(name: str) -> None:
    s = _stack()
    for i in range(len(s) - 1, -1, -1):
        if s[i][1] == name:
            del s[i]
            return


class RankedLock:
    """``threading.Lock`` plus per-thread rank-order assertion.

    ``enabled()`` is consulted per ACQUISITION: a lock built at import
    time starts asserting the moment the env var flips on. ``release``
    always pops (a no-op when nothing was pushed) so toggling
    enforcement mid-hold cannot leak a stack entry."""

    def __init__(self, name: str, rank: Optional[int] = None):
        self.name = name
        self.rank = RANKS.get(name, DEFAULT_RANK) if rank is None \
            else int(rank)
        self._lock = threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if enabled():
            _push(self.name, self.rank, _site())   # check BEFORE
            #             blocking: the inversion must raise, not
            #             deadlock first
        got = self._lock.acquire(blocking, timeout)
        if not got:
            _pop(self.name)
        return got

    def release(self) -> None:
        self._lock.release()
        _pop(self.name)

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self) -> "RankedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> bool:
        self.release()
        return False

    # Condition protocol: Condition.__init__ binds acquire/release AND
    # (when the lock defines them) _release_save/_acquire_restore/
    # _is_owned as INSTANCE attributes from its inner lock — defining
    # them here keeps every binding ranked, so wait()'s release/re-take
    # gap pops and re-pushes the stack entry symmetrically
    def _release_save(self):
        self.release()

    def _acquire_restore(self, saved) -> None:
        self.acquire()

    def _is_owned(self) -> bool:
        if self._lock.acquire(False):
            self._lock.release()
            return False
        return True

    def __repr__(self) -> str:
        return "<RankedLock %s rank=%d>" % (self.name, self.rank)


class RankedCondition(threading.Condition):
    """``threading.Condition`` over a ``RankedLock``.

    ``threading.Condition.__init__`` binds ``acquire``/``release`` (and
    ``_release_save``/``_acquire_restore``/``_is_owned`` when the lock
    defines them) as instance attributes taken from the inner lock —
    overriding them on the Condition subclass is a trap: the instance
    bindings shadow the overrides, acquisitions go unranked, and the
    class-level restore hook leaks a phantom stack entry on every
    ``wait()``. Passing a RankedLock as the inner lock routes every one
    of those bindings through the rank accounting instead: ``wait()``
    releases the lock (entry popped with it) and re-takes it on wake
    (entry re-pushed) — a waiter that was legitimately innermost cannot
    trip the check on re-acquire, and a thread that waits while holding
    a HIGHER-ranked lock still fails at the original acquisition like
    any other inversion."""

    def __init__(self, name: str, rank: Optional[int] = None):
        self.name = name
        self.rank = RANKS.get(name, DEFAULT_RANK) if rank is None \
            else int(rank)
        threading.Condition.__init__(self, RankedLock(name, self.rank))

    def __repr__(self) -> str:
        return "<RankedCondition %s rank=%d>" % (self.name, self.rank)


def lock(name: str) -> RankedLock:
    """A mutex for the named role. Always a RankedLock — whether an
    acquisition is rank-checked is decided per-acquire by ``enabled()``,
    so locks constructed before the env var flips (module-level
    registries, import-time singletons) still enforce. The literal name
    is ALSO what tools/cxxlint.py uses as the lock's node in the static
    acquisition graph — keep it unique and stable."""
    return RankedLock(name)


def condition(name: str) -> RankedCondition:
    """Condition-variable counterpart of ``lock()``."""
    return RankedCondition(name)


# ----------------------------------------------------------------------
def selftest(verbose: bool = False) -> int:
    # locks constructed BEFORE enforcement flips on — the per-acquire
    # gate must cover import-time singletons (telemetry's registry)
    a = lock("servd.queue")          # rank 10
    b = lock("telemetry.registry")   # rank 100
    c = condition("servd.conn")      # rank 30

    # enforcement off: inverted order is (dangerously) silent and cheap
    with b:
        with a:
            pass
    assert not held(), "disabled acquisitions touched the stack"

    ctx = enforced()
    ctx.__enter__()
    try:
        _selftest_enforced(a, b, c)
    finally:
        ctx.__exit__()
    assert not enabled(), "selftest leaked CXXNET_LOCKRANK into the env"
    if verbose:
        print("lockrank selftest: ordered/inverted/condition-wait/"
              "unranked paths ok (%d ranked locks)" % len(RANKS))
    return 0


def _selftest_enforced(a, b, c) -> None:
    # in-order nesting is silent
    with a:
        with c:
            with b:
                pass
    assert not held(), "rank stack leaked: %r" % held()

    # inversion raises and names both sides
    try:
        with b:
            with a:
                raise AssertionError("inversion not detected")
    except LockOrderError as e:
        msg = str(e)
        assert "servd.queue" in msg and "telemetry.registry" in msg, msg
        # both acquisition sites present (path:line, or <string>:line
        # when driven through python -c)
        assert len(re.findall(r"at \S+:\d+", msg)) >= 2, \
            "diagnostic lacks both sites: " + msg
    assert not held(), "rank stack leaked after inversion: %r" % held()

    # a condition-entered inversion reports the CALLER's site, not the
    # threading.py internals the acquisition tunnels through
    try:
        with b:
            with c:
                raise AssertionError("condition inversion not detected")
    except LockOrderError as e:
        assert "threading.py" not in str(e), \
            "condition site hidden behind stdlib frames: " + str(e)
    assert not held(), "rank stack leaked: %r" % held()

    # condition wait/notify keeps the stack honest across the gap
    ping = []

    def waiter():
        with c:
            while not ping:
                c.wait(1.0)
            with b:                  # re-acquired c (30) -> b (100): ok
                ping.append("seen")

    t = threading.Thread(target=waiter, daemon=True)
    t.start()
    import time
    time.sleep(0.05)
    with c:
        ping.append("go")
        c.notify()
    t.join(2.0)
    assert "seen" in ping, "condition waiter never resumed"
    # regression: a timed-out wait must leave NO phantom stack entry
    # (Condition.__init__ binds acquire/release from the inner lock as
    # instance attrs — a subclass override leaks one per wait())
    with c:
        c.wait(0.01)
    assert not held(), "condition wait leaked a stack entry: %r" % held()

    # a try-lock that fails must not leave a stack entry
    got = b.acquire()
    assert got
    b.release()
    assert not held()

    # unranked locks sit outermost: taking one UNDER a ranked lock fails
    u = lock("not.in.ranks")
    with u:
        with a:
            pass
    try:
        with a:
            with u:
                raise AssertionError("unranked-under-ranked not detected")
    except LockOrderError:
        pass


if __name__ == "__main__":
    if "--selftest" in sys.argv[1:]:
        sys.exit(selftest(verbose=True))
    print(__doc__)
    sys.exit(1)
