"""Replicated serving fleet: a health-aware TCP router over servd.

One ``servd`` process is production-grade (PR 5-8) but it is not a
fleet: a replica crash, wedge, or reload is a total outage. This module
is the fleet layer — the TF-Serving-era topology (arxiv 1605.08695)
where replicated model servers sit behind health-checked load balancing
— as a stdlib-only TCP router in the servd/statusd design language. It
speaks the EXACT servd line protocol (one request line in, one response
line out, ``DEADLINE``/``ADMIN`` prefixes, ``ERR <class> <detail>``),
so a client cannot tell the fleet from a single replica.

Per-replica state machine, fed by two signal paths:

* **probe path** — a prober thread polls each replica's statusd
  ``/healthz`` (readiness) every ``probe_ms`` and classifies:
  200 → ``up`` (and the replica's live ``queue_depth`` /
  ``in_flight`` gauges — read via its ``ADMIN stats``, the same
  values exported on ``/metrics`` — refresh the load estimate),
  503 mentioning draining → ``draining``, any other 503 (breaker open,
  stalled backend) → ``breaker_open``, unreachable → ``dead``.
* **dispatch path** — outcomes observed while routing move the machine
  without waiting a probe interval: connect-refused → ``dead``,
  ``ERR busy breaker`` → ``breaker_open``, ``ERR draining`` →
  ``draining``.

A ``dead`` replica is EJECTED and re-probed on the shared exponential
backoff schedule (``checkpoint.backoff_delay`` — the breaker/retry-IO
curve): each consecutive failed re-probe doubles the wait, a successful
probe re-admits it and resets the backoff.

Dispatch is least-loaded with power-of-two-choices: two eligible
replicas are sampled, the one with the lower load — probed queue_depth
+ in_flight plus the router's own live outstanding count, MINUS the
replica's probed free decode slots — wins (ties go to the lower replica
index, so behavior under zero load is deterministic). ``free_slots`` is
the continuous-batching capacity signal a batching replica reports in
``ADMIN stats`` (bucket capacity − active sequences): a replica that
can batch the request into a running decode pass beats one that would
queue it. Old replicas simply omit the field (treated as 0) — the
pre-batching ordering is unchanged, backward compatible by absence.
Only ``up`` replicas not held out by a rolling reload are eligible.

**Retry-on-shed, exactly-once preserved.** The third token of a servd
error line is a machine-readable detail token (utils/servd.py), and the
router retries a request on a DIFFERENT replica only when that token
proves the request never dispatched:

    response                     dispatched?   router action
    ------------------------     -----------   -----------------------
    ERR busy queue ...           never         retry elsewhere
    ERR busy breaker ...         never         eject + retry elsewhere
    ERR busy tenant ...          never         relay (no retry: the
                                               fair-share verdict holds
                                               fleet-wide)
    ERR draining server ...      never         mark draining + retry
    ERR draining shutdown ...    never         mark draining + retry
    ERR draining backend ...     MAYBE         relay (no retry)
    ERR backend rescued ...      yes, rescued  REPLAY elsewhere (the
                                               replica evicted a wedged
                                               batch: no answer left it)
    ERR backend ...              yes           relay (no retry)
    ERR parse / empty / deadline deterministic relay (no retry)
    connect refused              never         mark dead + retry
    sent, then no response       MAYBE         REPLAY elsewhere (close
                                               the old socket, reap+
                                               discard a late answer)

**Deterministic replay failover.** Generation in this stack is a pure
function of (prompt, seed, model version) — re-executing a lost
request on a survivor is idempotent at the token level, so ``lost``
is a RECOVERABLE outcome: the attempt is re-executed on a different
replica (``route.replays``), spending from the same parsed deadline
budget, with the original socket handed to a reaper so a late answer
is read, counted (``route.discarded_late``) and dropped — the client
can never see two answers. Replay is gated three ways: it is denied
to a tenant over its weighted fair share (``route.replay_denied`` —
a flood must not double itself through failover), it never applies
to ``ADMIN``/reload traffic (those bypass ``_route`` entirely), and
a model-generation guard refuses to splice answers across a
mid-replay weight push: the replay carries the lost replica's last
advertised reload count (``ADMIN stats`` ``reloads``) and is denied
when the survivor's differs (``ERR backend generation ...``).
``route_replay = 0`` restores the old relay-an-error behavior.

**Tail hedging.** After ``route_hedge_ms`` (or, at ``-1``, the live
federated serve p99) a still-unanswered first attempt launches ONE
duplicate on another replica (``route.hedges``); the first served
answer wins (``route.hedge_wins``), the loser's answer is read and
discarded (``route.discarded_late``) — determinism means both
answers are identical when both arrive (``route.hedge_mismatch``
counts any divergence). Hedges are capped at ``route_hedge_max_pct``
of the requests in flight and denied to over-share tenants; a hedge
never worsens an outcome (an ERR from the hedge lane is only used
when the primary also failed).

Retries respect the client's remaining ``DEADLINE`` budget: the router
parses the bound once at accept, and every forward carries the budget
REMAINING at that instant (so replica-side queue waits spend from the
same clock); a budget that runs out between attempts is answered ``ERR
deadline`` by the router itself. Requests without a deadline are
bounded per attempt by ``stall_s`` — the accept-but-never-answer
(partition) detector.

**Fleet ADMIN.** ``ADMIN stats`` aggregates every reachable replica's
counters (the per-replica counters each reconcile ``accepted == served
+ errors + shed + deadline``, so the fleet sums do too). ``ADMIN
reload`` starts a ROLLING reload: one replica at a time is held out of
rotation, its in-router outstanding requests drain to zero, ``ADMIN
reload`` is forwarded, and the replica rejoins only after its reload
counter moved and ``/healthz`` reads ready — so fleet capacity never
drops below N-1 and a model update is client-invisible. Each hold is
recorded as a (replica, t_out, t_back) drain window (the zero-downtime
acceptance asserts the windows never overlap).

Counters reconcile at the router too: ``accepted == served + errors +
shed + deadline`` (``retries`` and ``admin`` ride outside). statusd
surfaces: ``statusd.set_fleet(router)`` exports ``/fleetz`` and the
``cxxnet_fleet_*`` series; ``health_probe``/``liveness_probe`` plug
into ``/healthz``/``/livez`` like servd's.

**Fleet observability plane** (the cross-process half of
doc/observability.md "Request tracing & SLOs"):

* **Trace propagation** — the router mints ONE fleet-wide request id
  per client request (or adopts a valid client-sent ``TRACE <id>``)
  and stamps ``TRACE <id>`` on every forward attempt; each servd
  replica adopts it as its own request id, so the id names the request
  on every process that touched it (the Dapper idea). Pre-TRACE
  replicas degrade gracefully: a TRACE-prefixed attempt answered ``ERR
  parse`` is resent once WITHOUT the prefix (a parse rejection proves
  the request never dispatched, so the resend is exactly-once safe);
  if the bare resend succeeds the replica is latched ``no_trace`` and
  future forwards skip the prefix (cleared when the replica is
  re-admitted from DEAD — a restart may have upgraded it).
* **Router flight recorder** — every routed request's full routing
  life lands in a bounded ring (``route_flight_cap``): the
  power-of-two candidates and their load signals at pick time, each
  attempt's replica/outcome/latency, retry reasons, and the deadline
  budget spend. Router ``/requestz`` lists it; ``/trace?request=<id>``
  returns the STITCHED cross-process Chrome trace: the router's
  attempt lane plus each touched replica's phase lanes, fetched live
  over the replicas' statusd (``/requestz?request=<id>``) and aligned
  on the shared wall-clock epoch — a retried request shows both
  attempts under one id. Each request also emits a
  ``route_request_done`` event (the ``--fleet`` report join key).
* **Live federation** — every ``fleet_federate_ms`` the prober
  additionally pulls each reachable replica's RAW metrics snapshot
  (statusd ``/metrics?json=1``) and merges the serve histograms and
  counters EXACTLY (shared fixed buckets: merge is bucket-count
  addition, never re-binning) into ``cxxnet_fleet_*`` series on the
  router's own ``/metrics``: fleet TTFT/latency percentiles, a
  fleet-wide SLO burn account over the merged windows (each replica
  just under its own alert floor can still be fleet-over), and a
  per-replica **outlier detector** — a replica whose serve p99
  diverges from the median of the OTHER replicas (leave-one-out, so a
  2-replica fleet can still flag its slow half) by
  ``fleet_outlier_ratio`` x (with at least ``fleet_outlier_min_n``
  requests in its histogram) flips the
  ``cxxnet_fleet_outlier{replica=...}`` gauge, emits a transition-only
  ``fleet_outlier`` event, and is flagged on ``/fleetz``.

**Closed-loop fleet autoscaler** (doc/robustness.md "Fleet
autoscaling"): ``standby_replicas`` lists pre-provisioned replicas
held OUT of dispatch; one policy pass per prober sweep
(``autoscale_now``) admits a standby when the federated fleet SLO burn
reaches ``scale_up_burn`` or there is queued work with zero free
decode slots anywhere (bounds ``scale_min``/``scale_max``), and
retires a scale-up-admitted replica idle for ``scale_down_idle_s`` —
at most one action per ``scale_cooldown_s`` (hysteresis; any load
resets the idle timers). Decisions are transition-only ``fleet_scale``
events + the ``cxxnet_fleet_target_replicas`` /
``cxxnet_fleet_scale_events_total`` series and an /fleetz section.

**Multi-tenant weighted-fair QoS** (doc/serving.md "Multi-tenant
QoS"): with a ``tenants`` table the router validates/forwards the
``TENANT`` prefix (same downgrade discipline as TRACE — a pre-TENANT
replica's ``ERR parse`` pays progressively barer resends, each safe
because a parse rejection proves the request never dispatched, and
latches ``no_tenant``), keeps per-tenant reconciling books, sheds an
over-share tenant at the door when the fleet is saturated, and merges
per-tenant SLO windows/latency histograms into
``cxxnet_fleet_tenant_*{tenant=}`` series. Its own per-tenant trackers
observe ONLY zero-attempt outcomes (door sheds), so the federated
merge never counts a request twice.

Deliberately jax-free (the replicas are other processes); ``python -m
cxxnet_tpu.utils.routerd --selftest`` drives routing, retry, ejection,
rolling reload and drain over real loopback sockets with in-process
servd replicas — ``make check`` gates on it. The driver surface is
``task = route`` (conf keys ``route_port`` / ``route_replicas`` /
``route_probe_ms`` / ``route_retries`` / ``route_stall_s`` /
``route_host`` / ``route_standby_replicas`` / ``route_scale_*`` /
``route_tenants`` — doc/serving.md "Replicated serving fleet").
"""

from __future__ import annotations

import json
import random
import re
import socket
import statistics
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

from . import autopsy
from . import checkpoint as ckpt
from . import health
from . import lockrank
from . import servd
from . import telemetry

__all__ = ["Replica", "Router", "parse_replicas", "retryable",
           "route_chrome_trace", "stitched_chrome_trace",
           "UP", "DRAINING", "WARMING", "BREAKER_OPEN", "DEAD",
           "selftest"]

UP = "up"
DRAINING = "draining"
# warming: the replica's /healthz answers 503 "warming: ..." — its
# warm-grid readiness gate (servd.set_warm_account) is unmet. Probed
# and ADMIN-answering (the warm fraction keeps refreshing onto
# /fleetz) but NOT routed; flips to UP by itself once the grid
# compiles. The autoscaler MAY admit a warming standby — that is
# exactly the "admitted vs useful" gap the scale-up event's warm_pct
# field measures.
WARMING = "warming"
BREAKER_OPEN = "breaker_open"
DEAD = "dead"

# stat key -> telemetry counter (reconciliation mirrors servd's:
# accepted == served + errors + shed + deadline; retries/admin outside)
_COUNTERS = {
    "accepted": "route.accepted",
    "served": "route.served",
    "errors": "route.errors",
    "shed": "route.shed",
    "deadline": "route.deadline",
    "admin": "route.admin",
    "retries": "route.retries",
    "client_gone": "route.client_gone",
    # failover account (module docstring "Deterministic replay
    # failover" / "Tail hedging"): OUTSIDE the reconciling subset like
    # retries — these count extra ATTEMPTS and discarded duplicate
    # answers; the client request is charged exactly once above
    "lost_contact": "route.lost_contact",
    "replays": "route.replays",
    "replay_denied": "route.replay_denied",
    "hedges": "route.hedges",
    "hedge_wins": "route.hedge_wins",
    "discarded_late": "route.discarded_late",
}
# the per-tenant reconciling subset — ONE definition with servd (the
# shared-parser discipline: router and replica books must never
# desynchronize): accepted == served + errors + shed + deadline per
# tenant
_TENANT_KEYS = servd._TENANT_KEYS



def parse_replicas(spec) -> List[Tuple[str, int, int]]:
    """``route_replicas`` conf value -> [(host, serve_port,
    status_port)]. Items are comma/whitespace separated, each
    ``host:serve_port:status_port`` (host defaults to 127.0.0.1 when
    only two fields are given)."""
    if not isinstance(spec, str):
        return list(spec)
    out: List[Tuple[str, int, int]] = []
    for item in re.split(r"[,\s]+", spec.strip()):
        if not item:
            continue
        bits = item.rsplit(":", 2)
        if len(bits) == 2:
            host, port, sport = "127.0.0.1", bits[0], bits[1]
        elif len(bits) == 3:
            host, port, sport = bits
        else:
            raise ValueError(
                "route_replicas item %r is not host:port:status_port"
                % item)
        out.append((host or "127.0.0.1", int(port), int(sport)))
    return out


def retryable(resp: str) -> bool:
    """The retryability half of the wire contract (module docstring):
    True only when the response PROVES the request never dispatched to
    a backend — a shed (``ERR busy``) or a drain refusal that is not
    the drain-gave-up-on-in-flight case (``ERR draining backend``) —
    AND a different replica could rule differently. ``ERR busy
    tenant`` is the exception on the second clause: it never
    dispatched, but it is the weighted-fair POLICY verdict, and every
    replica shares the tenant table — retrying a flooding tenant's
    shed elsewhere only doubles the flood's traffic. Everything else
    stays with the replica: exactly-once beats availability."""
    toks = resp.split()
    if toks[:2] == ["ERR", "busy"]:
        return toks[2:3] != ["tenant"]
    if toks[:2] == ["ERR", "draining"]:
        return toks[2:3] != ["backend"]
    return False


def _http_get(host: str, port: int, path: str,
              timeout: float) -> Tuple[int, str]:
    """Tiny GET helper -> (status, body); raises OSError when the
    endpoint is unreachable (URLError is an OSError)."""
    from urllib.error import HTTPError
    from urllib.request import urlopen
    try:
        with urlopen("http://%s:%d%s" % (host, port, path),
                     timeout=timeout) as resp:
            return resp.status, resp.read().decode("utf-8", "replace")
    except HTTPError as e:
        return e.code, e.read().decode("utf-8", "replace")


class _SloMerge:
    """Accumulate SLOTracker.snapshot() dicts from N replicas into ONE
    merged-window account (requests/bad summed, the tightest budget,
    fleet-wide alert floors) — the shape ``federation_snapshot`` hangs
    on ``slo`` fleet-wide and on ``slo_tenants`` per tenant."""

    def __init__(self):
        self.req = self.bad = 0
        self.budget = None
        self.floor_req = self.floor_bad = 1
        self.seen = False

    def add(self, slo) -> None:
        if not slo:
            return
        self.seen = True
        self.req += int(slo.get("requests", 0))
        self.bad += int(slo.get("bad", 0))
        if slo.get("budget") is not None:
            b = float(slo["budget"])
            self.budget = b if self.budget is None \
                else min(self.budget, b)
        self.floor_req = max(self.floor_req,
                             int(slo.get("min_requests", 1)))
        self.floor_bad = max(self.floor_bad, int(slo.get("min_bad", 1)))

    def result(self):
        if not self.seen or self.budget is None:
            return None
        bad_fraction = self.bad / float(self.req) if self.req else 0.0
        burn = bad_fraction / self.budget
        return {"requests": self.req, "bad": self.bad,
                "budget": round(self.budget, 6),
                "bad_fraction": round(bad_fraction, 6),
                "burn_rate": round(burn, 4),
                "alert": 1 if (self.req >= self.floor_req
                               and self.bad >= self.floor_bad
                               and burn >= 1.0) else 0}


class Replica:
    """One replica's routing state. All mutable fields are guarded by
    the router's fleet lock; the object itself is a dumb record."""

    __slots__ = ("name", "host", "port", "status_port", "state",
                 "detail", "hold", "queue_depth", "in_flight",
                 "free_slots", "has_slots", "kv_blocks_total",
                 "kv_blocks_free", "has_kv_blocks",
                 "kv_retained_blocks", "kv_retained_hits",
                 "has_kv_retained",
                 "warm_programs", "expected_programs", "has_warm",
                 "buckets", "outstanding", "reloads", "lost",
                 "probe_fails", "ejections", "next_probe_at",
                 "last_probe", "no_trace", "trace_ok",
                 "no_tenant", "tenant_ok", "standby", "from_standby")

    def __init__(self, host: str, port: int, status_port: int,
                 standby: bool = False):
        self.host = host
        self.port = int(port)
        self.status_port = int(status_port)
        self.name = "%s:%d" % (host, self.port)
        # optimistic start: routable until a probe or a dispatch says
        # otherwise — a router must not refuse traffic for probe_ms
        # after startup when the fleet is healthy
        self.state = UP
        self.detail = "unprobed (optimistic)"
        self.hold = False            # rolling reload: out of rotation
        self.queue_depth = 0         # last probed gauges (load signal)
        self.in_flight = 0
        self.free_slots = 0          # continuous-batching capacity: a
        #                              batching replica reports free
        #                              decode slots; old replicas omit
        #                              the field (0 = no bonus)
        self.has_slots = False       # whether the replica REPORTS
        #                              free_slots at all — absent means
        #                              no batching, and 0 must then read
        #                              as "unknown", not "saturated"
        self.kv_blocks_total = 0     # paged-KV pool level from ADMIN
        self.kv_blocks_free = 0      # stats (kv_blocks_total/free):
        #                              process-global (the pool is
        #                              shared across buckets), so the
        #                              fleet sum is exact. Absent on
        #                              dense/pre-paging replicas —
        self.has_kv_blocks = False   # the same absence-is-the-
        #                              capability-signal discipline as
        #                              free_slots
        self.kv_retained_blocks = 0  # retained conversation cache
        self.kv_retained_hits = 0    # (ADMIN kv_retained_blocks/
        #                              kv_retained_hits): refcount-0
        #                              blocks parked for revival and
        #                              the lifetime revival count —
        #                              absent on pre-retention
        self.has_kv_retained = False  # replicas ("-", never 0)
        self.warm_programs = 0       # warm-grid readiness from ADMIN
        self.expected_programs = 0   # stats (compiled vs expected
        #                              serving programs) — the /fleetz
        #                              warm column and the scale-up
        #                              event's warm_pct read these.
        self.has_warm = False        # absent on replicas with no
        #                              declared grid: "-", never 0%
        self.buckets = {}            # per-bucket load signal from
        #                              ADMIN stats (bucket.<b>.warm /
        #                              .active): {b: {"warm", "active"}}
        #                              — what /fleetz shows and
        #                              disaggregated scheduling will
        #                              route on; empty pre-batching
        self.outstanding = 0         # router-side live request count
        self.reloads = -1            # model generation: the replica's
        #                              ADMIN stats reload count, -1
        #                              until probed — the replay
        #                              failover's generation guard
        #                              compares the lost replica's
        #                              against the survivor's
        self.lost = 0                # lost-contact attempts charged to
        #                              this replica (the /fleetz
        #                              failover column)
        self.probe_fails = 0
        self.ejections = 0           # backoff exponent while dead
        self.next_probe_at = 0.0     # monotonic; dead replicas re-probe
        #                              on the backoff schedule only
        self.last_probe: Optional[float] = None
        # pre-TRACE replica latch (module docstring): once a TRACE
        # prefix was proven unsupported (ERR parse on the traced line,
        # success on the bare resend) forwards skip the prefix; cleared
        # on re-admission from DEAD (a restart may have upgraded it).
        # trace_ok is the POSITIVE latch: one traced exchange answered
        # by anything but ERR parse proves the replica parsed the
        # prefix, so later ERR parse answers are genuine client body
        # errors and never pay the downgrade resend (also cleared on
        # re-admission — a rollback may have downgraded the binary)
        self.no_trace = False
        self.trace_ok = False
        # the TENANT prefix's pre-tenant latch pair — exactly the TRACE
        # discipline: no_tenant after a proven downgrade, tenant_ok
        # after a proven parse, both re-learned on DEAD -> UP
        self.no_tenant = False
        self.tenant_ok = False
        # autoscaler state: a standby replica is listed in the conf but
        # held OUT of dispatch until a scale-up admits it; from_standby
        # marks scale-up admits as the ones a scale-down may retire
        # (the fleet returns to its configured shape when idle)
        self.standby = bool(standby)
        self.from_standby = bool(standby)

    def warm_pct(self) -> Optional[float]:
        """Warm fraction of the replica's expected program grid, or
        None when it reports no grid (fleet-lock caller)."""
        if not self.has_warm or self.expected_programs <= 0:
            return None
        return round(100.0 * self.warm_programs
                     / self.expected_programs, 1)

    def snapshot(self, now: float) -> dict:
        return {"name": self.name, "state": self.state,
                "standby": self.standby,
                "detail": self.detail, "hold": self.hold,
                "queue_depth": self.queue_depth,
                "in_flight": self.in_flight,
                "free_slots": self.free_slots,
                "kv_blocks_total": self.kv_blocks_total
                if self.has_kv_blocks else None,
                "kv_blocks_free": self.kv_blocks_free
                if self.has_kv_blocks else None,
                "kv_retained_blocks": self.kv_retained_blocks
                if self.has_kv_retained else None,
                "kv_retained_hits": self.kv_retained_hits
                if self.has_kv_retained else None,
                "warm_programs": self.warm_programs
                if self.has_warm else None,
                "expected_programs": self.expected_programs
                if self.has_warm else None,
                "warm_pct": self.warm_pct(),
                "buckets": {str(b): dict(d) for b, d
                            in sorted(self.buckets.items())},
                "outstanding": self.outstanding,
                "reloads": self.reloads if self.reloads >= 0 else None,
                "lost": self.lost,
                "ejections": self.ejections,
                "probe_fails": self.probe_fails,
                "last_probe_age_s": None if self.last_probe is None
                else round(now - self.last_probe, 3)}


class Router:
    """The fleet router. ``replicas`` is a ``parse_replicas`` spec (or
    its output). Lifecycle mirrors servd: ``start()`` (prober thread) →
    ``listen(port)`` (accept thread) → ``drain()``.

    Client connections are handled one request at a time per connection
    (the positional line protocol pairs responses to requests, and the
    forward is synchronous), so fleet concurrency comes from concurrent
    connections — exactly the shape of the serving chaos harness."""

    def __init__(self, replicas, probe_ms: float = 200.0,
                 retries: int = 2, stall_s: float = 30.0,
                 drain_ms: float = 5000.0,
                 connect_timeout: float = 1.0,
                 probe_timeout: float = 1.0,
                 client_timeout: float = 10.0,
                 probe_backoff_cap_s: float = 30.0,
                 reload_timeout_s: float = 30.0,
                 flight_cap: int = 256,
                 federate_ms: float = 1000.0,
                 outlier_ratio: float = 3.0,
                 outlier_min_n: int = 20,
                 standby_replicas=None,
                 scale_min: int = 0, scale_max: int = 0,
                 scale_up_burn: float = 1.0,
                 scale_down_idle_s: float = 30.0,
                 scale_cooldown_s: float = 10.0,
                 tenants=None, tenant_default: str = "default",
                 slo_tenants=None,
                 replay: bool = True,
                 hedge_ms: float = 0.0,
                 hedge_max_pct: float = 10.0):
        specs = parse_replicas(replicas)
        if not specs:
            raise ValueError("router needs at least one replica")
        self._replicas = [Replica(*s) for s in specs]
        # autoscaler (module docstring "Fleet autoscaling"): standby
        # replicas ride the same probe/state machinery but are held out
        # of dispatch until autoscale_now() admits one; bounds default
        # to [primary count, total count]
        standby_specs = parse_replicas(standby_replicas or [])
        self._replicas += [Replica(*s, standby=True)
                           for s in standby_specs]
        n_primary = len(specs)
        self.scale_min = int(scale_min) if scale_min > 0 else n_primary
        self.scale_max = int(scale_max) if scale_max > 0 \
            else len(self._replicas)
        self.scale_up_burn = float(scale_up_burn)
        self.scale_down_idle_s = float(scale_down_idle_s)
        self.scale_cooldown_s = float(scale_cooldown_s)
        # scale decisions + idle bookkeeping live under their own rank
        # (lockrank "routerd.scale", OUTSIDE the fleet lock: a decision
        # reads fleet state and then marks replicas under it); all IO —
        # probing a standby before admitting it — stays outside
        self._scale_lock = lockrank.lock("routerd.scale")
        self._scale_last = -float("inf")   # monotonic of last action
        self._scale_events = 0
        self._scale_log: List[dict] = []
        self._idle_since: Dict[str, float] = {}
        # multi-tenant weighted-fair QoS: the shared tenant table (one
        # parse_tenants implementation with servd — the processes
        # enforcing fairness must agree on it)
        self._tenants = servd.parse_tenants(tenants)
        self.tenant_default = str(tenant_default)
        if self._tenants and self.tenant_default not in self._tenants:
            self._tenants[self.tenant_default] = 1.0
        self._tstats: Dict[str, Dict[str, int]] = {
            t: {k: 0 for k in _TENANT_KEYS} for t in self._tenants}
        self._tenant_active: Dict[str, int] = {
            t: 0 for t in self._tenants}
        # per-tenant SLO trackers for requests that NEVER touched a
        # replica (door sheds — the fair-share gate, no-routable-fleet,
        # router-side deadline): every replica-touching request is
        # already in some replica's own window, so observing only the
        # zero-attempt outcomes here keeps the federated merge
        # double-count-free while a flood shed entirely at the router's
        # door still burns ITS tenant's fleet-wide budget (the
        # burn-reads-0-under-total-overload trap, the router edition)
        self.slo_tenants = dict(slo_tenants or {})
        self.probe_s = max(0.01, float(probe_ms) / 1e3)
        self.retries = max(0, int(retries))
        self.stall_s = float(stall_s)
        self.drain_ms = float(drain_ms)
        self.connect_timeout = float(connect_timeout)
        self.probe_timeout = float(probe_timeout)
        self.client_timeout = float(client_timeout)
        self.probe_backoff_cap_s = float(probe_backoff_cap_s)
        self.reload_timeout_s = float(reload_timeout_s)
        # federation cadence (0 disables) + outlier thresholds: a
        # replica whose serve p99 exceeds outlier_ratio x the median
        # of the OTHER replicas (with >= outlier_min_n observations
        # behind it) is flagged — conf keys fleet_federate_ms /
        # fleet_outlier_*
        self.federate_s = max(0.0, float(federate_ms) / 1e3)
        self.outlier_ratio = float(outlier_ratio)
        self.outlier_min_n = max(1, int(outlier_min_n))
        # ranked locks (utils/lockrank.py): fleet state outermost, then
        # stats — both may record telemetry (registry is innermost)
        self._lock = lockrank.lock("routerd.fleet")
        self._slock = lockrank.lock("routerd.stats")
        # federated per-replica metric snapshots + outlier verdicts
        # (written by the prober's federation sweep, read per scrape)
        self._fed_lock = lockrank.lock("routerd.fed")
        self._fed: Dict[str, dict] = {}
        self._fed_outlier: Dict[str, dict] = {}
        self._fed_at = 0.0
        # the routing flight recorder: one record per routed request —
        # candidates at pick time, per-attempt replica/outcome/latency,
        # deadline spend (statusd /requestz + the /trace stitch source)
        self.flight = telemetry.FlightRecorder(flight_cap)
        # fleet-wide trace-id minting: a short random prefix makes ids
        # from a restarted (or second) router distinguishable without
        # coordination; the counter rides the stats lock
        self._trace_prefix = "r%05x" % random.randrange(16 ** 5)
        self._trace_n = 0
        self._stats = {k: 0 for k in _COUNTERS}
        # failover knobs (module docstring "Deterministic replay
        # failover" / "Tail hedging"): replay gates the lost->replay
        # path, hedge_ms the duplicate-attempt delay (0 off, -1 = live
        # federated serve p99), hedge_max_pct the in-flight hedge cap
        self.replay = bool(replay)
        self.hedge_ms = float(hedge_ms)
        self.hedge_max_pct = float(hedge_max_pct)
        self._hedge_auto_s: Optional[float] = None  # GIL-atomic store,
        #                              written by the federation sweep
        self._hedges_live = 0        # under _slock: duplicate attempts
        #                              currently in flight (the cap)
        self._draining = False
        self._stop = False
        self._active = 0             # requests currently being handled
        self._reloading = False
        self._windows: List[Tuple[str, float, float]] = []
        self._wake = threading.Event()
        self._probe_thread: Optional[threading.Thread] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._sock: Optional[socket.socket] = None
        self.port: Optional[int] = None

    # -- lifecycle -----------------------------------------------------
    def start(self) -> "Router":
        telemetry.declare_hist("route.request")
        telemetry.gauge("route.replicas", len(self._replicas))
        telemetry.gauge("route.replicas_up", len(self._replicas))
        telemetry.audit_register("route.books", self._law_books)
        telemetry.audit_register("route.tenant_books",
                                 self._law_tenant_books)
        telemetry.audit_register("fleet.federation",
                                 self._law_federation)
        self._probe_thread = threading.Thread(
            target=self._prober_run, name="cxn-routerd-probe",
            daemon=True)
        self._probe_thread.start()
        return self

    def listen(self, port: int = 0, host: str = "") -> int:
        self._sock = socket.create_server((host or "127.0.0.1",
                                           int(port)))
        self._sock.settimeout(0.25)
        self.port = self._sock.getsockname()[1]
        self._accept_thread = threading.Thread(
            target=self._accept_run, name="cxn-routerd-accept",
            daemon=True)
        self._accept_thread.start()
        telemetry.event({"ev": "route_listen", "port": self.port,
                         "replicas": [r.name for r in self._replicas]})
        return self.port

    def stats(self) -> dict:
        with self._slock:
            return dict(self._stats)

    def _bump(self, *names: str) -> None:
        with self._slock:
            for name in names:
                self._stats[name] += 1
        for name in names:
            telemetry.count(_COUNTERS[name])

    # -- conservation laws (telemetry.BooksAuditor) --------------------
    def _law_books(self) -> Optional[str]:
        """``accepted == served + errors + shed + deadline`` up to the
        requests in flight. _handle's ordering makes both directions
        sound: the active slot is claimed BEFORE accepted is bumped and
        released AFTER the outcome lands, so at every instant
        ``active >= accepted - outcomes`` — outcomes exceeding accepted
        is an immediate violation, the forward direction must persist
        across stable-snapshot brackets (a bracket the books moved
        through is inconclusive, never a latch)."""
        detail = None
        for _ in range(6):
            with self._slock:
                s1 = dict(self._stats)
            with self._lock:
                active = self._active
            with self._slock:
                s2 = dict(self._stats)
            if s1 != s2:
                return None          # the books moved mid-bracket
            a = s1["accepted"]
            o = (s1["served"] + s1["errors"] + s1["shed"]
                 + s1["deadline"])
            if o > a:
                return ("route books: outcomes %d exceed accepted %d "
                        "(served %d + errors %d + shed %d + deadline "
                        "%d)" % (o, a, s1["served"], s1["errors"],
                                 s1["shed"], s1["deadline"]))
            if a <= o + active:
                return None
            detail = ("route books: accepted %d != outcomes %d + "
                      "in-flight %d" % (a, o, active))
            time.sleep(0.005)        # let an in-limbo answer land
        return detail

    def _law_tenant_books(self) -> Optional[str]:
        """Per outcome key, the tenant charges sum to at most the
        door's own books — exact, because ONE stats-lock snapshot
        covers both, and _handle bumps the global counter BEFORE the
        tenant's for accepted and outcome alike."""
        if not self._tenants:
            return None
        with self._slock:
            g = dict(self._stats)
            ts = {t: dict(st) for t, st in self._tstats.items()}
        for k in _TENANT_KEYS:
            tot = sum(st[k] for st in ts.values())
            if tot > g[k]:
                return ("route tenant books: tenant %s charges sum to "
                        "%d, the door counted %d" % (k, tot, g[k]))
        return None

    def _law_federation(self) -> Optional[str]:
        """Every federated fleet counter equals the sum of the stored
        replica feeds — the merge must never invent or lose a count.
        Recomputed from the SAME stored snapshots the snapshot method
        reads; a federation sweep landing mid-check makes the bracket
        inconclusive (epoch recheck), never a latch."""
        with self._fed_lock:
            if not self._fed:
                return None
            at1 = self._fed_at
            feeds = [d["snap"] for d in self._fed.values()]
        snap = self.federation_snapshot()
        with self._fed_lock:
            if self._fed_at != at1:
                return None          # a sweep landed mid-check
        if snap is None:
            return None
        expect: Dict[str, float] = {}
        for s in feeds:
            for cname, v in ((s.get("metrics") or {})
                             .get("counters") or {}).items():
                if cname.startswith("serve.") \
                        and isinstance(v, (int, float)):
                    expect[cname] = expect.get(cname, 0) + v
        got = snap.get("counters") or {}
        for cname, v in expect.items():
            if got.get(cname, 0) != v:
                return ("federation books: fleet %s = %r != sum of "
                        "replica feeds %r"
                        % (cname, got.get(cname, 0), v))
        return None

    # -- health (statusd probes) ---------------------------------------
    def health_probe(self) -> Tuple[bool, str]:
        """Readiness: the router can place a request somewhere."""
        if self._draining:
            return False, "draining: not accepting new requests"
        with self._lock:
            n = sum(1 for r in self._replicas
                    if r.state == UP and not r.hold
                    and not r.standby)
            total = len(self._replicas)
        if n == 0:
            return False, ("no routable replica (0 of %d up)" % total)
        return True, "routing to %d of %d replicas" % (n, total)

    def liveness_probe(self) -> Tuple[bool, str]:
        t = self._probe_thread
        if t is not None and not t.is_alive() and not self._stop:
            return False, "router prober thread died"
        return True, "alive"

    # -- fleet snapshot (statusd /fleetz + cxxnet_fleet_* series) ------
    def fleet_snapshot(self) -> dict:
        now = time.monotonic()
        with self._lock:
            reps = [r.snapshot(now) for r in self._replicas]
            eligible = sum(1 for r in self._replicas
                           if r.state == UP and not r.hold
                           and not r.standby)
            windows = [{"replica": n, "out_s": round(a, 3),
                        "back_s": round(b, 3)}
                       for n, a, b in self._windows[-32:]]
            body = {"replicas": reps, "eligible": eligible,
                    "draining": self._draining,
                    "reloading": self._reloading,
                    "windows": windows}
        body["stats"] = self.stats()
        fed = self.federation_snapshot()
        if fed is not None:
            body["federation"] = fed
            for rsnap in reps:
                v = fed["outliers"].get(rsnap["name"])
                if v is not None:
                    rsnap["outlier"] = v["outlier"]
                    rsnap["p99_ms"] = v["p99_ms"]
        if self.scaling_enabled():
            body["scale"] = self.scale_snapshot()
        if self._tenants:
            # one per-tenant table joining the router's own books, the
            # federated fleet books, and the per-tenant fleet SLO — the
            # /fleetz "tenants" section and the cxxnet_fleet_tenant_*
            # label rows render from exactly this
            tstats = self.tenant_stats()
            ften = (fed or {}).get("tenants") or {}
            fslo = (fed or {}).get("slo_tenants") or {}
            body["tenants"] = {
                t: {"weight": self._tenants[t],
                    "router": tstats.get(t) or {},
                    "fleet": ften.get(t) or {},
                    "slo": fslo.get(t),
                    "p99_ms": (ften.get(t) or {}).get("p99_ms")}
                for t in sorted(self._tenants)}
        return body

    # -- replica state machine (fleet lock) ----------------------------
    def _mark(self, r: Replica, state: str, detail: str) -> None:
        """Move one replica's state machine; emits a transition event
        (never per-observation spam). Lock taken here — callers must
        NOT hold the fleet lock (the event emission nests registry
        under fleet, which the rank order allows, but the IO callers
        around this must stay lock-free)."""
        with self._lock:
            prev = r.state
            r.state = state
            r.detail = detail
            if state == UP and prev == DEAD:
                # re-admission after death: the process may have been
                # restarted on a newer (or OLDER) build — re-learn its
                # TRACE capability from scratch
                r.no_trace = False
                r.trace_ok = False
                r.no_tenant = False
                r.tenant_ok = False
            if state == DEAD:
                # ejection: re-probe on the shared backoff curve; each
                # consecutive failure doubles the wait
                r.next_probe_at = time.monotonic() + ckpt.backoff_delay(
                    r.ejections, base_delay=self.probe_s,
                    cap=self.probe_backoff_cap_s)
                r.ejections += 1
                r.probe_fails += 1
            elif state == UP:
                r.ejections = 0
                r.probe_fails = 0
            up = sum(1 for x in self._replicas if x.state == UP)
            changed = prev != state
            if changed:
                telemetry.count("route.transitions")
                telemetry.event({"ev": "route_replica",
                                 "replica": r.name, "state": state,
                                 "prev": prev, "detail": detail[:120]})
        if changed:
            telemetry.gauge("route.replicas_up", up)

    # -- prober --------------------------------------------------------
    def probe_now(self) -> None:
        """One synchronous probe sweep (tests, and the driver's initial
        fleet check) — same classification as the prober thread."""
        for r in list(self._replicas):
            with self._lock:
                if r.state == DEAD and \
                        time.monotonic() < r.next_probe_at:
                    continue             # still backing off
                host, sport = r.host, r.status_port
            self._probe_one(r, host, sport)

    def _probe_one(self, r: Replica, host: str, sport: int) -> None:
        # ALL IO lock-free; the classification lands via _mark
        try:
            code, body = _http_get(host, sport, "/healthz",
                                   self.probe_timeout)
        except OSError as e:
            self._mark(r, DEAD, "statusd unreachable: %r" % (e,))
            return
        with self._lock:
            r.last_probe = time.monotonic()
        if code == 200:
            self._refresh_load(r)
            self._mark(r, UP, "ready")
        else:
            lower = body.lower()
            if "draining" in lower:
                self._mark(r, DRAINING, body.strip()[:120])
            elif "warming" in lower:
                # warm-grid gate unmet (servd.set_warm_account): out
                # of rotation like breaker_open, but the replica's
                # ADMIN surface is live — keep refreshing its load and
                # warm counts so /fleetz shows the warm fraction
                # CLIMB, not a stale snapshot from admission time
                self._refresh_load(r)
                self._mark(r, WARMING, body.strip()[:120])
            else:
                # breaker open, stalled backend, anomaly: unready for a
                # cause other than drain — grouped as breaker_open (out
                # of rotation until a ready probe; statusd reachable,
                # so no backoff ejection)
                self._mark(r, BREAKER_OPEN, body.strip()[:120])

    def _refresh_load(self, r: Replica) -> None:
        """Refresh one replica's load/capability signals from its own
        ADMIN stats (the live queue_depth/in_flight gauges, read under
        its admission lock): per-replica-exact even when replicas
        share one telemetry registry in-process, and far cheaper than
        a /metrics scrape (which runs the replica's whole probe pass +
        registry snapshot per poll). The same gauges ride /metrics for
        dashboards. IO lock-free; the update lands under the fleet
        lock."""
        st = self._replica_stats(r)
        if st is None:
            return
        with self._lock:
            r.queue_depth = st.get("queue_depth", r.queue_depth)
            r.in_flight = st.get("in_flight", r.in_flight)
            # model generation (reload count): the replay failover's
            # generation guard — defensive parse, same reason as below
            if "reloads" in st:
                try:
                    r.reloads = int(st["reloads"])
                except (TypeError, ValueError):
                    pass
            # absent on pre-batching replicas: reset to 0, not
            # last-known — the field IS the capability signal
            r.free_slots = st.get("free_slots", 0)
            r.has_slots = "free_slots" in st
            # paged-KV pool level: same absent-means-dense
            # discipline, and the same defensive parse — a
            # foreign replica may emit any value shape, and an
            # exception here would kill the prober for good
            try:
                r.kv_blocks_total = int(st.get("kv_blocks_total", 0))
                r.kv_blocks_free = int(st.get("kv_blocks_free", 0))
            except (TypeError, ValueError):
                r.kv_blocks_total = r.kv_blocks_free = 0
            r.has_kv_blocks = "kv_blocks_total" in st
            # retained conversation cache (PR 18): same absent-means-
            # no-retention discipline, same defensive parse — garbage
            # from a foreign replica must not kill the prober
            try:
                r.kv_retained_blocks = int(
                    st.get("kv_retained_blocks", 0))
                r.kv_retained_hits = int(
                    st.get("kv_retained_hits", 0))
            except (TypeError, ValueError):
                r.kv_retained_blocks = r.kv_retained_hits = 0
            r.has_kv_retained = "kv_retained_blocks" in st
            # warm-grid readiness (warm_programs/expected_programs):
            # the compile-cliff account — absent on replicas with no
            # declared grid, and the same defensive parse
            try:
                r.warm_programs = int(st.get("warm_programs", 0))
                r.expected_programs = int(
                    st.get("expected_programs", 0))
            except (TypeError, ValueError):
                r.warm_programs = r.expected_programs = 0
            r.has_warm = "expected_programs" in st
            # per-bucket warm/active counts (bucket.<b>.warm /
            # bucket.<b>.active): the per-bucket load signal —
            # wholesale replacement, same absent-means-none
            # discipline as free_slots
            buckets: Dict[int, dict] = {}
            for k, v in st.items():
                if not k.startswith("bucket."):
                    continue
                # defensive parse: a foreign/old replica may
                # emit any 'bucket.*' shape, and a ValueError
                # here would kill the prober thread for good
                parts = k.split(".")
                if len(parts) != 3 \
                        or parts[2] not in ("warm", "active",
                                            "blocks_held"):
                    continue
                try:
                    buckets.setdefault(
                        int(parts[1]), {})[parts[2]] = v
                except ValueError:
                    continue
            r.buckets = buckets

    def _prober_run(self) -> None:
        # wait FIRST: replicas start optimistic (routable), so the
        # sweep is refresh, not gate — and a driver that wants a
        # verified fleet before serving calls probe_now() itself
        while True:
            health.beat("route.probe")
            self._wake.wait(self.probe_s)
            with self._lock:
                if self._draining or self._stop:
                    break
            self.probe_now()
            if self.federate_s > 0:
                with self._fed_lock:
                    due = (time.monotonic() - self._fed_at
                           >= self.federate_s)
                if due:
                    self.federate_now()
            # the control-plane half: every sweep's fresh signals feed
            # one autoscale policy pass (no-op without standbys; its
            # own cooldown is the hysteresis)
            self.autoscale_now()
        health.pause("route.probe")

    # -- dispatch ------------------------------------------------------
    def _load(self, r: Replica) -> float:
        # free decode slots SUBTRACT: a request a replica can batch
        # into its running decode pass costs no queueing there — the
        # power-of-two pick prefers the replica that can batch it in
        # (may go negative: idle batching capacity beats idle solo)
        return (r.queue_depth + r.in_flight + r.outstanding
                - r.free_slots)

    def _pick(self, exclude) -> Tuple[Optional[Replica], List[dict]]:
        """Power-of-two-choices among eligible replicas (up, not held,
        not yet tried for this request); the checked-out replica's
        outstanding count is bumped under the same lock so concurrent
        picks see each other's load. Also returns the sampled
        candidates' load signals AT PICK TIME — the flight record keeps
        them, so a routing decision stays explainable after the fact."""
        with self._lock:
            elig = [r for r in self._replicas
                    if r.state == UP and not r.hold and not r.standby
                    and r.name not in exclude]
            if not elig:
                return None, []
            if len(elig) == 1:
                r = elig[0]
                sample = [r]
            else:
                a, b = random.sample(elig, 2)
                sample = [a, b]
                la, lb = self._load(a), self._load(b)
                if la == lb:
                    # deterministic tie-break: the lower replica index
                    # (selftest + zero-load behavior must not flap)
                    r = a if self._replicas.index(a) \
                        < self._replicas.index(b) else b
                else:
                    r = a if la < lb else b
            cands = [{"replica": x.name, "load": self._load(x),
                      "queue_depth": x.queue_depth,
                      "in_flight": x.in_flight,
                      "free_slots": x.free_slots,
                      "outstanding": x.outstanding} for x in sample]
            r.outstanding += 1
            return r, cands

    def _checkin(self, r: Replica) -> None:
        with self._lock:
            r.outstanding = max(0, r.outstanding - 1)

    def _forward_keep(self, r: Replica, line: str, timeout: float
                      ) -> Tuple[str, Optional[str], Optional[socket.socket]]:
        """One attempt against one replica -> (status, response, sock):
        ``ok`` (a response line), ``noconnect`` (the request never
        left: SAFE to retry), ``lost`` (sent, then EOF/timeout: the
        request MAY have dispatched). On a read TIMEOUT the still-open
        socket is returned so the replay path can reap — and count —
        a late answer (``route.discarded_late``); on EOF/send failure
        there is nothing to reap and sock is None. A fresh connection
        per attempt: a pooled socket into a replica that died between
        requests would turn an innocent request into a false 'lost'."""
        try:
            c = socket.create_connection((r.host, r.port),
                                         timeout=self.connect_timeout)
        except OSError:
            return "noconnect", None, None
        keep = False
        try:
            c.settimeout(max(0.05, timeout))
            try:
                c.sendall((line + "\n").encode("utf-8", "replace"))
                resp = c.makefile("r", encoding="utf-8").readline()
            except socket.timeout:
                # subclass of OSError — MUST be caught first: a timeout
                # means the replica may still answer on this socket
                keep = True
                return "lost", None, c
            except OSError:
                return "lost", None, None
            if not resp:
                return "lost", None, None
            return "ok", resp.rstrip("\n"), None
        finally:
            if not keep:
                try:
                    c.close()
                except OSError:
                    pass

    def _forward(self, r: Replica, line: str,
                 timeout: float) -> Tuple[str, Optional[str]]:
        """_forward_keep without the reapable socket (probes, ADMIN):
        any kept socket is closed — a late probe answer has no replay
        to feed."""
        status, resp, sock = self._forward_keep(r, line, timeout)
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
        return status, resp

    def _reap_socket(self, sock: socket.socket, rname: str, tid: str,
                     grace_s: float) -> None:
        """Drain a lost attempt's kept socket in the background: a late
        answer arriving after the request was replayed elsewhere is
        discarded and COUNTED (route.discarded_late) — the observable
        half of the exactly-once-to-the-client guarantee. Always closes
        the socket; daemon thread, never joined (drain does not wait on
        a dead replica's silence)."""
        def run():
            late = False
            try:
                sock.settimeout(max(0.05, grace_s))
                late = bool(sock.makefile(
                    "r", encoding="utf-8").readline())
            except OSError:
                pass
            finally:
                try:
                    sock.close()
                except OSError:
                    pass
            if late:
                self._bump("discarded_late")
                telemetry.event({"ev": "route_discarded_late",
                                 "replica": rname, "request": tid})
        threading.Thread(target=run, name="cxn-routerd-reap",
                         daemon=True).start()

    def _mint_trace_id(self) -> str:
        """One fleet-wide request id (router prefix + counter): valid
        per the shared servd contract, unique per router lifetime."""
        with self._slock:
            self._trace_n += 1
            return "%s-%d" % (self._trace_prefix, self._trace_n)

    def _bump_tenant(self, tenant: Optional[str], *names: str) -> None:
        """Per-tenant half of _bump (the reconciling subset) plus the
        ``route.tenant.<t>.<key>`` telemetry mirror — tenant names are
        conf-bounded, so the series set is too."""
        if not self._tenants or tenant not in self._tstats:
            return
        keys = [n for n in names if n in _TENANT_KEYS]
        if not keys:
            return
        with self._slock:
            st = self._tstats[tenant]
            for k in keys:
                st[k] += 1
        for k in keys:
            telemetry.count("route.tenant.%s.%s" % (tenant, k))

    def tenant_stats(self) -> dict:
        with self._slock:
            return {t: dict(st) for t, st in self._tstats.items()}

    def _tenant_gate(self, tenant: Optional[str]) -> Optional[str]:
        """The router's weighted-fair admission check: when the fleet
        is SATURATED — every eligible replica either has a queued
        backlog, or (a batching replica, which reports ``free_slots``)
        is busy with zero free decode slots; a merely-busy solo replica
        with an empty queue is NOT saturated — a tenant already holding
        at least its weighted fair share of the router's in-flight
        requests is shed at the door: third token ``tenant``, the
        policy verdict that holds fleet-wide under the shared tenant
        table (never retried: every replica would rule the same way).
        The share is computed over the tenants ACTIVE right now
        (work-conserving, like _FairQueue's borrow rule: the only
        sending tenant owns the whole fleet — an idle tenant's share is
        never reserved against live traffic), and is floored at 1, so
        a tenant with nothing in flight is never gated. An unsaturated
        fleet admits everyone — fairness never taxes an idle fleet.
        Returns the shed line, or None to admit."""
        if not self._tenants or tenant is None:
            return None
        with self._lock:
            elig = [r for r in self._replicas
                    if r.state == UP and not r.hold and not r.standby]
            saturated = bool(elig) and all(
                r.queue_depth > 0
                or (r.has_slots and r.free_slots <= 0
                    and (r.in_flight + r.outstanding) > 0)
                for r in elig)
        if not saturated:
            return None
        with self._slock:
            active = dict(self._tenant_active)
        total = sum(active.values()) + 1      # the arrival included
        live = {t for t, n in active.items() if n > 0}
        live.add(tenant)
        weight_sum = sum(self._tenants[t] for t in live)
        share = max(1, int(total * self._tenants[tenant] / weight_sum))
        mine = active.get(tenant, 0)
        if mine < share:
            return None
        return ("ERR busy tenant %s over fair share (router: %d "
                "in flight / share %d, fleet saturated)"
                % (tenant, mine, share))

    def _handle(self, line: str) -> str:
        """Route one request line; returns the one response line."""
        parts = line.split()
        # trace propagation: adopt a valid client-sent TRACE id (a
        # request already named upstream keeps its name through this
        # hop — router-of-routers composes), refuse a malformed one
        # with the same ERR proto a replica would (ONE shared checker:
        # servd.parse_trace_prefix), mint otherwise
        tid, proto_detail, parts = servd.parse_trace_prefix(parts)
        # the tenant prefix rides the same discipline (one shared
        # checker: servd.parse_tenant_prefix; TRACE first, then TENANT,
        # then DEADLINE). tenant_sent is the id to FORWARD — a
        # defaulted tenant stays off the wire so prefix-less clients
        # hit the replica byte-identically to the pre-tenant protocol
        tenant_sent = None
        if proto_detail is None:
            tenant_sent, proto_detail, parts = \
                servd.parse_tenant_prefix(parts)
            if proto_detail is None and tenant_sent is not None \
                    and self._tenants \
                    and tenant_sent not in self._tenants:
                proto_detail = ("tenant %s is not in the configured "
                                "tenant table" % tenant_sent)
        # the accounted tenant: None on a protocol violation (nothing
        # to charge a malformed/unknown id to), the configured default
        # for prefix-less clients otherwise
        tenant = None
        if proto_detail is None:
            tenant = tenant_sent
            if tenant is None and self._tenants:
                tenant = self.tenant_default
        proto_err = None if proto_detail is None \
            else "ERR proto " + proto_detail
        if proto_err is None and parts and parts[0] == "ADMIN":
            return self._handle_admin(parts[1:])
        t0 = time.monotonic()
        # parse the deadline ONCE at accept: every retry spends from
        # this clock. A malformed bound is forwarded untouched — the
        # replica's parser answers ERR parse (one implementation).
        deadline = None
        deadline_ms: Optional[float] = None
        rest = parts
        if parts[:1] == ["DEADLINE"] and len(parts) >= 2:
            try:
                budget = float(parts[1]) / 1e3
            except ValueError:
                budget = None
            if budget is not None and 0 <= budget < float("inf"):
                deadline = t0 + budget
                deadline_ms = budget * 1e3
                rest = parts[2:]
        # admission + accounting in one critical section with drain()'s
        # flag flip (the servd rule): a post-drain arrival is refused
        # WITHOUT entering the accounting
        with self._lock:
            if self._draining or self._stop:
                return "ERR draining router is shutting down"
            self._active += 1
        self._bump("accepted")
        self._bump_tenant(tenant, "accepted")
        if tid is None:
            tid = self._mint_trace_id()
        tracked = bool(self._tenants) and tenant is not None
        try:
            attempts: List[dict] = []
            if proto_err is not None:
                text, outcome = proto_err, "errors"
            else:
                # the weighted-fair admission gate BEFORE any replica
                # is touched: a saturated fleet sheds the over-share
                # tenant at the router's door instead of burning a
                # replica queue slot (and a retry) on a verdict every
                # replica would reach anyway
                gate = self._tenant_gate(tenant)
                if gate is not None:
                    text, outcome = gate, "shed"
                else:
                    if tracked:
                        with self._slock:
                            self._tenant_active[tenant] += 1
                    try:
                        text, outcome = self._route(
                            tid, rest, deadline, t0, attempts,
                            tenant=tenant_sent, tenant_acct=tenant)
                    finally:
                        if tracked:
                            with self._slock:
                                self._tenant_active[tenant] -= 1
            reached = any(a.get("status") != "noconnect"
                          for a in attempts)
            if not reached and tenant is not None \
                    and outcome != "served":
                # nothing reached a replica window — zero attempts
                # (door sheds, router deadline, proto) or every
                # attempt connect-refused (fleet-wide outage): the
                # router's own per-tenant tracker burns for it. A
                # "lost" attempt counts as reached — the replica MAY
                # have accepted it into its own window, and the merge
                # must never count a request twice.
                tr = self.slo_tenants.get(tenant)
                if tr is not None:
                    tr.observe(ok=False)
            total = time.monotonic() - t0
            # the flight record + route_request_done event land BEFORE
            # the response goes out (the servd rule): a client that
            # just read its answer can immediately /trace?request=<id>
            self._record_request(tid, outcome, text, attempts, total,
                                 deadline_ms, tenant)
            # outcome lands BEFORE the active slot is released: drain()
            # snapshots final stats the moment _active hits 0, and an
            # accepted-but-not-yet-outcomed request would read as
            # non-reconciling books in the route_done event
            self._bump(outcome)
            self._bump_tenant(tenant, outcome)
            telemetry.hist("route.request", total)
        finally:
            with self._lock:
                self._active -= 1
        return text

    def _record_request(self, tid: str, outcome: str, text: str,
                        attempts: List[dict], total: float,
                        deadline_ms: Optional[float],
                        tenant: Optional[str] = None) -> None:
        rec = {"id": tid, "outcome": outcome,
               "tenant": tenant,
               "resp": " ".join(text.split()[:3])
               if text.startswith("ERR") else "served",
               # cxxlint: disable=wallclock — flight-record accept
               # epoch: the cross-process stitch aligns the router and
               # replica lanes on this shared wall clock, never a
               # duration
               "t_wall": round(time.time() - total, 6),
               "total_s": round(total, 6),
               "deadline_ms": deadline_ms,
               "retries": max(0, len(attempts) - 1),
               "attempts": attempts}
        # the router-side autopsy rides the record AND the done event:
        # /why?request=<id> refines it with the replica's books (the
        # stitch); a log consumer gets the verdict with zero joins
        rec["autopsy"] = autopsy.classify_route(rec)
        self.flight.record(rec)
        ev = {"ev": "route_request_done", "req": tid,
              "outcome": outcome,
              "attempts": len(attempts),
              "replicas": [a["replica"] for a in attempts],
              "retries": rec["retries"],
              "total_s": rec["total_s"],
              "autopsy": rec["autopsy"]}
        if tenant is not None:
            ev["tenant"] = tenant
        telemetry.event(ev)

    def _attempt_once(self, r: Replica, tid: str, sendbody: str,
                      tenant: Optional[str], timeout: float, att: dict
                      ) -> Tuple[str, Optional[str],
                                 Optional[socket.socket]]:
        """One full attempt against one PICKED replica (prefix build,
        forward, capability-downgrade ladder) -> (status, resp, sock).
        sock is the kept socket of a timed-out read (reapable), else
        None. Checks the replica back in; fills att latency/status."""
        with self._lock:
            traced = not r.no_trace
            tenanted = tenant is not None and not r.no_tenant
        # wire order: TRACE <id> TENANT <t> DEADLINE <ms> <toks> —
        # the replica parser strips them in exactly this order
        sendline = sendbody
        if tenanted:
            sendline = "TENANT %s %s" % (tenant, sendline)
        if traced:
            sendline = "TRACE %s %s" % (tid, sendline)
        t_att = time.monotonic()
        sock: Optional[socket.socket] = None
        try:
            status, resp, sock = self._forward_keep(r, sendline,
                                                    timeout)
            if (traced or tenanted) and status == "ok":
                if not resp.startswith("ERR parse"):
                    # ANY other answer to a prefixed line proves
                    # the prefixes were parsed: latch the positive
                    # capability flags so later genuine client
                    # parse errors never pay the downgrade resends
                    # (one write each, then steady)
                    if (traced and not r.trace_ok) \
                            or (tenanted and not r.tenant_ok):
                        with self._lock:
                            if traced:
                                r.trace_ok = True
                            if tenanted:
                                r.tenant_ok = True
                else:
                    # maybe an OLD replica rejecting a prefix
                    # itself: a parse rejection proves the request
                    # never dispatched, so each progressively
                    # barer resend is exactly-once safe. A genuine
                    # client parse error comes back identical at
                    # every rung and is relayed; a changed answer
                    # proves which prefix the replica predates —
                    # latch it (the ladder: drop TENANT first —
                    # newer than TRACE — then TRACE too).
                    status, resp = self._prefix_downgrade(
                        r, tid, sendbody, traced, tenanted,
                        timeout, att, resp)
        finally:
            self._checkin(r)
        att["latency_s"] = round(time.monotonic() - t_att, 6)
        att["status"] = status
        return status, resp, sock

    def _tenant_over_share(self, tenant: Optional[str]) -> bool:
        """True when the tenant already holds MORE than its weighted
        fair share of the router's in-flight requests — the replay and
        hedge denial gate. Unlike _tenant_gate there is no saturation
        requirement: a replay or hedge is EXTRA fleet work on top of
        an already-charged request, so an over-share tenant is denied
        even on an idle fleet (a flood must not double itself through
        failover). A sole-active tenant is never denied — its share is
        the whole router."""
        if not self._tenants or tenant is None \
                or tenant not in self._tenants:
            return False
        with self._slock:
            active = dict(self._tenant_active)
        total = sum(active.values())
        live = {t for t, n in active.items() if n > 0}
        live.add(tenant)
        weight_sum = sum(self._tenants[t] for t in live)
        share = max(1, int(total * self._tenants[tenant] / weight_sum))
        return active.get(tenant, 0) > share

    def _hedge_delay(self) -> Optional[float]:
        """The hedge launch delay in seconds, or None when hedging is
        off: route_hedge_ms > 0 is a fixed bound; -1 tracks the live
        fleet-merged serve p99 (None until the federation sweep has
        enough observations to trust); 0 disables."""
        if self.hedge_ms > 0:
            return self.hedge_ms / 1e3
        if self.hedge_ms < 0:
            return self._hedge_auto_s
        return None

    def _hedge_allowed(self, tenant: Optional[str]) -> bool:
        """Claim one hedge slot -> bool. Hedges are capped at
        hedge_max_pct of the requests in flight right now (floored at
        one) and denied to a tenant over its fair share. Sequential
        lock takes (fleet, then stats) — never nested."""
        if self._tenant_over_share(tenant):
            return False
        with self._lock:
            active = self._active
        with self._slock:
            cap = max(1, int(self.hedge_max_pct / 100.0 * active))
            if self._hedges_live >= cap:
                return False
            self._hedges_live += 1
            return True

    def _discard_loser(self, status: str, resp: Optional[str],
                       sock: Optional[socket.socket], rname: str,
                       tid: str, winner_resp: Optional[str]) -> None:
        """Account a hedge race's losing lane: a full answer is
        discarded and COUNTED (route.discarded_late) — and
        cross-checked against the winner's, because determinism says
        duplicate answers are identical: a mismatch is a correctness
        alarm (route.hedge_mismatch), not noise. A kept socket (the
        lane timed out) is reaped in the background."""
        if status == "ok" and resp:
            self._bump("discarded_late")
            if (winner_resp and not resp.startswith("ERR")
                    and not winner_resp.startswith("ERR")
                    and resp != winner_resp):
                telemetry.count("route.hedge_mismatch")
                telemetry.event({"ev": "route_hedge_mismatch",
                                 "request": tid, "replica": rname,
                                 "loser": resp[:80],
                                 "winner": winner_resp[:80]})
        if sock is not None:
            self._reap_socket(sock, rname, tid, self.stall_s)

    def _attempt_hedged(self, r: Replica, att: dict, tid: str,
                        sendbody: str, tenant: Optional[str],
                        tenant_acct: Optional[str], timeout: float,
                        delay: float, t0: float, tried: set,
                        attempts_out: List[dict]
                        ) -> Tuple[str, Optional[str],
                                   Optional[socket.socket],
                                   Replica, dict]:
        """Race a primary attempt against ONE delayed duplicate on a
        different replica -> (status, resp, sock, replica, att) for
        the winning lane. First genuine answer wins; the loser is
        discarded+counted by whichever side observes the race outcome
        — the CPython-atomic dict.setdefault claim makes it exactly
        one. A hedge answer is adopted only when it is a real answer
        (ok, non-ERR): hedging never worsens an outcome — a lost
        primary still flows into the replay machinery upstream."""
        res: dict = {}
        evt = threading.Event()

        def primary():
            try:
                res["p"] = self._attempt_once(r, tid, sendbody,
                                              tenant, timeout, att)
            except Exception:
                res["p"] = ("lost", None, None)
            evt.set()
            # loser self-accounting: the main thread already adopted
            # the hedge answer AND saw this lane unfinished — the
            # setdefault claim picks exactly one accountant
            if res.get("winner") == "hedge" \
                    and res.setdefault("acct", "p") == "p":
                st, rp, sk = res["p"]
                self._discard_loser(st, rp, sk, r.name, tid,
                                    res.get("winner_resp"))

        threading.Thread(target=primary, name="cxn-routerd-hedge-pri",
                         daemon=True).start()
        if evt.wait(delay):
            st, rp, sk = res["p"]
            return st, rp, sk, r, att
        # the downgrade ladder can legally take ~3x the per-forward
        # timeout: the pathological wait bound for the primary lane
        bound = 3.0 * timeout + 1.0
        if not self._hedge_allowed(tenant_acct):
            evt.wait(bound)
            st, rp, sk = res.get("p", ("lost", None, None))
            return st, rp, sk, r, att
        try:
            r2, cands2 = self._pick(tried | {r.name})
            if r2 is None:
                evt.wait(bound)
                st, rp, sk = res.get("p", ("lost", None, None))
                return st, rp, sk, r, att
            self._bump("hedges")
            att2 = {"replica": r2.name, "cls": "hedge",
                    "t_off_s": round(time.monotonic() - t0, 6),
                    "candidates": cands2}
            st2, rp2, sk2 = self._attempt_once(r2, tid, sendbody,
                                               tenant, timeout, att2)
            tried.add(r2.name)
        finally:
            with self._slock:
                self._hedges_live -= 1
        p = res.get("p") if evt.is_set() else None
        if p is not None and p[0] == "ok" and p[1] is not None \
                and not p[1].startswith("ERR"):
            # the primary produced a genuine answer: it wins (it was
            # first, or close enough that order doesn't matter —
            # determinism makes the answers identical either way)
            att2["outcome"] = "hedge_loser"
            attempts_out.append(att2)
            self._discard_loser(st2, rp2, sk2, r2.name, tid, p[1])
            return p[0], p[1], p[2], r, att
        if st2 == "ok" and rp2 is not None \
                and not rp2.startswith("ERR"):
            # the hedge produced the genuine answer: adopt it; the
            # primary lane (late, lost, or errored) is the loser
            res["winner_resp"] = rp2
            res["winner"] = "hedge"
            self._bump("hedge_wins")
            if evt.is_set() and res.setdefault("acct", "m") == "m":
                st, rp, sk = res["p"]
                self._discard_loser(st, rp, sk, r.name, tid, rp2)
            att["outcome"] = "hedge_loser"
            attempts_out.append(att)
            return st2, rp2, sk2, r2, att2
        # the hedge failed too (ERR / lost / noconnect): wait out the
        # primary — it may still answer, and a lost primary flows into
        # the replay machinery upstream
        att2["outcome"] = (" ".join(rp2.split()[:3])
                           if (st2 == "ok" and rp2) else st2)
        attempts_out.append(att2)
        if sk2 is not None:
            self._reap_socket(sk2, r2.name, tid, self.stall_s)
        if evt.wait(bound):
            st, rp, sk = res["p"]
            return st, rp, sk, r, att
        # pathological: the primary never returned inside the bound —
        # report it lost; its late completion self-discards via the
        # winner flag
        res["winner"] = "hedge"
        res["winner_resp"] = None
        res.setdefault("acct", "p")
        return "lost", None, None, r, att

    def _route(self, tid: str, rest: List[str],
               deadline: Optional[float], t0: float,
               attempts_out: List[dict],
               tenant: Optional[str] = None,
               tenant_acct: Optional[str] = None) -> Tuple[str, str]:
        tried: set = set()
        attempts = 0
        last_shed: Optional[str] = None
        loss: Optional[str] = None    # the last lost/rescued attempt's
        #                               text (replayable losses)
        replay_gen: Optional[int] = None  # expected model generation
        #                               once replaying (the guard)
        body = " ".join(rest)
        while True:
            now = time.monotonic()
            if deadline is not None and now >= deadline:
                return ("ERR deadline expired %.0fms past the budget "
                        "(router)" % (1e3 * (now - deadline)),
                        "deadline")
            r, cands = self._pick(tried)
            if r is None:
                if loss is not None:
                    # a replayable loss with no survivor left: the
                    # honest verdict is the loss, not a fleet shed
                    return (loss + " (no survivor to replay on)",
                            "errors")
                if last_shed is not None:
                    return last_shed, "shed"
                return ("ERR busy fleet no routable replica (%s)"
                        % self._states_brief(), "shed")
            if replay_gen is not None and replay_gen >= 0:
                # generation guard (module docstring): a replay must
                # not splice tokens across a weight push — the
                # survivor's live ADMIN reload count must match the
                # lost replica's (a fresh probe per replay: replays
                # are rare, staleness here splices generations)
                st = self._replica_stats(r)
                sg = -1
                if st is not None:
                    try:
                        sg = int(st.get("reloads", -1))
                    except (TypeError, ValueError):
                        sg = -1
                if sg >= 0 and sg != replay_gen:
                    self._checkin(r)
                    attempts_out.append(
                        {"replica": r.name, "cls": "replay",
                         "status": "denied",
                         "outcome": "replay_denied_generation"})
                    self._bump("replay_denied")
                    return ("ERR backend generation moved mid-replay "
                            "(expected %d, replica %s at %d)"
                            % (replay_gen, r.name, sg), "errors")
            timeout = self.stall_s
            sendbody = body
            if deadline is not None:
                rem = deadline - now
                timeout = min(timeout, rem)
                # forward the budget REMAINING, not the original: the
                # replica's own queue-expiry check spends the same clock
                sendbody = "DEADLINE %d %s" % (max(1, int(rem * 1e3)),
                                               body)
            att = {"replica": r.name,
                   "t_off_s": round(time.monotonic() - t0, 6),
                   "candidates": cands}
            if replay_gen is not None:
                att["cls"] = "replay"
            hdelay = self._hedge_delay()
            sock: Optional[socket.socket] = None
            if not tried and hdelay is not None and hdelay < timeout:
                # first attempt only: a retry/replay already burned
                # tail budget, doubling it again helps no one
                status, resp, sock, r, att = self._attempt_hedged(
                    r, att, tid, sendbody, tenant, tenant_acct,
                    timeout, hdelay, t0, tried, attempts_out)
            else:
                status, resp, sock = self._attempt_once(
                    r, tid, sendbody, tenant, timeout, att)
            tried.add(r.name)
            if status == "noconnect":
                # never sent: safe. Eject now — waiting a probe
                # interval would burn every retry on a dead replica.
                att["outcome"] = "noconnect"
                attempts_out.append(att)
                self._mark(r, DEAD, "connect refused at dispatch")
                if self._retry_allowed(attempts):
                    attempts += 1
                    self._bump("retries")
                    att["retried"] = True
                    continue
                return ("ERR busy fleet replicas unreachable", "shed")
            # a lost attempt (sent, then silence/EOF) or a replica-side
            # batch rescue (``ERR backend rescued``: the replica evicted
            # a wedged batch — provably no answer left it): both are
            # REPLAYABLE losses under the determinism argument in the
            # module docstring. The prober decides whether the replica
            # is dead (SIGKILL) or merely slow, so no hard eject here.
            rescued = (status == "ok" and resp is not None
                       and resp.split()[:3]
                       == ["ERR", "backend", "rescued"])
            if status == "lost" or rescued:
                with self._lock:
                    r.lost += 1
                    gen = r.reloads
                if status == "lost":
                    att["outcome"] = "lost"
                    attempts_out.append(att)
                    self._bump("lost_contact")
                    if sock is not None:
                        # the timed-out socket: reap (and count) a
                        # late answer in the background so the replay
                        # below stays exactly-once to the client
                        grace = self.stall_s
                        if deadline is not None:
                            grace = min(grace, max(
                                0.05, deadline - time.monotonic()))
                        self._reap_socket(sock, r.name, tid, grace)
                        sock = None
                    loss = ("ERR backend replica %s lost contact "
                            "mid-request" % r.name)
                else:
                    att["outcome"] = "ERR backend rescued"
                    attempts_out.append(att)
                    loss = resp
                if not self.replay:
                    if rescued:
                        return loss, "errors"
                    return (loss + " (not retried: may have "
                            "dispatched)", "errors")
                if self._tenant_over_share(tenant_acct):
                    # a flood must not double itself through failover:
                    # the over-share tenant eats its loss
                    self._bump("replay_denied")
                    return (loss + " (not replayed: tenant %s over "
                            "fair share)" % tenant_acct, "errors")
                self._bump("replays")
                att["replayed"] = True
                if replay_gen is None:
                    replay_gen = gen
                continue
            att["outcome"] = " ".join(resp.split()[:3]) \
                if resp.startswith("ERR") else "served"
            attempts_out.append(att)
            # a response line: dispatch on the retryability contract
            if retryable(resp):
                last_shed = resp
                toks = resp.split()
                detail = toks[2] if len(toks) > 2 else ""
                if toks[:2] == ["ERR", "busy"] and detail == "breaker":
                    self._mark(r, BREAKER_OPEN, resp[:120])
                elif toks[:1] == ["ERR"] and toks[1:2] == ["draining"]:
                    self._mark(r, DRAINING, resp[:120])
                if self._retry_allowed(attempts):
                    attempts += 1
                    self._bump("retries")
                    att["retried"] = True
                    continue
                return resp, "shed"
            if resp.split()[:3] == ["ERR", "busy", "tenant"]:
                # the replica's fair-share verdict: a shed (never
                # dispatched), relayed WITHOUT retry — the tenant
                # table is fleet-wide, so every replica rules the same
                return resp, "shed"
            if resp.startswith("ERR deadline"):
                return resp, "deadline"
            if resp.startswith("ERR"):
                return resp, "errors"
            return resp, "served"

    def _prefix_downgrade(self, r: Replica, tid: str, sendbody: str,
                          traced: bool, tenanted: bool, timeout: float,
                          att: dict,
                          first_resp: str) -> Tuple[str, Optional[str]]:
        """The pre-TRACE / pre-TENANT compat ladder (module docstring):
        the prefixed attempt came back ``ERR parse``, which proves it
        never dispatched — so each progressively barer resend is
        exactly-once safe. Rung 1 drops TENANT (the newer prefix; a
        changed answer latches ``no_tenant`` — and proves TRACE parsed,
        so ``trace_ok`` latches too). Rung 2 drops TRACE as well (a
        pre-TRACE replica certainly predates TENANT: both latch). An
        answer identical at every rung is a genuine client parse error,
        relayed verbatim with no latch. Skips rungs whose capability is
        already proven (``trace_ok``/``tenant_ok``) — a proven prefix
        cannot be what the replica rejected."""
        status, resp = "ok", first_resp
        if tenanted and not r.tenant_ok:
            line = sendbody if not traced \
                else "TRACE %s %s" % (tid, sendbody)
            status, resp = self._forward(r, line, timeout)
            if status != "ok":
                return status, resp
            if not resp.startswith("ERR parse"):
                with self._lock:
                    r.no_tenant = True
                    if traced:
                        r.trace_ok = True
                att["tenant_downgraded"] = True
                telemetry.count("route.tenant_downgrades")
                telemetry.event({"ev": "route_tenant_downgrade",
                                 "replica": r.name})
                return status, resp
        if traced and not r.trace_ok:
            status, resp = self._forward(r, sendbody, timeout)
            if status == "ok" and not resp.startswith("ERR parse"):
                with self._lock:
                    r.no_trace = True
                    if tenanted:
                        # a replica too old for TRACE predates TENANT
                        r.no_tenant = True
                att["trace_downgraded"] = True
                telemetry.count("route.trace_downgrades")
                telemetry.event({"ev": "route_trace_downgrade",
                                 "replica": r.name})
        return status, resp

    def _retry_allowed(self, attempts: int) -> bool:
        """Another attempt is allowed while the retry budget holds AND
        the router is not draining — drain bounds its wait on 'every
        in-flight request finishes within one attempt', so a request
        mid-retry must stop chaining attempts once drain begins."""
        if attempts >= self.retries:
            return False
        with self._lock:
            return not self._draining

    def _states_brief(self) -> str:
        with self._lock:
            by: Dict[str, int] = {}
            for r in self._replicas:
                if r.standby:
                    key = "standby"
                elif r.state == UP and r.hold:
                    key = "held"
                else:
                    key = r.state
                by[key] = by.get(key, 0) + 1
        return " ".join("%s=%d" % kv for kv in sorted(by.items()))

    # -- fleet ADMIN ---------------------------------------------------
    def _handle_admin(self, args: List[str]) -> str:
        with self._lock:
            if self._draining or self._stop:
                return "ERR draining router is shutting down"
        self._bump("admin")
        if args and args[0] == "stats":
            return self._fleet_stats_text()
        if args and args[0] == "reload":
            if self.request_rolling_reload():
                return "OK fleet reload rolling (one replica at a time)"
            return "ERR busy reload already rolling"
        if args and args[0] == "fleet":
            snap = self.fleet_snapshot()
            return "OK " + " ".join(
                "%s=%s:%d:%d" % (x["name"], x["state"],
                                 x["queue_depth"] + x["in_flight"],
                                 x["outstanding"])
                for x in snap["replicas"])
        return ("ERR parse unknown ADMIN command %r"
                % " ".join(args))

    def _replica_stats(self, r: Replica) -> Optional[Dict[str, int]]:
        """One replica's ``ADMIN stats`` counters (None when
        unreachable) — short probe timeout, never the stall bound."""
        status, resp = self._forward(r, "ADMIN stats",
                                     self.probe_timeout)
        if status != "ok" or not resp.startswith("OK "):
            return None
        out: Dict[str, int] = {}
        for kv in resp[3:].split():
            k, _, v = kv.partition("=")
            try:
                out[k] = int(v)
            except ValueError:
                continue
        return out

    def _fleet_stats_text(self) -> str:
        """Aggregate ``ADMIN stats`` over every reachable replica. Each
        replica reconciles accepted == served + errors + shed +
        deadline, so the sums reconcile too; ``replicas``/``reachable``
        ride along so a partial aggregate is visible as partial."""
        with self._lock:
            reps = [(r, r.state) for r in self._replicas]
        totals: Dict[str, int] = {}
        reachable = 0
        for r, state in reps:
            if state == DEAD:
                continue             # don't burn a timeout per scrape
            st = self._replica_stats(r)
            if st is None:
                continue
            reachable += 1
            for k, v in st.items():
                totals[k] = totals.get(k, 0) + v
        totals["replicas"] = len(reps)
        totals["reachable"] = reachable
        return "OK " + " ".join("%s=%d" % kv
                                for kv in sorted(totals.items()))

    # -- live fleet federation (metrics + SLO + outliers) --------------
    def federate_now(self) -> int:
        """One federation sweep: pull each non-dead replica's RAW
        metrics snapshot (statusd ``/metrics?json=1`` — exact bucket
        counts, no text-format round trip) plus its SLO window, store
        them, and recompute the outlier verdicts. Returns the number of
        replicas federated. All IO lock-free; the prober thread calls
        this every ``federate_s`` (tests and the selftest call it
        directly for determinism)."""
        with self._lock:
            reps = [(r.name, r.state, r.host, r.status_port)
                    for r in self._replicas]
        snaps: Dict[str, dict] = {}
        for name, state, host, sport in reps:
            if state == DEAD:
                continue             # don't burn a timeout per sweep
            try:
                code, body = _http_get(host, sport, "/metrics?json=1",
                                       self.probe_timeout)
                if code != 200:
                    continue
                snap = json.loads(body)
            except (OSError, ValueError):
                continue
            if isinstance(snap, dict) and "metrics" in snap:
                snaps[name] = snap
        now = time.monotonic()
        with self._fed_lock:
            # a replica that missed THIS sweep (one slow scrape, a GC
            # pause) keeps its last-known snapshot: dropping it would
            # make every cxxnet_fleet_* counter/bucket series dip and
            # recover, which Prometheus reads as a process reset and
            # re-counts the replica's lifetime totals as new traffic.
            # Only DEAD replicas leave the merge (a real reset).
            prev = self._fed
            live = {name for name, state, _, _ in reps
                    if state != DEAD}
            merged = {}
            for name, snap in snaps.items():
                merged[name] = {"snap": snap, "t": now}
            for name, entry in prev.items():
                if name not in merged and name in live:
                    merged[name] = entry
            self._fed = merged
            self._fed_at = now
            det = {name: e["snap"] for name, e in merged.items()}
        self._detect_outliers(det)
        return len(snaps)

    def _detect_outliers(self, snaps: Dict[str, dict]) -> None:
        """Per-replica serve p99 vs the median of the OTHER replicas
        (leave-one-out — against a median that includes itself, a
        2-replica fleet could NEVER flag its slow half: the median of
        two values is their mean, so p99 > ratio*median is impossible
        for ratio >= 2): a replica diverging by ``outlier_ratio`` x
        (with >= ``outlier_min_n`` requests behind its histogram) is an
        outlier. Verdicts are stored for /fleetz + the
        cxxnet_fleet_outlier gauges; transitions emit ONE
        ``fleet_outlier`` event each (never per-sweep spam)."""
        p99s: Dict[str, float] = {}
        h_all = telemetry.Histogram()
        for name, snap in snaps.items():
            d = (snap.get("metrics") or {}).get("hists", {}) \
                .get("serve.request")
            if not d:
                continue
            h = telemetry.Histogram()
            try:
                h.merge_dict(d)
                h_all.merge_dict(d)
            except (ValueError, TypeError):
                continue
            if h.n >= self.outlier_min_n:
                p99s[name] = h.percentile(99)
        if h_all.n >= self.outlier_min_n:
            # the fleet-merged serve p99: the live hedge delay when
            # route_hedge_ms = -1 (GIL-atomic float store; floored so
            # a degenerate all-fast histogram can't hedge everything)
            self._hedge_auto_s = max(0.001, h_all.percentile(99))
        flips = []
        with self._fed_lock:
            prev = self._fed_outlier
            verdicts: Dict[str, dict] = {}
            for name, p99 in sorted(p99s.items()):
                others = [v for n, v in p99s.items() if n != name]
                med = statistics.median(others) if others else None
                out = bool(med and med > 0
                           and p99 > self.outlier_ratio * med)
                verdicts[name] = {"outlier": out,
                                  "p99_ms": round(1e3 * p99, 3),
                                  "fleet_p99_ms":
                                  round(1e3 * med, 3)
                                  if med is not None else None}
                was = prev.get(name, {}).get("outlier", False)
                if out != was:
                    flips.append((name, verdicts[name]))
            # a FLAGGED replica that left the verdict set (died, or its
            # fresh histogram fell under min_n after a restart) must
            # emit its clearing transition — an event consumer watching
            # outlier=1 with no outlier=0 would page on it forever
            for name, was in prev.items():
                if name not in verdicts and was.get("outlier"):
                    flips.append((name, {"outlier": False,
                                         "p99_ms": None,
                                         "fleet_p99_ms": None}))
            self._fed_outlier = verdicts
        for name, v in flips:
            telemetry.count("route.outlier_flips")
            telemetry.event({"ev": "fleet_outlier", "replica": name,
                             "outlier": int(v["outlier"]),
                             "p99_ms": v["p99_ms"],
                             "fleet_p99_ms": v["fleet_p99_ms"]})

    def federation_slo(self) -> Optional[dict]:
        """The fleet-wide merged-window SLO account alone (None before
        the first sweep or without SLO-carrying replicas) — the
        autoscaler reads this every prober sweep, so it must not pay
        the full federation_snapshot histogram/counter merge per tick
        just to extract one burn rate."""
        with self._fed_lock:
            snaps = [d["snap"] for d in self._fed.values()]
        if not snaps:
            return None
        acc = _SloMerge()
        for snap in snaps:
            acc.add(snap.get("slo"))
        return acc.result()

    def federation_snapshot(self) -> Optional[dict]:
        """The merged fleet view (None before the first sweep): serve
        histograms merged EXACTLY (shared fixed buckets: bucket-count
        addition), serve counters summed, the fleet-wide SLO account
        over the replicas' merged windows, per-replica p99 + outlier
        verdicts. Rides ``fleet_snapshot()`` onto /fleetz and the
        router's /metrics (``cxxnet_fleet_*`` series)."""
        with self._fed_lock:
            if not self._fed:
                return None
            fed = {name: d["snap"] for name, d in self._fed.items()}
            age = time.monotonic() - self._fed_at
            outliers = {name: dict(v)
                        for name, v in self._fed_outlier.items()}
        hists: Dict[str, telemetry.Histogram] = {}
        counters: Dict[str, float] = {}
        slo_acc = _SloMerge()
        slo_tenant_acc: Dict[str, _SloMerge] = {}
        # the decode KV/convoy account (the replicas' batch feed):
        # byte sums are EXACT (each replica accounts its own cache),
        # live pct recomputed from the sums — never a mean of means
        dec_reps = dec_kv = dec_live = dec_convoy = 0
        # the paged-KV pool federation: block counts and prefix-token
        # tallies sum exactly (each replica's pool is its own), the
        # fleet hit rate is recomputed from the token sums — never a
        # mean of per-replica rates. Foreign/dense replicas simply
        # lack the "pool" key (the PR 13 guard: absent never kills)
        pool_reps = blk_total = blk_free = 0
        pfx_hit_toks = pfx_prompt_toks = kv_defers = 0
        blk_retained = ret_hits = ret_hit_toks = pressure_reps = 0
        for name, snap in sorted(fed.items()):
            b = snap.get("batch")
            if isinstance(b, dict):
                dec_reps += 1
                dec_kv += int(b.get("kv_bytes") or 0)
                dec_live += int(b.get("kv_live_bytes") or 0)
                dec_convoy += 1 if b.get("convoy") else 0
                pl = b.get("pool")
                if isinstance(pl, dict):
                    try:
                        pool_reps += 1
                        blk_total += int(pl.get("blocks_total") or 0)
                        blk_free += int(pl.get("blocks_free") or 0)
                        pfx_hit_toks += int(
                            pl.get("prefix_hit_tokens") or 0)
                        pfx_prompt_toks += int(
                            pl.get("prompt_tokens") or 0)
                        kv_defers += int(pl.get("alloc_failures") or 0)
                        blk_retained += int(
                            pl.get("blocks_retained") or 0)
                        ret_hits += int(pl.get("retained_hits") or 0)
                        ret_hit_toks += int(
                            pl.get("retained_hit_tokens") or 0)
                        pressure_reps += 1 if pl.get("pressure") else 0
                    except (TypeError, ValueError):
                        pass
            m = snap.get("metrics") or {}
            for hname, d in (m.get("hists") or {}).items():
                if not hname.startswith("serve."):
                    continue
                try:
                    hists.setdefault(
                        hname, telemetry.Histogram()).merge_dict(d)
                except (ValueError, TypeError):
                    continue
            for cname, v in (m.get("counters") or {}).items():
                if cname.startswith("serve."):
                    counters[cname] = counters.get(cname, 0) + v
            # the merged-window account: each replica's rolling
            # window contributes its request/bad counts. The alert
            # floors are fleet-wide — N replicas each one bad
            # request under their own min_bad can still page here
            # (the fleet-over case no single replica triggers).
            # Per-tenant windows merge the same way, per tenant.
            slo_acc.add(snap.get("slo"))
            for t, tslo in (snap.get("slo_tenants") or {}).items():
                slo_tenant_acc.setdefault(str(t), _SloMerge()).add(tslo)
        # the router's own per-tenant windows (door sheds only — see
        # __init__: zero-attempt outcomes, so no request is counted in
        # two windows) join the fleet merge
        for t, tr in sorted(self.slo_tenants.items()):
            slo_tenant_acc.setdefault(str(t), _SloMerge()).add(
                tr.snapshot())
        out = {"replicas": len(fed), "age_s": round(age, 3),
               "series": {name: dict(h.stats(),
                                     buckets=h.to_dict()["buckets"])
                          for name, h in sorted(hists.items())},
               "counters": counters,
               "outliers": outliers,
               "slo": slo_acc.result(),
               "slo_tenants": {t: res
                               for t, acc in
                               sorted(slo_tenant_acc.items())
                               for res in [acc.result()]
                               if res is not None}}
        if dec_reps:
            out["decode"] = {
                "replicas": dec_reps, "kv_bytes": dec_kv,
                "kv_live_bytes": dec_live,
                "kv_live_pct": round(100.0 * dec_live / dec_kv, 2)
                if dec_kv else None,
                "convoy_replicas": dec_convoy}
            if pool_reps:
                out["decode"]["pool"] = {
                    "replicas": pool_reps,
                    "blocks_total": blk_total,
                    "blocks_free": blk_free,
                    "prefix_hit_tokens": pfx_hit_toks,
                    "prompt_tokens": pfx_prompt_toks,
                    "prefix_hit_rate":
                    round(100.0 * pfx_hit_toks / pfx_prompt_toks, 2)
                    if pfx_prompt_toks else None,
                    "kv_defers": kv_defers,
                    # retained conversation cache: block/hit sums are
                    # exact, the fleet hit rate recomputed from token
                    # sums (never a mean of per-replica rates), and
                    # pressure_replicas counts latched replicas
                    "blocks_retained": blk_retained,
                    "retained_hits": ret_hits,
                    "retained_hit_tokens": ret_hit_toks,
                    "retained_hit_rate":
                    round(100.0 * ret_hit_toks / pfx_prompt_toks, 2)
                    if pfx_prompt_toks else None,
                    "pressure_replicas": pressure_reps}
        # the per-tenant fleet account, parsed back out of the summed
        # serve.tenant.<t>.<key> counter series and the merged
        # serve.tenant.<t>.request histograms: fleet-wide per-tenant
        # books (reconciling like the replica-local ones) plus each
        # tenant's fleet p99 — what "the victim's p99 holds" is read
        # from
        tenants: Dict[str, dict] = {}
        for cname, v in counters.items():
            if not cname.startswith("serve.tenant."):
                continue
            t, _, key = cname[len("serve.tenant."):].rpartition(".")
            if t:
                tenants.setdefault(t, {})[key] = v
        for hname, h in hists.items():
            if hname.startswith("serve.tenant.") \
                    and hname.endswith(".request"):
                t = hname[len("serve.tenant."):-len(".request")]
                if t:
                    p99 = h.percentile(99)
                    tenants.setdefault(t, {})["p99_ms"] = \
                        round(1e3 * p99, 3) if p99 is not None else None
        if tenants:
            out["tenants"] = tenants
        return out

    # -- closed-loop fleet autoscaler ----------------------------------
    def scaling_enabled(self) -> bool:
        return any(r.from_standby for r in self._replicas)

    def scale_snapshot(self) -> dict:
        """The autoscaler's account for /fleetz and the
        ``cxxnet_fleet_target_replicas`` /
        ``cxxnet_fleet_scale_events_total`` series: the current target
        (active replicas), bounds, and the recent decisions."""
        with self._lock:
            active = sum(1 for r in self._replicas if not r.standby)
            standby = sum(1 for r in self._replicas if r.standby)
        with self._scale_lock:
            events = self._scale_events
            recent = list(self._scale_log[-16:])
        return {"target_replicas": active, "standby": standby,
                "min": self.scale_min, "max": self.scale_max,
                "up_burn": self.scale_up_burn,
                "down_idle_s": self.scale_down_idle_s,
                "cooldown_s": self.scale_cooldown_s,
                "events": events, "recent": recent}

    def autoscale_now(self) -> Optional[str]:
        """One policy pass over the federated signals (module
        docstring): returns "up"/"down" when a scale action was taken,
        None otherwise. The prober runs this each sweep; tests and the
        selftest call it directly for determinism. Policy:

        * **up** — fleet SLO burn >= ``scale_up_burn`` (the federated
          merged-window account), OR queued work with zero free decode
          slots anywhere (demand the fleet provably cannot absorb) —
          admit one standby, bounded by ``scale_max``. A fleet below
          ``scale_min`` admits unconditionally (the floor is a floor).
        * **down** — the fleet is quiet (no queued work, burn < 1) and
          a scale-up-admitted replica has been completely idle for
          ``scale_down_idle_s`` — retire it back to standby, never
          below ``scale_min``.
        * **hysteresis** — at most one action per ``scale_cooldown_s``
          (the floor-repair case excepted), and any sign of load
          resets every idle timer: flap costs a replica a drain.

        Decisions are recorded as transition-only ``fleet_scale``
        events; counters/gauges ride ``scale_snapshot()``. All IO
        (probing a standby before admitting it) runs lock-free."""
        if not self.scaling_enabled():
            return None
        now = time.monotonic()
        with self._lock:
            active = [r for r in self._replicas if not r.standby]
            active_up = [r for r in active
                         if r.state == UP and not r.hold]
            standbys = [r for r in self._replicas
                        if r.standby and r.state != DEAD]
            # pressure = work WAITING (queue depth), never mere
            # in-flight: one slow request on an otherwise idle solo
            # fleet must not ratchet capacity to scale_max
            queue_total = sum(r.queue_depth for r in active_up)
            busy_total = sum(r.queue_depth + r.in_flight
                             for r in active_up)
            free_total = sum(r.free_slots for r in active_up)
            outstanding = sum(r.outstanding for r in active)
            idle_names = {r.name for r in active
                          if r.from_standby and r.state == UP
                          and not r.hold
                          and r.queue_depth + r.in_flight
                          + r.outstanding == 0}
        fslo = self.federation_slo()
        burn = None if fslo is None else fslo.get("burn_rate")
        pressure = queue_total > 0 and free_total <= 0
        burning = burn is not None and burn >= self.scale_up_burn
        with self._scale_lock:
            cool = now - self._scale_last >= self.scale_cooldown_s
            below_min = len(active_up) < self.scale_min
            want_up = standbys and len(active) < self.scale_max \
                and (below_min or (cool and (burning or pressure)))
            # idle bookkeeping: any load anywhere resets every timer —
            # a fleet that still has work in it must not shed capacity
            if burning or pressure or busy_total or outstanding:
                self._idle_since.clear()
            else:
                for name in list(self._idle_since):
                    if name not in idle_names:
                        del self._idle_since[name]
                for name in idle_names:
                    self._idle_since.setdefault(name, now)
            ripe = [n for n, t in self._idle_since.items()
                    if now - t >= self.scale_down_idle_s]
            want_down = (not want_up and cool and ripe
                         and len(active_up) > self.scale_min
                         and not (burning or pressure))
        if want_up:
            # prefer a standby already probed UP, then a WARMING one
            # (admissible — it turns routable by itself once its grid
            # compiles; the event's warm_pct records how cold it was
            # at admission); IO-free — the admitted replica keeps
            # being probed like any other, and a dead-on-arrival
            # standby is ejected by the normal dispatch/probe
            # machinery
            pick = next((r for r in standbys if r.state == UP),
                        next((r for r in standbys
                              if r.state == WARMING), standbys[0]))
            reason = ("below scale_min (%d up < %d)"
                      % (len(active_up), self.scale_min)) \
                if below_min else \
                ("fleet slo burn %.2fx >= %g" % (burn or 0.0,
                                                 self.scale_up_burn)
                 if burning else
                 "queued work (%d) with zero free slots" % queue_total)
            self._scale_apply(pick, up=True, reason=reason,
                              now=now)
            return "up"
        if want_down:
            with self._lock:
                pick = next((r for r in self._replicas
                             if r.name == ripe[0]), None)
            if pick is None:
                return None
            self._scale_apply(pick, up=False,
                              reason="idle %.1fs >= %g"
                              % (now - self._idle_since.get(
                                  pick.name, now),
                                 self.scale_down_idle_s), now=now)
            return "down"
        return None

    def _scale_apply(self, r: Replica, up: bool, reason: str,
                     now: float) -> None:
        with self._lock:
            r.standby = not up
            active = sum(1 for x in self._replicas if not x.standby)
            # the replica's warm fraction AT the scale decision: on a
            # scale-up this is the honest "admitted vs useful" gap —
            # 0.0 means every program still compiles ahead (the
            # serve_scale_up_to_first_token_s cost); None = no grid
            warm_pct = r.warm_pct()
        with self._scale_lock:
            self._scale_last = now
            self._scale_events += 1
            self._idle_since.pop(r.name, None)
            self._scale_log.append({"action": "up" if up else "down",
                                    "replica": r.name,
                                    "reason": reason,
                                    "active": active,
                                    "warm_pct": warm_pct})
            if len(self._scale_log) > 64:
                del self._scale_log[:-64]
        telemetry.count("route.scale_events")
        telemetry.gauge("route.target_replicas", active)
        telemetry.event({"ev": "fleet_scale",
                         "action": "up" if up else "down",
                         "replica": r.name, "reason": reason,
                         "active": active, "warm_pct": warm_pct})

    # -- stitched cross-process traces ---------------------------------
    def _fetch_hops(self, rec: dict) -> List[Tuple[str, dict]]:
        """The flight records of every replica one routed request
        touched, fetched live over each replica's statusd
        (``/requestz?request=<id>``) — the shared hop source of the
        /trace stitch and the /why autopsy. A replica that is gone (or
        has evicted the record) simply contributes no hop — the router
        lane still names it."""
        rid = str(rec.get("id"))
        with self._lock:
            by_name = {r.name: (r.host, r.status_port)
                       for r in self._replicas}
        hops: List[Tuple[str, dict]] = []
        seen = set()
        for att in rec.get("attempts") or []:
            name = att.get("replica")
            if name in seen or name not in by_name:
                continue
            seen.add(name)
            host, sport = by_name[name]
            try:
                code, body = _http_get(
                    host, sport, "/requestz?request=%s" % rid,
                    self.probe_timeout)
                if code != 200:
                    continue
                rrec = json.loads(body)
            except (OSError, ValueError):
                continue
            if isinstance(rrec, dict) and rrec.get("id") == rid:
                hops.append((name, rrec))
        return hops

    def stitched_trace(self, request_id) -> Optional[dict]:
        """ONE Chrome trace for one routed request: the router's
        attempt lane plus the phase lane of every replica that touched
        it, aligned on the shared wall-clock epoch. None when the
        router never saw the id."""
        rec = self.flight.get(str(request_id))
        if rec is None:
            return None
        return stitched_chrome_trace(rec, self._fetch_hops(rec))

    def stitched_why(self, request_id) -> Optional[dict]:
        """ONE cross-process autopsy for one routed request (the
        router's /why): the router-lane verdict refined by the winning
        replica's own cause decomposition, ``slow_replica`` absorbing
        the latency the replica's books cannot account for. None when
        the router never saw the id."""
        rec = self.flight.get(str(request_id))
        if rec is None:
            return None
        return autopsy.stitch_route(rec, self._fetch_hops(rec))

    def fleet_eventz(self, n: Optional[int] = None) -> List[dict]:
        """The fleet incident timeline (the router's /eventz): this
        process's own incident rows merged with every non-dead
        replica's ``/eventz?json=1`` rows, aligned on the shared
        wall-clock epoch. Each replica row is tagged with the replica
        name; the router's own rows say "router". ``n`` bounds the
        output to the NEWEST rows AFTER the merge — a bound applied
        per-feed would drop old-but-fleet-relevant rows unevenly."""
        rows = autopsy.incidents(
            telemetry.recent_events(),
            t0_wall=telemetry.wall_epoch(),
            records=self.flight.list(), process="router")
        with self._lock:
            reps = [(r.name, r.state, r.host, r.status_port)
                    for r in self._replicas]
        for name, state, host, sport in reps:
            if state == DEAD:
                continue             # don't burn a timeout per render
            try:
                code, body = _http_get(host, sport, "/eventz?json=1",
                                       self.probe_timeout)
                if code != 200:
                    continue
                snap = json.loads(body)
            except (OSError, ValueError):
                continue
            if not isinstance(snap, dict):
                continue
            for row in snap.get("rows") or []:
                if isinstance(row, dict):
                    row = dict(row)
                    row["process"] = name
                    rows.append(row)
        rows.sort(key=lambda r: r.get("t_wall") or 0.0)
        if n is not None and n > 0:
            rows = rows[-int(n):]
        return rows

    # -- rolling reload ------------------------------------------------
    def request_rolling_reload(self) -> bool:
        """Start the rolling fleet reload (one drain window at a time);
        False when one is already running or the router is draining.
        Safe from a SIGHUP handler? NO — this takes locks; the driver's
        handler sets a flag and calls this from its main loop."""
        with self._lock:
            if self._reloading or self._draining or self._stop:
                return False
            self._reloading = True
        t = threading.Thread(target=self._rolling_reload_run,
                             name="cxn-routerd-reload", daemon=True)
        t.start()
        return True

    def _rolling_reload_run(self) -> None:
        try:
            for r in list(self._replicas):
                with self._lock:
                    skip = r.state == DEAD
                if skip:
                    telemetry.event({"ev": "route_reload",
                                     "replica": r.name,
                                     "phase": "skipped_dead"})
                    continue
                self._reload_one(r)
            telemetry.event({"ev": "route_reload", "phase": "complete"})
        finally:
            with self._lock:
                self._reloading = False

    def _reload_one(self, r: Replica) -> None:
        with self._lock:
            r.hold = True
            t_out = time.monotonic()
        telemetry.event({"ev": "route_reload", "replica": r.name,
                         "phase": "drain"})
        by = t_out + self.reload_timeout_s
        ok = False
        ready = False
        try:
            # 1. drain THIS router's outstanding requests off the
            # replica (new picks already skip it)
            while time.monotonic() < by:
                with self._lock:
                    n = r.outstanding
                if n == 0:
                    break
                time.sleep(0.01)
            # 2. reload; completion = the replica's reload_seen counter
            # moved — it counts every PROCESSED reload request (real
            # swap, no-op already-newest skip, and failed reload alike;
            # the old model keeps serving on failure — still 'complete'
            # for the roll). Waiting on `reloads` alone would burn the
            # whole timeout out of rotation on a no-op roll.
            base = self._replica_stats(r)
            status, resp = self._forward(r, "ADMIN reload",
                                         self.probe_timeout)
            if status != "ok" or not resp.startswith("OK"):
                self._mark(r, DEAD, "reload dispatch failed: %r"
                           % (resp,))
                return
            while time.monotonic() < by:
                st = self._replica_stats(r)
                if base is None or (st is not None and
                                    st.get("reload_seen", 0)
                                    > base.get("reload_seen", 0)):
                    ok = True
                    break
                time.sleep(0.05)
            # 3. rejoin only once readiness confirms (a reload that
            # tripped the breaker must not re-enter rotation)
            while time.monotonic() < by:
                try:
                    code, _ = _http_get(r.host, r.status_port,
                                        "/healthz", self.probe_timeout)
                except OSError:
                    code = None
                if code == 200:
                    ready = True
                    break
                time.sleep(0.05)
        finally:
            with self._lock:
                r.hold = False
                t_back = time.monotonic()
                self._windows.append((r.name, t_out, t_back))
                if len(self._windows) > 64:
                    # bounded: /fleetz reads the last 32; a cron'd
                    # SIGHUP refresh must not grow this for months
                    del self._windows[:-64]
                demote = not ready and r.state == UP
            if demote:
                # /healthz never read ready inside the window: the
                # documented invariant is rejoin-only-when-ready, so
                # the replica leaves rotation until a ready probe —
                # NOT silently back into picks still unready
                self._mark(r, BREAKER_OPEN,
                           "not ready within %gs after reload"
                           % self.reload_timeout_s)
            telemetry.event({"ev": "route_reload", "replica": r.name,
                             "phase": "done", "ok": ok,
                             "ready": ready,
                             "window_s": round(t_back - t_out, 3)})

    # -- TCP front -----------------------------------------------------
    def _accept_run(self) -> None:
        sock = self._sock
        while True:
            with self._lock:
                if self._draining or self._stop:
                    break
            health.beat("route.accept")
            try:
                conn, _addr = sock.accept()
            except socket.timeout:
                continue
            except OSError:
                break               # listener closed (drain)
            conn.settimeout(self.client_timeout)
            threading.Thread(target=self._client_run, args=(conn,),
                             name="cxn-routerd-client",
                             daemon=True).start()
        health.pause("route.accept")

    def _client_run(self, conn: socket.socket) -> None:
        # one request at a time per connection: the forward is
        # synchronous, so responses leave in request order by
        # construction (no reply-slot machinery needed)
        try:
            buf = b""
            while True:
                try:
                    chunk = conn.recv(65536)
                except socket.timeout:
                    continue        # idle client: keep the connection
                except OSError:
                    break
                eof = not chunk
                if eof and buf:
                    buf += b"\n"    # unterminated final line = request
                buf += chunk
                while b"\n" in buf:
                    raw, buf = buf.split(b"\n", 1)
                    line = raw.decode("utf-8", "replace").rstrip("\r")
                    text = self._handle(line)
                    try:
                        conn.sendall((text + "\n")
                                     .encode("utf-8", "replace"))
                    except OSError:
                        self._bump("client_gone")
                        return
                if eof:
                    break
        finally:
            try:
                conn.close()
            except OSError:
                pass

    # -- drain ---------------------------------------------------------
    def drain(self, timeout_ms: Optional[float] = None) -> dict:
        """Stop accepting, let in-flight routed requests finish, stop
        the prober, flush telemetry, return the final stats.
        Idempotent. Replicas are NOT told to drain — they are their own
        processes with their own lifecycle; the fleet drain is the
        router getting out of the traffic path cleanly.

        Exactly-one-response holds through drain WITHOUT servd's
        claim machinery because every in-flight request is bounded:
        each forward times out within ``stall_s`` (or its remaining
        deadline) and the drain flag stops further retry attempts — so
        waiting ``max(budget, stall_s)`` + slack guarantees every
        accepted request's handler returned and its response line
        reached the client before this returns."""
        budget = (self.drain_ms if timeout_ms is None
                  else float(timeout_ms)) / 1e3
        t0 = time.monotonic()
        with self._lock:
            self._draining = True
        telemetry.event({"ev": "route_drain", "phase": "begin"})
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=2.0)
            self._accept_thread = None
        self._wake.set()
        if self._probe_thread is not None:
            self._probe_thread.join(timeout=2.0)
            self._probe_thread = None
        # the hard bound: one in-flight attempt per active request,
        # each <= stall_s (2x for the one-shot pre-TRACE downgrade
        # resend) — past it something is wrong enough that
        # leftover_active is reported instead of waited on forever
        hard_by = t0 + max(budget, 2.0 * self.stall_s + 2.0)
        while time.monotonic() < hard_by:
            with self._lock:
                if self._active == 0:
                    break
            time.sleep(0.02)
        with self._lock:
            self._stop = True
            leftovers = self._active
        health.pause("route.accept")
        health.pause("route.probe")
        # the laws leave the auditor with the process (a latched
        # violation survives: BooksAuditor latches are sticky)
        for law in ("route.books", "route.tenant_books",
                    "fleet.federation"):
            telemetry.audit_unregister(law)
        stats = self.stats()
        telemetry.event(dict({"ev": "route_drain", "phase": "end",
                              "seconds": round(time.monotonic() - t0,
                                               3),
                              "leftover_active": leftovers}, **stats))
        telemetry.flush()
        return stats


# ----------------------------------------------------------------------
def stitched_chrome_trace(router_rec: dict, hops) -> dict:
    """ONE cross-process Chrome trace from a router flight record plus
    ``hops`` = [(replica_name, replica_flight_record), ...]. Pure
    function — ``Router.stitched_trace`` feeds it live HTTP fetches,
    the tests feed it dicts. Lanes: pid 0 is the router (a request row
    plus an attempts row), pid 1..N one per replica hop (the replica's
    phase/recompile lanes, via ``telemetry.request_chrome_trace``).
    Every lane is placed on the SHARED wall-clock epoch (the earliest
    ``t_wall`` across the records): each flight record stamps its
    accept wall time, so cross-process alignment is a subtraction, and
    a retried request renders both attempts — shed lane and served
    lane — in true time order under one id."""
    rid = str(router_rec.get("id", "?"))
    walls = [router_rec.get("t_wall")] \
        + [r.get("t_wall") for _, r in hops]
    walls = [t for t in walls if isinstance(t, (int, float))]
    epoch = min(walls) if walls else 0.0
    r_off = float(router_rec.get("t_wall") or epoch) - epoch
    trace: List[dict] = [
        {"ph": "M", "name": "process_name", "pid": 0,
         "args": {"name": "router request %s" % rid}},
        {"ph": "M", "name": "thread_name", "pid": 0, "tid": 0,
         "args": {"name": "request"}},
        {"ph": "M", "name": "thread_name", "pid": 0, "tid": 1,
         "args": {"name": "attempts"}},
    ]
    total = float(router_rec.get("total_s") or 0.0)
    trace.append({
        "ph": "X", "name": "route:%s" % router_rec.get("outcome", "?"),
        "pid": 0, "tid": 0, "ts": round(r_off * 1e6, 1),
        "dur": round(total * 1e6, 1),
        "args": {"request": rid,
                 "outcome": router_rec.get("outcome", "?"),
                 "retries": router_rec.get("retries", 0),
                 "deadline_ms": router_rec.get("deadline_ms")}})
    for i, att in enumerate(router_rec.get("attempts") or []):
        ts = r_off + float(att.get("t_off_s") or 0.0)
        trace.append({
            "ph": "X",
            # hedge/replay lanes carry their attempt class in the
            # name so the duplicate attempt is visually distinct
            "name": ("hedge:%s" if att.get("cls") == "hedge"
                     else "forward:%s") % att.get("replica", "?"),
            "pid": 0, "tid": 1, "ts": round(ts * 1e6, 1),
            "dur": round(float(att.get("latency_s") or 0.0) * 1e6, 1),
            "args": {"request": rid, "attempt": i + 1,
                     "outcome": att.get("outcome", "?"),
                     "class": att.get("cls"),
                     "candidates": att.get("candidates")}})
    for i, (name, rrec) in enumerate(hops):
        sub = telemetry.request_chrome_trace(rrec)
        off_us = (float(rrec.get("t_wall") or epoch) - epoch) * 1e6
        for ev in sub["traceEvents"]:
            ev = dict(ev)
            ev["pid"] = i + 1
            if ev.get("ph") == "M":
                if ev.get("name") == "process_name":
                    ev["args"] = {"name": "replica %s" % name}
            else:
                ev["ts"] = round(float(ev.get("ts", 0.0)) + off_us, 1)
            trace.append(ev)
    return {"traceEvents": trace, "displayTimeUnit": "ms"}


def route_chrome_trace(rec: dict) -> dict:
    """Router-lane-only Chrome trace of one routing flight record (the
    stitch with zero replica hops)."""
    return stitched_chrome_trace(rec, [])


# ----------------------------------------------------------------------
def _ask(port: int, line: str, timeout: float = 5.0) -> str:
    return servd._ask(port, line, timeout=timeout)


def selftest(verbose: bool = False) -> int:
    """Drive routing, retry-on-shed, breaker ejection, dead-replica
    ejection + re-admission, deadline-budget forwarding, fleet stats
    aggregation, rolling reload, and drain over real loopback sockets
    with in-process servd replicas — jax-free; ``make check`` gates on
    it. Runs with runtime lock-order enforcement on."""
    with lockrank.enforced():
        return _selftest_body(verbose)


def _selftest_body(verbose: bool = False) -> int:
    from . import statusd

    # in-memory telemetry so the replicas' statusd serve real metric
    # snapshots — the federation half of this selftest needs exact
    # histogram buckets to merge (restored at the end)
    owns_telemetry = not telemetry.enabled()
    if owns_telemetry:
        telemetry.enable()

    # two replicas with DISTINGUISHABLE models: +1 and +1000 — every
    # assertion below can see which replica answered
    wedge1 = threading.Event()
    wedge1.set()
    model1 = {"v": 1}
    reload2 = []

    def backend1(toks, seq):
        wedge1.wait(10.0)
        return [t + model1["v"] for t in toks]

    def backend2(toks, seq):
        return [t + 1000 for t in toks]

    fe1 = servd.ServeFrontend(backend1, queue_size=1, breaker_fails=1,
                              breaker_cooldown_ms=50.0, drain_ms=2000.0,
                              reload_fn=lambda: model1.update(
                                  v=model1["v"] + 1) or True)
    fe2 = servd.ServeFrontend(backend2, drain_ms=2000.0,
                              reload_fn=lambda: reload2.append(1)
                              or True)
    fe1.start()
    fe2.start()
    p1, p2 = fe1.listen(0), fe2.listen(0)
    s1 = statusd.StatusServer(0, host="127.0.0.1").start()
    s2 = statusd.StatusServer(0, host="127.0.0.1").start()
    s1.register_probe("serving", fe1.health_probe)
    s2.register_probe("serving", fe2.health_probe)
    # each replica's flight ring on its statusd: the stitched-trace
    # fetch reads /requestz?request=<id> per hop
    s1.flight = fe1.flight
    s2.flight = fe2.flight

    # probing OFF the clock (probe_ms huge): every state transition in
    # this selftest is driven deterministically — by dispatch outcomes
    # or explicit probe_now() sweeps (federation likewise: off the
    # clock, federate_now() drives it)
    router = Router([("127.0.0.1", p1, s1.port),
                     ("127.0.0.1", p2, s2.port)],
                    probe_ms=3600e3, retries=2, stall_s=5.0,
                    drain_ms=2000.0, probe_backoff_cap_s=0.2,
                    reload_timeout_s=10.0, federate_ms=3600e3,
                    outlier_min_n=1)
    router.start()
    rport = router.listen(0)
    r1, r2 = router._replicas
    srv = statusd.StatusServer(0, host="127.0.0.1").start()
    srv.fleet = router
    srv.flight = router.flight
    try:
        # zero load, index tie-break: replica 1 answers
        assert _ask(rport, "1 2") == "2 3"
        # retry-on-shed: wedge replica 1 and fill its 1-slot queue so
        # any pick of it sheds `ERR busy queue`; the router must retry
        # on replica 2 transparently
        wedge1.clear()
        fe1.submit("7", lambda t: None)      # occupies the worker
        deadline = time.monotonic() + 5.0
        while not fe1._inflight and time.monotonic() < deadline:
            time.sleep(0.01)                 # wait for the worker pop
        fe1.submit("8", lambda t: None)      # fills the 1-slot queue
        # direct shed proves the detail token (the wire contract)
        direct = _ask(p1, "9")
        assert direct.startswith("ERR busy queue"), direct
        assert retryable(direct)
        assert _ask(rport, "5") == "1005"    # retried onto replica 2
        st = router.stats()
        assert st["retries"] >= 1 and st["served"] == 2, st
        wedge1.set()                         # un-wedge; queue drains
        deadline = time.monotonic() + 5.0
        while fe1.stats()["served"] < 2 and \
                time.monotonic() < deadline:
            time.sleep(0.01)

        # breaker ejection: one failure opens replica 1's breaker
        # (breaker_fails=1). The failure itself is relayed (dispatched:
        # never retried); the NEXT pick of replica 1 sheds `ERR busy
        # breaker`, which both retries elsewhere AND ejects it.
        fe1.backend = servd_explode
        assert _ask(rport, "3").startswith("ERR backend")
        st = router.stats()
        assert st["errors"] == 1, st
        assert fe1.breaker.state == "open"
        assert _ask(rport, "4") == "1004"    # shed by 1, served by 2
        assert r1.state == BREAKER_OPEN, r1.state
        # ejected: routed straight to replica 2, no retry spent
        pre = router.stats()["retries"]
        assert _ask(rport, "6") == "1006"
        assert router.stats()["retries"] == pre

        # re-admission by probe: heal the backend, close the breaker
        # with a direct half-open probe, then one probe sweep
        fe1.backend = backend1
        time.sleep(0.08)                     # past the 50ms cooldown
        assert _ask(p1, "1") == "2"
        assert fe1.breaker.state == "closed"
        router.probe_now()
        assert r1.state == UP, (r1.state, r1.detail)

        # dead-replica ejection + backoff re-probe: a replica whose
        # ports answer nothing is marked dead at dispatch (connect
        # refused: never sent, SAFE retry) and re-probed on the
        # backoff schedule
        with socket.socket() as tmp:
            tmp.bind(("127.0.0.1", 0))
            dead_port = tmp.getsockname()[1]
        router2 = Router([("127.0.0.1", dead_port, dead_port),
                          ("127.0.0.1", p2, s2.port)],
                         probe_ms=3600e3, retries=2, stall_s=5.0,
                         drain_ms=1000.0, probe_backoff_cap_s=0.2)
        router2.start()
        rport2 = router2.listen(0)
        try:
            assert _ask(rport2, "11") == "1011"
            d1 = router2._replicas[0]
            assert d1.state == DEAD and d1.ejections == 1
            assert router2.stats()["retries"] == 1
            # backing off: a sweep before next_probe_at skips it
            fails = d1.probe_fails
            router2.probe_now()
            assert d1.probe_fails == fails, "re-probed inside backoff"
            time.sleep(0.25)                 # past the 0.2s cap
            router2.probe_now()
            assert d1.probe_fails == fails + 1, "backoff re-probe ran"
        finally:
            router2.drain(timeout_ms=500)

        # deadline budget forwarding: a mirror replica echoes the line
        # it was sent — the forwarded line must carry the minted TRACE
        # id and the REMAINING deadline budget, not the original
        mirror = _MirrorReplica().start()
        router3 = Router([("127.0.0.1", mirror.port, mirror.port)],
                         probe_ms=3600e3, retries=0, stall_s=5.0,
                         drain_ms=1000.0)
        router3.start()
        rport3 = router3.listen(0)
        try:
            resp = _ask(rport3, "DEADLINE 5000 1 2 3")
            toks = resp.split()
            assert toks[0] == "TRACE" and servd.valid_trace_id(toks[1])
            assert toks[2] == "DEADLINE" and toks[4:] == ["1", "2", "3"]
            assert 0 < int(toks[3]) <= 5000, resp
            # a client-sent TRACE id is adopted, not re-minted
            resp = _ask(rport3, "TRACE client-1 9 9")
            assert resp.split()[:2] == ["TRACE", "client-1"], resp
            assert router3.flight.get("client-1") is not None
            # an expired budget is answered by the ROUTER, not routed
            assert _ask(rport3, "DEADLINE 0 9") \
                .startswith("ERR deadline")
            assert router3.stats()["deadline"] == 1
        finally:
            router3.drain(timeout_ms=500)
            mirror.stop()

        # fleet ADMIN stats aggregates and reconciles
        resp = _ask(rport, "ADMIN stats")
        assert resp.startswith("OK "), resp
        agg = {k: int(v) for k, _, v in
               (kv.partition("=") for kv in resp[3:].split())}
        assert agg["reachable"] == 2 and agg["replicas"] == 2
        assert agg["accepted"] == (agg["served"] + agg["errors"]
                                   + agg["shed"] + agg["deadline"]), agg
        assert _ask(rport, "ADMIN fleet").startswith("OK ")
        assert _ask(rport, "ADMIN bogus").startswith("ERR parse")

        # rolling reload: both replicas reload one at a time, the drain
        # windows never overlap (capacity stays >= N-1), and the fleet
        # keeps serving throughout
        v_before = model1["v"]
        assert _ask(rport, "ADMIN reload").startswith("OK fleet")
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            with router._lock:
                done = len(router._windows) >= 2 \
                    and not router._reloading
            if done:
                break
            # the fleet keeps answering while the roll is in flight
            assert not _ask(rport, "2").startswith("ERR")
            time.sleep(0.02)
        snap = router.fleet_snapshot()
        assert len(snap["windows"]) == 2, snap["windows"]
        w1, w2 = snap["windows"]
        assert w1["back_s"] <= w2["out_s"] or \
            w2["back_s"] <= w1["out_s"], "drain windows overlap"
        assert model1["v"] == v_before + 1 and reload2, \
            "rolling reload did not reach both replicas"

        # /fleetz + cxxnet_fleet_* ride statusd
        code, body = _http_status(srv.port, "/fleetz?json=1")
        assert code == 200 and '"replicas"' in body
        code, metrics = _http_status(srv.port, "/metrics")
        assert "cxxnet_fleet_replicas" in metrics
        assert 'cxxnet_fleet_replica_up{' in metrics

        # -- fleet observability plane ---------------------------------
        # ONE trace id names the request on the router AND on the
        # replica that served it (TRACE propagation end to end)
        assert not _ask(rport, "TRACE obs-1 2").startswith("ERR")
        rrec = router.flight.get("obs-1")
        assert rrec is not None and rrec["outcome"] == "served", rrec
        served_by = rrec["attempts"][-1]["replica"]
        hop_fe = fe1 if served_by.endswith(":%d" % p1) else fe2
        hop = hop_fe.flight.get("obs-1")
        assert hop is not None and hop["outcome"] == "served", hop
        # the stitched cross-process trace off the router's statusd:
        # router attempt lane (pid 0) + the replica's phase lane
        code, body = _http_status(srv.port, "/trace?request=obs-1")
        assert code == 200, body
        stitched = json.loads(body)
        xs = [t for t in stitched["traceEvents"] if t.get("ph") == "X"]
        assert any(t["name"].startswith("forward:") for t in xs)
        assert any(t["name"] == "prefill" and t["pid"] >= 1
                   for t in xs), xs
        assert all(t.get("args", {}).get("request") == "obs-1"
                   for t in xs)
        code, body = _http_status(srv.port, "/trace?request=missing")
        assert code == 404
        # router /requestz: bounded listing of the routing flights
        code, body = _http_status(srv.port, "/requestz?json=1&n=2")
        assert code == 200
        lst = json.loads(body)
        assert lst["shown"] <= 2 and lst["total"] >= 2

        # the autopsy plane: every routing record carries its verdict;
        # stitched_why (the /why source) refines the winner's latency
        # lane with the replica's own books and still tiles total_s
        assert rrec["autopsy"]["primary"] in autopsy.CAUSES, rrec
        why = router.stitched_why("obs-1")
        assert why is not None and why["hops"], why
        maut = why["autopsy"]
        assert abs(sum(maut["causes"].values()) - maut["wall_s"]) \
            <= max(1e-6, 0.05 * maut["wall_s"]), maut
        assert router.stitched_why("missing") is None
        # the fleet incident timeline merges this router's incident
        # rows with every replica's /eventz feed, wall-clock ordered
        rows = router.fleet_eventz(n=64)
        assert rows, "fleet_eventz returned no rows"
        walls = [r["t_wall"] for r in rows]
        assert walls == sorted(walls)
        assert any(r.get("process") != "router" for r in rows), rows
        # the conservation-law auditor sweeps clean over a healthy
        # router + fleet (route books / tenant books / federation)
        viol = telemetry.audit_sweep()
        assert not any(viol.values()), viol
        assert not telemetry.auditor().snapshot()["broken"]

        # live federation: EXACT histogram merge — for every merged
        # series the fleet bucket counts equal the sum of the
        # per-replica snapshot buckets (the acceptance criterion)
        code, b1 = _http_status(s1.port, "/metrics?json=1")
        code2, b2 = _http_status(s2.port, "/metrics?json=1")
        assert code == 200 and code2 == 200
        shards = [json.loads(b1)["metrics"]["hists"],
                  json.loads(b2)["metrics"]["hists"]]
        assert router.federate_now() == 2
        fed = router.federation_snapshot()
        assert fed is not None and fed["replicas"] == 2
        assert "serve.request" in fed["series"], fed["series"].keys()
        for name, h in fed["series"].items():
            expect: Dict[str, int] = {}
            for shard in shards:
                for i, c in (shard.get(name, {}).get("buckets")
                             or {}).items():
                    expect[i] = expect.get(i, 0) + c
            assert h["buckets"] == expect, (name, h["buckets"], expect)
        # no outlier between two identically-loaded replicas; the
        # verdicts (and the federated series) ride /fleetz + /metrics
        assert fed["outliers"] and not any(
            v["outlier"] for v in fed["outliers"].values())
        code, metrics = _http_status(srv.port, "/metrics")
        assert "cxxnet_fleet_serve_request_seconds_bucket" in metrics
        assert "cxxnet_fleet_federated_replicas" in metrics
        assert "cxxnet_fleet_outlier{" in metrics
        for line in metrics.splitlines():
            if line and not line.startswith("#"):
                assert statusd.PROM_LINE_RE.match(line), line

        assert router.health_probe()[0] and router.liveness_probe()[0]
    finally:
        stats = router.drain()
        srv.stop()
        s1.stop()
        s2.stop()
        fe1.drain(timeout_ms=1000)
        fe2.drain(timeout_ms=1000)
        if owns_telemetry:
            telemetry.disable()
    assert stats["accepted"] == (stats["served"] + stats["errors"]
                                 + stats["shed"] + stats["deadline"]), \
        "router counters do not reconcile: %r" % (stats,)
    assert router.health_probe() == (
        False, "draining: not accepting new requests")
    if verbose:
        print("routerd selftest: routing/retry-on-shed/breaker-eject/"
              "dead-eject+backoff/deadline-budget/fleet-stats/"
              "rolling-reload/drain + trace-propagation/stitched-trace/"
              "exact-federation/outliers ok (%r)" % (stats,))
    return 0


def servd_explode(toks, seq):
    raise RuntimeError("injected replica failure")


class _MirrorReplica:
    """A fake replica that answers every request line with the line
    itself — the fixture that makes the router's DEADLINE rewrite
    observable (a real servd consumes the prefix)."""

    def __init__(self):
        self.port = None
        self._sock = None
        self._thread = None

    def start(self) -> "_MirrorReplica":
        self._sock = socket.create_server(("127.0.0.1", 0))
        self._sock.settimeout(0.25)
        self.port = self._sock.getsockname()[1]
        self._thread = threading.Thread(target=self._run,
                                        name="cxn-mirror", daemon=True)
        self._thread.start()
        return self

    def _run(self) -> None:
        while self._sock is not None:
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            with conn:
                try:
                    line = conn.makefile("r").readline()
                    conn.sendall(line.encode())
                except OSError:
                    pass

    def stop(self) -> None:
        s, self._sock = self._sock, None
        if s is not None:
            try:
                s.close()
            except OSError:
                pass


def _http_status(port: int, path: str) -> Tuple[int, str]:
    try:
        return _http_get("127.0.0.1", port, path, 5.0)
    except OSError as e:
        return 0, repr(e)


if __name__ == "__main__":
    if "--selftest" in sys.argv[1:]:
        sys.exit(selftest(verbose=True))
    print(__doc__)
    sys.exit(1)
