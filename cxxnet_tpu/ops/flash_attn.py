"""Single-chip flash attention: blocked online-softmax fwd + bwd in Pallas.

The framework's long-context story has two tiers (SURVEY.md §5): across
chips the sequence shards over the mesh "sp" axis (parallel/ring.py); on
one chip this kernel keeps attention O(L) in memory by never materializing
the (L, L) score matrix — Q tiles stay resident while K/V tiles stream
through VMEM and the softmax is accumulated online (running max + sum, the
same log-sum-exp carry ring attention uses across devices).

Forward: grid (batch*heads, Lq/block_q, Lk/block_k), K/V innermost so the
(m, l, acc) carry lives in VMEM scratch across the sequential kv steps;
the MXU sees (block_q, d) x (d, block_k) and (block_q, block_k) x
(block_k, d) matmuls. Saves the per-row logsumexp for backward.

Backward (FlashAttention-2 factorization): with P = exp(S - lse) the
gradients are
    dV = Pᵀ dO
    dS = P ∘ (dO Vᵀ - D),  D = rowsum(dO ∘ O)
    dQ = scale · dS K      (kernel: grid over q tiles, kv streams)
    dK = scale · dSᵀ Q     (kernel: grid over kv tiles, q streams)
computed by two kernels that recompute S blockwise from the saved lse;
D is computed once (fused XLA reduce) and streamed in as (bh, L, 1)
tiles — O(L) memory end to end.

Numerics are golden-tested against the dense reference on CPU
(interpret=True) in tests/test_flash_attention.py and on the chip by
tools/check_tpu_kernels.py. The kernel-escape-hatch precedent in the
reference is the hand-written insanity pooling plan
(src/layer/insanity_pooling_layer-inl.hpp:13-100).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

NEG_INF = -1e30  # finite stand-in for -inf: keeps exp()/max() NaN-free


def _mask(s, q_blk, kv_blk, block_q, block_k, causal, kv_len, window=0):
    """Causal / sliding-window / padded-tail masking of a score tile.
    kv_len is the true (pre-padding) sequence length — static, so the
    where() folds away entirely for tile-aligned inputs. window > 0 keeps
    only the last ``window`` keys per query (requires causal)."""
    kpos = kv_blk * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    keep = kpos < kv_len
    if causal:
        qpos = q_blk * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        keep = jnp.logical_and(keep, qpos >= kpos)
        if window > 0:
            keep = jnp.logical_and(keep, qpos - kpos < window)
    return jnp.where(keep, s, NEG_INF)


def _block_needed(causal, q_blk, kv_blk, block_q, block_k, window=0):
    """False for kv tiles strictly above the causal diagonal, and (with a
    sliding window) for tiles entirely older than the window — both are
    skipped wholesale (the flash causal/local speedup). q_blk/kv_blk are
    traced program ids; window is static."""
    if not causal:
        return True
    need = kv_blk * block_k <= q_blk * block_q + (block_q - 1)
    if window > 0:
        # newest key of this tile vs oldest query of the q tile
        need = jnp.logical_and(
            need,
            (q_blk * block_q) - (kv_blk * block_k + block_k - 1) < window)
    return need


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr, *, scale, causal, block_q, block_k,
                kv_len, padded, window=0):
    kv_i = pl.program_id(2)
    n_kv = pl.num_programs(2)
    q_blk = pl.program_id(1)

    @pl.when(kv_i == 0)
    def _():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    @pl.when(_block_needed(causal, q_blk, kv_i, block_q, block_k, window))
    def _():
        # operands stay in their input dtype (bf16 on the fast MXU path);
        # every accumulation is f32 via preferred_element_type
        q, k, v = q_ref[0], k_ref[0], v_ref[0]
        s = jax.lax.dot_general(
            q, k, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale    # (bq, bk) f32
        if causal or padded:
            s = _mask(s, q_blk, kv_i, block_q, block_k, causal, kv_len,
                      window)
        m_prev = m_scr[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)                              # (bq, bk) f32
        m_scr[...] = m_new
        l_scr[...] = l_scr[...] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[...] = acc_scr[...] * alpha + jnp.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32)

    @pl.when(kv_i == n_kv - 1)
    def _():
        l = jnp.maximum(l_scr[...], 1e-30)
        o_ref[0] = (acc_scr[...] / l).astype(o_ref.dtype)
        # lse = m + log(l): per-row logsumexp for the backward recompute
        lse_ref[0] = m_scr[...] + jnp.log(l)


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               dq_scr, *, scale, causal, block_q, block_k, kv_len, padded,
               window=0):
    kv_i = pl.program_id(2)
    n_kv = pl.num_programs(2)
    q_blk = pl.program_id(1)

    @pl.when(kv_i == 0)
    def _():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    @pl.when(_block_needed(causal, q_blk, kv_i, block_q, block_k, window))
    def _():
        q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
        s = jax.lax.dot_general(
            q, k, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal or padded:
            s = _mask(s, q_blk, kv_i, block_q, block_k, causal, kv_len,
                      window)
        p = jnp.exp(s - lse_ref[0])                         # (bq, bk) f32
        dp = jax.lax.dot_general(
            do, v, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)             # (bq, bk)
        ds = p * (dp - delta_ref[0]) * scale
        dq_scr[...] += jnp.dot(ds.astype(k.dtype), k,
                               preferred_element_type=jnp.float32)

    @pl.when(kv_i == n_kv - 1)
    def _():
        dq_ref[0] = dq_scr[...].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_scr, dv_scr,
                *, scale, causal, block_q, block_k, kv_len, padded,
                window=0):
    q_i = pl.program_id(2)
    n_q = pl.num_programs(2)
    kv_blk = pl.program_id(1)

    @pl.when(q_i == 0)
    def _():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    @pl.when(_block_needed(causal, q_i, kv_blk, block_q, block_k, window))
    def _():
        q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
        delta = delta_ref[0]                                # (bq, 1)
        s = jax.lax.dot_general(
            q, k, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale     # (bq, bk)
        if causal or padded:
            s = _mask(s, q_i, kv_blk, block_q, block_k, causal, kv_len,
                      window)
        p = jnp.exp(s - lse_ref[0])
        dv_scr[...] += jax.lax.dot_general(
            p.astype(do.dtype), do,
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)             # (bk, d)
        dp = jax.lax.dot_general(
            do, v, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)             # (bq, bk)
        ds = p * (dp - delta) * scale
        dk_scr[...] += jax.lax.dot_general(
            ds.astype(q.dtype), q,
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)             # (bk, d)

    @pl.when(q_i == n_q - 1)
    def _():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _pick_block(L: int, target: int = 256) -> int:
    """Sequence tile: lane-aligned (multiple of 128) so the (bq, bk) score
    tile maps onto the MXU cleanly. Exact divisors are preferred (zero
    padding); otherwise L is padded up to a multiple of the tile."""
    for b in (target, 128):
        if L % b == 0:
            return b
    return target if L >= target else 128


def _padded_len(L: int, block: int) -> int:
    return -(-L // block) * block


def supports(L: int, d: int) -> bool:
    """Shapes the kernel path accepts: any L >= 128 (padded to a lane-
    aligned tile, tail masked in-kernel) and a sublane-aligned head dim."""
    return pltpu is not None and L >= 128 and d % 8 == 0


def _dims():
    # the innermost stream dim carries the scratch accumulator across steps:
    # must be sequential ("arbitrary"); batch*heads and the tile dim are
    # parallel (Mosaic may split them over the two TensorCores)
    return pltpu.CompilerParams(
        dimension_semantics=("parallel", "parallel", "arbitrary"))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal: bool = False,
                    scale: Optional[float] = None, interpret: bool = False,
                    window: int = 0):
    """Memory-O(L) attention. q: (b, h, L, d) -> (b, h, L, d); k/v may
    carry FEWER heads (grouped-query attention, nkv | h): the kernels read
    the shared kv head per query group through the BlockSpec index map, so
    K/V HBM footprint and traffic stay nkv-sized.

    Same contract as parallel.attention_reference (incl. sliding
    ``window``, causal-only); the caller gates on supports().
    `interpret=True` runs the kernels in the Pallas interpreter so CPU
    tests cover the exact kernel code.
    """
    out, _ = _flash_fwd(q, k, v, causal, scale, interpret, window)
    return out


def _merge_bh(x):
    b, h, L, d = x.shape
    return x.reshape(b * h, L, d)


def _pad_seq(x, Lp):
    L = x.shape[1]
    if L == Lp:
        return x
    return jnp.pad(x, ((0, 0), (0, Lp - L), (0, 0)))


def _kv_row_map(nh: int, nkv: int):
    """Grid row (over b*nh) -> K/V array row (over b*nkv): grouped-query
    attention reads the SHARED kv head of each query-head group straight
    from the nkv-sized array — K/V HBM footprint and traffic stay
    nkv-sized, never broadcast to the query heads."""
    grp = nh // nkv
    def to_kv(g):
        return (g // nh) * nkv + (g % nh) // grp
    return to_kv


def _flash_fwd(q, k, v, causal, scale, interpret, window=0):
    b, h, L, d = q.shape
    nkv = k.shape[1]
    assert h % nkv == 0, "query heads must be a multiple of kv heads"
    if scale is None:
        scale = d ** -0.5
    assert window == 0 or causal, "window attention requires causal"
    bq = bk = _pick_block(L)
    Lp = _padded_len(L, bq)
    qf = _pad_seq(_merge_bh(q), Lp)
    kf, vf = (_pad_seq(_merge_bh(t), Lp) for t in (k, v))
    bh = b * h
    to_kv = _kv_row_map(h, nkv)
    kern = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                             block_q=bq, block_k=bk, kv_len=L,
                             padded=Lp > L, window=window)
    out, lse = pl.pallas_call(
        kern,
        grid=(bh, Lp // bq, Lp // bk),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda g, i, j: (g, i, 0)),
            pl.BlockSpec((1, bk, d), lambda g, i, j: (to_kv(g), j, 0)),
            pl.BlockSpec((1, bk, d), lambda g, i, j: (to_kv(g), j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda g, i, j: (g, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda g, i, j: (g, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, Lp, d), q.dtype),
            jax.ShapeDtypeStruct((bh, Lp, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, d), jnp.float32),
        ] if pltpu is not None else [],
        compiler_params=None if interpret else _dims(),
        interpret=interpret,
    )(qf, kf, vf)
    out = out[:, :L].reshape(b, h, L, d)
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, scale, interpret, window, res, g):
    q, k, v, out, lse = res
    b, h, L, d = q.shape
    nkv = k.shape[1]
    grp = h // nkv
    if scale is None:
        scale = d ** -0.5
    bq = bk = _pick_block(L)
    Lp = _padded_len(L, bq)
    qf = _pad_seq(_merge_bh(q), Lp)
    kf, vf = (_pad_seq(_merge_bh(t), Lp) for t in (k, v))
    dof, of = (_pad_seq(_merge_bh(t), Lp) for t in (g, out))
    bh = b * h
    to_kv = _kv_row_map(h, nkv)
    # D = rowsum(dO ∘ O), computed once here (cheap elementwise + reduce,
    # XLA fuses it) and streamed to both kernels as a (bh, Lp, 1) tile
    # input; padded rows have dO = 0 so their D is 0 and every padded-row
    # contribution to dk/dv vanishes
    delta = jnp.sum(dof.astype(jnp.float32) * of.astype(jnp.float32),
                    axis=-1, keepdims=True)
    # the saved lse residual is already padded: (bh, Lp, 1)

    q_spec_i = pl.BlockSpec((1, bq, d), lambda g_, i, j: (g_, i, 0))
    kv_spec_j = pl.BlockSpec((1, bk, d), lambda g_, i, j: (to_kv(g_), j, 0))
    lse_spec_i = pl.BlockSpec((1, bq, 1), lambda g_, i, j: (g_, i, 0))
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal,
                          block_q=bq, block_k=bk, kv_len=L, padded=Lp > L,
                          window=window),
        grid=(bh, Lp // bq, Lp // bk),
        in_specs=[q_spec_i, kv_spec_j, kv_spec_j, q_spec_i,
                  lse_spec_i, lse_spec_i],
        out_specs=q_spec_i,
        out_shape=jax.ShapeDtypeStruct((bh, Lp, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),
        ] if pltpu is not None else [],
        compiler_params=None if interpret else _dims(),
        interpret=interpret,
    )(qf, kf, vf, dof, lse, delta)

    # dkv: kv tiles are the resident (parallel) dim, q tiles stream. With
    # GQA the kernel reads k/v via the grouped row map but WRITES dk/dv at
    # query-head resolution (each grid row owns its output row — no race
    # across the parallel dim); the group-sum to kv resolution happens
    # outside as one XLA reduce
    q_spec_s = pl.BlockSpec((1, bq, d), lambda g_, j, i: (g_, i, 0))
    kv_spec_in = pl.BlockSpec((1, bk, d),
                              lambda g_, j, i: (to_kv(g_), j, 0))
    kv_spec_r = pl.BlockSpec((1, bk, d), lambda g_, j, i: (g_, j, 0))
    lse_spec_s = pl.BlockSpec((1, bq, 1), lambda g_, j, i: (g_, i, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal,
                          block_q=bq, block_k=bk, kv_len=L, padded=Lp > L,
                          window=window),
        grid=(bh, Lp // bk, Lp // bq),
        in_specs=[q_spec_s, kv_spec_in, kv_spec_in, q_spec_s,
                  lse_spec_s, lse_spec_s],
        out_specs=[kv_spec_r, kv_spec_r],
        out_shape=[
            jax.ShapeDtypeStruct((bh, Lp, d), k.dtype),
            jax.ShapeDtypeStruct((bh, Lp, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
        ] if pltpu is not None else [],
        compiler_params=None if interpret else _dims(),
        interpret=interpret,
    )(qf, kf, vf, dof, lse, delta)

    dq = dq[:, :L].reshape(b, h, L, d)
    dk = dk[:, :L].reshape(b, nkv, grp, L, d).sum(axis=2)
    dv = dv[:, :L].reshape(b, nkv, grp, L, d).sum(axis=2)
    return dq, dk.astype(k.dtype), dv.astype(v.dtype)


flash_attention.defvjp(_flash_fwd, _flash_bwd)
