"""TPU-native op library: the jax/XLA equivalents of the mshadow expressions
consumed by the reference (inventory: SURVEY.md §2.11).

Each function here replaces one mshadow expression-template kernel:
conv2d          <- unpack_patch2col + dot + swapaxis   (src/layer/convolution_layer-inl.hpp:79-105)
pool2d          <- pool<Reducer> / unpool              (src/layer/pooling_layer-inl.hpp)
chpool_sum      <- chpool<red::sum>                    (LRN, src/layer/lrn_layer-inl.hpp:55-60)
softmax         <- mshadow::Softmax                    (src/layer/loss/softmax_layer-inl.hpp)

Design notes (TPU):
* conv lowers to the MXU through lax.conv_general_dilated with
  feature_group_count for grouped conv (ngroup) — no im2col materialization,
  XLA tiles directly.
* pooling/LRN lower to lax.reduce_window; XLA fuses the elementwise pre/post
  ops into the window reduction.
* shape semantics replicate the reference exactly (ceil-mode pooling with
  clamp) so config-declared nets produce identical node shapes.
"""

from __future__ import annotations

from functools import partial
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax


def conv_out_dim(x: int, k: int, s: int, p: int) -> int:
    """Conv output size, reference: src/layer/convolution_layer-inl.hpp:180-183."""
    return (x + 2 * p - k) // s + 1


def pool_out_dim(x: int, k: int, s: int) -> int:
    """Pooling output size (ceil-mode with clamp),
    reference: src/layer/pooling_layer-inl.hpp:104-106."""
    return min(x - k + s - 1, x - 1) // s + 1


def conv2d(x: jnp.ndarray, w: jnp.ndarray, *, stride: int = 1,
           pad: Tuple[int, int] = (0, 0), groups: int = 1,
           layout: str = "NCHW") -> jnp.ndarray:
    """2-D convolution. x: (N, C, H, W) — or (N, H, W, C) with
    layout="NHWC", the TPU-preferred channels-last activation layout
    (measured +24% on the inception topology, tools/layout_experiment.py).
    w is always (O, C/groups, KH, KW) OIHW — the reference's storage layout
    — so params, checkpoints, and TP shardings are layout-independent; XLA
    folds the (small) kernel transpose into its conv emitter.

    Result dtype follows the inputs: under bf16 mixed precision the MXU
    still accumulates each pass in f32 internally, and keeping the output
    bf16 gives JAX's conv transpose matching dtypes (a forced f32
    preferred_element_type breaks the backward pass for bf16 operands)."""
    return lax.conv_general_dilated(
        x, w,
        window_strides=(stride, stride),
        padding=[(pad[0], pad[0]), (pad[1], pad[1])],
        dimension_numbers=(layout, "OIHW", layout),
        feature_group_count=groups,
    )


def to_nhwc(x: jnp.ndarray) -> jnp.ndarray:
    """(N, C, H, W) -> (N, H, W, C)."""
    return jnp.transpose(x, (0, 2, 3, 1))


def to_nchw(x: jnp.ndarray) -> jnp.ndarray:
    """(N, H, W, C) -> (N, C, H, W)."""
    return jnp.transpose(x, (0, 3, 1, 2))


def _pool_padding(h: int, w: int, k: Tuple[int, int], s: int):
    oh, ow = pool_out_dim(h, k[0], s), pool_out_dim(w, k[1], s)
    ph = max((oh - 1) * s + k[0] - h, 0)
    pw = max((ow - 1) * s + k[1] - w, 0)
    return (oh, ow), (ph, pw)


@partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
def _max_pool(x, kernel, stride, padding):
    """Max pooling whose BACKWARD is a k*k shift-accumulate of equality
    masks instead of XLA's select-and-scatter. Measured SLOWER on TPU
    v5lite (GoogLeNet b256 bf16: 2.3k img/s vs 4.6k with
    select-and-scatter — the 9 input-sized compare/select passes cost
    more than they save), so this is OPT-IN via CXXNET_POOL=mask; kept
    because it reproduces the reference's unpool tie semantics exactly —
    EVERY input equal to the window max receives the full output gradient
    (mshadow unpool, reference src/layer/pooling_layer-inl.hpp Backprop)
    — where select-and-scatter picks a single winner per window."""
    window = (1, 1, kernel[0], kernel[1])
    strides = (1, 1, stride, stride)
    return lax.reduce_window(x, -jnp.inf, lax.max, window, strides,
                             [(0, 0), (0, 0)] + list(padding))


def _max_pool_fwd(x, kernel, stride, padding):
    y = _max_pool(x, kernel, stride, padding)
    return y, (x, y)


def _max_pool_bwd(kernel, stride, padding, res, g):
    x, y = res
    n, c, h, w = x.shape
    (ylo, yhi), (xlo, xhi) = padding
    s = stride
    oh, ow = y.shape[2], y.shape[3]
    xp = jnp.pad(x, ((0, 0), (0, 0), (ylo, yhi), (xlo, xhi)),
                 constant_values=-jnp.inf)
    # upsample y/g to the stride lattice (interior zeros never contribute:
    # their g is zero, so a spurious equality adds zero)
    interior = ((0, 0, 0), (0, 0, 0), (0, 0, s - 1), (0, 0, s - 1))
    yu = lax.pad(y, jnp.asarray(-jnp.inf, y.dtype), interior)
    gu = lax.pad(g, jnp.asarray(0, g.dtype), interior)
    uh, uw = (oh - 1) * s + 1, (ow - 1) * s + 1
    hp, wp = xp.shape[2], xp.shape[3]
    dxp = None
    for a in range(kernel[0]):
        for b in range(kernel[1]):
            xs = xp[:, :, a: a + uh, b: b + uw]
            contrib = jnp.where(xs == yu, gu, jnp.asarray(0, g.dtype))
            # pad-and-sum (not .at[].add: overlapping in-place updates
            # serialize with full-array copies and wreck fusion)
            part = jnp.pad(contrib, ((0, 0), (0, 0),
                                     (a, hp - uh - a), (b, wp - uw - b)))
            dxp = part if dxp is None else dxp + part
    return (dxp[:, :, ylo: ylo + h, xlo: xlo + w],)


_max_pool.defvjp(_max_pool_fwd, _max_pool_bwd)


# A fused Pallas max-pool BACKWARD (reference tie semantics in one VMEM
# pass, replacing select-and-scatter) lived here through r4 and was
# deleted after its on-chip A/B: GoogLeNet b128 bf16 measured 2,435
# img/s vs 4,707 with select-and-scatter (onchip_logs/poolab.log, r5) —
# and it needed three fixes against a moving Mosaic target just to
# compile (f32-only vector compares, no interior-pad lowering, 16M
# VMEM stack limits). XLA's select-and-scatter is the fast path on
# v5lite; CXXNET_POOL=mask below keeps the reference-exact tie
# semantics available in plain HLO.


def pool2d(x: jnp.ndarray, mode: str, kernel: Tuple[int, int], stride: int,
           pad: Tuple[int, int] = (0, 0),
           layout: str = "NCHW") -> jnp.ndarray:
    """Pooling with the reference's ceil-mode output shape.

    mode: 'max' | 'sum' | 'avg'. avg divides by k*k regardless of padding,
    matching src/layer/pooling_layer-inl.hpp:44-46. ``pad`` adds symmetric
    input padding first (beyond the reference — needed for same-size pool
    towers, e.g. GoogLeNet's 3x3/1 pool branch); max pads with -inf, so
    padding never wins the max. layout="NHWC" pools a channels-last input
    (window over axes 1,2).

    CXXNET_POOL=mask selects the equality-mask custom VJP (_max_pool:
    reference unpool tie semantics, but measured slower on TPU — see its
    docstring); the default is XLA's reduce_window autodiff
    (select-and-scatter backward).
    """
    import os
    if layout == "NHWC":
        n, h, w, c = x.shape
    else:
        n, c, h, w = x.shape
    py, px = pad
    (_, _), (ph, pw) = _pool_padding(h + 2 * py, w + 2 * px, kernel, stride)
    if layout == "NHWC":
        window = (1, kernel[0], kernel[1], 1)
        strides = (1, stride, stride, 1)
        padding = [(0, 0), (py, py + ph), (px, px + pw), (0, 0)]
    else:
        window = (1, 1, kernel[0], kernel[1])
        strides = (1, 1, stride, stride)
        padding = [(0, 0), (0, 0), (py, py + ph), (px, px + pw)]
    if mode == "max":
        pool_knob = os.environ.get("CXXNET_POOL")
        if pool_knob == "mask":
            # the mask VJP kernel is written for NCHW; wrap for NHWC
            # (opt-in knob — the transposes are acceptable there)
            if layout == "NHWC":
                return to_nhwc(_max_pool(to_nchw(x), kernel, stride,
                                         ((py, py + ph), (px, px + pw))))
            return _max_pool(x, kernel, stride,
                             ((py, py + ph), (px, px + pw)))
        return lax.reduce_window(x, -jnp.inf, lax.max, window,
                                 strides, padding)
    elif mode in ("sum", "avg"):
        out = lax.reduce_window(x, 0.0, lax.add, window, strides, padding)
        if mode == "avg":
            out = out * (1.0 / (kernel[0] * kernel[1]))
    else:
        raise ValueError("unknown pooling mode %s" % mode)
    return out


def chpool_sum(x: jnp.ndarray, nsize: int, axis: int = 1) -> jnp.ndarray:
    """Cross-channel sliding-window sum (mshadow chpool<red::sum>).

    For channel i, sums channels [i - nsize//2, i - nsize//2 + nsize) clipped
    to the valid range — the AlexNet LRN neighborhood. ``axis`` is the
    channel dimension (1 for NCHW, 3 for NHWC).
    """
    pad_lo = nsize // 2
    pad_hi = nsize - 1 - pad_lo
    window = [1, 1, 1, 1]
    window[axis] = nsize
    padding = [(0, 0)] * 4
    padding[axis] = (pad_lo, pad_hi)
    return lax.reduce_window(
        x, 0.0, lax.add,
        window_dimensions=tuple(window),
        window_strides=(1, 1, 1, 1),
        padding=padding,
    )


def lrn_xla(x: jnp.ndarray, nsize: int, alpha: float, beta: float,
            knorm: float) -> jnp.ndarray:
    """Pure-XLA LRN (reduce_window channel sum), the golden model for the
    Pallas kernel and the non-TPU fallback."""
    salpha = alpha / nsize
    norm = chpool_sum(jnp.square(x), nsize) * salpha + knorm
    return x * jnp.power(norm, -beta)


_use_pallas = None  # tri-state: None = auto (TPU only), True/False = forced


def set_use_pallas(flag) -> None:
    """Force (True/False) or reset (None = auto) Pallas kernel dispatch."""
    global _use_pallas
    _use_pallas = flag


def use_pallas() -> bool:
    if _use_pallas is not None:
        return _use_pallas
    return jax.default_backend() == "tpu"


def lrn_nhwc(x: jnp.ndarray, nsize: int, alpha: float, beta: float,
             knorm: float) -> jnp.ndarray:
    """Channels-last LRN: with C minor the cross-channel window sum is a
    reduce_window directly over the last axis — no layout change, no
    custom kernel, O(C * nsize) work. (A full C x C banded matmul also
    expresses it but wastes C/nsize of the MXU — measured 45% off
    AlexNet's step at C=256.)"""
    salpha = alpha / nsize
    norm = chpool_sum(jnp.square(x), nsize, axis=3) * salpha + knorm
    return x * jnp.power(norm, -beta)


def lrn(x: jnp.ndarray, nsize: int, alpha: float, beta: float, knorm: float,
        layout: str = "NCHW") -> jnp.ndarray:
    """Local response normalization across channels
    (reference: src/layer/lrn_layer-inl.hpp:52-60). NHWC inputs window-sum
    over the minor axis in place (lrn_nhwc — a reduce_window, no layout
    change). NCHW dispatches to the fused Pallas kernel on TPU
    (banded-matmul window sum on the MXU), XLA reduce_window elsewhere;
    CXXNET_LRN=xla forces the reduce_window path on TPU too — the banded
    matmul costs O(C^2) MACs per pixel (conv-sized at AlexNet's C=256), so
    which wins is measured, not assumed (tools/mfu_experiments.py
    ablation)."""
    import os
    if layout == "NHWC":
        if os.environ.get("CXXNET_LRN") == "xla":
            return to_nhwc(lrn_xla(to_nchw(x), nsize, alpha, beta, knorm))
        return lrn_nhwc(x, nsize, alpha, beta, knorm)
    if use_pallas() and os.environ.get("CXXNET_LRN") != "xla":
        from . import pallas_kernels
        return pallas_kernels.lrn(x, nsize, alpha, beta, knorm)
    return lrn_xla(x, nsize, alpha, beta, knorm)


def flash_supported(L: int, d: int) -> bool:
    """True when (seq, head_dim) fits the Pallas flash-attention tiling."""
    from . import flash_attn as _fa
    return _fa.supports(L, d)


def flash_attention(q, k, v, *, causal: bool = False, scale=None,
                    window: int = 0):
    """Memory-O(L) blocked attention (ops/flash_attn.py). Off-TPU the
    kernels run in the Pallas interpreter so forced-on tests (and any CPU
    debugging) execute the exact kernel code. window > 0 (causal only)
    keeps the last ``window`` keys per query — sliding-window attention;
    out-of-window kv tiles are skipped wholesale."""
    from . import flash_attn as _fa
    interpret = jax.default_backend() != "tpu"
    return _fa.flash_attention(q, k, v, causal, scale, interpret, window)


def softmax(x: jnp.ndarray, axis: int = -1) -> jnp.ndarray:
    return jax.nn.softmax(x, axis=axis)


def xelu(x: jnp.ndarray, b) -> jnp.ndarray:
    """Leaky relu with *divisor* b (reference op::xelu, src/layer/op.h:56-60)."""
    return jnp.where(x > 0, x, x / b)


def mxelu(x: jnp.ndarray, m) -> jnp.ndarray:
    """Leaky relu with *multiplier* m (reference op::mxelu,
    src/layer/prelu_layer-inl.hpp:10-14)."""
    return jnp.where(x > 0, x, x * m)
