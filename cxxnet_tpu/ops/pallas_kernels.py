"""Pallas TPU kernels for ops XLA doesn't fuse well.

The reference proves it needs a custom-kernel escape hatch (the hand-written
`InsanityPoolingExp` Plan::Eval, src/layer/insanity_pooling_layer-inl.hpp:13-100,
and mshadow's chpool for LRN); on TPU that escape hatch is Pallas
(SURVEY.md §2.11). Kernels here:

* ``lrn``: AlexNet cross-channel LRN, forward + analytic backward fused into
  one VMEM pass each. The channel-window sum is expressed as a static banded
  0/1 matrix multiplied on the MXU — (c, c) x (c, h*w) — instead of nsize
  shifted adds on the VPU: one systolic pass computes the whole window sum,
  and the band matrix transposes for the mirrored-window term in backward.
* ``rrelu``: the insanity layer's per-element random negative slope drawn
  with the on-core PRNG (pltpu.prng_random_bits) — no HBM round trip for the
  mask; the slope mask is returned for the backward pass.

Each kernel has an `interpret` switch so the numerics are unit-tested on CPU
(tests/test_pallas.py) against the pure-XLA implementations in ops/__init__.
"""

from __future__ import annotations

import functools
from typing import Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None


def _band_matrix(c: int, nsize: int) -> np.ndarray:
    """W[i, j] = 1 iff channel j is in i's LRN window
    [i - nsize//2, i - nsize//2 + nsize) — mshadow chpool's neighborhood."""
    lo = nsize // 2
    w = np.zeros((c, c), np.float32)
    for i in range(c):
        w[i, max(0, i - lo): min(c, i - lo + nsize)] = 1.0
    return w


def _lrn_fwd_kernel(x_ref, band_ref, o_ref, n_ref, *, salpha, beta, knorm):
    x = x_ref[0]
    sq = x * x
    norm = knorm + salpha * jnp.dot(band_ref[...], sq,
                                    preferred_element_type=jnp.float32)
    n_ref[0] = norm
    o_ref[0] = x * norm ** (-beta)


def _lrn_bwd_kernel(x_ref, band_ref, n_ref, g_ref, dx_ref, *, salpha, beta):
    x = x_ref[0]
    norm = n_ref[0]
    g = g_ref[0]
    # dx_m = g_m n_m^-b - 2 a b x_m * sum_{i: m in w(i)} g_i x_i n_i^{-b-1}
    # the mirrored window is the band transpose
    inner = g * x * norm ** (-beta - 1.0)
    s = jax.lax.dot_general(band_ref[...], inner,
                            dimension_numbers=(((0,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
    dx_ref[0] = g * norm ** (-beta) - (2.0 * salpha * beta) * x * s


def _lrn_call(x4d, nsize, salpha, beta, knorm, interpret):
    b, c, h, w = x4d.shape
    x = x4d.reshape(b, c, h * w)
    band = jnp.asarray(_band_matrix(c, nsize))
    out, norm = pl.pallas_call(
        functools.partial(_lrn_fwd_kernel, salpha=salpha, beta=beta,
                          knorm=knorm),
        grid=(b,),
        in_specs=[pl.BlockSpec((1, c, h * w), lambda i: (i, 0, 0)),
                  pl.BlockSpec((c, c), lambda i: (0, 0))],
        out_specs=[pl.BlockSpec((1, c, h * w), lambda i: (i, 0, 0)),
                   pl.BlockSpec((1, c, h * w), lambda i: (i, 0, 0))],
        out_shape=[jax.ShapeDtypeStruct((b, c, h * w), x.dtype),
                   jax.ShapeDtypeStruct((b, c, h * w), x.dtype)],
        interpret=interpret,
    )(x, band)
    return out.reshape(b, c, h, w), norm


def _lrn_bwd_call(x4d, norm, g4d, nsize, salpha, beta, interpret):
    b, c, h, w = x4d.shape
    x = x4d.reshape(b, c, h * w)
    g = g4d.reshape(b, c, h * w)
    band = jnp.asarray(_band_matrix(c, nsize))
    dx = pl.pallas_call(
        functools.partial(_lrn_bwd_kernel, salpha=salpha, beta=beta),
        grid=(b,),
        in_specs=[pl.BlockSpec((1, c, h * w), lambda i: (i, 0, 0)),
                  pl.BlockSpec((c, c), lambda i: (0, 0)),
                  pl.BlockSpec((1, c, h * w), lambda i: (i, 0, 0)),
                  pl.BlockSpec((1, c, h * w), lambda i: (i, 0, 0))],
        out_specs=pl.BlockSpec((1, c, h * w), lambda i: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((b, c, h * w), x.dtype),
        interpret=interpret,
    )(x, band, norm, g)
    return dx.reshape(b, c, h, w)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4, 5))
def lrn(x, nsize: int, alpha: float, beta: float, knorm: float,
        interpret: bool = False):
    """Fused Pallas LRN (reference numerics: src/layer/lrn_layer-inl.hpp:52-60,
    salpha = alpha / nsize)."""
    out, _ = _lrn_call(x, nsize, alpha / nsize, beta, knorm, interpret)
    return out


def _lrn_fwd(x, nsize, alpha, beta, knorm, interpret):
    out, norm = _lrn_call(x, nsize, alpha / nsize, beta, knorm, interpret)
    return out, (x, norm)


def _lrn_bwd(nsize, alpha, beta, knorm, interpret, res, g):
    x, norm = res
    dx = _lrn_bwd_call(x, norm, g, nsize, alpha / nsize, beta, interpret)
    return (dx,)


lrn.defvjp(_lrn_fwd, _lrn_bwd)


# ---------------------------------------------------------------------------
# RReLU (insanity layer) with in-kernel PRNG
# ---------------------------------------------------------------------------
def _rrelu_kernel(seed_ref, x_ref, o_ref, m_ref, *, lb, ub):
    pltpu.prng_seed(seed_ref[0])
    x = x_ref[...]
    # prng_random_bits yields int32; shift logically as uint32, then bitcast
    # back to int32 (top byte now zero) since Mosaic can't cast uint32->f32.
    # 24 high bits -> exact float32 uniform [0, 1) ladder.
    bits = pltpu.bitcast(pltpu.prng_random_bits(x.shape), jnp.uint32) >> 8
    u = pltpu.bitcast(bits, jnp.int32).astype(jnp.float32) * (1.0 / (1 << 24))
    slope = u * (ub - lb) + lb
    m_ref[...] = slope
    o_ref[...] = jnp.where(x > 0, x, x / slope)


def rrelu(x, seed, lb: float, ub: float) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Training-mode insanity/RReLU forward: per-element random slope drawn
    on-core (reference src/layer/insanity_layer-inl.hpp:14 divides the
    negative part by U[lb, ub]). Returns (out, slope_mask); the mask is the
    residual for the backward's xelu gradient. TPU-only (on-core PRNG)."""
    b = x.shape[0]
    flat = x.reshape(b, -1)
    seed_arr = jnp.asarray([seed], jnp.int32)
    out, mask = pl.pallas_call(
        functools.partial(_rrelu_kernel, lb=lb, ub=ub),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=[pl.BlockSpec(memory_space=pltpu.VMEM),
                   pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_shape=[jax.ShapeDtypeStruct(flat.shape, x.dtype),
                   jax.ShapeDtypeStruct(flat.shape, x.dtype)],
    )(seed_arr, flat)
    return out.reshape(x.shape), mask.reshape(x.shape)
