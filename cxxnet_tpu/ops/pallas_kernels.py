"""Pallas TPU kernels for ops XLA doesn't fuse well.

The reference proves it needs a custom-kernel escape hatch (the hand-written
`InsanityPoolingExp` Plan::Eval, src/layer/insanity_pooling_layer-inl.hpp:13-100,
and mshadow's chpool for LRN); on TPU that escape hatch is Pallas
(SURVEY.md §2.11). Kernels here:

* ``lrn``: AlexNet cross-channel LRN, forward + analytic backward fused into
  one VMEM pass each. The channel-window sum is expressed as a static banded
  0/1 matrix multiplied on the MXU — (c, c) x (c, h*w) — instead of nsize
  shifted adds on the VPU: one systolic pass computes the whole window sum,
  and the band matrix transposes for the mirrored-window term in backward.
* ``uniform`` / ``rrelu_mask``: the insanity layer's per-element random
  negative slope drawn with the on-core PRNG (pltpu.prng_random_bits) — no
  HBM round trip for the mask.

The LRN kernels have an `interpret` switch so their numerics are unit-tested
on CPU (tests/test_pallas.py) against the pure-XLA implementations in
ops/__init__. The PRNG kernels are TPU-only (pltpu's PRNG primitives have no
CPU interpret path) and are validated on-device by tools/check_tpu_kernels.py.
"""

from __future__ import annotations

import functools
from typing import Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None


def _band_matrix(c: int, nsize: int) -> np.ndarray:
    """W[i, j] = 1 iff channel j is in i's LRN window
    [i - nsize//2, i - nsize//2 + nsize) — mshadow chpool's neighborhood."""
    lo = nsize // 2
    w = np.zeros((c, c), np.float32)
    for i in range(c):
        w[i, max(0, i - lo): min(c, i - lo + nsize)] = 1.0
    return w


def _lrn_fwd_kernel(x_ref, band_ref, o_ref, n_ref, *, salpha, beta, knorm):
    # compute in f32 regardless of the activation dtype (bf16 nets); the
    # norm residual n_ref stays f32, the output is cast back
    x = x_ref[0].astype(jnp.float32)
    sq = x * x
    norm = knorm + salpha * jnp.dot(band_ref[...], sq,
                                    preferred_element_type=jnp.float32)
    n_ref[0] = norm
    o_ref[0] = (x * norm ** (-beta)).astype(o_ref.dtype)


def _lrn_bwd_kernel(x_ref, band_ref, n_ref, g_ref, dx_ref, *, salpha, beta):
    x = x_ref[0].astype(jnp.float32)
    norm = n_ref[0]
    g = g_ref[0].astype(jnp.float32)
    # dx_m = g_m n_m^-b - 2 a b x_m * sum_{i: m in w(i)} g_i x_i n_i^{-b-1}
    # the mirrored window is the band transpose
    inner = g * x * norm ** (-beta - 1.0)
    s = jax.lax.dot_general(band_ref[...], inner,
                            dimension_numbers=(((0,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
    dx_ref[0] = (g * norm ** (-beta)
                 - (2.0 * salpha * beta) * x * s).astype(dx_ref.dtype)


def _lrn_call(x4d, nsize, salpha, beta, knorm, interpret):
    b, c, h, w = x4d.shape
    x = x4d.reshape(b, c, h * w)
    band = jnp.asarray(_band_matrix(c, nsize))
    out, norm = pl.pallas_call(
        functools.partial(_lrn_fwd_kernel, salpha=salpha, beta=beta,
                          knorm=knorm),
        grid=(b,),
        in_specs=[pl.BlockSpec((1, c, h * w), lambda i: (i, 0, 0)),
                  pl.BlockSpec((c, c), lambda i: (0, 0))],
        out_specs=[pl.BlockSpec((1, c, h * w), lambda i: (i, 0, 0)),
                   pl.BlockSpec((1, c, h * w), lambda i: (i, 0, 0))],
        out_shape=[jax.ShapeDtypeStruct((b, c, h * w), x.dtype),
                   jax.ShapeDtypeStruct((b, c, h * w), jnp.float32)],
        interpret=interpret,
    )(x, band)
    return out.reshape(b, c, h, w), norm


def _lrn_bwd_call(x4d, norm, g4d, nsize, salpha, beta, interpret):
    b, c, h, w = x4d.shape
    x = x4d.reshape(b, c, h * w)
    g = g4d.reshape(b, c, h * w)
    band = jnp.asarray(_band_matrix(c, nsize))
    dx = pl.pallas_call(
        functools.partial(_lrn_bwd_kernel, salpha=salpha, beta=beta),
        grid=(b,),
        in_specs=[pl.BlockSpec((1, c, h * w), lambda i: (i, 0, 0)),
                  pl.BlockSpec((c, c), lambda i: (0, 0)),
                  pl.BlockSpec((1, c, h * w), lambda i: (i, 0, 0)),
                  pl.BlockSpec((1, c, h * w), lambda i: (i, 0, 0))],
        out_specs=pl.BlockSpec((1, c, h * w), lambda i: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((b, c, h * w), x.dtype),
        interpret=interpret,
    )(x, band, norm, g)
    return dx.reshape(b, c, h, w)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4, 5))
def lrn(x, nsize: int, alpha: float, beta: float, knorm: float,
        interpret: bool = False):
    """Fused Pallas LRN (reference numerics: src/layer/lrn_layer-inl.hpp:52-60,
    salpha = alpha / nsize)."""
    out, _ = _lrn_call(x, nsize, alpha / nsize, beta, knorm, interpret)
    return out


def _lrn_fwd(x, nsize, alpha, beta, knorm, interpret):
    out, norm = _lrn_call(x, nsize, alpha / nsize, beta, knorm, interpret)
    return out, (x, norm)


def _lrn_bwd(nsize, alpha, beta, knorm, interpret, res, g):
    x, norm = res
    dx = _lrn_bwd_call(x, norm, g, nsize, alpha / nsize, beta, interpret)
    return (dx,)


lrn.defvjp(_lrn_fwd, _lrn_bwd)


# ---------------------------------------------------------------------------
# RReLU (insanity layer) with in-kernel PRNG
# ---------------------------------------------------------------------------
def _uniform_kernel(seed_ref, u_ref):
    # one grid step = one (block_rows, 128) tile; re-seed per block so each
    # tile draws an independent stream and the whole array never has to fit
    # in VMEM at once. prng_seed hashes its operands, so (seed, block) pairs
    # never alias across neighboring seeds the way seed+block would.
    pltpu.prng_seed(seed_ref[0], pl.program_id(0))
    # prng_random_bits yields int32; shift logically as uint32, then bitcast
    # back to int32 (top byte now zero) since Mosaic can't cast uint32->f32.
    # 24 high bits -> exact float32 uniform [0, 1) ladder.
    bits = pltpu.bitcast(pltpu.prng_random_bits(u_ref.shape), jnp.uint32) >> 8
    u = pltpu.bitcast(bits, jnp.int32).astype(jnp.float32) * (1.0 / (1 << 24))
    u_ref[...] = u.astype(u_ref.dtype)


def uniform(seed, shape, dtype=jnp.float32) -> jnp.ndarray:
    """U[0, 1) tensor drawn with the on-core TPU PRNG — no HBM round trip
    for the random bits. `seed` may be a traced int32 scalar. TPU-only:
    pltpu's PRNG primitives have no CPU interpret path, so this kernel is
    validated on-device (tools/check_tpu_kernels.py) rather than in the CPU
    suite."""
    if pltpu is None:
        raise RuntimeError(
            "pallas uniform needs TPU support (jax.experimental.pallas.tpu)")
    flat = int(np.prod(shape))
    # pad the flat draw up to a (rows, 128) lane tile, then grid over row
    # blocks so VMEM holds one ~1 MB tile at a time regardless of total size
    cols = 128
    rows = -(-flat // cols)
    block_rows = min(rows, 2048)
    grid = -(-rows // block_rows)
    seed_arr = jnp.asarray([seed], jnp.int32).reshape((1,))
    u = pl.pallas_call(
        _uniform_kernel,
        grid=(grid,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_specs=pl.BlockSpec((block_rows, cols), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((grid * block_rows, cols), dtype),
    )(seed_arr)
    return u.reshape(-1)[:flat].reshape(shape)


def rrelu_mask(seed, shape, lb, ub, dtype=jnp.float32) -> jnp.ndarray:
    """Per-element random slope in [lb, ub) — the insanity/RReLU divisor
    (reference src/layer/insanity_layer-inl.hpp:14 divides the negative part
    by U[lb, ub]); the consumer applies ops.xelu(x, mask). The affine
    transform runs in XLA (fuses with the consumer) so lb/ub may be traced
    (calm_start/calm_end annealing)."""
    u = uniform(seed, shape, dtype)
    return u * (ub - lb) + lb


def rrelu(x, seed, lb: float, ub: float) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Training-mode insanity/RReLU forward. Returns (out, slope_mask); the
    slope draw happens in-kernel, the elementwise division stays in XLA so
    autodiff gives the xelu gradient for free."""
    mask = rrelu_mask(seed, x.shape, lb, ub, x.dtype)
    return jnp.where(x > 0, x, x / mask), mask
# (The fused max-pool backward kernel that lived here through r4 was
# deleted after losing its on-chip A/B 2:1 to XLA select-and-scatter —
# see ops.pool2d and onchip_logs/poolab.log.)
